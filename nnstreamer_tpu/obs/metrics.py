"""Process-wide metrics registry: counters, gauges, histograms + the
pipeline collector that absorbs the runtime's scattered stats.

Two kinds of metric enter one registry:

- **Instruments** — labeled ``Counter``/``Gauge``/``Histogram`` families
  created via :meth:`MetricsRegistry.counter` etc., bumped directly by
  whoever owns them (thread-safe, one lock per family).
- **Collected state** — the stats the runtime already keeps are *pulled*
  at snapshot time, not pushed per buffer: ``Element.count_stat``
  flow counters, ``InvokeStats.snapshot()`` (one consistent read under
  one lock), MicroBatcher/SharedBatcher flush reasons and pending
  depth, ``queue`` depth/drops, and the serving ``ModelPool`` entries.
  A pipeline registers itself on ``start()`` and unregisters on
  ``stop()`` (weakly referenced — a dropped pipeline never leaks);
  between scrapes the hot path pays **nothing** beyond the counters it
  was already keeping.  This is why metrics stay near-zero-cost when
  passive (the ISSUE-4 acceptance bound: <3% frames/s delta).

Outputs:

- :meth:`MetricsRegistry.exposition` — Prometheus text format 0.0.4;
- :meth:`MetricsRegistry.snapshot` — one JSON-able dict with both the
  flat metric families and a structured per-pipeline/per-pool view
  (what ``nns-top`` renders and ``bench.py --metrics`` embeds);
- :func:`serve_metrics` — a stdlib-http endpoint (``/metrics`` text,
  ``/json`` snapshot).  Setting ``NNS_TPU_METRICS_PORT`` serves the
  global registry automatically when the first pipeline starts, so any
  running process can be observed by ``nns-top`` without touching its
  code.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

#: v10: + ``profile`` table (host-execution profiler — per-element
#: cpu/run/wait seconds with sample shares, top sampled stacks,
#: GIL-pressure proxy — obs/prof.py);
#: v9: + ``tenants`` table (per-(pool, tenant) device-second/frame/SLO
#: attribution with scrape-time dollars — obs/tenantstat.py) and
#: ``forecasts`` table (latest predictive-rule rows + per-pool
#: capacity headroom — obs/forecast.py);
#: v8: + ``stages`` table (disaggregated pipeline split: per-stage
#: cross-subset handoff frames/bytes + inter-stage depth, cascade
#: offload rows — obs/stagestat.py), pool rows grow ``stage``
#: (v7: + ``models`` table (model lifecycle: per-pool version registry
#: with per-version serving stats, canary state and swap provenance —
#: runtime/lifecycle.py), pool rows grow ``lifecycle``;
#: v6: + ``control`` table, admission rows grow ``ramp_start``;
#: v5: + ``executables`` and ``mesh`` tables, filter/pool ``model``;
#: v4: + ``transfers`` and ``device_memory`` tables, pool ``weights``;
#: v3: + ``compiles`` table, phase fields and ``cache``; all additive —
#: older consumers read what they know, and the exact-top-level-shape
#: golden makes a new table a deliberate version bump, not a silent
#: append)
SNAPSHOT_VERSION = 10

_KINDS = ("counter", "gauge", "histogram")


def _fmt_value(v: float) -> str:
    """Prometheus sample value: ints bare, floats repr'd."""
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _fmt_labels(labels: Dict[str, Any]) -> str:
    if not labels:
        return ""
    esc = []
    for k in sorted(labels):
        v = str(labels[k]).replace("\\", r"\\").replace('"', r"\"") \
            .replace("\n", r"\n")
        esc.append(f'{k}="{v}"')
    return "{" + ",".join(esc) + "}"


class _Child:
    """One labeled time series of a family."""

    __slots__ = ("_family", "labels", "value", "_buckets", "_sum", "_count")

    def __init__(self, family: "Family", labels: Dict[str, str]):
        self._family = family
        self.labels = labels
        self.value = 0.0
        if family.kind == "histogram":
            self._buckets = [0] * len(family.buckets)
            self._sum = 0.0
            self._count = 0

    def inc(self, n: float = 1.0) -> None:
        if self._family.kind == "histogram":
            raise ValueError("inc() on a histogram (use observe())")
        if self._family.kind == "counter" and n < 0:
            raise ValueError("counters only go up")
        with self._family._lock:
            self.value += n

    def dec(self, n: float = 1.0) -> None:
        if self._family.kind != "gauge":
            raise ValueError(f"dec() on a {self._family.kind}")
        with self._family._lock:
            self.value -= n

    def set(self, v: float) -> None:
        if self._family.kind != "gauge":
            raise ValueError(f"set() on a {self._family.kind}")
        with self._family._lock:
            self.value = float(v)

    def observe(self, v: float) -> None:
        if self._family.kind != "histogram":
            raise ValueError(f"observe() on a {self._family.kind}")
        with self._family._lock:
            self._sum += v
            self._count += 1
            # non-cumulative per-bucket counts; the exposition renderer
            # cumulates them into Prometheus `le` semantics
            for i, le in enumerate(self._family.buckets):
                if v <= le:
                    self._buckets[i] += 1
                    break

    def hist_state(self) -> Tuple[List[int], float, int]:
        """One consistent read of this histogram child's cumulative
        state: (per-bucket counts [non-cumulative], sum, count).  The
        consumer API for controllers that derive their signal from the
        exported histogram (runtime/admission.py) — the same numbers a
        scrape renders, read under the same lock."""
        if self._family.kind != "histogram":
            raise ValueError(f"hist_state() on a {self._family.kind}")
        with self._family._lock:
            return list(self._buckets), self._sum, self._count

    @property
    def bucket_bounds(self) -> Tuple[float, ...]:
        return self._family.buckets


class Family:
    """A named metric with a fixed label schema; ``labels()`` returns
    (creating on first use) the child series for one label value set."""

    def __init__(self, name: str, help: str, kind: str,
                 labelnames: Tuple[str, ...] = (),
                 buckets: Optional[Tuple[float, ...]] = None):
        if kind not in _KINDS:
            raise ValueError(f"unknown metric kind {kind!r}")
        self.name = name
        self.help = help
        self.kind = kind
        self.labelnames = tuple(labelnames)
        self.buckets = tuple(buckets or ()) if kind == "histogram" else ()
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], _Child] = {}

    def labels(self, **kv: Any) -> _Child:
        if set(kv) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(kv)} != declared "
                f"{sorted(self.labelnames)}")
        key = tuple(str(kv[k]) for k in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = _Child(self, dict(zip(self.labelnames, key)))
                self._children[key] = child
            return child

    def collect(self) -> List[Tuple[Dict[str, str], float]]:
        """(labels, value) samples; histograms expand to
        ``_bucket``/``_sum``/``_count`` in the exposition renderer."""
        with self._lock:
            return [(dict(c.labels), c.value)
                    for c in self._children.values()]

    def _hist_rows(self):
        with self._lock:
            return [(dict(c.labels), list(c._buckets), c._sum, c._count)
                    for c in self._children.values()]


class MetricsRegistry:
    """Thread-safe registry of instrument families + pull collectors."""

    DEFAULT_BUCKETS = (.0005, .001, .0025, .005, .01, .025, .05, .1,
                       .25, .5, 1.0, 2.5, 5.0, float("inf"))

    def __init__(self, collect_links: bool = False,
                 collect_compiles: bool = False,
                 collect_transfers: bool = False,
                 collect_devices: bool = False,
                 collect_executables: bool = False,
                 collect_mesh: bool = False,
                 collect_stages: bool = False,
                 collect_tenants: bool = False,
                 collect_prof: bool = False):
        self._lock = threading.Lock()
        self._families: Dict[str, Family] = {}
        self._collectors: List[Callable[[], Iterable[tuple]]] = []
        self._pipelines: Dict[int, Any] = {}  # id -> weakref.ref
        self._server = None
        # the LinkMetrics, CompileStats, TransferLedger, device-memory,
        # XlaCostStats and MeshStats stores are process-wide (edge
        # connections / framework compiles / host<->device crossings /
        # compiled executables don't know which registry observes
        # them): only registries that opt in — the global REGISTRY
        # does — pull them, so a private/test registry's exposition
        # isn't polluted by unrelated state.  The executables join is
        # additionally STATEFUL (scrape-to-scrape delta windows), so
        # exactly one registry should drive it.
        self._collect_links = bool(collect_links)
        self._collect_compiles = bool(collect_compiles)
        self._collect_transfers = bool(collect_transfers)
        self._collect_devices = bool(collect_devices)
        self._collect_executables = bool(collect_executables)
        self._collect_mesh = bool(collect_mesh)
        self._collect_stages = bool(collect_stages)
        self._collect_tenants = bool(collect_tenants)
        self._collect_prof = bool(collect_prof)

    # -- instruments ---------------------------------------------------------

    def _family(self, name: str, help: str, kind: str,
                labelnames=(), buckets=None) -> Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = Family(name, help, kind, labelnames, buckets)
                self._families[name] = fam
            elif fam.kind != kind or fam.labelnames != tuple(labelnames) \
                    or (kind == "histogram"
                        and fam.buckets != tuple(buckets or ())):
                raise ValueError(
                    f"metric {name!r} already registered as {fam.kind} "
                    f"with labels {fam.labelnames}"
                    + (f" and buckets {fam.buckets}"
                       if fam.kind == "histogram" else ""))
            return fam

    def counter(self, name: str, help: str = "", labelnames=()) -> Family:
        return self._family(name, help, "counter", labelnames)

    def gauge(self, name: str, help: str = "", labelnames=()) -> Family:
        return self._family(name, help, "gauge", labelnames)

    def histogram(self, name: str, help: str = "", labelnames=(),
                  buckets: Optional[Tuple[float, ...]] = None) -> Family:
        b = tuple(sorted(buckets or self.DEFAULT_BUCKETS))
        if b[-1] != float("inf"):
            b = b + (float("inf"),)
        return self._family(name, help, "histogram", labelnames, b)

    # -- pull collectors -----------------------------------------------------

    def register_collector(self, fn: Callable[[], Iterable[tuple]]) -> None:
        """``fn()`` yields ``(name, kind, help, labels, value)`` tuples at
        every scrape (the Prometheus custom-collector pattern)."""
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def unregister_collector(self, fn) -> None:
        with self._lock:
            if fn in self._collectors:
                self._collectors.remove(fn)

    # -- pipeline registration (runtime/pipeline.py drives this) -------------

    def register_pipeline(self, pipe) -> None:
        import weakref

        with self._lock:
            self._pipelines[id(pipe)] = weakref.ref(pipe)
        maybe_serve_from_env(self)

    def unregister_pipeline(self, pipe) -> None:
        with self._lock:
            self._pipelines.pop(id(pipe), None)

    def _live_pipelines(self) -> List[Any]:
        with self._lock:
            refs = list(self._pipelines.items())
        out = []
        for key, ref in refs:
            p = ref()
            if p is None:
                with self._lock:
                    self._pipelines.pop(key, None)
            else:
                out.append(p)
        return out

    # -- outputs -------------------------------------------------------------

    def collect(self) -> "Dict[str, dict]":
        """name -> {name, kind, help, samples:[{labels, value}]} merged
        from instruments, collector callbacks, and registered
        pipelines."""
        return self._collect_all()[-1]

    def _collect_all(self):
        """ONE walk of the runtime state per scrape: the structured
        per-pipeline/per-pool/per-link/compile tables are read first
        (one lock acquisition per element-stats dict / InvokeStats /
        LinkMetrics / CompileStats / TransferLedger), and the flat
        metric samples are DERIVED from those tables — so the two
        views in one snapshot can never disagree, and the hot-path
        locks are not taken a second time.  Returns ``(tables, pools,
        links, compiles, transfers, devmem, execs, mesh, stages,
        fams)``."""
        fams: Dict[str, dict] = {}
        with self._lock:
            instruments = list(self._families.values())
            collectors = list(self._collectors)
        tables = [_pipeline_table(p) for p in self._live_pipelines()]
        pools = _pool_table()
        models = _models_table()
        links = _link_table() if self._collect_links else []
        compiles = _compile_table() if self._collect_compiles else []
        transfers = _transfer_table() if self._collect_transfers else []
        devmem = _device_table() if self._collect_devices else []
        execs, exec_util = _executable_join() \
            if self._collect_executables else ([], [])
        mesh = _mesh_table() if self._collect_mesh else []
        stages = _stage_table() if self._collect_stages else []
        tenants = _tenant_table() if self._collect_tenants else []

        def add(name, kind, help, labels, value, sample_name=None):
            fam = fams.setdefault(name, {
                "name": name, "kind": kind, "help": help, "samples": []})
            sample = {"labels": dict(labels), "value": value}
            if sample_name is not None:
                # histogram sub-series (name_bucket/_sum/_count) stay
                # under ONE family so the exposition declares a single
                # `# TYPE <name> histogram` (Prometheus text 0.0.4)
                sample["name"] = sample_name
            fam["samples"].append(sample)

        for f in instruments:
            if f.kind == "histogram":
                for labels, buckets, s, n in f._hist_rows():
                    for le, cum in zip(f.buckets, _cumulate(buckets)):
                        add(f.name, "histogram", f.help,
                            {**labels, "le": _le_str(le)}, cum,
                            sample_name=f.name + "_bucket")
                    add(f.name, "histogram", f.help, labels, s,
                        sample_name=f.name + "_sum")
                    add(f.name, "histogram", f.help, labels, n,
                        sample_name=f.name + "_count")
            else:
                for labels, value in f.collect():
                    add(f.name, f.kind, f.help, labels, value)
        for fn in collectors:
            for name, kind, help, labels, value in fn():
                add(name, kind, help, labels, value)
        for name, kind, help, labels, value in _pipeline_samples(tables):
            add(name, kind, help, labels, value)
        for name, kind, help, labels, value in _pool_samples(pools):
            add(name, kind, help, labels, value)
        for name, kind, help, labels, value in _model_samples(models):
            add(name, kind, help, labels, value)
        for name, kind, help, labels, value in _link_samples(links):
            add(name, kind, help, labels, value)
        for name, kind, help, labels, value in _compile_samples(compiles):
            add(name, kind, help, labels, value)
        for name, kind, help, labels, value in _transfer_samples(transfers):
            add(name, kind, help, labels, value)
        for name, kind, help, labels, value in _device_samples(devmem):
            add(name, kind, help, labels, value)
        for name, kind, help, labels, value in _executable_samples(execs):
            add(name, kind, help, labels, value)
        for name, kind, help, labels, value in _util_samples(exec_util):
            add(name, kind, help, labels, value)
        for name, kind, help, labels, value in _mesh_samples(mesh):
            add(name, kind, help, labels, value)
        for name, kind, help, labels, value in _stage_samples(stages):
            add(name, kind, help, labels, value)
        for name, kind, help, labels, value in _tenant_samples(tenants):
            add(name, kind, help, labels, value)
        if self._collect_stages:
            for name, kind, help, labels, value \
                    in _placement_overlap_samples():
                add(name, kind, help, labels, value)
        from .transfer import TRANSFER_SECONDS_BUCKETS

        for row in transfers:
            # per-row transfer duration distribution as a proper
            # Prometheus histogram (bucket/sum/count under ONE TYPE)
            labels = {"pipeline": row["pipeline"],
                      "source": row["source"],
                      "direction": row["direction"],
                      "reason": row["reason"]}
            hname = "nns_transfer_seconds"
            hhelp = "duration of one host<->device crossing"
            for le, cum in zip(TRANSFER_SECONDS_BUCKETS,
                               _cumulate(row["buckets"])):
                add(hname, "histogram", hhelp,
                    {**labels, "le": _le_str(le)}, cum,
                    sample_name=hname + "_bucket")
            add(hname, "histogram", hhelp, labels, row["seconds"],
                sample_name=hname + "_sum")
            add(hname, "histogram", hhelp, labels, row["count"],
                sample_name=hname + "_count")
        for row in links:
            # the RTT distribution renders as a proper Prometheus
            # histogram (bucket/sum/count under ONE TYPE declaration)
            labels = {"link": row["link"], "peer": row["peer"],
                      "kind": row["kind"]}
            rtt = row["rtt"]
            hname = "nns_edge_rtt_seconds"
            hhelp = "request round-trip time over the link"
            for le, cum in zip(EDGE_RTT_BUCKETS,
                               _cumulate(rtt["buckets"])):
                add(hname, "histogram", hhelp,
                    {**labels, "le": _le_str(le)}, cum,
                    sample_name=hname + "_bucket")
            add(hname, "histogram", hhelp, labels, rtt["sum_s"],
                sample_name=hname + "_sum")
            add(hname, "histogram", hhelp, labels, rtt["count"],
                sample_name=hname + "_count")
        # host-execution profiler (obs/prof.py): the exact per-element
        # run/wait/CPU accumulators as counter families, plus the
        # sampled GIL-pressure proxy while the profiler runs; the
        # accounts store is process-wide, so (like the ledgers above)
        # only opted-in registries pull it
        from . import prof as _prof

        prof_rows = _prof.account_rows() if self._collect_prof else []
        for row in prof_rows:
            labels = {"pipeline": row["pipeline"],
                      "element": row["element"]}
            add("nns_element_cpu_seconds_total", "counter",
                "host CPU seconds consumed by the element's loop "
                "thread", labels, row["cpu_s"])
            add("nns_element_run_seconds_total", "counter",
                "wall seconds the element loop spent running its "
                "chain", labels, row["run_s"])
            add("nns_element_wait_seconds_total", "counter",
                "wall seconds the element loop spent waiting for "
                "work", labels, row["wait_s"])
        if self._collect_prof and _prof.PROFILER.running:
            add("nns_gil_waiters", "gauge",
                "sampled runnable-but-not-running threads (GIL "
                "pressure proxy)", {},
                float(_prof.PROFILER.gil_waiters))
        return (tables, pools, models, links, compiles, transfers,
                devmem, execs, mesh, stages, tenants, fams)

    def exposition(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: List[str] = []
        fams = self.collect()
        for name in sorted(fams):
            fam = fams[name]
            if fam["help"]:
                lines.append(f"# HELP {name} {fam['help']}")
            lines.append(f"# TYPE {name} {fam['kind']}")
            for s in fam["samples"]:
                lines.append(
                    f"{s.get('name', name)}{_fmt_labels(s['labels'])} "
                    f"{_fmt_value(s['value'])}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """One JSON-able dict: the flat metric families plus the
        structured per-pipeline / per-pool / per-link / compile /
        transfer / device-memory tables ``nns-top`` renders — all
        views derived from the same single read of the runtime state
        (see :meth:`_collect_all`)."""
        (tables, pools, models, links, compiles, transfers, devmem,
         execs, mesh, stages, tenants, fams) = self._collect_all()
        return {
            "version": SNAPSHOT_VERSION,
            "time": time.time(),
            "host": _host_tag(),
            "pipelines": tables,
            "pools": pools,
            "models": models,
            "links": links,
            "compiles": compiles,
            "transfers": transfers,
            "device_memory": devmem,
            "executables": execs,
            "mesh": mesh,
            "stages": stages,
            "tenants": tenants,
            "forecasts": _forecast_table(),
            "control": _control_table(),
            "profile": _profile_table(),
            "metrics": fams,
        }

    def serve(self, port: int = 0, host: str = "127.0.0.1"
              ) -> "MetricsServer":
        """Start (once) the background HTTP endpoint for this registry.
        A closed server deregisters itself, so serve() after close()
        starts a fresh listener instead of returning the dead one."""
        with self._lock:
            if self._server is None:
                self._server = MetricsServer(self, port=port, host=host)
            return self._server


def _host_tag() -> str:
    from .tracectx import host_tag

    return host_tag()


def _cumulate(buckets: List[int]) -> List[int]:
    out, acc = [], 0
    for b in buckets:
        acc += b
        out.append(acc)
    return out


def bucket_quantile(bounds: Tuple[float, ...], dist: List[float],
                    q: float) -> Optional[float]:
    """Interpolated quantile of one NON-cumulative bucket distribution
    (``dist[i]`` observations in ``(bounds[i-1], bounds[i]]``): the one
    histogram→quantile definition in the codebase, shared by the
    admission controller's shed signal (``runtime/admission.py``) and
    the watchdog's windowed series (``obs/watch.py``) so the number an
    external controller derives from a scrape is bit-identical to the
    one the in-process consumers act on.

    Linear interpolation within the bucket where the cumulative
    fraction crosses ``q``; ``None`` when the distribution is empty or
    the quantile lands in the ``+Inf`` bucket (no upper bound to
    interpolate toward — callers fall back to their own signal)."""
    total = sum(dist)
    if total <= 0:
        return None
    target = q * total
    acc = 0.0
    for i, n in enumerate(dist):
        if acc + n >= target and n > 0:
            hi = bounds[i]
            if hi == float("inf"):
                return None
            lo = bounds[i - 1] if i > 0 else 0.0
            return lo + (hi - lo) * (target - acc) / n
        acc += n
    return None


def _le_str(le: float) -> str:
    return "+Inf" if le == float("inf") else _fmt_value(le)


# -- the pipeline walk (pull side) -------------------------------------------


def _factory(e) -> str:
    return getattr(e, "FACTORY", "") or type(e).__name__


def pool_label(entry) -> str:
    """Stable short label of a ModelPool entry: framework:model-tail."""
    key = getattr(entry, "key", ("?", "?"))
    model = os.path.basename(str(key[1] if len(key) > 1 else "?"))
    return f"{key[0]}:{model}"


def _batcher_info(b) -> Optional[dict]:
    if b is None:
        return None
    return {
        "pending": b.pending,
        "max_batch": b.max_batch,
        "flushes": {"full": b.flushes_full,
                    "deadline": b.flushes_deadline,
                    "forced": b.flushes_forced,
                    "adaptive": b.flushes_adaptive},
    }


def _element_row(e) -> dict:
    with e._stats_lock:
        stats = dict(e.stats)
    row: dict = {"element": e.name, "factory": _factory(e),
                 "stats": stats}
    if hasattr(e, "current_level_buffers"):
        row["queue"] = {"depth": e.current_level_buffers,
                        "capacity": int(getattr(e, "max_size_buffers", 0))}
    inv = getattr(e, "invoke_stats", None)
    if inv is not None:
        f = inv.snapshot()
        f["batch"] = int(getattr(e, "batch", 1) or 1)
        b = _batcher_info(getattr(e, "_batcher", None))
        if b is not None:
            f["batcher"] = b
        mn = getattr(getattr(e, "subplugin", None), "model_name", None)
        if callable(mn):
            # join key for the executables table (obs/xlacost.py): the
            # model this element's dispatches run
            f["model"] = mn()
        entry = getattr(e, "_pool_entry", None)
        if entry is not None:
            f["pool"] = pool_label(entry)
        else:
            # executable-cache counters of THIS element's own sub-plugin
            # instance; pooled elements share the pool's instance, whose
            # counters export once on the POOL row instead
            cache = getattr(getattr(e, "subplugin", None),
                            "cache_snapshot", None)
            if callable(cache):
                f["cache"] = cache()
        row["filter"] = f
    return row


def _pipeline_table(pipe) -> dict:
    return {
        "pipeline": pipe.name,
        "playing": bool(getattr(pipe, "playing", False)),
        "elements": [_element_row(e)
                     for e in list(pipe.elements.values())],
    }


def _pool_entries() -> List[Any]:
    try:
        from ..runtime.serving import MODEL_POOL
    except ImportError:  # pragma: no cover - partial checkouts
        return []
    with MODEL_POOL._lock:
        return list(MODEL_POOL._entries.values())


def _pool_table() -> List[dict]:
    out = []
    for entry in _pool_entries():
        row = {
            "pool": pool_label(entry),
            "refcount": entry.refcount,
            "streams": entry.attached_streams,
            "stats": entry.stats.snapshot(),
        }
        cache = getattr(entry.subplugin, "cache_snapshot", None)
        if callable(cache):
            row["cache"] = cache()
        mn = getattr(entry.subplugin, "model_name", None)
        if callable(mn):
            row["model"] = mn()
        rp = getattr(entry, "placement", None)
        if rp is not None:
            # pool ↔ mesh join: the entry's placement names the shard
            # topology, the MESH_STATS row (keyed by the pooled model)
            # carries how this pool's windows actually split — so a
            # sharded pool's skew is visible NEXT TO its serving stats
            # (nns-top POOL SHARE%/IMBAL/PAD% columns), not only in
            # the separate MESH section
            from .meshstat import MESH_STATS

            row["placement"] = rp.describe()
            # v8: which explicit device subset ("0-3") this pool's
            # stage runs on — "" for whole-inventory placements
            row["stage"] = getattr(rp, "stage", "")
            m = MESH_STATS.get(row.get("model", "")) or {}
            sf = m.get("shard_frames") or []
            total = sum(sf)
            row["mesh"] = {
                "shards": int(rp.data_axis_size),
                "processes": int(rp.num_processes),
                "max_shard_share": (max(sf) / total) if total else 0.0,
                "imbalance": m.get("imbalance", 0.0),
                "pad_frac": m.get("pad_frac", 0.0),
                "replicated_dispatches": m.get(
                    "replicated_dispatches", 0),
            }
        weights = getattr(entry.subplugin, "weight_bytes", None)
        if callable(weights):
            w = weights()
            if w is not None:
                # params footprint + placement of the pooled model —
                # the nns_model_weight_bytes{pool,placement} gauge
                row["weights"] = w
        b = _batcher_info(getattr(entry, "batcher", None))
        if b is not None:
            row["batcher"] = b
        adm = getattr(entry, "admission", None)
        if adm is not None:
            row["admission"] = adm.snapshot()
        lc = getattr(entry, "_lifecycle", None)
        if lc is not None and lc.engaged:
            # model-lifecycle join (runtime/lifecycle.py): swap /
            # canary state NEXT TO the pool's serving stats; the
            # per-version detail lives in the snapshot's `models` table
            row["lifecycle"] = lc.summary()
        out.append(row)
    return out


def _models_table() -> List[dict]:
    """The snapshot v7 ``models`` table: one row per (pool, model
    version) with that version's serving stats, state and provenance —
    present only for pools whose lifecycle was ENGAGED (a pool that
    never swapped has exactly one implicit version: itself; mere
    actuator discovery does not count)."""
    rows: List[dict] = []
    for entry in _pool_entries():
        lc = getattr(entry, "_lifecycle", None)
        if lc is not None and lc.engaged:
            rows.extend(lc.snapshot_rows())
    return rows


#: numeric encoding of the version states on nns_model_version_state
_MODEL_STATE_CODE = {"staged": 0, "serving": 1, "canary": 2,
                     "retired": 3, "rolled-back": 4}


def _model_samples(models) -> Iterable[tuple]:
    """Flat ``nns_model_version_*`` samples derived from the models
    table (same single-read rule as :func:`_pipeline_samples`)."""
    for row in models:
        labels = {"pool": row["pool"], "version": row["version"]}
        yield ("nns_model_version_invokes_total", "counter",
               "dispatches served by this model version", labels,
               row["invokes"])
        yield ("nns_model_version_frames_total", "counter",
               "frames served by this model version", labels,
               row["frames"])
        yield ("nns_model_version_errors_total", "counter",
               "failed dispatches attributed to this version", labels,
               row["errors"])
        if row["latency_us"] >= 0:
            yield ("nns_model_version_latency_us", "gauge",
                   "rolling mean dispatch latency of this version "
                   "(sampled)", labels, row["latency_us"])
        yield ("nns_model_version_state", "gauge",
               "lifecycle state (0 staged, 1 serving, 2 canary, "
               "3 retired, 4 rolled-back)", labels,
               _MODEL_STATE_CODE.get(row["state"], -1))


# -- edge link metrics (nns_edge_*) -------------------------------------------

#: RTT histogram bounds (seconds): 100µs loopback .. multi-second WAN
EDGE_RTT_BUCKETS = (.0001, .00025, .0005, .001, .0025, .005, .01, .025,
                    .05, .1, .25, .5, 1.0, 2.5, float("inf"))


class LinkMetrics:
    """Per-connection edge-link stats (``nns_edge_*``): bytes/messages
    tx+rx, RTT distribution, in-flight requests, timeouts, reconnects.

    One instance per (kind, link, peer) — ``kind`` names the role
    (``query``/``query-server``/``edge``...), ``link`` the owning
    element, ``peer`` the remote address.  Obtained via :meth:`get`
    (process-wide registry, same instance across reconnects so the
    counters stay monotonic); the transports bump bytes per framed
    message, the elements bump RTT/in-flight/timeouts.  Pulled into the
    global registry at scrape time like every other collected stat —
    the snapshot's ``links`` table and the flat ``nns_edge_*`` samples
    derive from one consistent read."""

    _REG_LOCK = threading.Lock()
    _REG: Dict[Tuple[str, str, str], "LinkMetrics"] = {}

    def __init__(self, link: str, peer: str, kind: str = "edge"):
        self.link, self.peer, self.kind = link, peer, kind
        self._lock = threading.Lock()
        self.tx_bytes = 0
        self.rx_bytes = 0
        self.tx_msgs = 0
        self.rx_msgs = 0
        self.inflight = 0
        self.timeouts = 0
        self.reconnects = 0
        self.bad_frames = 0  # frames rejected by the wire codec
        # retry-policy state (chaos/retrypolicy.py): breaker_state is
        # 0 closed / 1 half-open / 2 open, backoff_level the failure
        # streak driving the exponential delay
        self.backoff_level = 0
        self.breaker_state = 0
        self.breaker_opens = 0
        self._rtt_buckets = [0] * len(EDGE_RTT_BUCKETS)
        self._rtt_sum = 0.0
        self._rtt_count = 0
        self._rtt_last: Optional[float] = None

    @classmethod
    def get(cls, link: str, peer: str, kind: str = "edge") -> "LinkMetrics":
        key = (kind, str(link), str(peer))
        with cls._REG_LOCK:
            m = cls._REG.get(key)
            if m is None:
                m = cls(str(link), str(peer), kind)
                cls._REG[key] = m
            return m

    @classmethod
    def all_links(cls) -> List["LinkMetrics"]:
        with cls._REG_LOCK:
            return [cls._REG[k] for k in sorted(cls._REG)]

    @classmethod
    def clear_all(cls) -> None:
        """Tests/bench only: drop every registered link."""
        with cls._REG_LOCK:
            cls._REG.clear()

    # -- producers (transports + elements) -----------------------------------

    def on_tx(self, nbytes: int) -> None:
        with self._lock:
            self.tx_bytes += int(nbytes)
            self.tx_msgs += 1

    def on_rx(self, nbytes: int) -> None:
        with self._lock:
            self.rx_bytes += int(nbytes)
            self.rx_msgs += 1

    def observe_rtt(self, seconds: float) -> None:
        with self._lock:
            self._rtt_sum += seconds
            self._rtt_count += 1
            self._rtt_last = seconds
            for i, le in enumerate(EDGE_RTT_BUCKETS):
                if seconds <= le:
                    self._rtt_buckets[i] += 1
                    break

    def set_inflight(self, n: int) -> None:
        with self._lock:
            self.inflight = int(n)

    def timeout(self) -> None:
        with self._lock:
            self.timeouts += 1

    def reconnect(self) -> None:
        with self._lock:
            self.reconnects += 1

    def on_bad_frame(self) -> None:
        """A received frame the wire codec rejected (e.g. corrupted in
        transit): dropped, but never silently — this counter is part of
        the zero-silent-drops accounting."""
        with self._lock:
            self.bad_frames += 1

    def set_retry_state(self, state: int, level: int, opens: int) -> None:
        """Mirror of the link's RetryPolicy (chaos/retrypolicy.py)."""
        with self._lock:
            self.breaker_state = int(state)
            self.backoff_level = int(level)
            self.breaker_opens = int(opens)

    # -- pull side -----------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "link": self.link, "peer": self.peer, "kind": self.kind,
                "tx_bytes": self.tx_bytes, "rx_bytes": self.rx_bytes,
                "tx_msgs": self.tx_msgs, "rx_msgs": self.rx_msgs,
                "inflight": self.inflight,
                "timeouts": self.timeouts,
                "reconnects": self.reconnects,
                "bad_frames": self.bad_frames,
                "backoff_level": self.backoff_level,
                "breaker_state": self.breaker_state,
                "breaker_opens": self.breaker_opens,
                "rtt": {
                    "count": self._rtt_count,
                    "sum_s": self._rtt_sum,
                    "mean_us": (self._rtt_sum / self._rtt_count * 1e6)
                    if self._rtt_count else None,
                    "last_us": self._rtt_last * 1e6
                    if self._rtt_last is not None else None,
                    "buckets": list(self._rtt_buckets),
                },
            }


def _link_table() -> List[dict]:
    return [m.snapshot() for m in LinkMetrics.all_links()]


def _link_samples(links) -> Iterable[tuple]:
    """Flat ``nns_edge_*`` samples derived from the structured link
    table (same single-read rule as :func:`_pipeline_samples`); the RTT
    histogram expands separately in ``_collect_all``."""
    for row in links:
        labels = {"link": row["link"], "peer": row["peer"],
                  "kind": row["kind"]}
        yield ("nns_edge_tx_bytes_total", "counter",
               "bytes sent over the link (framed size)", labels,
               row["tx_bytes"])
        yield ("nns_edge_rx_bytes_total", "counter",
               "bytes received over the link (framed size)", labels,
               row["rx_bytes"])
        yield ("nns_edge_tx_messages_total", "counter",
               "messages sent over the link", labels, row["tx_msgs"])
        yield ("nns_edge_rx_messages_total", "counter",
               "messages received over the link", labels, row["rx_msgs"])
        yield ("nns_edge_inflight", "gauge",
               "requests awaiting an answer", labels, row["inflight"])
        yield ("nns_edge_timeouts_total", "counter",
               "requests that outlived their deadline", labels,
               row["timeouts"])
        yield ("nns_edge_reconnects_total", "counter",
               "mid-stream failovers/reconnects", labels,
               row["reconnects"])
        yield ("nns_edge_bad_frames_total", "counter",
               "received frames rejected by the wire codec", labels,
               row.get("bad_frames", 0))
        yield ("nns_edge_backoff_level", "gauge",
               "consecutive reconnect failures driving the backoff",
               labels, row.get("backoff_level", 0))
        yield ("nns_edge_breaker_state", "gauge",
               "circuit breaker: 0 closed / 1 half-open / 2 open",
               labels, row.get("breaker_state", 0))
        yield ("nns_edge_breaker_opens_total", "counter",
               "times the link's circuit breaker opened", labels,
               row.get("breaker_opens", 0))


def _pipeline_samples(tables) -> Iterable[tuple]:
    """Flat samples DERIVED from the structured pipeline tables (one
    read of the runtime state per scrape — the hot path never pushed
    any of these).  Unknown values (the InvokeStats ``-1`` "no data
    yet" sentinels) are omitted rather than exported as time-series
    points."""
    for table in tables:
        pl = table["pipeline"]
        for row in table["elements"]:
            labels = {"pipeline": pl, "element": row["element"]}
            for key, val in sorted(row["stats"].items()):
                if key == "buffers_in":
                    yield ("nns_element_buffers_in_total", "counter",
                           "buffers entering the element", labels, val)
                elif key == "buffers_out":
                    yield ("nns_element_buffers_out_total", "counter",
                           "buffers leaving the element", labels, val)
                else:
                    yield ("nns_element_stat_total", "counter",
                           "per-element flow counter",
                           {**labels, "stat": key}, val)
            q = row.get("queue")
            if q is not None:
                yield ("nns_queue_depth", "gauge",
                       "buffers parked in the queue", labels,
                       q["depth"])
                yield ("nns_queue_capacity", "gauge",
                       "queue bound (max-size-buffers)", labels,
                       q["capacity"])
            s = row.get("filter")
            if s is not None:
                yield ("nns_filter_invokes_total", "counter",
                       "XLA dispatches issued", labels, s["invokes"])
                yield ("nns_filter_frames_total", "counter",
                       "frames carried by those dispatches", labels,
                       s["frames"])
                if s["latency_us"] >= 0:
                    yield ("nns_filter_latency_us", "gauge",
                           "rolling mean invoke latency (sampled)",
                           labels, s["latency_us"])
                if s["throughput_milli_fps"] >= 0:
                    yield ("nns_filter_throughput_milli_fps", "gauge",
                           "1000x frames/s over the run", labels,
                           s["throughput_milli_fps"])
                if s["dispatch_milli_fps"] >= 0:
                    yield ("nns_filter_dispatch_milli_fps", "gauge",
                           "1000x dispatches/s over the run", labels,
                           s["dispatch_milli_fps"])
                yield ("nns_filter_batch_occupancy", "gauge",
                       "mean frames per dispatch", labels,
                       s["avg_batch_occupancy"])
                yield ("nns_filter_stream_occupancy", "gauge",
                       "mean distinct streams per dispatch", labels,
                       s["avg_stream_occupancy"])
                b = s.get("batcher")
                if b is not None:
                    yield ("nns_batcher_pending", "gauge",
                           "frames parked in the coalescing window",
                           labels, b["pending"])
                    for reason, n in sorted(b["flushes"].items()):
                        yield ("nns_batcher_flushes_total", "counter",
                               "window closes by reason",
                               {**labels, "reason": reason}, n)
                yield from _cache_samples(labels, s.get("cache"))


def _cache_samples(labels: Dict[str, str], cache) -> Iterable[tuple]:
    """Per-bucket executable-cache hit/miss counters of one sub-plugin
    instance (element- or pool-labeled), derived from its
    ``cache_snapshot()`` in the structured tables."""
    if not cache:
        return
    for bucket, hm in sorted(cache.get("by_bucket", {}).items()):
        bl = {**labels, "bucket": bucket}
        yield ("nns_executable_cache_hits_total", "counter",
               "micro-batch executable cache hits", bl, hm["hits"])
        yield ("nns_executable_cache_misses_total", "counter",
               "micro-batch executable cache misses (one XLA compile "
               "each)", bl, hm["misses"])


def _control_table() -> dict:
    """The closed-loop controller's decision view (obs/control.py):
    playbooks, action totals, recent audit entries — empty-but-present
    when no controller runs, so the snapshot shape is stable."""
    from .control import control_table

    return control_table()


def _compile_table() -> List[dict]:
    from ..utils.stats import COMPILE_STATS

    return COMPILE_STATS.snapshot()


def _compile_samples(compiles) -> Iterable[tuple]:
    """Flat ``nns_compiles_total`` / ``nns_compile_seconds_total``
    samples derived from the structured compile table (same single-read
    rule as :func:`_pipeline_samples`)."""
    for row in compiles:
        labels = {"framework": row["framework"], "kind": row["kind"],
                  "bucket": row["bucket"]}
        yield ("nns_compiles_total", "counter",
               "XLA compiles by path (cold/reshape/reload/bucket)",
               labels, row["count"])
        yield ("nns_compile_seconds_total", "counter",
               "time spent compiling (trace + first-call XLA build)",
               labels, row["seconds"])


def _transfer_table() -> List[dict]:
    from .transfer import LEDGER

    return LEDGER.snapshot()


def _transfer_samples(transfers) -> Iterable[tuple]:
    """Flat ``nns_transfer_*`` counters derived from the structured
    transfer table (same single-read rule as
    :func:`_pipeline_samples`); the duration histogram expands
    separately in ``_collect_all``."""
    for row in transfers:
        labels = {"pipeline": row["pipeline"], "source": row["source"],
                  "direction": row["direction"],
                  "reason": row["reason"]}
        yield ("nns_transfer_bytes_total", "counter",
               "bytes crossing the host<->device boundary (exact "
               "payload nbytes)", labels, row["bytes"])
        yield ("nns_transfer_count_total", "counter",
               "host<->device crossings", labels, row["count"])


def _device_table() -> List[dict]:
    from .devicemem import device_memory_table

    return device_memory_table()


def _device_samples(devmem) -> Iterable[tuple]:
    """Flat ``nns_device_memory_bytes`` gauges derived from the
    structured device-memory table (absent kinds — e.g. the CPU
    backend's whole row — are simply not exported)."""
    for row in devmem:
        for kind in ("in_use", "peak", "limit"):
            v = row.get(kind)
            if v is not None:
                yield ("nns_device_memory_bytes", "gauge",
                       "device allocator view (memory_stats)",
                       {"device": row["device"], "kind": kind}, v)


def _executable_join():
    """The executables table + live utilization samples: static XLA
    cost (obs/xlacost.py) joined at scrape time with the measured
    ``nns_invoke_device_seconds`` histogram — see
    :meth:`XlaCostStats.join`."""
    from .xlacost import XLA_COST

    return XLA_COST.join(_INVOKE_DEVICE._hist_rows())


def _executable_samples(execs) -> Iterable[tuple]:
    """Flat ``nns_executable_*`` gauges derived from the structured
    executables table (same single-read rule as
    :func:`_pipeline_samples`)."""
    for row in execs:
        labels = {"source": row["source"],
                  "bucket": str(row["bucket"]),
                  "placement": row["placement"]}
        yield ("nns_executable_flops", "gauge",
               "FLOPs of one dispatch of the executable (XLA cost "
               "analysis of the serving program)", labels, row["flops"])
        yield ("nns_executable_bytes", "gauge",
               "bytes accessed by one dispatch of the executable",
               labels, row["bytes"])
        yield ("nns_executable_peak_memory_bytes", "gauge",
               "peak memory of the executable (cost analysis, or the "
               "static I/O footprint when the backend reports none)",
               labels, row["peak_memory_bytes"])


def _util_samples(exec_util) -> Iterable[tuple]:
    """Live ``nns_mfu`` / ``nns_hbm_bw_util`` gauges: static executable
    cost over the measured device seconds of the scrape window (absent
    on unknown backends — intensity-only fallback, obs/hwspec.py)."""
    for s in exec_util:
        labels = s["labels"]
        if "mfu" in s:
            yield ("nns_mfu", "gauge",
                   "model flops utilization of the measured device "
                   "time (flops x dispatches / device_seconds / peak)",
                   labels, s["mfu"])
        if "hbm_bw_util" in s:
            yield ("nns_hbm_bw_util", "gauge",
                   "HBM bandwidth utilization of the measured device "
                   "time", labels, s["hbm_bw_util"])


def _mesh_table() -> List[dict]:
    from .meshstat import MESH_STATS

    return MESH_STATS.snapshot()


def _mesh_samples(mesh) -> Iterable[tuple]:
    """Flat per-shard attribution samples derived from the structured
    mesh table (same single-read rule as :func:`_pipeline_samples`)."""
    from .meshstat import shard_device_label

    for row in mesh:
        labels = {"source": row["source"]}
        yield ("nns_shard_imbalance", "gauge",
               "per-shard useful-frame imbalance (max/mean - 1; 0.0 "
               "on even splits)", labels, row["imbalance"])
        yield ("nns_mesh_dispatches_total", "counter",
               "dispatches issued over the mesh", labels,
               row["dispatches"])
        yield ("nns_mesh_pad_slots_total", "counter",
               "micro-batch pad slots executed on the mesh (wasted "
               "device time)", labels, row["pad_slots"])
        yield ("nns_mesh_replicated_dispatches_total", "counter",
               "mesh dispatches whose batch could not shard over the "
               "data axis (input replicated onto every chip)", labels,
               row["replicated_dispatches"])
        for i, n in enumerate(row["shard_frames"]):
            yield ("nns_mesh_shard_frames_total", "counter",
                   "useful frames attributed to one shard of the mesh",
                   {**labels, "shard": str(i),
                    "device": shard_device_label(row, i)}, n)


def _stage_table() -> List[dict]:
    from .stagestat import STAGE_STATS

    return STAGE_STATS.snapshot()


def _stage_samples(stages) -> Iterable[tuple]:
    """Flat per-stage samples derived from the structured stages table
    (same single-read rule as :func:`_pipeline_samples`): the
    cross-subset handoff counters + inter-stage depth, and the cascade
    offload ratio of routing ``tensor_if`` elements."""
    for row in stages:
        if row["kind"] == "handoff":
            labels = {"pipeline": row["pipeline"], "stage": row["stage"],
                      "from": row["from"], "to": row["to"]}
            yield ("nns_stage_handoff_frames_total", "counter",
                   "frames handed device-to-device into the stage's "
                   "subset (never a host crossing)", labels,
                   row["frames"])
            yield ("nns_stage_handoff_bytes_total", "counter",
                   "exact payload bytes of the cross-subset handoffs",
                   labels, row["bytes"])
            yield ("nns_stage_depth", "gauge",
                   "inter-stage queue depth: frames handed into the "
                   "stage but not yet emitted by it", labels,
                   row["depth"])
        else:
            labels = {"pipeline": row["pipeline"],
                      "element": row["stage"]}
            yield ("nns_cascade_offload_ratio", "gauge",
                   "fraction of judged frames the conditional cascade "
                   "routed to the heavy (offload) stage", labels,
                   row["ratio"])
            yield ("nns_cascade_offloaded_total", "counter",
                   "frames routed down the offload branch", labels,
                   row["offloaded"])
            yield ("nns_cascade_kept_total", "counter",
                   "frames kept on the local (cheap) branch", labels,
                   row["kept"])


def _tenant_table() -> List[dict]:
    from .tenantstat import TENANT_STATS

    return TENANT_STATS.snapshot()


def _forecast_table() -> dict:
    from .forecast import FORECASTS

    return FORECASTS.snapshot()


def _profile_table() -> dict:
    from .prof import profile_table

    return profile_table()


def _tenant_samples(tenants) -> Iterable[tuple]:
    """Flat per-(pool, tenant) samples derived from the structured
    tenants table (same single-read rule as :func:`_pipeline_samples`):
    the device-second/frame attribution split EXACTLY out of the
    pool's dispatch clock reads, the scrape-time dollars derivation,
    per-tenant SLO attainment and shed counts."""
    for row in tenants:
        labels = {"pool": row["pool"], "tenant": row["tenant"]}
        yield ("nns_tenant_device_seconds_total", "counter",
               "device time attributed to the tenant's frames (sums "
               "EXACTLY to the pool's nns_invoke_device_seconds)",
               labels, row["device_seconds"])
        yield ("nns_tenant_frames_total", "counter",
               "useful frames the tenant parked in pool windows",
               labels, row["frames"])
        yield ("nns_tenant_dollars_total", "counter",
               "attributed device time priced at the chip-hour rate "
               "(obs/hwspec.py, NNS_TPU_CHIP_HOUR_USD overridable)",
               labels, row["dollars"])
        if row["slo_attainment"] is not None:
            yield ("nns_tenant_slo_attainment", "gauge",
                   "fraction of the tenant's demuxed frames inside "
                   "the pool SLO (the admission latency signal)",
                   labels, row["slo_attainment"])
        for reason, n in sorted(row["shed"].items()):
            yield ("nns_tenant_shed_total", "counter",
                   "tenant frames shed at admission, by reason",
                   {**labels, "reason": reason}, n)


def _placement_overlap_samples() -> Iterable[tuple]:
    """``nns_placement_overlap`` gauges: one series per detected pair
    of overlapping explicit ``devices=`` subsets (value = times the
    overlapping resolution happened).  Zero series means no overlap —
    the healthy state; any sample at all is the loud signal next to
    the warning the placement layer already logged."""
    from ..parallel.placement import overlap_snapshot

    for row in overlap_snapshot():
        yield ("nns_placement_overlap", "gauge",
               "explicit device subsets sharing chips (per-shard "
               "attribution is unreliable while this fires)",
               {"platform": row["platform"], "a": row["a"],
                "b": row["b"], "shared": row["shared"]}, row["count"])


def alert_health(registry: "MetricsRegistry") -> dict:
    """Cheap alert summary for ``/healthz``: the current
    ``nns_alert_state`` gauge children (exported by an attached
    ``obs/watch.py`` watchdog; empty when none runs) — firing count by
    severity plus the firing rule names, WITHOUT a full snapshot
    walk."""
    with registry._lock:
        fam = registry._families.get("nns_alert_state")
    if fam is None:
        return {"firing": 0, "by_severity": {}, "rules": []}
    by_sev: Dict[str, int] = {}
    rules: List[str] = []
    for labels, value in fam.collect():
        if value:
            sev = labels.get("severity", "warning")
            by_sev[sev] = by_sev.get(sev, 0) + 1
            rules.append(labels.get("rule", "?"))
    return {"firing": len(rules), "by_severity": by_sev,
            "rules": sorted(rules)}


def _control_health() -> dict:
    from .control import control_health

    return control_health()


def _prof_health() -> dict:
    from .prof import prof_health

    return prof_health()


def capacity_health() -> dict:
    """Cheap capacity summary for ``/healthz``: the per-pool headroom
    rows an attached watchdog's forecast tick published (empty when
    none runs) — worst headroom plus the pools predicted to overload,
    WITHOUT a full snapshot walk."""
    from .forecast import FORECASTS

    rows = FORECASTS.snapshot()["capacity"]
    if not rows:
        return {"pools": 0, "min_headroom": None, "at_risk": []}
    worst = min(rows, key=lambda r: r["headroom"])
    return {
        "pools": len(rows),
        "min_headroom": round(worst["headroom"], 4),
        "at_risk": sorted(r["pool"] for r in rows
                          if r["headroom"] <= 0.0),
    }


def _pool_samples(pools) -> Iterable[tuple]:
    """Flat samples derived from the structured pool table (same
    single-read rule as :func:`_pipeline_samples`)."""
    for row in pools:
        labels = {"pool": row["pool"]}
        s = row["stats"]
        yield ("nns_pool_streams", "gauge",
               "streams attached to the pool entry", labels,
               row["streams"])
        yield ("nns_pool_refcount", "gauge",
               "filters holding the pool entry", labels,
               row["refcount"])
        yield ("nns_pool_dispatches_total", "counter",
               "cross-stream XLA dispatches", labels, s["invokes"])
        yield ("nns_pool_frames_total", "counter",
               "frames carried by pool dispatches", labels, s["frames"])
        if s["latency_us"] >= 0:
            yield ("nns_pool_latency_us", "gauge",
                   "rolling mean pool dispatch latency (sampled)",
                   labels, s["latency_us"])
        yield ("nns_pool_batch_occupancy", "gauge",
               "mean frames per pool dispatch", labels,
               s["avg_batch_occupancy"])
        yield ("nns_pool_stream_occupancy", "gauge",
               "mean distinct streams per pool dispatch", labels,
               s["avg_stream_occupancy"])
        w = row.get("weights")
        if w is not None:
            yield ("nns_model_weight_bytes", "gauge",
                   "params footprint of the pooled model",
                   {**labels, "placement": w["placement"]}, w["bytes"])
        m = row.get("mesh")
        if m is not None:
            # pool-side view of the mesh join (the per-shard detail
            # stays on the nns_mesh_* families keyed by model): skew
            # and waste OF THIS POOL's coalesced windows
            yield ("nns_pool_shards", "gauge",
                   "data-parallel shards the pool window spreads over",
                   labels, m["shards"])
            yield ("nns_pool_shard_imbalance", "gauge",
                   "max/mean-1 of useful frames across the pool's "
                   "shards", labels, m["imbalance"])
            yield ("nns_pool_pad_frac", "gauge",
                   "fraction of the pool's window slots that were "
                   "padding", labels, m["pad_frac"])
        yield from _cache_samples(labels, row.get("cache"))
        b = row.get("batcher")
        if b is not None:
            yield ("nns_pool_pending", "gauge",
                   "frames parked in the cross-stream window", labels,
                   b["pending"])
            for reason, n in sorted(b["flushes"].items()):
                yield ("nns_pool_flushes_total", "counter",
                       "pool window closes by reason",
                       {**labels, "reason": reason}, n)
        lc = row.get("lifecycle")
        if lc is not None:
            yield ("nns_model_swaps_total", "counter",
                   "hot swaps committed on the pool", labels,
                   lc["swaps"])
            yield ("nns_model_promotions_total", "counter",
                   "canaries promoted to serving", labels,
                   lc["promotes"])
            yield ("nns_model_rollbacks_total", "counter",
                   "canary/swap rollbacks", labels, lc["rollbacks"])
            yield ("nns_model_swap_stall_seconds", "gauge",
                   "flip stall of the last hot swap (window-boundary "
                   "hold)", labels, lc["last_swap_stall_s"])
            yield ("nns_model_canary_streams", "gauge",
                   "streams currently routed to the canary version",
                   labels, lc["canary_streams"])
            if lc.get("canary_n", 0) >= 2:
                # the comparator pair: one plain nns-watch threshold
                # rule with per= IS the canary judge (canary latency
                # vs baseline latency of the SAME pool, same labels)
                cl = lc.get("canary_latency_us", -1)
                bl = lc.get("baseline_latency_us", -1)
                if cl is not None and cl >= 0:
                    yield ("nns_model_canary_latency_us", "gauge",
                           "rolling mean dispatch latency of the "
                           "canary version", labels, cl)
                if bl is not None and bl >= 0:
                    yield ("nns_model_baseline_latency_us", "gauge",
                           "rolling mean dispatch latency of the "
                           "baseline while a canary runs", labels, bl)
                yield ("nns_model_canary_errors_total", "counter",
                       "failed dispatches on the canary version",
                       labels, lc.get("canary_errors", 0))
                yield ("nns_model_canary_frames_total", "counter",
                       "frames the canary version served", labels,
                       lc.get("canary_frames", 0))
        a = row.get("admission")
        if a is not None:
            yield ("nns_admission_slo_at_risk", "gauge",
                   "1 while the pool's p99 threatens the SLO "
                   "(load-shedding active)", labels,
                   1 if a["at_risk"] else 0)
            yield ("nns_admission_p99_us", "gauge",
                   "admission controller's rolling p99 serve latency",
                   labels, a["p99_ms"] * 1e3)
            for prio, n in sorted(a["submitted"].items()):
                yield ("nns_admission_submitted_total", "counter",
                       "frames offered to the shared window",
                       {**labels, "priority": prio}, n)
            for prio, n in sorted(a["shed"].items()):
                yield ("nns_admission_shed_total", "counter",
                       "frames shed by the admission controller",
                       {**labels, "priority": prio, "reason": "slo"}, n)
            for prio, n in sorted(a["shed_queue_full"].items()):
                yield ("nns_admission_shed_total", "counter",
                       "frames shed by the admission controller",
                       {**labels, "priority": prio,
                        "reason": "queue-full"}, n)


# -- HTTP endpoint -----------------------------------------------------------


class MetricsServer:
    """stdlib-http scrape endpoint: ``/metrics`` (Prometheus text),
    ``/json`` (full snapshot), ``/healthz`` (cheap liveness probe:
    status + pipeline/pool/link counts, no full snapshot walk).  Runs
    on a daemon thread; ``port=0`` binds an ephemeral port readable
    back from :attr:`port`."""

    def __init__(self, registry: MetricsRegistry, port: int = 0,
                 host: str = "127.0.0.1"):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self._registry = registry
        reg = registry

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - stdlib API name
                path = self.path.split("?", 1)[0]
                if path in ("/metrics", "/"):
                    body = reg.exposition().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif path == "/json":
                    body = json.dumps(reg.snapshot()).encode()
                    ctype = "application/json"
                elif path == "/healthz":
                    # fleet probes need liveness + rough shape, not a
                    # full snapshot parse: counts only, no stats locks
                    # beyond the registries' own — plus the device
                    # in-use bytes (an HBM leak is a health problem)
                    from .devicemem import device_memory_summary

                    body = json.dumps({
                        "status": "ok",
                        "host": _host_tag(),
                        "pipelines": len(reg._live_pipelines()),
                        "pools": len(_pool_table()),
                        "links": len(_link_table())
                        if reg._collect_links else 0,
                        "device_memory": device_memory_summary()
                        if reg._collect_devices else [],
                        # alerting view (obs/watch.py): a fleet
                        # controller probing liveness sees WHAT is
                        # firing, not just that the process answers
                        "alerts": alert_health(reg),
                        # actuation view (obs/control.py): playbooks
                        # loaded, decisions taken, the last action —
                        # whether the loop is CLOSED, not only that
                        # alarms ring
                        "control": _control_health(),
                        # predictive view (obs/forecast.py): whether
                        # arrivals are forecast to outrun capacity —
                        # the probe sees trouble BEFORE alerts fire
                        "capacity": capacity_health(),
                        # host-execution view (obs/prof.py): whether
                        # the sampling profiler runs, its tick/sample
                        # counts and the GIL-pressure proxy
                        "prof": _prof_health(),
                        "time": time.time(),
                    }).encode()
                    ctype = "application/json"
                elif path == "/prof":
                    # host profiler export (obs/prof.py): collapsed-
                    # stack text by default (flamegraph.pl input),
                    # ?format=trace for Perfetto/Chrome trace events,
                    # ?last=S to restrict to the recent-sample ring
                    from .prof import PROFILER

                    query = self.path.split("?", 1)[1] \
                        if "?" in self.path else ""
                    qs = dict(kv.split("=", 1)
                              for kv in query.split("&") if "=" in kv)
                    last = None
                    try:
                        if qs.get("last"):
                            last = float(qs["last"])
                    except ValueError:
                        last = None
                    if qs.get("format") == "trace":
                        body = json.dumps(
                            PROFILER.chrome_trace()).encode()
                        ctype = "application/json"
                    else:
                        text = PROFILER.ring_collapsed(last) \
                            if last is not None else PROFILER.collapsed()
                        body = (text + "\n").encode()
                        ctype = "text/plain; charset=utf-8"
                elif path == "/dump":
                    # flight recorder: explicit black-box dump — the
                    # response carries the trace + snapshot, and when
                    # the recorder is armed the same dump also lands
                    # on disk (obs/flightrec.py)
                    from .flightrec import FLIGHT

                    body = json.dumps(
                        FLIGHT.trigger_dump("endpoint")).encode()
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # quiet scrapes
                pass

        self._httpd = ThreadingHTTPServer((host, int(port)), Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        from . import prof as _prof

        self._thread = _prof.named_thread(
            "metrics", "http", self._httpd.serve_forever)
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)
        # deregister so a later serve() starts a fresh listener instead
        # of handing back this dead one
        reg = self._registry
        with reg._lock:
            if reg._server is self:
                reg._server = None


#: the process-wide registry every Pipeline registers with on start();
#: the only registry that pulls the (equally process-wide) link,
#: compile, transfer-ledger and device-memory stores
REGISTRY = MetricsRegistry(collect_stages=True,
                           collect_links=True, collect_compiles=True,
                           collect_transfers=True, collect_devices=True,
                           collect_executables=True, collect_mesh=True,
                           collect_tenants=True, collect_prof=True)


# -- dispatch cost attribution (nns_invoke_*) ---------------------------------

#: phase histogram bounds (seconds): 10µs CPU-backend dispatches up to
#: multi-second remote-tunnel round trips
INVOKE_PHASE_BUCKETS = (1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, .001,
                        .0025, .005, .01, .025, .05, .1, .25, .5, 1.0,
                        2.5, float("inf"))

_INVOKE_DEVICE = REGISTRY.histogram(
    "nns_invoke_device_seconds",
    "device phase of one sampled dispatch (issue -> block_until_ready)",
    labelnames=("kind", "source", "bucket"),
    buckets=INVOKE_PHASE_BUCKETS)
_INVOKE_HOST = REGISTRY.histogram(
    "nns_invoke_host_seconds",
    "host phases of one sampled dispatch (phase=prep: input "
    "gather/convert/place; phase=drain: output wrap/demux)",
    labelnames=("kind", "source", "bucket", "phase"),
    buckets=INVOKE_PHASE_BUCKETS)


def observe_invoke_phases(kind: str, source: str, bucket: int,
                          prep_s: float, device_s: float,
                          drain_s: float) -> None:
    """Feed one sampled dispatch's host/device split into the global
    registry.  ``kind`` is ``element`` (single-filter chain or
    micro-batch window) or ``pool`` (SharedBatcher cross-stream
    dispatch); ``source`` the element name / pool label; ``bucket`` the
    padded batch size (1 for the single-frame chain).  Called only on
    stat-sampled dispatches — the phases need the ``block_until_ready``
    fence, which unsampled async dispatches deliberately skip."""
    labels = {"kind": kind, "source": str(source), "bucket": str(bucket)}
    _INVOKE_DEVICE.labels(**labels).observe(device_s)
    _INVOKE_HOST.labels(**labels, phase="prep").observe(prep_s)
    _INVOKE_HOST.labels(**labels, phase="drain").observe(drain_s)


#: serve-latency histogram bounds (seconds): resolution concentrated in
#: the 1-250 ms band where serving SLOs live, so a p99 derived from the
#: bucket boundaries lands within ~25% of the true value there
ADMISSION_LATENCY_BUCKETS = (.001, .0025, .005, .0075, .01, .015, .02,
                             .03, .05, .075, .1, .15, .25, .5, 1.0,
                             2.5, float("inf"))

_ADMISSION_LATENCY = REGISTRY.histogram(
    "nns_admission_latency_seconds",
    "pool serve latency (window park -> results demuxed) — the SAME "
    "signal the admission controller's shed decision reads",
    labelnames=("pool",),
    buckets=ADMISSION_LATENCY_BUCKETS)


def admission_latency_hist(pool: str):
    """The per-pool serve-latency histogram child the admission
    controller both feeds and READS its p99 from — so an external
    controller scraping the registry sees exactly the signal the
    in-process shedder acts on."""
    return _ADMISSION_LATENCY.labels(pool=str(pool))


def serve_metrics(port: int = 0, host: str = "127.0.0.1") -> MetricsServer:
    """Serve the global registry over HTTP (idempotent; returns the
    running server)."""
    return REGISTRY.serve(port=port, host=host)


_env_checked = False


def maybe_serve_from_env(registry: MetricsRegistry) -> None:
    """``NNS_TPU_METRICS_PORT=<port>`` auto-serves the registry when the
    first pipeline starts — the hook that lets ``nns-top`` observe ANY
    running process (e.g. the serve bench) without instrumenting it."""
    global _env_checked
    if _env_checked:
        return
    _env_checked = True
    port = os.environ.get("NNS_TPU_METRICS_PORT", "")
    if not port:
        return
    try:
        registry.serve(port=int(port))
    except (OSError, ValueError) as e:
        from ..utils.log import logw

        logw("cannot serve metrics on NNS_TPU_METRICS_PORT=%s: %s",
             port, e)

"""XLA executable cost capture + the scrape-time MFU join.

The observability stack (PRs 4–8) can say *where* time goes — host
phases, device phases, transfers — but not whether the device time is
any *good*: ``bench.py`` computed FLOPs/MFU/roofline one-shot from
``compiled.cost_analysis()`` and none of it reached the registry.  This
module makes model efficiency first-class telemetry:

- **Capture** — :func:`capture` is called at the existing
  ``_compile`` / ``_compile_batched`` seams in ``filters/jax_xla.py``
  with the jit *lowering* of every executable.  ``Lowered.
  cost_analysis()`` runs XLA's HLO cost analysis without paying a
  second device compile (measured: ~1 ms vs a full recompile), and its
  flops / "bytes accessed" figures are the same computation-intrinsic
  numbers the bench's one-shot roofline reads.  Rows are keyed
  ``(source, bucket)`` — ``source`` is the model name, ``bucket`` the
  micro-batch bucket (0 for the single-frame executable) — and a
  recompile (reshape/reload) overwrites its row: the gauges always
  describe the executable currently serving.
- **Join** — at scrape time :func:`executable_table` joins the static
  cost with the *measured* ``nns_invoke_device_seconds`` histogram
  (PR 7's cost attribution): windowed deltas of (sum, count) per
  ``{kind, source, bucket}`` give the mean device seconds of one
  dispatch, and ``MFU = flops x dispatches / (device_seconds x
  peak_flops)`` — utilization of the device time actually spent, not
  of wall clock.  Dispatch sources (element names, pool labels) map to
  model names via :func:`map_source`, fed by ``elements/filter.py``
  and ``runtime/serving.py`` when a model is opened.
- **Roofline** — arithmetic intensity (flops/byte) against the
  hardware ridge (:mod:`.hwspec`) classifies every executable
  compute- vs bandwidth-bound.  On an unknown backend (the CPU tests
  run on) the spec resolves to None: flops / bytes / intensity still
  export — they are properties of the program — but no utilization
  gauge is derived.

Exported by the metrics registry like every other collected stat:
``nns_executable_{flops,bytes,peak_memory_bytes}{source,bucket,
placement}`` gauges, ``nns_mfu`` / ``nns_hbm_bw_util`` gauges, the
snapshot's ``executables`` table (v5), and the MFU column in
``nns-top``.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

from . import hooks as _hooks
from .hwspec import HwSpec, spec_for_platform

#: fast-path flag (same contract as obs/transfer.py): honors the global
#: obs kill switch at process start
ACTIVE = not _hooks.DISABLED


def cost_of(stage) -> dict:
    """The raw ``cost_analysis()`` dict of a jax ``Lowered`` /
    ``Compiled`` stage, list-unwrapped; ``{}`` when the backend doesn't
    support cost analysis.  The one extraction helper ``bench.py`` and
    the capture seam share (satellite: one source of truth)."""
    try:
        ca = stage.cost_analysis()
    except Exception:  # noqa: BLE001 - backend-dependent API surface
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    return dict(ca) if isinstance(ca, dict) else {}


def flops_bytes(stage) -> Tuple[float, float]:
    """(flops, bytes accessed) of a lowered/compiled stage (0.0 when
    unavailable)."""
    ca = cost_of(stage)
    return float(ca.get("flops", 0.0) or 0.0), \
        float(ca.get("bytes accessed", 0.0) or 0.0)


def _peak_memory(ca: dict, in_bytes: int, out_bytes: int
                 ) -> Tuple[int, bool]:
    """Peak memory of one executable: the cost-analysis figure when the
    backend reports one, else the static I/O footprint (arguments +
    outputs — a lower bound; temporaries are unknown before compile).
    Returns ``(bytes, estimated)``."""
    for key in ("peak memory", "peak_memory", "bytes accessed peak"):
        v = ca.get(key)
        if v:
            return int(v), False
    return int(in_bytes) + int(out_bytes), True


class _Row:
    __slots__ = ("placement", "platform", "flops", "bytes",
                 "peak_memory", "peak_memory_estimated", "in_bytes",
                 "out_bytes", "compiles")

    def __init__(self):
        self.placement = ""
        self.platform = ""
        self.flops = 0.0
        self.bytes = 0.0
        self.peak_memory = 0
        self.peak_memory_estimated = True
        self.in_bytes = 0
        self.out_bytes = 0
        self.compiles = 0


class XlaCostStats:
    """Process-wide store of per-executable static cost + the
    scrape-to-scrape state the live MFU join needs."""

    def __init__(self):
        self._lock = threading.Lock()
        self._rows: Dict[Tuple[str, int], _Row] = {}
        self._sources: Dict[str, str] = {}  # dispatch source -> model
        # previous scrape's (sum, count) per device-histogram child —
        # the delta window "live" utilization derives from.  BOTH
        # consumers of one registry (Prometheus exposition and
        # snapshot/nns-top polls) advance it, so interleaved consumers
        # see shorter windows; the _last_* caches below keep an idle
        # (possibly zero-sample) window re-exporting the last derived
        # figure instead of flapping to the lifetime average.
        self._prev_hist: Dict[Tuple, Tuple[float, int]] = {}
        self._last_util: Dict[Tuple, dict] = {}
        self._last_exec: Dict[Tuple[str, int], dict] = {}

    # -- capture (filters/jax_xla.py) ----------------------------------------

    def record(self, source: str, bucket: int, placement: str,
               platform: str, ca: dict, in_bytes: int = 0,
               out_bytes: int = 0) -> None:
        key = (str(source), int(bucket))
        peak, est = _peak_memory(ca, in_bytes, out_bytes)
        with self._lock:
            row = self._rows.get(key)
            if row is None:
                row = self._rows[key] = _Row()
            row.placement = str(placement)
            row.platform = str(platform)
            row.flops = float(ca.get("flops", 0.0) or 0.0)
            row.bytes = float(ca.get("bytes accessed", 0.0) or 0.0)
            row.peak_memory = peak
            row.peak_memory_estimated = est
            row.in_bytes = int(in_bytes)
            row.out_bytes = int(out_bytes)
            row.compiles += 1

    def map_source(self, source: str, model: str) -> None:
        """Register a dispatch-source label (element name / pool label)
        as serving ``model`` — the join key between the measured
        ``nns_invoke_device_seconds`` series and the executable rows.
        Source labels follow PR 7's histogram labeling (element name /
        pool label), so two live pipelines with same-named filters
        serving DIFFERENT models share one measured series — the join
        can't untangle that, and the remap warning below is the loud
        signal to rename one of them."""
        prev = None
        with self._lock:
            prev = self._sources.get(str(source))
            self._sources[str(source)] = str(model)
        if prev is not None and prev != str(model):
            from ..utils.log import logw

            logw("obs: dispatch source %r remapped from model %r to "
                 "%r — if both are live, their nns_invoke_device_"
                 "seconds series merge and nns_mfu misattributes "
                 "device time; give the filters distinct names",
                 source, prev, model)

    def model_of(self, source: str) -> str:
        with self._lock:
            # a model invoked outside any mapped element (FilterSingle,
            # direct ShardedModel use) dispatches under its own name
            return self._sources.get(str(source), str(source))

    def get(self, source: str, bucket: int = 0) -> Optional[dict]:
        """One raw captured row (tests/bench cross-checks)."""
        with self._lock:
            row = self._rows.get((str(source), int(bucket)))
            if row is None:
                return None
            return {"flops": row.flops, "bytes": row.bytes,
                    "peak_memory": row.peak_memory,
                    "placement": row.placement,
                    "platform": row.platform, "compiles": row.compiles}

    def reset(self) -> None:
        """Tests/bench only: drop every row and all join state."""
        with self._lock:
            self._rows.clear()
            self._sources.clear()
            self._prev_hist.clear()
            self._last_util.clear()
            self._last_exec.clear()

    # -- the scrape-time join ------------------------------------------------

    def _exec_key_for(self, rows: Dict[Tuple[str, int], _Row],
                      source: str, bucket_label: str
                      ) -> Optional[Tuple[str, int]]:
        """Map one measured series' (source, bucket) to an executable
        row key — resolved against the caller's row snapshot so a row
        captured mid-join can't pass the check and miss the lookup.
        The dispatch source resolves to its model, and the single-frame
        chain path (hist bucket "1") to the bucket-0 executable when no
        bucket-1 one exists."""
        try:
            b = int(bucket_label)
        except (TypeError, ValueError):
            return None
        model = self.model_of(source)
        if (model, b) in rows:
            return (model, b)
        if b == 1 and (model, 0) in rows:
            return (model, 0)
        return None

    def join(self, device_hist_rows: List[tuple]
             ) -> Tuple[List[dict], List[dict]]:
        """The scrape-time MFU join.  ``device_hist_rows`` is the
        ``nns_invoke_device_seconds`` family's ``_hist_rows()`` output
        (labels, buckets, sum, count).  Returns ``(executables table,
        utilization samples)``:

        - table rows: the static cost per executable annotated with
          intensity, roofline classification, and — when the hardware
          spec is known and device seconds were measured — live
          ``mfu`` / ``hbm_bw_util`` over the window since the previous
          scrape (cumulative on the first scrape / an idle window);
        - samples: per measured ``{kind, source, bucket}`` series, the
          same utilizations for the ``nns_mfu`` / ``nns_hbm_bw_util``
          gauges.
        """
        with self._lock:
            rows = dict(self._rows)
        samples: List[dict] = []
        # per exec row: accumulated (delta_sum, delta_count) across the
        # dispatch sources measured against it
        per_exec: Dict[Tuple[str, int], Tuple[float, int]] = {}
        for labels, _buckets, hsum, hcount in device_hist_rows:
            key = self._exec_key_for(rows, labels.get("source", ""),
                                     labels.get("bucket", ""))
            if key is None:
                continue
            row = rows[key]
            pkey = (labels.get("kind", ""), labels.get("source", ""),
                    labels.get("bucket", ""))
            with self._lock:
                prev = self._prev_hist.get(pkey)
                self._prev_hist[pkey] = (hsum, hcount)
            if prev is None:
                # first scrape of this series: the cumulative figures
                # ARE the window (the one-shot bench/test path)
                dsum, dcount = hsum, hcount
            else:
                dsum, dcount = hsum - prev[0], hcount - prev[1]
            if dcount <= 0 or dsum <= 0:
                # idle window (no new samples since the last consumer's
                # scrape): re-export the last derived figure
                with self._lock:
                    last = self._last_util.get(pkey)
                if last:
                    samples.append({"labels": dict(labels), **last})
                continue
            acc = per_exec.get(key, (0.0, 0))
            per_exec[key] = (acc[0] + dsum, acc[1] + dcount)
            spec = spec_for_platform(row.platform)
            util = _utilization(row, spec, dsum, dcount)
            if util:
                with self._lock:
                    self._last_util[pkey] = dict(util)
                samples.append({"labels": dict(labels), **util})
        table: List[dict] = []
        for (source, bucket), row in sorted(rows.items()):
            spec = spec_for_platform(row.platform)
            entry = {
                "source": source, "bucket": bucket,
                "placement": row.placement, "platform": row.platform,
                "flops": row.flops, "bytes": row.bytes,
                "peak_memory_bytes": row.peak_memory,
                "peak_memory_estimated": row.peak_memory_estimated,
                "compiles": row.compiles,
            }
            if row.bytes:
                intensity = row.flops / row.bytes
                entry["intensity_flops_per_byte"] = intensity
                if spec is not None:
                    entry["ridge_flops_per_byte"] = spec.ridge
                    entry["bound"] = "compute" \
                        if intensity >= spec.ridge else "bandwidth"
                    entry["mfu_ceiling"] = min(intensity / spec.ridge,
                                               1.0)
            dsum, dcount = per_exec.get((source, bucket), (0.0, 0))
            if dcount > 0 and dsum > 0:
                win = {"device_seconds_window": dsum,
                       "dispatches_window": dcount}
                win.update(_utilization(row, spec, dsum, dcount))
                with self._lock:
                    self._last_exec[(source, bucket)] = dict(win)
                entry.update(win)
            else:
                # idle window: keep the row's last derived figures so
                # the nns-top MFU column doesn't blank between polls
                with self._lock:
                    last = self._last_exec.get((source, bucket))
                if last:
                    entry.update(last)
            table.append(entry)
        return table, samples


def _utilization(row: _Row, spec: Optional[HwSpec], dsum: float,
                 dcount: int) -> dict:
    """{mfu, hbm_bw_util} of ``dcount`` dispatches of one executable
    over ``dsum`` measured device seconds; {} when the hardware peaks
    are unknown (intensity-only fallback)."""
    if spec is None or dsum <= 0 or dcount <= 0:
        return {}
    out: dict = {}
    if row.flops and spec.peak_flops:
        out["mfu"] = row.flops * dcount / (dsum * spec.peak_flops)
    if row.bytes and spec.hbm_bw:
        out["hbm_bw_util"] = row.bytes * dcount / (dsum * spec.hbm_bw)
    return out


#: the process-wide store every jax-xla compile seam feeds
XLA_COST = XlaCostStats()


def capture(source: str, lowered: Any, bucket: int = 0,
            placement: str = "", platform: str = "",
            in_bytes: int = 0, out_bytes: int = 0) -> None:
    """Record one executable's static cost from its jit lowering —
    called at the ``_compile`` / ``_compile_batched`` seams.  Inert
    under the global obs kill switch; never raises (a backend without
    cost analysis must not break compilation)."""
    if not ACTIVE:
        return
    ca = cost_of(lowered)
    if not ca:
        return
    XLA_COST.record(source, bucket, placement, platform, ca,
                    in_bytes=in_bytes, out_bytes=out_bytes)


def map_source(source: str, model: str) -> None:
    """Module-level shim of :meth:`XlaCostStats.map_source`."""
    if not ACTIVE:
        return
    XLA_COST.map_source(source, model)

"""tensor_query — offload a pipeline stage to a server pipeline.

Parity targets (/root/reference/gst/nnstreamer/tensor_query/):
- ``tensor_query_client`` — sink chain serializes the buffer, sends it to
  the server, blocks on an answer queue with a timeout, and pushes the
  answer on its src pad; outstanding requests beyond ``max-request`` drop
  the input instead of queueing unboundedly (tensor_query_client.c:673-741).
- ``tensor_query_serversrc`` — accepts client connections, stamps each
  incoming query with ``client_id`` meta, and pushes it into the server
  pipeline (tensor_query_serversrc.c:483, tensor_meta.c:23).
- ``tensor_query_serversink`` — reads the ``client_id`` meta off the
  processed buffer and sends it back to exactly that client; metaless
  frames are dropped, and a run of them errors the pipeline
  (tensor_query_serversink.c:290).
- the query-server registry pairing src/sink by ``id`` and holding the
  server's caps for client negotiation (tensor_query_server.c).

TPU-native notes: with ``connect-type=inproc`` the round-trip is a queue
hop carrying device-resident buffers (HBM never drained); ``tcp`` uses the
MetaInfo-headed wire codec for true cross-host offload.  For *intra-pod*
scale-out prefer sharding one jitted computation over the mesh
(parallel/sharded.py) — these elements are the cross-process/cross-host
axis, mirroring the reference's "among-device AI".
"""

from __future__ import annotations

import dataclasses
import queue
import threading
from typing import Dict, Optional

from ..core import Buffer, Caps, TensorFormat, TensorsSpec
from ..runtime.element import (
    Element,
    NegotiationError,
    Pad,
    SinkElement,
    SourceElement,
    StreamError,
)
from ..runtime.registry import register_element
from ..utils.log import logw
from .transport import Envelope, connect, make_server
from .wire import MSG_PUBLISH, MSG_QUERY, MSG_REPLY, MSG_SUBSCRIBE


# -- query server registry ----------------------------------------------------


class _QueryServerEntry:
    """Shared state of one query server ``id``: the transport (owned by
    serversrc) and the sink-side caps registered for client negotiation."""

    def __init__(self):
        self.transport = None
        self.sink_caps: str = ""


_REG_LOCK = threading.Lock()
_SERVERS: Dict[int, _QueryServerEntry] = {}


def query_server_entry(server_id: int) -> _QueryServerEntry:
    with _REG_LOCK:
        if server_id not in _SERVERS:
            _SERVERS[server_id] = _QueryServerEntry()
        return _SERVERS[server_id]


# -- client -------------------------------------------------------------------


@register_element("tensor_query_client")
class TensorQueryClient(Element):
    """Acts like a remote tensor_filter: every buffer round-trips through
    the server pipeline."""

    FACTORY = "tensor_query_client"

    def __init__(self, name=None, host: str = "localhost", port: int = 0,
                 dest_host: str = "", dest_port: int = 0,
                 connect_type: str = "tcp", timeout: int = 10000,
                 max_request: int = 8, caps=None, silent: bool = True,
                 alternate_hosts: str = "", **props):
        self.host = host
        self.port = port
        self.dest_host = dest_host      # server address (falls back to host)
        self.dest_port = dest_port
        self.connect_type = connect_type
        self.timeout = timeout          # ms, parity: client timeout prop
        self.max_request = max_request
        self.caps = caps                # explicit out-caps override
        self.silent = silent
        # failover list "host:port,host:port" tried in order when the
        # primary is unreachable (parity: MQTT-hybrid reconnect to
        # alternate servers, reference tensor_query/README.md:74-99)
        self.alternate_hosts = alternate_hosts
        super().__init__(name, **props)
        self.add_sink_pad()
        self.add_src_pad()
        self._conn = None
        self._seq = 0
        self._outstanding = 0
        self.dropped = 0
        self.connected_addr = None  # (host, port) actually in use

    # -- connection -----------------------------------------------------------

    def _server_addrs(self):
        primary_port = int(self.dest_port or self.port)
        addrs = [(self.dest_host or self.host, primary_port)]
        for tok in str(self.alternate_hosts or "").split(","):
            tok = tok.strip()
            if not tok:
                continue
            h, _, p = tok.rpartition(":")
            # a bare hostname inherits the primary's port (port 0 would
            # make the failover entry unconditionally unreachable)
            addrs.append((h or tok,
                          int(p) if p.isdigit() else primary_port))
        return addrs

    def _ensure_conn(self):
        if self._conn is None:
            errors = []
            for host, port in self._server_addrs():
                try:
                    self._conn = connect(host, port, self.connect_type)
                    self.connected_addr = (host, port)
                    break
                except OSError as e:
                    errors.append(f"{host}:{port}: {e}")
            if self._conn is None:
                raise NegotiationError(
                    f"{self.name}: no query server reachable "
                    f"({'; '.join(errors)})")
        return self._conn

    # -- negotiation ----------------------------------------------------------

    def pad_template_caps(self, pad: Pad) -> Caps:
        return Caps.any_tensors()

    def propose_src_caps(self, pad: Pad) -> Caps:
        from ..runtime.parser import parse_caps_string

        rate = self.sinkpad.spec.rate if self.sinkpad.spec else None
        if self.caps:
            return self.caps if isinstance(self.caps, Caps) \
                else parse_caps_string(str(self.caps))
        # ask the server what its pipeline outputs (registry caps exchange,
        # parity: tensor_query_server get/set caps)
        caps_str = self._ensure_conn().request_caps(timeout=2.0)
        if caps_str:
            try:
                return parse_caps_string(caps_str)
            except Exception:  # noqa: BLE001 - fall back to flexible
                logw("%s: unparseable server caps %r", self.name, caps_str)
        spec = TensorsSpec(format=TensorFormat.FLEXIBLE)
        if rate:
            spec = spec.with_rate(rate)
        return Caps.from_spec(spec)

    # -- hot path -------------------------------------------------------------

    def chain(self, pad: Pad, buf: Buffer) -> None:
        conn = self._ensure_conn()
        if self._outstanding >= int(self.max_request) > 0:
            # server too slow: drop the input rather than queue unboundedly
            self.dropped += 1
        else:
            self._seq += 1
            if conn.send(Envelope(MSG_QUERY, seq=self._seq, buffer=buf)):
                self._outstanding += 1
        env = conn.recv(timeout=float(self.timeout) / 1000.0)
        if env is None:
            logw("%s: no answer from query server within %sms",
                 self.name, self.timeout)
            return
        self._outstanding = max(0, self._outstanding - 1)
        out = env.buffer
        if out is None:
            return
        # metadata comes from the *incoming* buffer (reference copies
        # GST_BUFFER_COPY_METADATA from the input onto the answer)
        out = dataclasses.replace(
            out, pts=buf.pts, duration=buf.duration, offset=buf.offset,
            meta={**buf.meta,
                  **{k: v for k, v in out.meta.items()
                     if k not in ("client_id", "query_seq")}})
        self.push(out)

    def stop(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None


# -- server source ------------------------------------------------------------


@register_element("tensor_query_serversrc")
class TensorQueryServerSrc(SourceElement):
    """Entry of the server pipeline: owns the transport, stamps queries
    with ``client_id`` routing meta."""

    FACTORY = "tensor_query_serversrc"

    def __init__(self, name=None, host: str = "localhost", port: int = 0,
                 connect_type: str = "tcp", id: int = 0, caps=None,
                 num_buffers: int = -1, **props):
        self.host = host
        self.port = port
        self.connect_type = connect_type
        self.id = id
        self.caps = caps
        self.num_buffers = num_buffers
        super().__init__(name, **props)
        if isinstance(self.caps, str):
            from ..runtime.parser import parse_caps_string

            self.caps = parse_caps_string(self.caps)
        self._queue: "queue.Queue[Envelope]" = queue.Queue(maxsize=64)
        self._server = None
        self._count = 0

    def output_spec(self) -> TensorsSpec:
        if self.caps is not None:
            return self.caps.to_spec()
        return TensorsSpec(format=TensorFormat.FLEXIBLE)

    def _on_message(self, client_id: int, env: Envelope) -> None:
        if env.mtype != MSG_QUERY or env.buffer is None:
            return
        try:
            self._queue.put_nowait(env)
        except queue.Full:
            logw("%s: query queue full, dropping client %d request",
                 self.name, client_id)

    def start(self) -> None:
        entry = query_server_entry(int(self.id))
        if self._server is None:
            self._server = make_server(self.host, int(self.port),
                                       self.connect_type)
            self._server.on_message = self._on_message
            self._server.caps_provider = lambda: entry.sink_caps
            self._server.start()
            # expose the actual port (port=0 binds an ephemeral one)
            self.port = getattr(self._server, "port", self.port)
        entry.transport = self._server
        super().start()

    def stop(self) -> None:
        super().stop()
        if self._server is not None:
            self._server.stop()
            entry = query_server_entry(int(self.id))
            if entry.transport is self._server:
                entry.transport = None
            self._server = None

    def create(self) -> Optional[Buffer]:
        if 0 <= int(self.num_buffers) <= self._count:
            return None
        while self._running.is_set():
            try:
                env = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            self._count += 1
            buf = env.buffer
            # shallow-copy: never mutate the client's buffer (inproc
            # passes it by reference)
            buf = dataclasses.replace(buf, meta=dict(buf.meta))
            buf.meta["client_id"] = env.client_id
            buf.meta["query_seq"] = env.seq
            return buf
        return None


# -- server sink --------------------------------------------------------------


@register_element("tensor_query_serversink")
class TensorQueryServerSink(SinkElement):
    """Exit of the server pipeline: routes each answer to the client that
    asked, via the ``client_id`` meta."""

    FACTORY = "tensor_query_serversink"

    def __init__(self, name=None, id: int = 0,
                 metaless_frame_limit: int = 2, **props):
        self.id = id
        self.metaless_frame_limit = metaless_frame_limit
        super().__init__(name, **props)
        self._metaless = 0

    def caps_negotiated(self, pad: Pad) -> None:
        # register the server pipeline's output caps so clients can
        # negotiate against them (parity: serversink set_caps →
        # gst_tensor_query_server_set_caps)
        if pad.caps is not None:
            query_server_entry(int(self.id)).sink_caps = str(pad.caps)

    def render(self, buf: Buffer) -> None:
        client_id = buf.meta.get("client_id")
        if client_id is None:
            self._metaless += 1
            logw("%s: no client_id meta on buffer — an element in the "
                 "server pipeline dropped routing meta", self.name)
            if self._metaless >= int(self.metaless_frame_limit):
                raise StreamError(
                    f"{self.name}: {self._metaless} metaless frames; "
                    "check elements used in the query-server pipeline")
            return
        self._metaless = 0
        entry = query_server_entry(int(self.id))
        if entry.transport is None:
            raise StreamError(
                f"{self.name}: no serversrc transport for id={self.id}")
        entry.transport.send(
            int(client_id),
            Envelope(MSG_REPLY, client_id=int(client_id),
                     seq=int(buf.meta.get("query_seq", 0)), buffer=buf))


# -- edge pub/sub -------------------------------------------------------------


@register_element("edgesink")
class EdgeSink(SinkElement):
    """Publish a tensor stream: subscribers (edgesrc) receive every
    rendered buffer for their topic.

    Parity: /root/reference/gst/edge/edge_sink.c:291-334 (nns_edge server
    publishing over TCP/HYBRID with ``topic``)."""

    FACTORY = "edgesink"

    def __init__(self, name=None, host: str = "localhost", port: int = 0,
                 connect_type: str = "tcp", topic: str = "", **props):
        self.host = host
        self.port = port
        self.connect_type = connect_type
        self.topic = topic
        super().__init__(name, **props)
        self._server = None
        self.published = 0

    def start(self) -> None:
        if self._server is None:
            self._server = make_server(self.host, int(self.port),
                                       self.connect_type)
            self._server.caps_provider = lambda: (
                str(self.sinkpad.caps) if self.sinkpad.caps else "")
            self._server.start()
            self.port = getattr(self._server, "port", self.port)

    def stop(self) -> None:
        if self._server is not None:
            self._server.stop()
            self._server = None

    def render(self, buf: Buffer) -> None:
        if self._server is None:
            raise StreamError(f"{self.name}: not started")
        self.published += self._server.publish(
            Envelope(MSG_PUBLISH, info=str(self.topic), buffer=buf))


@register_element("edgesrc")
class EdgeSrc(SourceElement):
    """Subscribe to a published tensor stream by topic.

    Parity: /root/reference/gst/edge/edge_src.c (nns_edge client with
    ``dest-host``/``dest-port``/``topic``)."""

    FACTORY = "edgesrc"

    def __init__(self, name=None, dest_host: str = "localhost",
                 dest_port: int = 0, connect_type: str = "tcp",
                 topic: str = "", caps=None, num_buffers: int = -1,
                 **props):
        self.dest_host = dest_host
        self.dest_port = dest_port
        self.connect_type = connect_type
        self.topic = topic
        self.caps = caps
        self.num_buffers = num_buffers
        super().__init__(name, **props)
        if isinstance(self.caps, str):
            from ..runtime.parser import parse_caps_string

            self.caps = parse_caps_string(self.caps)
        self._conn = None
        self._count = 0

    def _ensure_conn(self):
        if self._conn is None:
            self._conn = connect(self.dest_host, int(self.dest_port),
                                 self.connect_type)
            self._conn.send(Envelope(MSG_SUBSCRIBE, info=str(self.topic)))
        return self._conn

    def output_spec(self) -> TensorsSpec:
        if self.caps is not None:
            return self.caps.to_spec()
        from ..runtime.parser import parse_caps_string

        caps_str = self._ensure_conn().request_caps(timeout=2.0)
        if caps_str:
            try:
                return parse_caps_string(caps_str).to_spec()
            except Exception:  # noqa: BLE001
                logw("%s: unparseable publisher caps %r", self.name,
                     caps_str)
        return TensorsSpec(format=TensorFormat.FLEXIBLE)

    def start(self) -> None:
        self._ensure_conn()
        super().start()

    def stop(self) -> None:
        super().stop()
        if self._conn is not None:
            self._conn.close()
            self._conn = None

    def create(self) -> Optional[Buffer]:
        if 0 <= int(self.num_buffers) <= self._count:
            return None
        conn = self._ensure_conn()
        while self._running.is_set():
            env = conn.recv(timeout=0.1)
            if env is None:
                continue
            if env.mtype != MSG_PUBLISH or env.buffer is None:
                continue
            self._count += 1
            return env.buffer
        return None

"""Unit tests for the tensor core (L1).

Modeled on the reference's unittest_common suite
(/root/reference/tests/common/unittest_common.cc): dim-string grammar,
type parsing, spec compare, meta header round-trips, sparse codec.
"""

import numpy as np
import pytest
from fractions import Fraction

from nnstreamer_tpu.core import (
    ANY,
    Buffer,
    Caps,
    CapsStruct,
    DType,
    MetaInfo,
    Range,
    Tensor,
    TensorFormat,
    TensorSpec,
    TensorsSpec,
    dims_equal,
    header_size,
    parse_dimension,
    sparse_from_dense,
    sparse_to_dense,
)


class TestDType:
    def test_all_eleven_reference_dtypes(self):
        for name in ["int32", "uint32", "int16", "uint16", "int8", "uint8",
                     "float64", "float32", "int64", "uint64", "float16"]:
            dt = DType.from_string(name)
            assert str(dt) == name

    def test_bfloat16_extension(self):
        dt = DType.from_string("bfloat16")
        assert dt.size == 2

    def test_sizes(self):
        assert DType.UINT8.size == 1
        assert DType.FLOAT32.size == 4
        assert DType.INT64.size == 8
        assert DType.FLOAT16.size == 2

    def test_bad_string(self):
        with pytest.raises(ValueError):
            DType.from_string("complex64")

    def test_np_roundtrip(self):
        for dt in DType:
            assert DType.from_np(dt.np_dtype) == dt


class TestDimGrammar:
    def test_parse_basic(self):
        assert parse_dimension("3:224:224:1") == (3, 224, 224, 1)

    def test_parse_trailing_zero_terminates(self):
        assert parse_dimension("3:224:224:0") == (3, 224, 224)

    def test_parse_single(self):
        assert parse_dimension("10") == (10,)

    def test_parse_rank16(self):
        s = ":".join(["2"] * 16)
        assert len(parse_dimension(s)) == 16

    def test_parse_rank17_fails(self):
        with pytest.raises(ValueError):
            parse_dimension(":".join(["2"] * 17))

    def test_parse_empty_fails(self):
        with pytest.raises(ValueError):
            parse_dimension("")

    def test_rank_flexible_equal(self):
        assert dims_equal((3, 224, 224), (3, 224, 224, 1, 1))
        assert not dims_equal((3, 224, 224), (3, 224, 224, 2))


class TestTensorSpec:
    def test_shape_is_reversed_dims(self):
        s = TensorSpec.parse("3:224:224:1", "uint8")
        assert s.shape == (1, 224, 224, 3)
        assert s.nbytes == 224 * 224 * 3

    def test_from_shape_roundtrip(self):
        s = TensorSpec.from_shape((1, 224, 224, 3), np.uint8)
        assert s.dim_string() == "3:224:224:1"

    def test_compatibility_rank_flex(self):
        a = TensorSpec.parse("3:224:224", "float32")
        b = TensorSpec.parse("3:224:224:1", "float32")
        assert a.is_compatible(b)
        assert not a.is_compatible(b.with_dtype(DType.UINT8))


class TestTensorsSpec:
    def test_parse_multi(self):
        ts = TensorsSpec.parse("3:224:224:1,1001:1", "uint8,float32",
                               rate=Fraction(30))
        assert ts.num_tensors == 2
        assert ts.dimensions_string() == "3:224:224:1,1001:1"
        assert ts.types_string() == "uint8,float32"
        assert ts.rate == 30

    def test_count_mismatch(self):
        with pytest.raises(ValueError):
            TensorsSpec.parse("3:4", "uint8,uint8")

    def test_limit_256(self):
        with pytest.raises(ValueError):
            TensorsSpec(tensors=tuple(
                TensorSpec.parse("1", "uint8") for _ in range(257)))

    def test_flexible_compat_ignores_payload(self):
        a = TensorsSpec(format=TensorFormat.FLEXIBLE)
        b = TensorsSpec.parse("3:4", "uint8").with_format(TensorFormat.FLEXIBLE)
        assert a.is_compatible(b)
        assert not a.is_compatible(TensorsSpec())


class TestMetaHeader:
    def test_roundtrip_flexible(self):
        spec = TensorSpec.parse("3:640:480:1", "uint8")
        mi = MetaInfo.from_spec(spec)
        packed = mi.pack()
        assert len(packed) == header_size(TensorFormat.FLEXIBLE)
        back = MetaInfo.unpack(packed)
        assert back.dims == (3, 640, 480, 1)
        assert back.dtype == DType.UINT8
        assert back.format == TensorFormat.FLEXIBLE

    def test_roundtrip_sparse_has_nnz(self):
        spec = TensorSpec.parse("100:1", "float32")
        mi = MetaInfo.from_spec(spec, format=TensorFormat.SPARSE, nnz=7)
        back = MetaInfo.unpack(mi.pack())
        assert back.nnz == 7
        assert header_size(TensorFormat.SPARSE) == \
            header_size(TensorFormat.FLEXIBLE) + 4

    def test_bad_magic(self):
        with pytest.raises(ValueError):
            MetaInfo.unpack(b"\x00" * 100)


class TestBuffer:
    def test_tensor_residences(self):
        arr = np.arange(12, dtype=np.float32).reshape(3, 4)
        t = Tensor(arr)
        assert t.spec.dim_string() == "4:3"
        assert t.tobytes() == arr.tobytes()
        j = t.jax()
        assert j.shape == (3, 4)

    def test_bytes_tensor_needs_spec(self):
        with pytest.raises(ValueError):
            Tensor(b"\x00" * 12)
        t = Tensor(b"\x00" * 12, TensorSpec.parse("3:1", "float32"))
        assert t.np().shape == (1, 3)

    def test_size_validation(self):
        with pytest.raises(ValueError):
            Tensor(b"\x00" * 11, TensorSpec.parse("3:1", "float32"))

    def test_buffer_flexible_roundtrip(self):
        a = np.random.randint(0, 255, (2, 5, 7), dtype=np.uint8)
        b = np.random.randn(3, 3).astype(np.float32)
        buf = Buffer.of(a, b, pts=1000)
        payloads = buf.pack_flexible()
        back = Buffer.unpack_flexible(payloads, pts=buf.pts)
        assert back.num_tensors == 2
        np.testing.assert_array_equal(back[0].np(), a)
        np.testing.assert_array_equal(back[1].np(), b)

    def test_sparse_roundtrip(self):
        arr = np.zeros((4, 8), dtype=np.float32)
        arr[1, 3] = 2.5
        arr[3, 7] = -1.0
        payload = sparse_from_dense(Tensor(arr))
        # much smaller than dense + header overhead bound
        assert len(payload) < arr.nbytes
        back = sparse_to_dense(payload)
        np.testing.assert_array_equal(back.np(), arr)

    def test_with_spec_reinterpret(self):
        arr = np.arange(12, dtype=np.float32)
        t = Tensor(arr).with_spec(TensorSpec.parse("4:3", "float32"))
        assert t.shape == (3, 4)


class TestCaps:
    def test_from_spec_and_back(self):
        ts = TensorsSpec.parse("3:224:224:1", "uint8", rate=Fraction(30))
        caps = Caps.from_spec(ts)
        assert caps.is_fixed()
        back = caps.to_spec()
        assert back.is_compatible(ts)
        assert back.rate == 30

    def test_intersect_any(self):
        ts = TensorsSpec.parse("3:224:224:1", "uint8")
        assert Caps.any_tensors().can_intersect(Caps.from_spec(ts))

    def test_intersect_mismatched_dims(self):
        a = Caps.from_spec(TensorsSpec.parse("3:224:224:1", "uint8"))
        b = Caps.from_spec(TensorsSpec.parse("3:300:300:1", "uint8"))
        assert not a.can_intersect(b)

    def test_rank_flexible_intersect(self):
        a = Caps.from_spec(TensorsSpec.parse("3:224:224", "uint8"))
        b = Caps.from_spec(TensorsSpec.parse("3:224:224:1", "uint8"))
        assert a.can_intersect(b)

    def test_template_free_dim(self):
        tpl = Caps.new(CapsStruct.make(
            "other/tensors", format="static", num_tensors=1,
            dimensions="3:0:0:1", types="uint8"))
        con = Caps.from_spec(TensorsSpec.parse("3:640:480:1", "uint8"))
        m = tpl.intersect(con)
        assert m and m.first().get("dimensions") == "3:640:480:1"

    def test_set_and_range_fields(self):
        a = Caps.new(CapsStruct.make("video/x-raw", format={"RGB", "BGRx"},
                                     width=Range(1, 4096)))
        b = Caps.new(CapsStruct.make("video/x-raw", format="RGB", width=640))
        m = a.intersect(b)
        assert m.first().get("format") == "RGB"
        assert m.first().get("width") == 640

    def test_framerate_zero_is_wildcardish(self):
        a = Caps.from_spec(TensorsSpec.parse("3:4", "uint8", rate=0))
        b = Caps.from_spec(TensorsSpec.parse("3:4", "uint8",
                                             rate=Fraction(30)))
        m = a.intersect(b)
        assert m and Fraction(m.first().get("framerate")) == 30

    def test_preference_order_preserved(self):
        a = Caps.new(CapsStruct.make("other/tensors", format="static"),
                     CapsStruct.make("other/tensors", format="flexible"))
        b = Caps.new(CapsStruct.make("other/tensors",
                                     format={"static", "flexible"}))
        m = a.intersect(b)
        assert m.structs[0].get("format") == "static"

    def test_fixate_picks_first_and_lowest(self):
        c = Caps.new(CapsStruct.make("video/x-raw", width=Range(320, 640),
                                     format={"RGB"}))
        f = c.fixate()
        assert f.is_fixed()
        assert f.first().get("width") == 320


class TestCapsRegressions:
    """Regressions from review: set×range intersection, trailing-zero dims."""

    def test_set_intersects_range(self):
        a = Caps.new(CapsStruct.make("video/x-raw", width=frozenset({480, 640})))
        b = Caps.new(CapsStruct.make("video/x-raw", width=Range(1, 4096)))
        m = a.intersect(b)
        assert m and m.first().get("width") == frozenset({480, 640})
        n = a.intersect(Caps.new(CapsStruct.make("video/x-raw",
                                                 width=Range(500, 4096))))
        assert n.first().get("width") == 640

    def test_trailing_zero_is_rank_end_not_template(self):
        a = Caps.new(CapsStruct.make("other/tensors", format="static",
                                     num_tensors=1, dimensions="3:224:224:0",
                                     types="uint8"))
        assert a.is_fixed()
        b = Caps.from_spec(TensorsSpec.parse("3:224:224:5", "uint8"))
        assert not a.can_intersect(b)
        c = Caps.from_spec(TensorsSpec.parse("3:224:224:1", "uint8"))
        assert a.can_intersect(c)

    def test_noncontiguous_reinterpret(self):
        arr = np.arange(24, dtype=np.float32).reshape(4, 6).T
        t = Tensor(arr).with_spec(TensorSpec.parse("96", "uint8"))
        assert t.shape == (96,)

    def test_to_spec_rejects_unfixed_template(self):
        tpl = Caps.new(CapsStruct.make(
            "other/tensors", format="static", num_tensors=1,
            dimensions="3:0:0:1", types="uint8"))
        with pytest.raises(ValueError, match="not fixed"):
            tpl.to_spec()

    def test_framerate_range_intersect(self):
        a = Caps.new(CapsStruct.make(
            "other/tensors", framerate=Range(Fraction(0), Fraction(120))))
        b = Caps.new(CapsStruct.make("other/tensors",
                                     framerate=Fraction(30)))
        m = a.intersect(b)
        assert m and m.first().get("framerate") == 30

    def test_wildcard_caps_not_fixed(self):
        assert not Caps.any().is_fixed()
        with pytest.raises(ValueError):
            Caps.any().fixate()

    def test_from_shapes_length_mismatch(self):
        with pytest.raises(ValueError):
            TensorsSpec.from_shapes([(2, 2), (3, 3)], ["float32"])

    def test_meta_pack_validates(self):
        from nnstreamer_tpu.core import MetaInfo as MI, DType as DT
        with pytest.raises(ValueError):
            MI(dtype=DT.UINT8, dims=(2,) * 17).pack()
        with pytest.raises(ValueError):
            MI(dtype=DT.UINT8, dims=(2 ** 33,)).pack()

    def test_meta_unpack_rejects_future_version(self):
        mi = MetaInfo.from_spec(TensorSpec.parse("3:4", "uint8"))
        mi.version = 999
        with pytest.raises(ValueError, match="version"):
            MetaInfo.unpack(mi.pack())

"""Wire-format converter/decoder sub-plugins: flexbuf, flatbuf, protobuf,
python3 script converter/decoder, custom-code converter, font overlay.

Parity model: the reference round-trips tensors through each wire via
``tensor_decoder mode=X ! tensor_converter`` pipelines
(tests/nnstreamer_converter_*/runTest.sh); same shape here, plus a
google.protobuf reflection cross-check of the hand-rolled proto3 codec.
"""

import textwrap
from fractions import Fraction

import numpy as np
import pytest

from nnstreamer_tpu.converters import (
    codecs,
    find_converter,
    list_converters,
    register_custom,
    unregister_custom,
)
from nnstreamer_tpu.core import Buffer, TensorFormat, TensorsSpec
from nnstreamer_tpu.decoders import find_decoder, list_decoders
from nnstreamer_tpu.elements.basic import AppSink, AppSrc
from nnstreamer_tpu.runtime import Pipeline
from nnstreamer_tpu.runtime.registry import make


def sample_buffer():
    return Buffer.of(
        np.arange(24, dtype=np.float32).reshape(2, 3, 4),
        np.array([7, 8, 9], dtype=np.uint8),
        np.array([[1.5, -2.5]], dtype=np.float64),
    )


CODECS = [
    (codecs.flexbuf_encode, codecs.flexbuf_decode),
    (codecs.flatbuf_encode, codecs.flatbuf_decode),
    (codecs.protobuf_encode, codecs.protobuf_decode),
]


class TestCodecs:
    @pytest.mark.parametrize("enc,dec", CODECS,
                             ids=["flexbuf", "flatbuf", "protobuf"])
    def test_roundtrip(self, enc, dec):
        b = sample_buffer()
        spec = b.spec(rate=Fraction(30))
        out, ospec = dec(enc(b, spec))
        assert len(out.tensors) == 3
        for got, want in zip(out.tensors, b.tensors):
            np.testing.assert_array_equal(got.np(), want.np())
            assert got.spec.dtype == want.spec.dtype
        assert ospec.rate == Fraction(30)

    def test_protobuf_wire_matches_google_runtime(self):
        """The hand-rolled codec must interoperate with real protobuf:
        parse our bytes with a dynamically-built descriptor mirroring
        /root/reference/ext/nnstreamer/include/nnstreamer.proto."""
        pb2 = pytest.importorskip("google.protobuf")
        from google.protobuf import descriptor_pb2, descriptor_pool
        from google.protobuf import message_factory

        fdp = descriptor_pb2.FileDescriptorProto()
        fdp.name = "nns_tpu_test.proto"
        fdp.package = "nns_tpu_test"
        fdp.syntax = "proto3"
        t = fdp.message_type.add()
        t.name = "Tensor"
        for i, (nm, ty, label) in enumerate([
                ("name", 9, 1), ("type", 13, 1),
                ("dimension", 13, 3), ("data", 12, 1)], 1):
            f = t.field.add()
            f.name, f.number, f.type, f.label = nm, i, ty, label
        ts = fdp.message_type.add()
        ts.name = "Tensors"
        fr = ts.nested_type.add()
        fr.name = "frame_rate"
        for i, nm in enumerate(["rate_n", "rate_d"], 1):
            f = fr.field.add()
            f.name, f.number, f.type, f.label = nm, i, 5, 1
        specs = [("num_tensor", 1, 13, 1, ""),
                 ("fr", 2, 11, 1, ".nns_tpu_test.Tensors.frame_rate"),
                 ("tensor", 3, 11, 3, ".nns_tpu_test.Tensor"),
                 ("format", 4, 5, 1, "")]
        for nm, num, ty, label, tyname in specs:
            f = ts.field.add()
            f.name, f.number, f.type, f.label = nm, num, ty, label
            if tyname:
                f.type_name = tyname
        pool = descriptor_pool.DescriptorPool()
        pool.Add(fdp)
        msg_cls = message_factory.GetMessageClass(
            pool.FindMessageTypeByName("nns_tpu_test.Tensors"))

        b = sample_buffer()
        data = codecs.protobuf_encode(b, b.spec(rate=Fraction(15)))
        msg = msg_cls()
        msg.ParseFromString(data)
        assert msg.num_tensor == 3
        assert (msg.fr.rate_n, msg.fr.rate_d) == (15, 1)
        assert msg.tensor[0].type == 7  # NNS_FLOAT32
        # writers pad dims to the 16-entry RANK_LIMIT like the reference
        assert list(msg.tensor[0].dimension) == [4, 3, 2] + [0] * 13
        np.testing.assert_array_equal(
            np.frombuffer(msg.tensor[0].data, np.float32).reshape(2, 3, 4),
            b.tensors[0].np())
        # and the reverse: google-serialized bytes parse with our decoder
        out, ospec = codecs.protobuf_decode(msg.SerializeToString())
        np.testing.assert_array_equal(out.tensors[0].np(), b.tensors[0].np())
        assert ospec.rate == Fraction(15)


class TestConverterSubplugins:
    def test_registered(self):
        assert {"flexbuf", "flatbuf", "protobuf"} <= set(list_converters())
        assert find_converter("other/flexbuf") is not None
        assert find_converter("other/flatbuf-tensor") is not None
        assert find_converter("other/protobuf-tensor") is not None

    @pytest.mark.parametrize("mime,enc", [
        ("other/flexbuf", codecs.flexbuf_encode),
        ("other/flatbuf-tensor", codecs.flatbuf_encode),
        ("other/protobuf-tensor", codecs.protobuf_encode),
    ])
    def test_pipeline_wire_to_tensors(self, mime, enc):
        orig = Buffer.of(np.arange(6, dtype=np.int32).reshape(2, 3))
        payload = enc(orig, orig.spec(rate=Fraction(30)))
        p = Pipeline()
        src = AppSrc(name="src", caps=mime)
        conv = make("tensor_converter", el_name="conv")
        sink = AppSink(name="out")
        p.add(src, conv, sink).link(src, conv, sink)
        with p:
            src.push_buffer(Buffer.of(np.frombuffer(payload, np.uint8),
                                      pts=1234))
            src.end_of_stream()
            assert p.wait_eos(timeout=10)
            got = sink.pull(timeout=1)
        assert got is not None
        assert got.format == TensorFormat.FLEXIBLE
        assert got.pts == 1234
        np.testing.assert_array_equal(got.tensors[0].np(),
                                      orig.tensors[0].np())

    def test_custom_code_mode(self):
        def conv_fn(buf):
            raw = buf.tensors[0].np()
            return Buffer.of(raw.astype(np.float32) * 2.0)

        register_custom("tconv_x2", conv_fn)
        try:
            p = Pipeline()
            src = AppSrc(name="src", caps="application/octet-stream")
            conv = make("tensor_converter", el_name="conv",
                        mode="custom-code:tconv_x2")
            sink = AppSink(name="out")
            p.add(src, conv, sink).link(src, conv, sink)
            with p:
                src.push_buffer(Buffer.of(np.arange(4, dtype=np.uint8)))
                src.end_of_stream()
                assert p.wait_eos(timeout=10)
                got = sink.pull(timeout=1)
            np.testing.assert_array_equal(
                got.tensors[0].np(), np.arange(4, dtype=np.float32) * 2)
        finally:
            assert unregister_custom("tconv_x2")

    def test_custom_script_mode(self, tmp_path):
        script = tmp_path / "conv.py"
        script.write_text(textwrap.dedent("""\
            import numpy as np

            class CustomConverter:
                def convert(self, arrays):
                    # reference 4-tuple return shape
                    raw = arrays[0]
                    info = [((len(raw),), np.uint8)]
                    return info, [raw[::-1].copy()], 10, 1
        """))
        p = Pipeline()
        src = AppSrc(name="src", caps="application/octet-stream")
        conv = make("tensor_converter", el_name="conv",
                    mode=f"custom-script:{script}")
        sink = AppSink(name="out")
        p.add(src, conv, sink).link(src, conv, sink)
        with p:
            src.push_buffer(Buffer.of(np.array([1, 2, 3], np.uint8)))
            src.end_of_stream()
            assert p.wait_eos(timeout=10)
            got = sink.pull(timeout=1)
        np.testing.assert_array_equal(got.tensors[0].np(),
                                      np.array([3, 2, 1], np.uint8))


class TestWireDecoders:
    @pytest.mark.parametrize("mode,dec", [
        ("flexbuf", codecs.flexbuf_decode),
        ("flatbuf", codecs.flatbuf_decode),
        ("protobuf", codecs.protobuf_decode),
    ])
    def test_decode_then_codec_roundtrip(self, mode, dec):
        assert mode in list_decoders()
        d = find_decoder(mode)()
        b = sample_buffer()
        spec = b.spec(rate=Fraction(30))
        caps = d.out_caps(spec)
        mime = caps.first().mime
        assert mime in ("other/flexbuf", "other/flatbuf-tensor",
                        "other/protobuf-tensor")
        wire = d.decode(b, spec)
        out, ospec = dec(wire.tensors[0].tobytes())
        for got, want in zip(out.tensors, b.tensors):
            np.testing.assert_array_equal(got.np(), want.np())

    @pytest.mark.parametrize("mode,mime", [
        ("flexbuf", "other/flexbuf"),
        ("flatbuf", "other/flatbuf-tensor"),
        ("protobuf", "other/protobuf-tensor"),
    ])
    def test_pipeline_decoder_to_converter_roundtrip(self, mode, mime):
        """tensors → decoder(wire) → converter(tensors): the reference's
        canonical converter test pipeline shape."""
        spec = TensorsSpec.from_shapes([(2, 3)], np.float32,
                                       rate=Fraction(30))
        p = Pipeline()
        src = AppSrc(name="src", spec=spec)
        dec = make("tensor_decoder", el_name="dec", mode=mode)
        conv = make("tensor_converter", el_name="conv")
        sink = AppSink(name="out")
        p.add(src, dec, conv, sink).link(src, dec, conv, sink)
        arr = np.linspace(0, 1, 6, dtype=np.float32).reshape(2, 3)
        with p:
            src.push_buffer(Buffer.of(arr, pts=77))
            src.end_of_stream()
            assert p.wait_eos(timeout=10)
            got = sink.pull(timeout=1)
        assert got.pts == 77
        np.testing.assert_array_equal(got.tensors[0].np(), arr)

    def test_python3_decoder_script(self, tmp_path):
        script = tmp_path / "dec.py"
        script.write_text(textwrap.dedent("""\
            class CustomDecoder:
                def getOutCaps(self):
                    return bytes('application/octet-stream', 'UTF-8')

                def decode(self, raw_data, in_info, rate_n, rate_d):
                    assert in_info[0].getDims()[0] == 4  # innermost dim
                    return b''.join(bytes(r) for r in raw_data)
        """))
        d = find_decoder("python3")()
        d.set_option(0, str(script))
        b = Buffer.of(np.arange(4, dtype=np.uint8))
        spec = b.spec(rate=Fraction(30))
        assert d.out_caps(spec).first().mime == "application/octet-stream"
        out = d.decode(b, spec)
        assert out.tensors[0].tobytes() == bytes(range(4))


class TestEdgeWireFlags:
    """Edge frame codec: flags threading + the trailing extension area
    (trace contexts) with both-direction forward compatibility."""

    def _msg(self, **kw):
        from nnstreamer_tpu.edge.wire import MSG_QUERY, EdgeMessage

        base = dict(mtype=MSG_QUERY, client_id=3, seq=9, pts=1234,
                    payloads=sample_buffer().pack_flexible())
        base.update(kw)
        return EdgeMessage(**base)

    def test_flags_roundtrip(self):
        from nnstreamer_tpu.edge.wire import EdgeMessage

        m2 = EdgeMessage.unpack(self._msg(flags=0x00A4).pack())
        assert m2.flags == 0x00A4  # unknown bits preserved, no raise
        assert m2.trace is None
        assert m2.seq == 9 and len(m2.payloads) == 3

    def test_trace_extension_roundtrip(self):
        from nnstreamer_tpu.edge.wire import EdgeMessage

        ctx = {"id": "ab-1", "t1": 0.125, "marks": [[0.1, "src", "source"]]}
        m2 = EdgeMessage.unpack(self._msg(trace=ctx).pack())
        assert m2.trace == ctx
        assert m2.flags == 0  # FLAG_EXT is representational, stripped
        out = m2.to_buffer()
        np.testing.assert_array_equal(
            out.tensors[0].np().reshape(2, 3, 4),
            sample_buffer().tensors[0].np())

    def test_old_decoder_shape_ignores_extension(self):
        """A v1 decoder stops at the last payload: the packed bytes up
        to there are IDENTICAL with and without a trace — the extension
        is purely trailing."""
        plain = self._msg().pack()
        traced = self._msg(trace={"id": "x"}).pack()
        # same bytes except the flags u16 (offset 6) and the trailer
        assert traced[:6] == plain[:6]
        assert traced[8:len(plain)] == plain[8:]
        assert len(traced) > len(plain)

    def test_unknown_extension_tag_skipped(self):
        import struct

        from nnstreamer_tpu.edge.wire import FLAG_EXT, EXT_TRACE, \
            EdgeMessage

        plain = self._msg().pack()
        # set FLAG_EXT and append: unknown tag block, then a trace block
        flagged = plain[:6] + struct.pack("<H", FLAG_EXT) + plain[8:]
        blob = b'{"id":"later"}'
        ext = struct.pack("<HI", 0x7F7F, 4) + b"\x00\x01\x02\x03" \
            + struct.pack("<HI", EXT_TRACE, len(blob)) + blob
        m2 = EdgeMessage.unpack(flagged + ext)
        assert m2.trace == {"id": "later"}  # found PAST the unknown tag
        assert len(m2.payloads) == 3

    def test_truncated_extension_tolerated(self):
        import struct

        from nnstreamer_tpu.edge.wire import FLAG_EXT, EXT_TRACE, \
            EdgeMessage

        plain = self._msg().pack()
        flagged = plain[:6] + struct.pack("<H", FLAG_EXT) + plain[8:]
        # declares 100 bytes but carries 3: decoder must not raise
        ext = struct.pack("<HI", EXT_TRACE, 100) + b"abc"
        m2 = EdgeMessage.unpack(flagged + ext)
        assert m2.trace is None
        assert len(m2.payloads) == 3
        # flag set but zero extension bytes at all: also fine
        assert EdgeMessage.unpack(flagged).trace is None

    def test_envelope_carries_trace_through_wire(self):
        from nnstreamer_tpu.edge.transport import (
            Envelope,
            _from_wire,
            _to_wire,
        )
        from nnstreamer_tpu.edge.wire import MSG_REPLY

        env = Envelope(MSG_REPLY, client_id=2, seq=5,
                       buffer=sample_buffer(),
                       trace={"id": "z", "t3": 1.0})
        env2 = _from_wire(_to_wire(env))
        assert env2.trace == {"id": "z", "t3": 1.0}
        assert env2.seq == 5


class TestFontOverlay:
    def test_draw_text_stamps_pixels(self):
        from nnstreamer_tpu.decoders.font import draw_text, text_mask

        frame = np.zeros((32, 64, 4), np.uint8)
        draw_text(frame, 2, 2, "A1", (255, 0, 0, 255))
        assert frame[..., 0].sum() > 0
        m = text_mask("A1")
        assert m.shape[0] == 13 and m.any()

    def test_draw_text_clips_at_edges(self):
        from nnstreamer_tpu.decoders.font import draw_text

        frame = np.zeros((10, 10, 4), np.uint8)
        draw_text(frame, -5, -5, "XYZ")      # partially off-frame
        draw_text(frame, 100, 100, "XYZ")    # fully off-frame: no-op
        assert frame.shape == (10, 10, 4)

    def test_boundingbox_labels_drawn(self):
        from nnstreamer_tpu.decoders.boxutil import Detection, draw_boxes

        d = Detection(x=0.25, y=0.5, w=0.4, h=0.3, score=0.9, class_id=1)
        d.label = "cat"
        plain = draw_boxes([d], 64, 64)
        labeled = draw_boxes([d], 64, 64, labels=True)
        assert (labeled != plain).any()

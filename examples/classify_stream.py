#!/usr/bin/env python
"""Streaming classification: device-staged frames → fused normalize +
MobileNetV1 → top-1 labels.

    python examples/classify_stream.py [num_buffers]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def main(num_buffers: int = 8):
    import jax

    from nnstreamer_tpu.core import TensorsSpec
    from nnstreamer_tpu.filters.jax_xla import register_model
    from nnstreamer_tpu.models.mobilenet import (
        mobilenet_v1_apply,
        mobilenet_v1_init,
    )
    from nnstreamer_tpu.runtime import parse_launch

    params = mobilenet_v1_init(jax.random.PRNGKey(0), num_classes=1001)
    register_model(
        "mnv1",
        lambda p, x: jax.numpy.argmax(mobilenet_v1_apply(p, x), -1),
        params=params, in_shapes=[(8, 224, 224, 3)])

    p = parse_launch(
        f"device_src name=src pattern=noise num-buffers={num_buffers} ! "
        "tensor_transform mode=arithmetic "
        "option=typecast:float32,add:-127.5,div:127.5 ! "
        "tensor_filter framework=jax-xla model=mnv1 ! "
        "appsink name=out")
    p["src"].spec = TensorsSpec.from_shapes([(8, 224, 224, 3)], np.uint8)
    with p:
        for i in range(num_buffers):
            b = p["out"].pull(timeout=120)
            labels = b.tensors[0].np()
            print(f"buffer {i}: top-1 classes {labels.tolist()}")
    print("transform fused into the filter:",
          bool(next(e for e in p.elements.values()
                    if e.FACTORY == "tensor_filter")._fused_pre))


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 8)

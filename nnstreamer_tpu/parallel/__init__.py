"""Multi-chip / multi-host layer: the TPU-native replacement of the
reference's inter-device stack (L5 — tensor_query/edge/mqtt/grpc,
SURVEY.md §2.5) and of its external nnstreamer-edge communication backend.

Where the reference moves tensors between devices over TCP/MQTT sockets
(`nns_edge_send`, /root/reference/gst/nnstreamer/tensor_query/
tensor_query_client.c:541-557), a TPU pod moves them over ICI: a pipeline
stage is *sharded* onto a `jax.sharding.Mesh` and XLA inserts the
collectives.  This package provides:

- :mod:`placement` — THE placement layer: the declarative
  ``mesh=``/``sharding=``/``devices=`` spec, its resolution to a built
  mesh (DCN axes included), and the canonical key every equivalent
  spelling dedups to (ModelPool / shared-instance identity);
- :mod:`mesh` — mesh construction/discovery over local or pod devices;
- :mod:`sharded` — sharded model invoke (data/model-parallel pjit) and the
  sharded training step used by the trainer element;
- :mod:`collectives` — shard_map stream primitives (ring exchange,
  all-gather fan-in, scatter fan-out) that implement mux/merge/demux
  semantics across chips.
"""

from .mesh import (  # noqa: F401
    MeshSpec,
    local_device_count,
    make_mesh,
    parse_device_indices,
)
from .multihost import hybrid_mesh, initialize, process_info  # noqa: F401
from .placement import (  # noqa: F401
    Placement,
    ResolvedPlacement,
    parse_accel_kind,
)
from .sharded import (  # noqa: F401
    PARAM_RULES,
    ShardedModel,
    batch_sharding,
    get_param_rules,
    mobilenet_param_rules,
    register_param_rules,
    replicated,
    replicated_param_rules,
    shard_params,
    train_step,
)

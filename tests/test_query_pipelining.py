"""tensor_query_client request pipelining, out-of-order completion, and
mid-stream failover.

Parity: the reference client overlaps requests through an async answer
queue while its edge thread keeps receiving
(/root/reference/gst/nnstreamer/tensor_query/tensor_query_client.c:673-741).
These tests drive the equivalent here: with a server that injects latency
per request, a pipelined client must sustain ≈ max_request requests in
flight (≥4× the serial 1/RTT rate), tolerate replies arriving out of
order, and fail over to an alternate server mid-stream.
"""

import threading
import time

import numpy as np

from nnstreamer_tpu.core import Buffer, TensorsSpec
from nnstreamer_tpu.edge import Envelope, MSG_QUERY
from nnstreamer_tpu.edge.transport import InprocServer
from nnstreamer_tpu.edge.wire import MSG_REPLY
from nnstreamer_tpu.elements.basic import AppSink, AppSrc
from nnstreamer_tpu.runtime import Pipeline
from nnstreamer_tpu.runtime.registry import make

SPEC = TensorsSpec.parse("4:1", "float32")


class DelayServer:
    """Inproc server that answers each query after ``delay`` seconds,
    each on its own timer thread (replies overlap like a pipelined remote
    pipeline's would)."""

    def __init__(self, host: str, port: int, delay: float,
                 reorder: bool = False):
        self.transport = InprocServer(host, port)
        self.transport.on_message = self._on_message
        self.transport.caps_provider = lambda: ""
        self.delay = delay
        self.reorder = reorder
        self.received = 0
        self._pair = []  # reorder: hold one request back, reply in reverse

    def start(self):
        self.transport.start()
        return self

    def stop(self):
        self.transport.stop()

    def _reply(self, client_id: int, env: Envelope):
        out = Buffer.of(env.buffer.tensors[0].np() * 2.0)
        self.transport.send(client_id, Envelope(
            MSG_REPLY, client_id=client_id, seq=env.seq, buffer=out))

    def _on_message(self, client_id: int, env: Envelope):
        if env.mtype != MSG_QUERY or env.buffer is None:
            return
        self.received += 1
        if self.reorder:
            # reply to pairs in reverse order: (2,1), (4,3), …
            self._pair.append((client_id, env))
            if len(self._pair) == 2:
                pair, self._pair = self._pair, []
                for cid, e in reversed(pair):
                    self._reply(cid, e)
            return
        t = threading.Timer(self.delay, self._reply, (client_id, env))
        t.daemon = True
        t.start()


def _client(host, port, **kw):
    p = Pipeline(name="qp-client")
    src = AppSrc(name="src", spec=SPEC)
    cli = make("tensor_query_client", el_name="cli", host=host, port=port,
               connect_type="inproc", timeout=10000, **kw)
    snk = AppSink(name="out", max_buffers=256)
    p.add(src, cli, snk).link(src, cli, snk)
    return p, src, cli, snk


def _drain(snk):
    out = []
    while True:
        b = snk.pull(timeout=0.3)
        if b is None:
            return out
        out.append(b)


class TestPipelining:
    def test_throughput_beats_serial_by_4x(self):
        delay, n = 0.2, 16
        srv = DelayServer("inproc-qp-thr", 7201, delay).start()
        try:
            p, src, cli, snk = _client("inproc-qp-thr", 7201,
                                       max_request=16)
            with p:
                t0 = time.perf_counter()
                for i in range(n):
                    src.push_buffer(Buffer.of(
                        np.full((1, 4), float(i), np.float32), pts=i))
                src.end_of_stream()
                assert p.wait_eos(timeout=30)
                elapsed = time.perf_counter() - t0
                out = _drain(snk)
        finally:
            srv.stop()
        serial = n * delay  # the old send-then-block chain's floor
        assert len(out) == n and cli.dropped == 0
        assert elapsed < serial / 4, \
            f"pipelined run took {elapsed:.2f}s vs serial floor {serial:.2f}s"
        for i, b in enumerate(out):  # stream order and per-seq matching
            assert b.pts == i
            np.testing.assert_array_equal(
                b.tensors[0].np(), np.full((1, 4), 2.0 * i, np.float32))

    def test_out_of_order_replies_push_in_stream_order(self):
        srv = DelayServer("inproc-qp-ooo", 7202, 0.0, reorder=True).start()
        try:
            p, src, cli, snk = _client("inproc-qp-ooo", 7202, max_request=8)
            with p:
                for i in range(8):
                    src.push_buffer(Buffer.of(
                        np.full((1, 4), float(i), np.float32), pts=i))
                src.end_of_stream()
                assert p.wait_eos(timeout=30)
                out = _drain(snk)
        finally:
            srv.stop()
        assert [b.pts for b in out] == list(range(8))
        for i, b in enumerate(out):
            np.testing.assert_array_equal(
                b.tensors[0].np(), np.full((1, 4), 2.0 * i, np.float32))

    def test_midstream_failover_resends_inflight(self):
        a = DelayServer("inproc-qp-a", 7203, 0.05).start()
        b = DelayServer("inproc-qp-b", 7204, 0.05).start()
        try:
            p, src, cli, snk = _client(
                "inproc-qp-a", 7203, max_request=8,
                alternate_hosts="inproc-qp-b:7204")
            with p:
                src.push_buffer(Buffer.of(np.zeros((1, 4), np.float32),
                                          pts=0))
                first = snk.pull(timeout=5)  # server A answered request 0
                assert first is not None and first.pts == 0
                # kill the primary with requests already flowing
                a.stop()
                for i in range(1, 6):
                    src.push_buffer(Buffer.of(
                        np.full((1, 4), float(i), np.float32), pts=i))
                src.end_of_stream()
                assert p.wait_eos(timeout=30)
                out = _drain(snk)
        finally:
            b.stop()
        assert cli.connected_addr == ("inproc-qp-b", 7204)
        assert b.received >= 1  # at least the resent in-flight requests
        # every remaining frame answered exactly once, in order
        assert [x.pts for x in out] == list(range(1, 6))
        for x in out:
            np.testing.assert_array_equal(
                x.tensors[0].np(),
                np.full((1, 4), 2.0 * x.pts, np.float32))

"""``tensor_sparse_enc`` / ``tensor_sparse_dec`` — static⇄sparse format.

Parity target: /root/reference/gst/nnstreamer/elements/
gsttensor_sparseenc.c / gsttensor_sparsedec.c with the codec in
gsttensor_sparseutil.c (:31 ``gst_tensor_sparse_to_dense``, :116
``gst_tensor_sparse_from_dense``): sparse wire layout = meta header +
nnz + u32 index list + values (core/buffer.py sparse codec).

Use case parity: shrinking the wire for inter-device streams whose
tensors are mostly zero (e.g. one-hot/activation-sparse outputs) before
an edge/query hop.
"""

from __future__ import annotations

from ..core import Buffer, Caps, TensorFormat, TensorsSpec
from ..core.buffer import sparse_from_dense, sparse_to_dense
from ..core.types import MIMETYPE_TENSORS
from ..core.caps import CapsStruct
from ..runtime.element import NegotiationError, Pad, TransformElement
from ..runtime.registry import register_element


@register_element("tensor_sparse_enc")
class TensorSparseEnc(TransformElement):
    FACTORY = "tensor_sparse_enc"

    def propose_src_caps(self, pad: Pad) -> Caps:
        in_spec = self.sinkpad.spec
        if in_spec is None:
            raise NegotiationError(f"{self.name}: no input caps")
        return Caps.from_spec(TensorsSpec(
            format=TensorFormat.SPARSE, rate=in_spec.rate))

    def transform(self, buf: Buffer) -> Buffer:
        from ..core import Tensor, TensorSpec
        import numpy as np

        payloads = [sparse_from_dense(t) for t in buf.tensors]
        tensors = [
            Tensor(np.frombuffer(p, np.uint8),
                   TensorSpec.from_shape((len(p),), np.uint8))
            for p in payloads]
        return Buffer(tensors=tensors, pts=buf.pts, duration=buf.duration,
                      format=TensorFormat.SPARSE, meta=dict(buf.meta))


@register_element("tensor_sparse_dec")
class TensorSparseDec(TransformElement):
    FACTORY = "tensor_sparse_dec"

    def pad_template_caps(self, pad: Pad) -> Caps:
        if pad.direction.value == "sink":
            return Caps.new(CapsStruct.make(
                MIMETYPE_TENSORS, format="sparse"))
        return Caps.any_tensors()

    def propose_src_caps(self, pad: Pad) -> Caps:
        in_spec = self.sinkpad.spec
        rate = in_spec.rate if in_spec is not None else None
        # payload schema travels per-buffer in the sparse meta header
        return Caps.from_spec(TensorsSpec(
            format=TensorFormat.FLEXIBLE,
            rate=rate if rate is not None else 0))

    def transform(self, buf: Buffer) -> Buffer:
        tensors = [sparse_to_dense(t.tobytes()) for t in buf.tensors]
        return Buffer(tensors=tensors, pts=buf.pts, duration=buf.duration,
                      format=TensorFormat.FLEXIBLE, meta=dict(buf.meta))

"""``pose_estimation`` decoder: keypoint heatmaps → skeleton overlay.

Parity target: /root/reference/ext/nnstreamer/tensor_decoder/
tensordec-pose.c (845 LoC): decodes PoseNet-style heatmaps (H, W, K) into
K keypoint coordinates (per-keypoint argmax + score), draws the skeleton
connecting them; option grammar:

- option1 — output size ``WIDTH:HEIGHT``
- option2 — model input size ``WIDTH:HEIGHT``
- option3 — optional label file of keypoint names
- option4 — ``heatmap-offset`` mode: refine coords with offset tensors
  (second input tensor of shape (H, W, 2K)), as posenet emits

Structured keypoints are attached at ``buffer.meta["keypoints"]``.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..core import Buffer, Caps, CapsStruct, Tensor, TensorSpec, TensorsSpec
from . import Decoder, JitFnCache, register_decoder
from .boxutil import load_labels, sigmoid

# COCO-17 style skeleton edge list (parity: pose.c connection table)
_EDGES: Tuple[Tuple[int, int], ...] = (
    (0, 1), (1, 3), (0, 2), (2, 4), (0, 5), (0, 6), (5, 7), (7, 9),
    (6, 8), (8, 10), (5, 11), (6, 12), (11, 13), (13, 15), (12, 14),
    (14, 16), (11, 12))

#: (shape, with_offsets) → jitted reduction (shared bounded cache)
_kp_fns = JitFnCache()


def _keypoint_prereduce_fn(shape, with_offsets: bool):
    """Device pre-reduction for PoseNet heatmaps: per-keypoint argmax,
    peak score and (optionally) the two offset values gather in HBM —
    only (K, 3) or (K, 5) float32 rows [y, x, raw_score, dy, dx] drain
    to host, once, instead of the full (H, W, K) heatmap volume."""
    def build():
        import jax
        import jax.numpy as jnp

        def f(hm, off=None):
            hm3 = hm.reshape(hm.shape[-3], hm.shape[-2], hm.shape[-1])
            h, w, k = hm3.shape
            flat = hm3.reshape(h * w, k)
            peak = jnp.argmax(flat, axis=0)          # (K,) flat indices
            y, x = peak // w, peak % w
            kidx = jnp.arange(k)
            score = flat[peak, kidx]
            cols = [y.astype(jnp.float32), x.astype(jnp.float32),
                    score.astype(jnp.float32)]
            if off is not None:
                off3 = off.reshape(off.shape[-3], off.shape[-2],
                                   off.shape[-1])
                cols.append(off3[y, x, kidx].astype(jnp.float32))      # dy
                cols.append(off3[y, x, k + kidx].astype(jnp.float32))  # dx
            return jnp.stack(cols, axis=1)

        return jax.jit(f)

    return _kp_fns.get_or_build((tuple(shape), bool(with_offsets)),
                                build)


@register_decoder
class PoseEstimation(Decoder):
    MODE = "pose_estimation"

    def __init__(self):
        super().__init__()
        self.out_w, self.out_h = 192, 192
        self.in_w, self.in_h = 192, 192
        self.names: List[str] = []
        self.use_offsets = False

    def options_updated(self) -> None:
        if self.options[0]:
            w, _, h = self.options[0].partition(":")
            self.out_w, self.out_h = int(w), int(h or w)
        if self.options[1]:
            w, _, h = self.options[1].partition(":")
            self.in_w, self.in_h = int(w), int(h or w)
        if self.options[2]:
            self.names = load_labels(self.options[2])
        if self.options[3]:
            self.use_offsets = self.options[3].strip() == "heatmap-offset"

    def out_caps(self, in_spec: TensorsSpec) -> Caps:
        return Caps.new(CapsStruct.make(
            "video/x-raw", format="RGBA", width=self.out_w,
            height=self.out_h, framerate=in_spec.rate))

    def prereduce_active(self, buf: Buffer) -> bool:
        t = buf.tensors[0]
        if not t.is_device or len(t.spec.shape) < 3:
            return False
        if self.use_offsets and buf.num_tensors > 1:
            return buf.tensors[1].is_device
        return True

    def _keypoint_rows(self, buf: Buffer):
        """(K, 3|5) rows of [y, x, raw_score(, dy, dx)] — on device via
        the pre-reduction program when the heatmaps are device-resident
        (one small drain), else computed from the host arrays."""
        t0 = buf.tensors[0]
        with_off = self.use_offsets and buf.num_tensors > 1
        if self.prereduce_active(buf):
            fn = _keypoint_prereduce_fn(t0.spec.shape, with_off)
            dev = fn(t0.jax(), buf.tensors[1].jax()) if with_off \
                else fn(t0.jax())
            rows = Tensor(dev).np()  # the one counted d2h drain
        else:
            hm = t0.np()
            hm = hm.reshape(hm.shape[-3], hm.shape[-2], hm.shape[-1])
            H, W, K = hm.shape
            flat = hm.reshape(H * W, K)
            peak = flat.argmax(axis=0)
            y, x = peak // W, peak % W
            kidx = np.arange(K)
            cols = [y.astype(np.float32), x.astype(np.float32),
                    flat[peak, kidx].astype(np.float32)]
            if with_off:
                off = buf.tensors[1].np()
                off = off.reshape(off.shape[-3], off.shape[-2],
                                  off.shape[-1])
                cols.append(off[y, x, kidx].astype(np.float32))
                cols.append(off[y, x, K + kidx].astype(np.float32))
            rows = np.stack(cols, axis=1)
        hshape = t0.spec.shape
        return rows, hshape[-3], hshape[-2]

    def _keypoints(self, buf: Buffer) -> List[dict]:
        rows, H, W = self._keypoint_rows(buf)
        kps = []
        for k, r in enumerate(rows):
            y, x = int(r[0]), int(r[1])
            score = float(sigmoid(np.asarray(r[2])))
            if rows.shape[1] > 3:
                # posenet layout: first K channels = dy, next K = dx
                py = (y / max(H - 1, 1)) * self.in_h + r[3]
                px = (x / max(W - 1, 1)) * self.in_w + r[4]
                nx, ny = px / self.in_w, py / self.in_h
            else:
                nx, ny = x / max(W - 1, 1), y / max(H - 1, 1)
            kps.append({
                "index": k,
                "name": self.names[k] if k < len(self.names) else str(k),
                "x": float(np.clip(nx, 0, 1)),
                "y": float(np.clip(ny, 0, 1)),
                "score": score})
        return kps

    def _draw(self, kps: List[dict]) -> np.ndarray:
        img = np.zeros((self.out_h, self.out_w, 4), np.uint8)
        green = np.array([0, 255, 0, 255], np.uint8)
        white = np.array([255, 255, 255, 255], np.uint8)
        for a, b in _EDGES:
            if a >= len(kps) or b >= len(kps):
                continue
            x0, y0 = kps[a]["x"] * (self.out_w - 1), \
                kps[a]["y"] * (self.out_h - 1)
            x1, y1 = kps[b]["x"] * (self.out_w - 1), \
                kps[b]["y"] * (self.out_h - 1)
            n = int(max(abs(x1 - x0), abs(y1 - y0))) + 1
            xs = np.linspace(x0, x1, n).astype(int)
            ys = np.linspace(y0, y1, n).astype(int)
            img[ys, xs] = white
        for kp in kps:
            x = int(kp["x"] * (self.out_w - 1))
            y = int(kp["y"] * (self.out_h - 1))
            img[max(y - 1, 0):y + 2, max(x - 1, 0):x + 2] = green
        return img

    def decode(self, buf: Buffer, in_spec: Optional[TensorsSpec]) -> Buffer:
        kps = self._keypoints(buf)
        frame = self._draw(kps)
        out = Buffer(
            tensors=[Tensor(frame,
                            TensorSpec.from_shape(frame.shape, np.uint8))],
            pts=buf.pts, duration=buf.duration, meta=dict(buf.meta))
        out.meta["keypoints"] = kps
        return out

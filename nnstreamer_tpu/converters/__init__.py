"""External converter sub-plugins (L3).

Parity target: ``NNStreamerExternalConverter`` ABI
(/root/reference/gst/nnstreamer/include/nnstreamer_plugin_api_converter.h:41-85):
``query_caps``, ``get_out_config``, ``convert``, keyed by mimetype.
Built-ins: ``flexbuf`` (this framework's flexible-tensor wire format) and
``python3`` (user callable).  protobuf/flatbuf wire codecs live in
nnstreamer_tpu.edge.wire and register here when available.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from ..core import Buffer, CapsStruct, TensorsSpec

_lock = threading.Lock()
_converters: Dict[str, "ExternalConverter"] = {}


class ExternalConverter:
    """Sub-plugin converting foreign-mimetype payloads into tensor buffers."""

    NAME = ""
    MIMES: tuple = ()

    def get_out_config(self, caps: CapsStruct) -> TensorsSpec:
        raise NotImplementedError

    def convert(self, buf: Buffer, caps: CapsStruct) -> Buffer:
        raise NotImplementedError


def register_converter(conv: ExternalConverter) -> ExternalConverter:
    with _lock:
        for m in conv.MIMES:
            _converters[m] = conv
        if conv.NAME:
            _converters[conv.NAME] = conv
    return conv


def find_converter(mime_or_name: str) -> Optional[ExternalConverter]:
    with _lock:
        return _converters.get(mime_or_name)


def list_converters():
    with _lock:
        return sorted({c.NAME for c in _converters.values()})

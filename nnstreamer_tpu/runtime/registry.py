"""Element factory registry + sub-plugin discovery.

Parity target: /root/reference/gst/nnstreamer/nnstreamer_subplugin.c:225
(``register_subplugin`` name→vtable hash, lazy discovery) and the element
registration table in registerer/nnstreamer.c:92-124.  Instead of dlopen'ing
.so files, discovery imports Python entry-point modules listed in the conf
system (utils/conf.py) — the TPU-native analog of the plugin search path.
"""

from __future__ import annotations

import importlib
import threading
from typing import Callable, Dict, Optional, Type

from .element import Element

_lock = threading.Lock()
_scan_lock = threading.Lock()  # held across the builtin imports
_factories: Dict[str, Type[Element]] = {}
_scanned = False


def register_element(name: Optional[str] = None) -> Callable:
    """Class decorator: ``@register_element("tensor_converter")``."""

    def deco(cls: Type[Element]) -> Type[Element]:
        fname = name or cls.FACTORY
        if not fname:
            raise ValueError(f"{cls.__name__} has no factory name")
        cls.FACTORY = fname
        with _lock:
            _factories[fname] = cls
        return cls

    return deco


def element_factory(name: str) -> Type[Element]:
    _ensure_scanned()
    with _lock:
        try:
            return _factories[name]
        except KeyError:
            known = ", ".join(sorted(_factories))
            raise KeyError(
                f"no element factory {name!r}; known: {known}") from None


def make(name: str, el_name: Optional[str] = None, **props) -> Element:
    """Parity: gst_element_factory_make."""
    return element_factory(name)(name=el_name, **props)


def list_elements():
    _ensure_scanned()
    with _lock:
        return sorted(_factories)


_BUILTIN_MODULES = [
    "nnstreamer_tpu.elements",
    "nnstreamer_tpu.filters",
    "nnstreamer_tpu.decoders",
    "nnstreamer_tpu.converters",
    "nnstreamer_tpu.edge",
]


def _ensure_scanned() -> None:
    """Lazy one-shot import of built-in element modules plus any extra
    modules configured via the conf system (parity: lazy g_module_open,
    nnstreamer_subplugin.c:108-137)."""
    global _scanned
    if _scanned:
        return
    # Concurrent callers block here until the import pass completes; the
    # flag is only set on success so a failed pass retries next call.
    with _scan_lock:
        if _scanned:
            return
        from ..utils.conf import get_conf

        mods = list(_BUILTIN_MODULES)
        mods += get_conf().extra_plugin_modules
        for m in mods:
            try:
                # nns-lint: disable=NNS303 -- intentional: concurrent
                # factory lookups must block until the one-shot builtin
                # import pass completes, or they'd see a partial registry
                importlib.import_module(m)
            except ImportError as e:
                # Built-ins must import; configured extras may be absent.
                if m in _BUILTIN_MODULES:
                    raise
                import logging

                logging.getLogger("nnstreamer_tpu").warning(
                    "plugin module %s failed to import: %s", m, e)
        _scanned = True

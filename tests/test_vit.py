"""ViT model family: functional correctness + filter integration +
flash-attention path consistency."""

import numpy as np
import pytest

from nnstreamer_tpu.models.vit import register_vit, vit_apply, vit_init


@pytest.fixture(scope="module")
def tiny():
    import jax

    params = vit_init(jax.random.PRNGKey(0), image_size=32, patch=8,
                      dim=256, depth=2, heads=2, mlp_dim=128,
                      num_classes=5)
    x = np.random.default_rng(0).standard_normal(
        (2, 32, 32, 3)).astype(np.float32)
    return params, x


class TestViT:
    def test_logits_shape_and_finite(self, tiny):
        import jax

        params, x = tiny
        y = jax.jit(lambda p, xx: vit_apply(p, xx, heads=2))(params, x)
        y = np.asarray(y)
        assert y.shape == (2, 5) and y.dtype == np.float32
        assert np.isfinite(y).all()

    def test_flash_and_reference_attention_agree(self, tiny):
        """dh=128 engages the Pallas kernel; forcing the jnp reference
        (via a non-tiling head dim) must give the same logits."""
        import jax

        params, x = tiny
        y_kernel = np.asarray(jax.jit(
            lambda p, xx: vit_apply(p, xx, heads=2))(params, x))
        # heads=4 → dh=64: flash_attention falls back to the reference
        # math but splits heads differently, so instead compare the same
        # config with the kernel disabled through monkeypatching
        from nnstreamer_tpu.ops import kernels

        orig = kernels.flash_attention
        try:
            kernels.flash_attention = kernels.flash_attention_reference
            import nnstreamer_tpu.ops as ops

            ops.flash_attention = kernels.flash_attention_reference
            y_ref = np.asarray(jax.jit(
                lambda p, xx: vit_apply(p, xx, heads=2))(params, x))
        finally:
            kernels.flash_attention = orig
            ops.flash_attention = orig
        np.testing.assert_allclose(y_kernel, y_ref, rtol=5e-2, atol=5e-2)

    def test_pipeline_through_filter(self, tiny):
        from fractions import Fraction

        from nnstreamer_tpu.core import Buffer, TensorsSpec
        from nnstreamer_tpu.runtime import parse_launch

        name = register_vit("vit_pipe_test", batch=1, image_size=32,
                            patch=8, dim=256, depth=1, heads=2,
                            mlp_dim=128, num_classes=5)
        p = parse_launch(
            "appsrc name=src ! tensor_transform mode=arithmetic "
            "option=typecast:float32,div:255.0 ! "
            f"tensor_filter framework=jax-xla model={name} ! "
            "appsink name=out")
        p["src"].spec = TensorsSpec.from_shapes([(1, 32, 32, 3)], np.uint8,
                                                rate=Fraction(10))
        x = np.random.default_rng(1).integers(0, 255, (1, 32, 32, 3),
                                              np.uint8)
        with p:
            p["src"].push_buffer(Buffer.of(x))
            p["src"].end_of_stream()
            assert p.wait_eos(timeout=120)
            got = p["out"].pull(timeout=1)
        assert got.tensors[0].np().shape == (1, 5)

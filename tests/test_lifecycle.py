"""`runtime/lifecycle.py` + `runtime/compilecache.py` — the
zero-downtime model lifecycle (ISSUE-14 surface).

Double-buffered hot swap on a live shared pool (staged + warmed
off-path, window-boundary flip, zero frame loss), canary routing with
per-version stats / FIFO demux / error isolation, the promote /
rollback verdict machinery and its actuators (incl. the 3-thread
swap-vs-start/stop race mirroring PR 11's harness), the persistent AOT
compile cache (hit/miss/store, corruption and version-skew fallback,
persist_hit CompileStats accounting), versioned model URIs + orbax
step-dir resolution, snapshot v7 `models` table + `nns_model_*`
export, the nns-top MODELS section, and NNS513's runtime counterparts.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.core import Buffer, TensorsSpec
from nnstreamer_tpu.elements.basic import AppSink, AppSrc, Queue
from nnstreamer_tpu.elements.filter import TensorFilter
from nnstreamer_tpu.filters.api import FilterProps
from nnstreamer_tpu.filters.jax_xla import (JaxXlaFilter,
                                            register_model,
                                            unregister_model)
from nnstreamer_tpu.filters.modeluri import (ModelUriError,
                                             resolve_model_uri,
                                             resolve_model_uri_versioned,
                                             split_model_version)
from nnstreamer_tpu.obs.metrics import REGISTRY
from nnstreamer_tpu.runtime import Pipeline
from nnstreamer_tpu.runtime import compilecache
from nnstreamer_tpu.runtime.actuators import (ActuationError,
                                              find_actuators,
                                              list_actuators)
from nnstreamer_tpu.runtime.lifecycle import (LifecycleError,
                                              parse_canary)
from nnstreamer_tpu.runtime.serving import MODEL_POOL
from nnstreamer_tpu.utils.stats import COMPILE_STATS

SHAPE = (4,)


@pytest.fixture(scope="module", autouse=True)
def _models():
    register_model("_t_lc", lambda x: x + 1.0, in_shapes=[SHAPE],
                   in_dtypes=np.float32)
    register_model("_t_lc_v2", lambda x: x + 3.0, in_shapes=[SHAPE],
                   in_dtypes=np.float32)
    yield
    for n in ("_t_lc", "_t_lc_v2"):
        unregister_model(n)


@pytest.fixture(autouse=True)
def _clean_pool():
    yield
    MODEL_POOL.clear()


def _pool_pipe(name, batch=4, canary="", timeout_ms=2.0,
               sample_ms=10.0):
    spec = TensorsSpec.from_shapes([SHAPE], np.float32)
    p = Pipeline(name=name)
    src = AppSrc(name="src", spec=spec, max_buffers=64)
    q = Queue(name="q", max_size_buffers=64)
    flt = TensorFilter(name="net", framework="jax-xla", model="_t_lc",
                       batch=batch, batch_timeout_ms=timeout_ms,
                       batch_buckets=str(batch), share_model=True,
                       is_updatable=True, canary=canary,
                       stat_sample_interval_ms=sample_ms)
    sink = AppSink(name="sink", max_buffers=256)
    p.add(src, q, flt, sink).link(src, q, flt, sink)
    return p, {"src": src, "q": q, "flt": flt, "sink": sink}


def _push_n(src, n, start=0):
    for i in range(n):
        src.push_buffer(Buffer.of(np.zeros(SHAPE, np.float32),
                                  pts=start + i), timeout=2.0)


def _pull_all(sink, expect, timeout=10.0):
    out, deadline = [], time.monotonic() + timeout
    while len(out) < expect and time.monotonic() < deadline:
        b = sink.pull(timeout=0.2)
        if b is not None:
            out.append(b)
    return out


def _vals(bufs):
    return [float(np.asarray(b.tensors[0].np()).ravel()[0])
            for b in bufs]


# -- canary grammar -----------------------------------------------------------


def test_parse_canary_grammar():
    assert parse_canary("") == ("", 0)
    assert parse_canary("next:1/4") == ("next", 4)
    assert parse_canary("v7:1/2") == ("v7", 2)
    assert parse_canary("1/8") == ("next", 8)
    for bad in ("2/3", "next:2/4", "1/1", "x", "1/0", "next:"):
        with pytest.raises(LifecycleError):
            parse_canary(bad)


# -- versioned model URIs (satellite) -----------------------------------------


def test_split_model_version(tmp_path):
    assert split_model_version("m.pkl@v2") == ("m.pkl", "v2")
    assert split_model_version("plain.pkl") == ("plain.pkl", "")
    assert split_model_version(123) == (123, "")
    # a file literally named with an '@' never splits
    lit = tmp_path / "x@y.pkl"
    lit.write_bytes(b"")
    assert split_model_version(str(lit)) == (str(lit), "")


def test_versioned_file_uri_resolves_with_tag(tmp_path):
    f = tmp_path / "net.pkl"
    f.write_bytes(b"stub")
    model, tag = resolve_model_uri_versioned(f"file://{f}@v2")
    assert model == str(f) and tag == "v2"
    # untagged keeps the old contract
    assert resolve_model_uri(f"file://{f}") == str(f)


def test_versioned_uri_unresolvable_suffix_is_a_clear_error(tmp_path):
    missing = tmp_path / "nope.pkl"
    with pytest.raises(ModelUriError, match="@v9"):
        resolve_model_uri_versioned(f"file://{missing}@v9")
    # a PLAIN string whose base names nothing on disk is a name, not a
    # versioned path: it passes through untouched (an in-process
    # registered model of any framework may contain '@')
    ref = str(missing) + "@v9"
    assert resolve_model_uri_versioned(ref) == (ref, "")


def test_orbax_step_dir_resolution(tmp_path):
    from nnstreamer_tpu.trainers.checkpoint import (latest_step,
                                                    list_steps,
                                                    resolve_step_dir)

    root = tmp_path / "ckpts"
    for step in (100, 200, 250):
        (root / str(step)).mkdir(parents=True)
    assert list_steps(str(root)) == [100, 200, 250]
    assert latest_step(str(root)) == 250
    path, tag = resolve_model_uri_versioned(f"{root}@latest")
    assert path == str(root / "250") and tag == "250"
    path, tag = resolve_model_uri_versioned(f"{root}@100")
    assert path == str(root / "100") and tag == "100"
    with pytest.raises(ModelUriError, match="@999"):
        resolve_model_uri_versioned(f"{root}@999")
    with pytest.raises(ValueError):
        resolve_step_dir(str(root), "not-a-step")


def test_registered_name_with_at_never_splits():
    register_model("_t_lc@weird", lambda x: x * 2.0,
                   in_shapes=[SHAPE], in_dtypes=np.float32)
    try:
        assert resolve_model_uri_versioned("_t_lc@weird") == \
            ("_t_lc@weird", "")
    finally:
        unregister_model("_t_lc@weird")


# -- prepare/commit swap (framework level) ------------------------------------


def test_prepare_swap_builds_warm_shadow_and_commit_flips():
    sp = JaxXlaFilter()
    sp.configure(FilterProps(framework="jax-xla", model="_t_lc"))
    x = np.ones(SHAPE, np.float32)
    sp.invoke_batched([[x]] * 2, 2)
    assert sp.hot_buckets() == (2,)
    before = {(r["kind"], r["bucket"]): r["count"]
              for r in COMPILE_STATS.snapshot()}
    shadow = sp.prepare_swap("_t_lc_v2")
    after = {(r["kind"], r["bucket"]): r["count"]
             for r in COMPILE_STATS.snapshot()}
    # the OLD model still serves: nothing flipped yet
    out = sp.invoke([x])
    assert float(np.asarray(out[0])[0]) == 2.0
    # the shadow's configure compile counts as a reload, and the hot
    # bucket recompiled off-path
    assert after.get(("reload", "0"), 0) - before.get(("reload", "0"),
                                                      0) == 1
    assert after.get(("bucket", "2"), 0) - before.get(("bucket", "2"),
                                                      0) == 1
    sp.commit_swap(shadow)
    out = sp.invoke([x])
    assert float(np.asarray(out[0])[0]) == 4.0
    # the transplanted bucket executable serves without a recompile
    outs = sp.invoke_batched([[x]] * 2, 2)
    assert float(np.asarray(outs[0][0])[0]) == 4.0
    final = {(r["kind"], r["bucket"]): r["count"]
             for r in COMPILE_STATS.snapshot()}
    assert final.get(("bucket", "2")) == after.get(("bucket", "2"))
    sp.close()


def test_prepare_swap_rejects_output_schema_change():
    def wide(x):
        import jax.numpy as jnp

        return jnp.concatenate([x, x])

    register_model("_t_lc_wide", wide, in_shapes=[SHAPE],
                   in_dtypes=np.float32)
    try:
        sp = JaxXlaFilter()
        sp.configure(FilterProps(framework="jax-xla", model="_t_lc"))
        from nnstreamer_tpu.filters.api import FilterError

        with pytest.raises(FilterError, match="output schema"):
            sp.prepare_swap("_t_lc_wide")
        sp.close()
    finally:
        unregister_model("_t_lc_wide")


def test_weights_only_swap_from_params_pytree():
    w0 = {"b": np.float32(1.0)}

    def apply(params, x):
        return x + params["b"]

    register_model("_t_lc_params", apply, params=w0,
                   in_shapes=[SHAPE], in_dtypes=np.float32)
    try:
        sp = JaxXlaFilter()
        sp.configure(FilterProps(framework="jax-xla",
                                 model="_t_lc_params"))
        x = np.zeros(SHAPE, np.float32)
        assert float(np.asarray(sp.invoke([x])[0])[0]) == 1.0
        shadow = sp.prepare_swap({"b": np.float32(9.0)})
        sp.commit_swap(shadow)
        assert float(np.asarray(sp.invoke([x])[0])[0]) == 9.0
        sp.close()
    finally:
        unregister_model("_t_lc_params")


# -- live pool hot swap -------------------------------------------------------


def test_pool_reload_hot_swaps_with_no_frame_loss():
    p, e = _pool_pipe("lc-swap", batch=4, timeout_ms=2.0)
    p.start()
    try:
        entry = e["flt"].pool
        _push_n(e["src"], 8)
        first = _pull_all(e["sink"], 8)
        assert _vals(first) == [1.0] * 8  # baseline x+1 on zeros
        res = entry.reload_model("_t_lc_v2", version="v2")
        assert res["version"] == "v2"
        lc = entry.lifecycle
        assert lc.swaps == 1 and lc.baseline.tag == "v2"
        assert lc.last_swap_stall_s < 1.0
        _push_n(e["src"], 8, start=100)
        swapped = _pull_all(e["sink"], 8)
        assert len(swapped) == 8  # no frame loss across the flip
        assert _vals(swapped) == [3.0] * 8  # v2: x+3 on zeros
        # provenance: the swap landed in the history trail
        assert any(ev["event"] == "swap" and ev["version"] == "v2"
                   for ev in lc.history)
    finally:
        p.stop()
    assert len(MODEL_POOL) == 0


def test_reload_event_routes_through_pool_and_respects_updatable():
    from nnstreamer_tpu.runtime.events import Event, EventKind

    p, e = _pool_pipe("lc-evt")
    p.start()
    try:
        e["flt"].handle_event(None, Event(
            EventKind.RELOAD_MODEL, data={"model": "_t_lc_v2",
                                          "version": "ev2"}))
        lc = e["flt"].pool.lifecycle
        assert lc.baseline.tag == "ev2" and lc.swaps == 1
    finally:
        p.stop()


def test_reload_event_not_updatable_posts_error():
    from nnstreamer_tpu.runtime.events import Event, EventKind

    spec = TensorsSpec.from_shapes([SHAPE], np.float32)
    p = Pipeline(name="lc-noupd")
    src = AppSrc(name="src", spec=spec)
    flt = TensorFilter(name="net", framework="jax-xla",
                       model="_t_lc", share_model=True)
    sink = AppSink(name="sink")
    p.add(src, flt, sink).link(src, flt, sink)
    errors = []
    from nnstreamer_tpu.runtime.events import MessageKind

    p.bus.add_watch(lambda m: errors.append(m)
                    if m.kind == MessageKind.ERROR else None)
    p.start()
    try:
        flt.handle_event(None, Event(EventKind.RELOAD_MODEL,
                                     data={"model": "_t_lc_v2"}))
        deadline = time.monotonic() + 5
        while not errors and time.monotonic() < deadline:
            time.sleep(0.01)
        assert errors, "expected a not-updatable error on the bus"
        lc = getattr(flt.pool, "_lifecycle", None)
        assert lc is None or lc.swaps == 0
    finally:
        p.stop()


# -- canary routing -----------------------------------------------------------


def _canary_rig(n_pipes=4, canary="next:1/2"):
    pipes = []
    for i in range(n_pipes):
        p, e = _pool_pipe(f"lc-can-{i}", batch=4, canary=canary)
        p.start()
        pipes.append((p, e))
    return pipes


def test_canary_routes_1_in_n_streams_with_per_version_fifo():
    pipes = _canary_rig(n_pipes=4, canary="next:1/2")
    try:
        entry = pipes[0][1]["flt"].pool
        res = entry.reload_model("_t_lc_v2", version="v2")
        assert res == {"version": "v2", "n": 2, "streams": 2}
        lc = entry.lifecycle
        assert lc.canary_active and lc.canary_n == 2
        n = 12
        threads = [threading.Thread(target=_push_n,
                                    args=(e["src"], n))
                   for _p, e in pipes]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        canary_streams = 0
        for _p, e in pipes:
            got = _pull_all(e["sink"], n)
            assert len(got) == n  # zero loss per stream
            # per-stream FIFO: pts strictly in order
            assert [b.pts for b in got] == sorted(b.pts for b in got)
            vals = set(_vals(got))
            # version-homogeneous stream: all frames served by ONE
            # version (1.0 = baseline x+1, 3.0 = canary x+3 on zeros)
            assert vals in ({1.0}, {3.0})
            if vals == {3.0}:
                canary_streams += 1
        assert canary_streams == 2  # exactly 1-in-2 of 4 streams
        summary = lc.summary()
        assert summary["canary_streams"] == 2
        assert summary["canary_frames"] == 2 * n
        # per-version rows land in the snapshot models table
        snap = REGISTRY.snapshot()
        assert snap["version"] == 10
        rows = {r["version"]: r for r in snap["models"]
                if r["pool"] == entry.label()}
        assert rows["v2"]["state"] == "canary"
        assert rows["v2"]["frames"] == 2 * n
        assert rows[lc.baseline.tag]["frames"] >= 2 * n
        lc.promote(force=True)
        assert not lc.canary_active and lc.baseline.tag == "v2"
    finally:
        for p, _e in pipes:
            p.stop()


def test_canary_rollback_restores_baseline_only_serving():
    pipes = _canary_rig(n_pipes=2, canary="next:1/2")
    try:
        entry = pipes[0][1]["flt"].pool
        entry.reload_model("_t_lc_v2", version="v2")
        lc = entry.lifecycle
        assert lc.canary_active
        res = lc.rollback()
        assert res["rolled_back"] and res["canary"]
        assert not lc.canary_active and lc.rollbacks == 1
        for _p, e in pipes:
            _push_n(e["src"], 4)
            got = _pull_all(e["sink"], 4)
            assert set(_vals(got)) == {1.0}  # baseline x+1 on zeros
    finally:
        for p, _e in pipes:
            p.stop()


def test_declared_canary_tag_gates_the_split():
    """`canary=v7:1/2` canaries only version v7: reloading any OTHER
    version cuts over directly (an undeclared version gets no split),
    while `next:1/N` canaries whatever gets staged."""
    pipes = _canary_rig(n_pipes=2, canary="v7:1/2")
    try:
        entry = pipes[0][1]["flt"].pool
        res = entry.reload_model("_t_lc_v2", version="v9")
        lc = entry.lifecycle
        assert not lc.canary_active  # v9 != v7: direct swap
        assert lc.swaps == 1 and res.get("version") == "v9"
        res = entry.reload_model("_t_lc", version="v7")
        assert lc.canary_active and res["n"] == 2  # declared tag
    finally:
        for p, _e in pipes:
            p.stop()


def test_actuator_discovery_does_not_engage_lifecycle_telemetry():
    """`nns-ctl --list` (list_actuators) builds a manager for every
    pool; a merely-discovered pool must NOT grow models rows or a
    lifecycle block — exported state changes only when the lifecycle
    is actually used."""
    p, e = _pool_pipe("lc-disc")
    p.start()
    try:
        entry = e["flt"].pool
        assert find_actuators("model", entry.label(), "swap")
        lc = entry._lifecycle
        assert lc is not None and not lc.engaged
        snap = REGISTRY.snapshot()
        assert not [r for r in snap["models"]
                    if r["pool"] == entry.label()]
        pool_row = [r for r in snap["pools"]
                    if r["pool"] == entry.label()][0]
        assert "lifecycle" not in pool_row
        entry.reload_model("_t_lc_v2")
        assert lc.engaged
        snap = REGISTRY.snapshot()
        assert [r for r in snap["models"]
                if r["pool"] == entry.label()]
    finally:
        p.stop()


def test_promote_refused_before_min_canary_frames():
    pipes = _canary_rig(n_pipes=2, canary="next:1/2")
    try:
        entry = pipes[0][1]["flt"].pool
        entry.reload_model("_t_lc_v2")
        lc = entry.lifecycle
        with pytest.raises(ActuationError, match="frames"):
            lc.promote()
        assert lc.canary_active  # still canarying; verdict deferred
    finally:
        for p, _e in pipes:
            p.stop()


def test_canary_error_isolated_to_canary_streams():
    register_model("_t_lc_boom", lambda x: x + 1.0,
                   in_shapes=[SHAPE], in_dtypes=np.float32)
    try:
        pipes = _canary_rig(n_pipes=2, canary="next:1/2")
        try:
            entry = pipes[0][1]["flt"].pool
            entry.reload_model("_t_lc_boom", version="vboom")
            lc = entry.lifecycle

            # break the canary's executable AFTER staging: every
            # canary window now raises while baseline serving stays
            # untouched
            def boom(*_a, **_k):
                raise RuntimeError("canary exploded")

            lc._canary.subplugin.invoke_batched = boom
            from nnstreamer_tpu.runtime.events import MessageKind

            errors = {i: [] for i in range(2)}
            for i, (p, _e) in enumerate(pipes):
                p.bus.add_watch(
                    lambda m, i=i: errors[i].append(m)
                    if m.kind == MessageKind.ERROR else None)
            canary_idx = [i for i, (_p, e) in enumerate(pipes)
                          if lc.is_canary_stream(e["flt"])]
            assert len(canary_idx) == 1
            for _p, e in pipes:
                _push_n(e["src"], 4)
            base_idx = 1 - canary_idx[0]
            got = _pull_all(pipes[base_idx][1]["sink"], 4)
            assert len(got) == 4 and set(_vals(got)) == {1.0}
            deadline = time.monotonic() + 5
            while not errors[canary_idx[0]] \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            assert errors[canary_idx[0]], "canary bus got no error"
            assert not errors[base_idx], "baseline bus polluted"
            assert lc._canary.errors >= 1
            # the error series feeds the rollback judge
            assert lc.summary()["canary_errors"] >= 1
        finally:
            for p, _e in pipes:
                p.stop()
    finally:
        unregister_model("_t_lc_boom")


# -- actuators ----------------------------------------------------------------


def test_model_actuators_swap_promote_rollback():
    p, e = _pool_pipe("lc-act")
    p.start()
    try:
        entry = e["flt"].pool
        acts = entry.lifecycle.actuators()
        assert set(acts) == {"swap", "canary", "promote", "rollback"}
        for a in acts.values():
            a.cooldown_s = 0.0
        res = acts["swap"].actuate("_t_lc_v2")
        assert res["applied"] == "_t_lc_v2"
        assert entry.lifecycle.baseline.tag == "v1"
        _push_n(e["src"], 4)
        assert set(_vals(_pull_all(e["sink"], 4))) == {3.0}
        # revert of a swap is a rollback to the retained prior
        acts["swap"].revert()
        assert entry.lifecycle.rollbacks == 1
        _push_n(e["src"], 4, start=50)
        assert set(_vals(_pull_all(e["sink"], 4))) == {1.0}
        # discovery: the model kind lists these knobs
        names = {(a.kind, a.name) for a in list_actuators("model")}
        assert ("model", "swap") in names
        assert find_actuators("model", entry.label(), "rollback")
    finally:
        p.stop()


def test_swap_rollback_actuators_race_pipeline_stop():
    """The PR-11 race harness on the lifecycle knobs: 3 threads
    hammering swap/revert while pipelines start, stream and stop —
    never a crash, torn-down targets fail with a clean
    ActuationError."""
    errors = []
    stop_evt = threading.Event()
    outcomes = {"ok": 0, "gone": 0}

    def actuator_thread():
        while not stop_evt.is_set():
            try:
                for act in list_actuators("model"):
                    if act.name not in ("swap", "rollback"):
                        continue
                    try:
                        act.cooldown_s = 0.0
                        if act.name == "swap":
                            act.actuate("_t_lc_v2")
                            act.revert()
                        else:
                            act.actuate(1.0)
                        outcomes["ok"] += 1
                    except ActuationError:
                        outcomes["gone"] += 1  # stop() won the race
            except Exception as exc:  # noqa: BLE001 - the assertion
                errors.append(exc)
                return

    # one long-lived sharer keeps the pool entry (and its lifecycle)
    # alive across rounds: a swap takes real compile time, so against
    # per-round entries alone EVERY actuation can lose the teardown
    # race and the "ok" leg would assert nothing.  The round pipes
    # still attach/detach streams and stop mid-actuation.
    keeper, ke = _pool_pipe("lc-race-keeper")
    keeper.start()
    threads = [threading.Thread(target=actuator_thread)
               for _ in range(3)]
    for t in threads:
        t.start()
    try:
        for round_i in range(5):
            p, e = _pool_pipe(f"lc-race-{round_i}")
            p.start()
            _push_n(e["src"], 4)
            e["src"].end_of_stream()
            p.wait_eos(timeout=10, raise_on_error=False)
            p.stop()
    finally:
        stop_evt.set()
        for t in threads:
            t.join(timeout=15)
        keeper.stop()
    assert not errors, errors
    assert outcomes["ok"] > 0


# -- persistent AOT compile cache ---------------------------------------------


def _heavyish(name):
    w = np.random.default_rng(3).standard_normal((32, 32)) \
        .astype(np.float32)

    def fn(x):
        import jax.numpy as jnp

        for _ in range(4):
            x = jnp.tanh(x @ w)
        return x

    register_model(name, fn, in_shapes=[(32,)], in_dtypes=np.float32)
    return name


def _persist_hits():
    return sum(r["count"] for r in COMPILE_STATS.snapshot()
               if r["kind"] == "persist_hit")


def test_persistent_cache_hits_and_counts(tmp_path, monkeypatch):
    monkeypatch.setenv("NNS_TPU_COMPILE_CACHE_DIR", str(tmp_path))
    name = _heavyish("_t_lc_pc1")
    try:
        x = np.zeros((32,), np.float32)

        def run():
            sp = JaxXlaFilter()
            sp.configure(FilterProps(framework="jax-xla", model=name))
            sp.invoke([x])[0].block_until_ready()
            outs = sp.invoke_batched([[x]] * 2, 2)
            for o in outs[0]:
                o.block_until_ready()
            sp.close()

        before = compilecache.CACHE_STATS.snapshot()
        hits0 = _persist_hits()
        run()  # populate: misses + stores, no hits
        mid = compilecache.CACHE_STATS.snapshot()
        assert mid["stores"] - before["stores"] == 2
        assert _persist_hits() == hits0
        run()  # fresh instance, warm cache: pure deserialize
        after = compilecache.CACHE_STATS.snapshot()
        assert after["hits"] - mid["hits"] == 2
        assert _persist_hits() - hits0 == 2
        # the registry exports the same persist_hit count
        fam = REGISTRY.collect()["nns_compiles_total"]
        exported = sum(s["value"] for s in fam["samples"]
                       if s["labels"].get("kind") == "persist_hit")
        assert exported == _persist_hits()
        assert len(os.listdir(str(tmp_path))) == 2
    finally:
        unregister_model(name)


def test_persistent_cache_corruption_falls_back(tmp_path, monkeypatch):
    monkeypatch.setenv("NNS_TPU_COMPILE_CACHE_DIR", str(tmp_path))
    name = _heavyish("_t_lc_pc2")
    try:
        x = np.zeros((32,), np.float32)

        def run():
            sp = JaxXlaFilter()
            sp.configure(FilterProps(framework="jax-xla", model=name))
            out = sp.invoke([x])
            out[0].block_until_ready()
            val = float(np.asarray(out[0])[0])
            sp.close()
            return val

        good = run()
        for f in os.listdir(str(tmp_path)):  # corrupt every entry
            with open(os.path.join(str(tmp_path), f), "wb") as fh:
                fh.write(b"not an executable")
        before = compilecache.CACHE_STATS.snapshot()
        assert run() == good  # recompiles, same result
        after = compilecache.CACHE_STATS.snapshot()
        assert after["errors"] > before["errors"]
        # the bad entries were dropped and re-stored
        assert after["stores"] > before["stores"]
    finally:
        unregister_model(name)


def test_persistent_cache_version_skew_misses(tmp_path, monkeypatch):
    """A jax/jaxlib version bump changes the KEY — a skewed process
    simply misses instead of deserializing an incompatible program."""
    monkeypatch.setenv("NNS_TPU_COMPILE_CACHE_DIR", str(tmp_path))
    name = _heavyish("_t_lc_pc3")
    try:
        x = np.zeros((32,), np.float32)
        sp = JaxXlaFilter()
        sp.configure(FilterProps(framework="jax-xla", model=name))
        sp.invoke([x])[0].block_until_ready()
        sp.close()
        n_entries = len(os.listdir(str(tmp_path)))
        monkeypatch.setattr(compilecache, "_versions",
                            lambda: ("99.0.0", "99.0.0"))
        before = compilecache.CACHE_STATS.snapshot()
        sp = JaxXlaFilter()
        sp.configure(FilterProps(framework="jax-xla", model=name))
        sp.invoke([x])[0].block_until_ready()
        sp.close()
        after = compilecache.CACHE_STATS.snapshot()
        assert after["hits"] == before["hits"]  # no cross-version hit
        assert after["misses"] > before["misses"]
        # the skewed build stored under ITS key; both coexist
        assert len(os.listdir(str(tmp_path))) > n_entries
    finally:
        unregister_model(name)


def test_cache_disabled_on_unwritable_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("NNS_TPU_COMPILE_CACHE_DIR",
                       str(tmp_path / "missing"))
    assert compilecache.cache_dir() is None
    assert not compilecache.enabled()
    assert compilecache.load("deadbeef") is None
    monkeypatch.setenv("NNS_TPU_COMPILE_CACHE_DIR", str(tmp_path))
    assert compilecache.cache_dir() == str(tmp_path)


# -- obs surface --------------------------------------------------------------


def test_pool_row_lifecycle_and_comparator_export():
    pipes = _canary_rig(n_pipes=2, canary="next:1/2")
    try:
        entry = pipes[0][1]["flt"].pool
        entry.reload_model("_t_lc_v2", version="v2")
        for _p, e in pipes:
            _push_n(e["src"], 8)
            _pull_all(e["sink"], 8)
        snap = REGISTRY.snapshot()
        pool_row = [r for r in snap["pools"]
                    if r["pool"] == entry.label()][0]
        lcrow = pool_row["lifecycle"]
        assert lcrow["canary_n"] == 2 and lcrow["canary_streams"] == 1
        fams = snap["metrics"]
        assert "nns_model_version_frames_total" in fams
        assert "nns_model_canary_frames_total" in fams
        # the comparator pair exports under the POOL label only
        for fam in ("nns_model_canary_latency_us",
                    "nns_model_baseline_latency_us"):
            if fam in fams:
                for s in fams[fam]["samples"]:
                    assert set(s["labels"]) == {"pool"}
        # nns-top renders the MODELS section
        from nnstreamer_tpu.obs.top import render

        txt = render(snap)
        assert "MODELS" in txt and "canary" in txt
        assert "1/2" in txt
    finally:
        for p, _e in pipes:
            p.stop()


def test_nns_ctl_swap_spec_parses_text_value():
    from nnstreamer_tpu.obs.control import _parse_spec

    kind, target, name, value = _parse_spec(
        "model:jax-xla:_t_lc:swap=file:///m.pkl@v2")
    assert (kind, name) == ("model", "swap")
    assert target == "jax-xla:_t_lc"
    assert value == "file:///m.pkl@v2"
    kind, target, name, value = _parse_spec("model:*:promote=1")
    assert value == 1.0


def test_controller_apply_routes_text_swap_through_audit():
    from nnstreamer_tpu.obs.control import Controller

    p, e = _pool_pipe("lc-ctl")
    p.start()
    try:
        entry = e["flt"].pool
        for a in entry.lifecycle.actuators().values():
            a.cooldown_s = 0.0
        ctl = Controller(playbooks=[])
        out = ctl.apply("model", entry.label(), "swap",
                        value="_t_lc_v2")
        assert out and out[0]["outcome"] == "applied"
        assert entry.lifecycle.swaps == 1
        # the decision landed in the audit ring like any playbook's
        assert any(d["actuator"] == "swap" for d in ctl.audit)
    finally:
        p.stop()

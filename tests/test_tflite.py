"""tensorflow-lite filter framework: .tflite import through XLA.

Parity target: the reference's flagship tflite sub-plugin and its
accuracy-bearing pipelines (/root/reference/ext/nnstreamer/
tensor_filter/tensor_filter_tensorflow_lite.cc:242-280;
tests/test_models/models/mobilenet_v2_1.0_224_quant.tflite classifying
tests/test_models/data/orange.png).  The semantic tests run the REAL
pretrained model on the REAL image and assert the REAL label — the
first accuracy-bearing coverage in the repo (round-3 verdict #2 of
"What's missing").
"""

import os

import numpy as np
import pytest

from nnstreamer_tpu.core import Buffer, TensorsSpec
from nnstreamer_tpu.elements.filter import FilterSingle
from nnstreamer_tpu.filters.api import FilterError
from nnstreamer_tpu.runtime import parse_launch

REF = "/root/reference/tests/test_models"
MODEL = os.path.join(REF, "models", "mobilenet_v2_1.0_224_quant.tflite")
SEG_MODEL = os.path.join(REF, "models", "deeplabv3_257_mv_gpu.tflite")
IMAGE = os.path.join(REF, "data", "orange.raw")
LABELS = os.path.join(REF, "labels", "labels.txt")

needs_assets = pytest.mark.skipif(
    not (os.path.isfile(MODEL) and os.path.isfile(IMAGE)
         and os.path.isfile(LABELS)),
    reason="reference test assets not present")
needs_seg = pytest.mark.skipif(
    not (os.path.isfile(SEG_MODEL) and os.path.isfile(IMAGE)),
    reason="reference test assets not present")


class TestImporter:
    @needs_assets
    def test_parse_structure(self):
        from nnstreamer_tpu.filters.tflite_import import TFLiteModel

        m = TFLiteModel(MODEL)
        assert len(m.operators) == 65
        assert {o["op"] for o in m.operators} == {
            "ADD", "AVERAGE_POOL_2D", "CONV_2D", "DEPTHWISE_CONV_2D",
            "RESHAPE"}
        t = m.tensors[m.inputs[0]]
        assert list(t.shape) == [1, 224, 224, 3]
        assert t.scale is not None  # quantized input

    def test_bad_file_raises_filter_error(self, tmp_path):
        bad = tmp_path / "junk.tflite"
        bad.write_bytes(b"\x00" * 64)
        with pytest.raises(FilterError):
            FilterSingle(framework="tensorflow-lite", model=str(bad))

    @pytest.mark.skipif(
        not os.path.isfile(os.path.join(REF, "models", "add.tflite")),
        reason="reference test assets not present")
    def test_minimal_add_model(self):
        """The reference's smallest test model: a single ADD of the
        input with a const 2.0 — exercises float tensors and the
        const-operand (params) path of the importer."""
        fs = FilterSingle(
            framework="tensorflow-lite",
            model=os.path.join(REF, "models", "add.tflite"))
        in_spec = fs.in_spec
        x = np.full(tuple(in_spec.tensors[0].shape), 3.5, np.float32)
        out = np.asarray(fs.invoke([x])[0])
        np.testing.assert_allclose(out, x + 2.0, rtol=1e-6)


class TestMeshPlacement:
    @needs_assets
    def test_imported_model_runs_on_a_mesh(self):
        """Imported models inherit the jax-xla machinery: the pretrained
        tflite graph compiles SPMD over a device mesh (weights travel as
        a params pytree, batch shards over data) and still answers
        "orange" for every shard's frames."""
        import jax

        if len(jax.devices("cpu")) < 8:
            pytest.skip("needs 8 virtual CPU devices")
        fs = FilterSingle(
            framework="tensorflow-lite", model=MODEL,
            accelerator="cpu", mesh="data:8",
            input_spec=TensorsSpec.from_shapes([(8, 224, 224, 3)],
                                               np.uint8))
        sp = fs.subplugin
        assert sp._mesh is not None and sp._mesh.devices.size == 8
        img = np.fromfile(IMAGE, np.uint8).reshape(1, 224, 224, 3)
        out = np.asarray(fs.invoke([np.repeat(img, 8, axis=0)])[0])
        assert out.shape[0] == 8
        assert (out.argmax(-1) == 951).all()  # "orange" on every shard


class TestSemantic:
    @needs_assets
    @pytest.mark.parametrize("qmode", ["auto", "bf16", "dequant", "float"])
    def test_orange_top1_single_shot(self, qmode):
        """Real weights, real image, real answer: ImageNet class 951 =
        'orange' must be the argmax (the reference's own accuracy
        fixture) — in EVERY low-precision execution mode (auto picks
        bf16 for quantized graphs; dequant runs uint8-resident)."""
        fs = FilterSingle(framework="tensorflow-lite", model=MODEL,
                          custom=f"qmode:{qmode}")
        img = np.fromfile(IMAGE, np.uint8).reshape(1, 224, 224, 3)
        out = np.asarray(fs.invoke([img])[0])
        labels = [ln.strip() for ln in open(LABELS)]
        top1 = int(out[0].argmax())
        assert labels[top1] == "orange", (top1, labels[top1])

    @needs_assets
    def test_orange_label_through_pipeline(self):
        """The reference-shaped accuracy pipeline: raw image → tflite
        filter (framework auto-detected from the extension) →
        image_labeling decoder → the literal label string."""
        p = parse_launch(
            f"appsrc name=src ! tensor_filter model={MODEL} ! "
            f"tensor_decoder mode=image_labeling option1={LABELS} ! "
            "appsink name=out")
        p["src"].spec = TensorsSpec.parse("3:224:224:1", "uint8", rate=0)
        img = np.fromfile(IMAGE, np.uint8).reshape(1, 224, 224, 3)
        with p:
            p["src"].push_buffer(Buffer.of(img))
            p["src"].end_of_stream()
            assert p.wait_eos(timeout=600)
            out = p["out"].pull(timeout=5)
        label = bytes(out[0].np()).decode("utf-8").strip("\x00").strip()
        assert label == "orange", label

    @needs_seg
    def test_deeplab_segmentation_float_model(self):
        """Float (non-quantized) model + dilated depthwise convs +
        RESIZE_BILINEAR: DeepLabV3 segments the orange image — an
        orange is none of the 20 VOC classes, so a correct segmentation
        is overwhelmingly background (a broken import yields noise
        across all 21 channels)."""
        fs = FilterSingle(framework="tensorflow-lite", model=SEG_MODEL)
        img = np.fromfile(IMAGE, np.uint8).reshape(1, 224, 224, 3)
        x = np.zeros((1, 257, 257, 3), np.float32)
        x[0, :224, :224] = img[0] / 127.5 - 1.0  # the graph's sub_7 input
        out = np.asarray(fs.invoke([x])[0])
        assert out.shape == (1, 257, 257, 21)
        seg = out[0].argmax(-1)
        assert (seg == 0).mean() > 0.9, (seg == 0).mean()

"""Shared-model serving runtime (`runtime/serving.py` +
`tensor_filter share-model=true`).

Covers the ISSUE-3 acceptance surface: per-stream FIFO order and pts
integrity under concurrent streams with cross-stream dispatch
coalescing, pool refcount lifecycle (one pipeline stopping mid-stream
while the survivor keeps dispatching, restart-after-stop reattaching),
the SUPPORTS_BATCH-less shared-instance/per-frame fallback without
frame loss, pool-level batch-property conflict detection, per-stream
EOS flushing only that stream's parked frames, the adaptive idle-flush
window, and the satellite timing fixes (`_record_dispatch` blocking on
ALL outputs of a sampled dispatch).
"""

import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.core import Buffer, TensorsSpec
from nnstreamer_tpu.elements.basic import AppSink, AppSrc, Queue
from nnstreamer_tpu.elements.filter import TensorFilter
from nnstreamer_tpu.filters.custom import (
    register_custom_easy,
    unregister_custom_easy,
)
from nnstreamer_tpu.filters.jax_xla import (
    JaxXlaFilter,
    register_model,
    unregister_model,
)
from nnstreamer_tpu.runtime import MODEL_POOL, Pipeline
from nnstreamer_tpu.runtime.serving import SharedBatcher
from nnstreamer_tpu.utils.stats import InvokeStats

SHAPE = (4,)
SPEC = TensorsSpec.from_shapes([SHAPE], np.float32)


@pytest.fixture(scope="module", autouse=True)
def _model():
    register_model("_t_serving", lambda x: x * 2.0 + 1.0,
                   in_shapes=[SHAPE], in_dtypes=np.float32)
    yield
    unregister_model("_t_serving")


@pytest.fixture(autouse=True)
def _pool_clean():
    yield
    # a failed test must not leak refcounts into the next one
    MODEL_POOL.clear()
    with JaxXlaFilter._shared_lock:
        JaxXlaFilter._shared_instances.clear()


def _frame(stream: int, i: int) -> Buffer:
    # stream-tagged values so demux mixups are detectable, not just
    # ordering slips
    return Buffer.of(np.full(SHAPE, stream * 1000.0 + i, np.float32),
                     pts=i)


def _pipeline(tag: str, share=True, batch=8, timeout_ms=50.0, n_bufs=64,
              framework="jax-xla", model="_t_serving", buckets=""):
    p = Pipeline(name=f"p_{tag}")
    src = AppSrc(name="src", spec=SPEC, max_buffers=n_bufs + 4)
    q = Queue(name="q", max_size_buffers=n_bufs + 4)
    flt = TensorFilter(name="net", framework=framework, model=model,
                       batch=batch, batch_timeout_ms=timeout_ms,
                       batch_buckets=buckets, share_model=share)
    sink = AppSink(name="out", max_buffers=n_bufs + 4)
    p.add(src, q, flt, sink).link(src, q, flt, sink)
    return p, src, flt, sink


def _pull_all(sink, n, timeout=10.0):
    out = []
    for _ in range(n):
        b = sink.pull(timeout=timeout)
        assert b is not None, f"stream stalled after {len(out)}/{n} buffers"
        out.append(b)
    return out


def _check_stream(bufs, stream: int):
    """Per-stream FIFO + pts + value integrity."""
    for i, b in enumerate(bufs):
        assert b.pts == i, f"stream {stream}: pts {b.pts} at slot {i}"
        np.testing.assert_allclose(
            b.tensors[0].np(),
            np.full(SHAPE, (stream * 1000.0 + i) * 2.0 + 1.0),
            err_msg=f"stream {stream} frame {i}: wrong payload (demux "
                    f"mixed streams?)")


# -- acceptance: FIFO/pts under concurrent streams + coalescing --------------


def test_concurrent_streams_fifo_pts_and_cross_stream_coalescing():
    n_streams, n = 4, 40
    pipes = [_pipeline(str(s)) for s in range(n_streams)]
    for p, *_ in pipes:
        p.start()
    flt0 = pipes[0][2]
    assert flt0.pool_streams == n_streams
    # every filter shares ONE sub-plugin instance (one params copy)
    assert all(p[2].subplugin is flt0.subplugin for p in pipes)

    def produce(s):
        _, src, _, _ = pipes[s]
        for i in range(n):
            src.push_buffer(_frame(s, i))
        src.end_of_stream()

    threads = [threading.Thread(target=produce, args=(s,))
               for s in range(n_streams)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for p, *_ in pipes:
        assert p.wait_eos(timeout=30)
    st = flt0.pool.stats
    assert st.total_frame_num == n_streams * n
    # cross-stream coalescing actually happened
    assert st.total_invoke_num < n_streams * n
    assert st.avg_stream_occupancy > 1.0
    for s, (p, _, flt, sink) in enumerate(pipes):
        outs = _pull_all(sink, n)
        _check_stream(outs, s)
        # the element's own frame count stays per-stream exact
        assert flt.invoke_stats.total_frame_num == n
        p.stop()
    assert len(MODEL_POOL) == 0


# -- pool lifecycle edges ----------------------------------------------------


def test_one_pipeline_stops_midstream_survivor_keeps_dispatching():
    p1, s1, f1, k1 = _pipeline("a")
    p2, s2, f2, k2 = _pipeline("b")
    p1.start()
    p2.start()
    assert f1.subplugin is f2.subplugin
    assert f1.pool.refcount == 2
    n = 10
    for i in range(n):
        s1.push_buffer(_frame(1, i))
        s2.push_buffer(_frame(2, i))
    _check_stream(_pull_all(k1, n), 1)
    entry = f2.pool
    p1.stop()  # refcount drops, entry survives for the survivor
    assert len(MODEL_POOL) == 1
    assert entry.refcount == 1
    assert entry.attached_streams == 1
    for i in range(n, 2 * n):
        s2.push_buffer(_frame(2, i))
    s2.end_of_stream()
    assert p2.wait_eos(timeout=30)
    _check_stream(_pull_all(k2, 2 * n), 2)
    p2.stop()
    assert len(MODEL_POOL) == 0


def test_restart_after_stop_reattaches_cleanly():
    p1, s1, f1, k1 = _pipeline("a")
    p2, s2, f2, k2 = _pipeline("b")
    p1.start()
    p2.start()
    p1.stop()
    assert f1.subplugin is None and f1.pool is None
    p1.start()  # re-acquires the (still alive) entry and reattaches
    assert f1.subplugin is f2.subplugin
    assert f1.pool is f2.pool and f1.pool.refcount == 2
    assert f1.pool.attached_streams == 2
    n = 6
    for i in range(n):
        s1.push_buffer(_frame(1, i))
    s1.end_of_stream()
    assert p1.wait_eos(timeout=30)
    _check_stream(_pull_all(k1, n), 1)
    p1.stop()
    p2.stop()
    assert len(MODEL_POOL) == 0


def test_framework_without_supports_batch_falls_back_per_frame():
    """share-model on a SUPPORTS_BATCH-less framework: the instance is
    shared (one user object) but frames dispatch per-frame — none are
    parked, none are lost."""
    register_custom_easy("_t_serving_easy",
                         lambda ins: [ins[0] * 2.0 + 1.0],
                         in_spec=SPEC, out_spec=SPEC)
    try:
        p1, s1, f1, k1 = _pipeline("a", framework="custom-easy",
                                   model="_t_serving_easy", batch=4)
        p2, s2, f2, k2 = _pipeline("b", framework="custom-easy",
                                   model="_t_serving_easy", batch=4)
        p1.start()
        p2.start()
        assert f1.subplugin is f2.subplugin  # shared instance
        assert f1._pool_batched is False     # but no shared window
        assert f1.pool.batcher is None
        n = 8
        for i in range(n):
            s1.push_buffer(_frame(1, i))
            s2.push_buffer(_frame(2, i))
        s1.end_of_stream()
        s2.end_of_stream()
        assert p1.wait_eos(timeout=30) and p2.wait_eos(timeout=30)
        _check_stream(_pull_all(k1, n), 1)  # every frame arrived
        _check_stream(_pull_all(k2, n), 2)
        assert f1.invoke_stats.total_invoke_num == n  # per-frame dispatch
        p1.stop()
        p2.stop()
        assert len(MODEL_POOL) == 0
    finally:
        unregister_custom_easy("_t_serving_easy")


# -- pool-level property validation ------------------------------------------


def test_conflicting_batch_settings_across_sharers_rejected():
    p1, s1, f1, k1 = _pipeline("a", batch=4)
    p2, s2, f2, k2 = _pipeline("b", batch=8)  # disagrees with the pool
    p1.start()
    with pytest.raises(ValueError, match="conflict"):
        p2.start()
    p2.stop()
    p1.stop()
    assert len(MODEL_POOL) == 0


def test_sharer_with_incompatible_caps_rejected_not_reshaped():
    """A second sharer whose upstream caps mismatch the pooled model
    must fail ITS negotiation — not recompile the shared executable
    under the first sharer's feet — and its failed start must roll the
    pool refcount back without an explicit stop()."""
    from nnstreamer_tpu.runtime import NegotiationError

    p1, s1, f1, k1 = _pipeline("a")
    p1.start()
    wide = TensorsSpec.from_shapes([(8,)], np.float32)  # model wants (4,)
    p2 = Pipeline(name="p_bad")
    src2 = AppSrc(name="src", spec=wide, max_buffers=8)
    q2 = Queue(name="q")
    f2 = TensorFilter(name="net", framework="jax-xla", model="_t_serving",
                      batch=8, batch_timeout_ms=50.0, share_model=True)
    k2 = AppSink(name="out")
    p2.add(src2, q2, f2, k2).link(src2, q2, f2, k2)
    with pytest.raises(NegotiationError, match="identical input"):
        p2.start()
    # failed start released p2's acquisition (no leak, no stop() needed)
    assert f1.pool.refcount == 1
    # the survivor still dispatches on the untouched (4,) executable
    n = 5
    for i in range(n):
        s1.push_buffer(_frame(1, i))
    s1.end_of_stream()
    assert p1.wait_eos(timeout=30)
    _check_stream(_pull_all(k1, n), 1)
    p1.stop()
    assert len(MODEL_POOL) == 0


def test_share_model_rejects_invoke_dynamic_but_allows_updatable():
    # invoke-dynamic still conflicts (per-buffer reshapes under every
    # sharer); is-updatable is ALLOWED since the lifecycle layer —
    # reloads route through PoolEntry.reload_model (runtime/lifecycle)
    flt = TensorFilter(name="net", framework="jax-xla",
                       model="_t_serving", share_model=True,
                       invoke_dynamic=True)
    with pytest.raises(ValueError, match="share-model"):
        flt.open_fw()
    assert len(MODEL_POOL) == 0
    upd = TensorFilter(name="net2", framework="jax-xla",
                       model="_t_serving", share_model=True,
                       is_updatable=True)
    upd.open_fw()
    assert upd.pool is not None
    upd._pool_entry = None  # release without start/stop machinery
    MODEL_POOL.clear()
    assert len(MODEL_POOL) == 0


# -- SharedBatcher unit: per-stream flush ------------------------------------


def test_flush_stream_drains_only_that_streams_parked_frames():
    flushed = []
    sb = SharedBatcher(max_batch=4, timeout_s=1000.0,
                       flush_fn=flushed.extend, adaptive=False)
    # no start(): no timer, windows only move when we say so
    sb.submit_from("A", 1)
    sb.submit_from("B", 2)
    sb.submit_from("A", 3)
    sb.flush_stream("A")
    # items are (stream, frame, deadline, enqueue-ts) tuples
    # B's frame 2 arrived BEFORE A's last frame: it rides along (FIFO)
    assert [it[:2] for it in flushed] == [("A", 1), ("B", 2), ("A", 3)]
    sb.submit_from("B", 4)
    sb.flush_stream("A")  # nothing of A parked: B's window is untouched
    assert [it[:2] for it in flushed] == [("A", 1), ("B", 2), ("A", 3)]
    assert sb.pending_of("B") == 1
    sb.flush_stream("B")
    assert flushed[-1][:2] == ("B", 4)


def test_shared_batcher_preserves_per_stream_order_across_windows():
    flushed = []
    sb = SharedBatcher(max_batch=3, timeout_s=1000.0,
                       flush_fn=flushed.extend, adaptive=False)
    sb.start()
    n_producers, per = 4, 30

    def produce(pid):
        for i in range(per):
            sb.submit_from(pid, i)

    threads = [threading.Thread(target=produce, args=(pid,))
               for pid in range(n_producers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    sb.flush()
    sb.stop()
    assert len(flushed) == n_producers * per
    for pid in range(n_producers):
        seq = [it[1] for it in flushed if it[0] == pid]
        assert seq == sorted(seq), f"stream {pid} reordered"


# -- adaptive window ----------------------------------------------------------


def test_adaptive_window_flushes_on_idle_device_before_deadline():
    """With a 60 s deadline a lone frame must still come out promptly:
    the idle device triggers the flush, not the timeout."""
    p, src, flt, sink = _pipeline("a", timeout_ms=60_000.0)
    with p:
        t0 = time.monotonic()
        src.push_buffer(_frame(0, 0))
        b = sink.pull(timeout=10.0)
        took = time.monotonic() - t0
        assert b is not None and b.pts == 0
        assert took < 5.0  # far below the 60 s deadline
        assert flt.pool.batcher.flushes_adaptive >= 1
        src.end_of_stream()
        assert p.wait_eos(timeout=30)
    assert len(MODEL_POOL) == 0


def test_plain_microbatcher_default_stays_deadline_driven():
    from nnstreamer_tpu.runtime.batching import MicroBatcher

    mb = MicroBatcher(max_batch=4, timeout_s=0.01, flush_fn=lambda b: None)
    assert mb.adaptive is False  # per-element batching is unchanged


# -- stats --------------------------------------------------------------------


def test_invoke_stats_stream_occupancy():
    st = InvokeStats()
    st.count(frames=8, streams=4)
    st.record(0.001, frames=2, streams=2)
    assert st.total_stream_num == 6
    assert st.avg_stream_occupancy == pytest.approx(3.0)
    assert st.avg_batch_occupancy == pytest.approx(5.0)
    empty = InvokeStats()
    assert empty.avg_stream_occupancy == 0.0


def test_pool_entry_stats_visible_on_element():
    p1, s1, f1, k1 = _pipeline("a")
    p2, s2, f2, k2 = _pipeline("b")
    p1.start()
    p2.start()
    n = 12
    for i in range(n):
        s1.push_buffer(_frame(1, i))
        s2.push_buffer(_frame(2, i))
    s1.end_of_stream()
    s2.end_of_stream()
    assert p1.wait_eos(timeout=30) and p2.wait_eos(timeout=30)
    _pull_all(k1, n)
    _pull_all(k2, n)
    assert f1.pool.stats is f2.pool.stats
    assert f1.pool.stats.total_frame_num == 2 * n
    assert f1.pool.stats.attached_streams == 2
    assert f1.pool_stream_occupancy >= 1.0
    p1.stop()
    p2.stop()


# -- satellite: sampled dispatch blocks on ALL outputs ------------------------


class _FakeOut:
    def __init__(self):
        self.blocked = 0

    def block_until_ready(self):
        self.blocked += 1


def test_record_dispatch_blocks_every_output_of_sampled_window():
    """The old micro-batch path blocked only on the LAST frame's outputs;
    on multi-output models the recorded latency could miss still-enqueued
    earlier outputs.  `_record_dispatch` drains the whole window."""
    flt = TensorFilter(name="net", framework="jax-xla", model="_t_serving")
    outs = [_FakeOut() for _ in range(6)]  # 3 frames x 2 outputs, flat
    flt._record_dispatch(list(outs), time.monotonic(), frames=3,
                         sample=True)
    assert all(o.blocked == 1 for o in outs)
    assert flt.invoke_stats.total_frame_num == 3
    assert flt.invoke_stats.total_invoke_num == 1
    assert flt._last_out is outs[-1]


def test_record_dispatch_unsampled_counts_without_blocking():
    flt = TensorFilter(name="net", framework="jax-xla", model="_t_serving")
    outs = [_FakeOut(), _FakeOut()]
    flt._record_dispatch(list(outs), time.monotonic(), frames=2,
                         sample=False)
    assert all(o.blocked == 0 for o in outs)
    assert flt.invoke_stats.total_frame_num == 2
    assert flt.invoke_stats.latency_us == -1  # no sample recorded

"""Platform services (L0): conf, logging, stats."""

from .conf import Conf, get_conf
from . import log

__all__ = ["Conf", "get_conf", "log"]

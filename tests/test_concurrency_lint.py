"""Concurrency analyzer (`nnstreamer_tpu.analyze.concurrency`) tests.

Every NNS6xx code gets a positive, a negative, and a suppression case;
the CLI surface (`--concurrency` text/JSON/DOT, the `--self` gate) is
golden-tested; and a regression harness proves the pass re-detects the
package's own historical concurrency bugs when their fixes are
reverted (the PR 11 ctl<->watch lock-order inversion -> NNS601, the
watch sampler scrape-under-lock -> NNS602).
"""

import io
import json
import os

import pytest

from nnstreamer_tpu.analyze import (
    LockGraph,
    analyze_package_concurrency,
    lint_concurrency_source,
)
from nnstreamer_tpu.analyze.cli import main as cli_main
from nnstreamer_tpu.analyze.concurrency import analyze_sources

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "nnstreamer_tpu")


def codes(diags):
    return {d.code for d in diags}


# -- known-bad corpus: one snippet per NNS6xx code ---------------------------
#
# (source, expected-codes) pairs; test_analyze.test_every_code_has_coverage
# imports this list so the catalog-coverage invariant spans both files.

NNS601_INVERSION = '''
import threading


class A:
    def __init__(self, b: "B"):
        self._lock = threading.Lock()
        self.b = b

    def one(self):
        with self._lock:
            self.b.poke()

    def grab(self):
        with self._lock:
            return 1


class B:
    def __init__(self, a: "A"):
        self._lock = threading.Lock()
        self.a = a

    def poke(self):
        with self._lock:
            pass

    def other(self):
        with self._lock:
            self.a.grab()
'''

NNS602_RECV = '''
import threading


class C:
    def __init__(self, sock):
        self._lock = threading.Lock()
        self.sock = sock

    def pull(self):
        with self._lock:
            return self.sock.recv(4096)
'''

NNS602_INTERPROC = '''
import threading


class C:
    def __init__(self, worker):
        self._lock = threading.Lock()
        self.worker = worker

    def _drain(self):
        self.worker.join(timeout=5.0)

    def stop(self):
        with self._lock:
            self._drain()
'''

NNS603_UNGUARDED = '''
import threading


class D:
    def __init__(self):
        self.count = 0
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        self.count += 1

    def bump(self):
        self.count += 1
'''

NNS604_LEAF_NESTS = '''
import threading


class E:
    def __init__(self):
        self._alock = threading.Lock()  # nns-lock: leaf
        self._big = threading.Lock()

    def bad(self):
        with self._alock:
            with self._big:
                pass
'''

CONCURRENCY_CORPUS = [
    (NNS601_INVERSION, {"NNS601"}),
    (NNS602_RECV, {"NNS602"}),
    (NNS602_INTERPROC, {"NNS602"}),
    (NNS603_UNGUARDED, {"NNS603"}),
    (NNS604_LEAF_NESTS, {"NNS604"}),
]


@pytest.mark.parametrize(
    "src,expected", CONCURRENCY_CORPUS,
    ids=[sorted(e)[0] + f"-{i}" for i, (_, e) in
         enumerate(CONCURRENCY_CORPUS)])
def test_bad_corpus(src, expected):
    diags = lint_concurrency_source(src, "pkg/mod.py")
    assert expected <= codes(diags), \
        f"want {expected}, got {[(d.code, d.message) for d in diags]}"


def test_nns601_prints_both_paths():
    """The cycle diagnostic carries BOTH acquisition paths — without
    the second path the report is unactionable."""
    diags = [d for d in lint_concurrency_source(NNS601_INVERSION,
                                                "pkg/mod.py")
             if d.code == "NNS601"]
    assert diags
    blob = (diags[0].message or "") + (diags[0].hint or "")
    assert "A._lock" in blob and "B._lock" in blob
    assert "->" in blob


def test_nns601_negative_consistent_order():
    """Same two locks, both call chains take them in the same order:
    an order edge, not a cycle."""
    src = NNS601_INVERSION.replace(
        "        with self._lock:\n            self.a.grab()",
        "        self.a.grab()")
    diags = lint_concurrency_source(src, "pkg/mod.py")
    assert "NNS601" not in codes(diags)


def test_nns601_file_suppression():
    src = ("# nns-lint: disable-file=NNS601 -- crafted inversion\n"
           + NNS601_INVERSION)
    diags = lint_concurrency_source(src, "pkg/mod.py")
    assert "NNS601" not in codes(diags)


def test_nns602_negative_hoisted_recv():
    """recv moved out of the critical section: clean."""
    src = NNS602_RECV.replace(
        "        with self._lock:\n            return self.sock.recv(4096)",
        "        data = self.sock.recv(4096)\n"
        "        with self._lock:\n            return data")
    assert "NNS602" not in codes(lint_concurrency_source(src, "p/m.py"))


def test_nns602_negative_condition_wait_is_exempt():
    """Condition.wait RELEASES its lock while waiting — holding the
    condition's own lock around wait() is the correct idiom."""
    src = '''
import threading


class W:
    def __init__(self):
        self._cond = threading.Condition()

    def take(self):
        with self._cond:
            self._cond.wait(timeout=1.0)
'''
    assert "NNS602" not in codes(lint_concurrency_source(src, "p/m.py"))


def test_nns602_suppression():
    src = NNS602_RECV.replace(
        "            return self.sock.recv(4096)",
        "            # nns-lint: disable=NNS602 -- framing lock\n"
        "            return self.sock.recv(4096)")
    assert "NNS602" not in codes(lint_concurrency_source(src, "p/m.py"))


def test_nns603_negative_guarded():
    src = '''
import threading


class D:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        with self._lock:
            self.count += 1

    def bump(self):
        with self._lock:
            self.count += 1
'''
    assert "NNS603" not in codes(lint_concurrency_source(src, "p/m.py"))


def test_nns603_suppression():
    # the diagnostic anchors at the FIRST unguarded write (_run's)
    src = NNS603_UNGUARDED.replace(
        "    def _run(self):\n        self.count += 1",
        "    def _run(self):\n"
        "        # nns-lint: disable=NNS603 -- test-only counter\n"
        "        self.count += 1")
    assert "NNS603" not in codes(lint_concurrency_source(src, "p/m.py"))


def test_nns604_negative_leaf_taken_last():
    """Leaf taken INSIDE the coarse lock is exactly the discipline the
    declaration promises."""
    src = NNS604_LEAF_NESTS.replace(
        "        with self._alock:\n            with self._big:",
        "        with self._big:\n            with self._alock:")
    assert "NNS604" not in codes(lint_concurrency_source(src, "p/m.py"))


def test_nns604_suppression():
    src = NNS604_LEAF_NESTS.replace(
        "            with self._big:",
        "            # nns-lint: disable=NNS604 -- crafted\n"
        "            with self._big:")
    assert "NNS604" not in codes(lint_concurrency_source(src, "p/m.py"))


# -- lock graph --------------------------------------------------------------


def test_lock_graph_nodes_edges_and_dot():
    diags, graph = analyze_sources({"pkg/mod.py": NNS601_INVERSION})
    assert isinstance(graph, LockGraph)
    doc = graph.as_graph_dict()
    keys = {n["key"] for n in doc["nodes"]}
    assert {"A._lock", "B._lock"} <= keys
    edges = {(e["src"], e["dst"]) for e in doc["edges"]}
    assert ("A._lock", "B._lock") in edges
    assert ("B._lock", "A._lock") in edges
    dot = graph.to_dot()
    assert dot.startswith("digraph")
    assert "A._lock" in dot and "->" in dot


def test_package_lock_graph_has_real_edges():
    """On the actual package the graph must see the known nesting
    Watch._lock inside Controller scope chains — and no cycles."""
    diags, graph = analyze_package_concurrency(PKG)
    doc = graph.as_graph_dict()
    assert len(doc["nodes"]) >= 20
    assert doc["edges"], "package lock graph should have order edges"
    assert graph.cycles() == []
    assert not [d for d in diags if d.code == "NNS601"]


# -- historical-bug regression harness ---------------------------------------


CTL_WATCH_FIXED = {
    "pkg/control.py": '''
import threading


class Controller:
    def __init__(self, watch: "Watch"):
        self._lock = threading.Lock()
        self.watch = watch

    def tick(self):
        alerts = self.watch.alerts()
        with self._lock:
            return len(alerts)

    def status(self):
        with self._lock:
            return {}
''',
    "pkg/watch.py": '''
import threading


class Watch:
    def __init__(self, ctl: "Controller"):
        self._lock = threading.Lock()
        self.ctl = ctl

    def alerts(self):
        with self._lock:
            return []

    def sample_once(self):
        with self._lock:
            pass
        return self.ctl.status()
''',
}


def test_regression_ctl_watch_inversion_redetected():
    """PR 11's bug, re-created: the controller tick reads alerts UNDER
    its own lock while the sampler calls back into controller status
    under the watch lock — the analyzer must close the cycle."""
    fixed_diags, _ = analyze_sources(CTL_WATCH_FIXED)
    assert "NNS601" not in codes(fixed_diags)

    reverted = dict(CTL_WATCH_FIXED)
    reverted["pkg/control.py"] = reverted["pkg/control.py"].replace(
        "        alerts = self.watch.alerts()\n"
        "        with self._lock:\n"
        "            return len(alerts)",
        "        with self._lock:\n"
        "            return len(self.watch.alerts())")
    reverted["pkg/watch.py"] = reverted["pkg/watch.py"].replace(
        "        with self._lock:\n"
        "            pass\n"
        "        return self.ctl.status()",
        "        with self._lock:\n"
        "            return self.ctl.status()")
    diags, graph = analyze_sources(reverted)
    assert "NNS601" in codes(diags)
    assert graph.cycles(), "reverted sources must show a lock cycle"


def test_regression_watch_scrape_under_lock_redetected():
    """The real watch.py with THIS PR's fix reverted (scrape moved back
    inside the watch lock) must re-fire NNS602 on the whole package."""
    watch_path = os.path.join(PKG, "obs", "watch.py")
    with open(watch_path, encoding="utf-8") as f:
        src = f.read()
    seeded = src.replace(
        "        entries = self._scrape()\n        with self._lock:",
        "        with self._lock:\n            entries = self._scrape()")
    assert seeded != src, "watch.py fix shape changed; update this test"

    sources = {}
    base = os.path.dirname(PKG)
    for dirpath, dirnames, filenames in os.walk(PKG):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", "build", "native")]
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            display = os.path.relpath(path, base).replace(os.sep, "/")
            with open(path, encoding="utf-8") as f:
                sources[display] = f.read()
    sources["nnstreamer_tpu/obs/watch.py"] = seeded
    diags, _ = analyze_sources(sources)
    hits = [d for d in diags if d.code == "NNS602"
            and d.element == "nnstreamer_tpu/obs/watch.py"]
    assert hits, "seeded scrape-under-lock must re-fire NNS602"


# -- CLI ---------------------------------------------------------------------


MINIPKG = {
    "mod.py": NNS602_RECV,
    "order.py": NNS601_INVERSION,
}


def _write_minipkg(tmp_path):
    pkg = tmp_path / "minipkg"
    pkg.mkdir()
    for name, src in MINIPKG.items():
        (pkg / name).write_text(src)
    return pkg


def test_cli_concurrency_text(tmp_path):
    pkg = _write_minipkg(tmp_path)
    buf = io.StringIO()
    rc = cli_main(["--concurrency", str(pkg)], out=buf)
    text = buf.getvalue()
    assert rc == 1  # NNS601 is ERROR severity: nonzero even unstrict
    assert "NNS601" in text and "NNS602" in text
    assert cli_main(["--concurrency", str(pkg), "--strict"],
                    out=io.StringIO()) == 1


def test_cli_concurrency_json_golden(tmp_path):
    """--concurrency --json carries diagnostics AND the lock graph and
    matches the committed golden byte-for-byte (after parsing)."""
    pkg = _write_minipkg(tmp_path)
    buf = io.StringIO()
    cli_main(["--concurrency", str(pkg), "--json"], out=buf)
    got = json.loads(buf.getvalue())
    golden_path = os.path.join(REPO, "tests", "golden",
                               "concurrency_cli.golden.json")
    with open(golden_path) as f:
        golden = json.load(f)
    assert got == golden


def test_cli_concurrency_dot(tmp_path):
    pkg = _write_minipkg(tmp_path)
    buf = io.StringIO()
    rc = cli_main(["--concurrency", str(pkg), "--dot"], out=buf)
    dot = buf.getvalue()
    assert rc == 1  # diag-based exit code holds under --dot too
    assert "digraph" in dot and "A._lock" in dot


def test_cli_concurrency_self_gate():
    """The CI gate: the package's own concurrency lint is clean under
    --strict (every remaining finding fixed or suppressed-with-reason)."""
    assert cli_main(["--self", "--concurrency", "--strict"],
                    out=io.StringIO()) == 0

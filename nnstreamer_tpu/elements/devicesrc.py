"""``device_src`` — a source whose frames are staged in device HBM.

The reference's converter guarantees zero-copy media ingestion on host
(video/x-raw → tensor without memcpy unless width%4≠0 —
/root/reference/gst/nnstreamer/elements/gsttensor_converter.md
"Performance Characteristics").  The TPU-native equivalent of "zero-copy"
is *device residence*: frames are staged into HBM once (a bounded pool,
double-buffer style) and the streaming loop never touches the host again —
each created Buffer references a pool slot.  This is the right source for
benchmarks and for any pipeline whose ingest can be prefetched (datarepo
replay, synthetic load, camera DMA staging).

Patterns (parity: videotestsrc patterns feeding tensor_converter in the
reference's SSAT pipelines): ``noise`` (PRNG uint8), ``gradient``,
``frames`` (a user-supplied ndarray pool, uploaded at start).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import itertools

import numpy as np

from ..core import Buffer, Tensor, TensorsSpec
from ..runtime.element import NegotiationError, SourceElement
from ..runtime.registry import register_element


_stage_seed = itertools.count(1)


@register_element("device_src")
class DeviceSrc(SourceElement):
    FACTORY = "device_src"

    def __init__(self, name=None, spec: Optional[TensorsSpec] = None,
                 pattern: str = "noise", frames: Optional[Sequence] = None,
                 pool_size: int = 4, num_buffers: int = -1,
                 fps: Optional[float] = None, **props):
        self.spec = spec
        self.pattern = pattern
        self.frames = frames
        self.pool_size = pool_size
        self.num_buffers = num_buffers
        self.fps = fps
        super().__init__(name, **props)
        self._pool: List[List[object]] = []  # pool[i] = per-tensor jax arrays
        self._i = 0

    def output_spec(self):
        if isinstance(self.spec, str):
            # pipeline-string form: `spec=3:224:224:64` or
            # `spec=3:224:224:1/float32,1000:1/float32` — dims[/type] per
            # tensor, type defaulting to the pattern dtype (uint8)
            dims, types = [], []
            for part in self.spec.split(","):
                d, _, t = part.partition("/")
                dims.append(d.strip())
                types.append(t.strip() or "uint8")
            self.spec = TensorsSpec.parse(",".join(dims), ",".join(types))
        if self.spec is None and self.frames is not None:
            first = self.frames[0]
            arrays = first if isinstance(first, (list, tuple)) else [first]
            self.spec = TensorsSpec.from_shapes(
                [a.shape for a in arrays], [np.dtype(a.dtype) for a in arrays])
        return self.spec

    def start(self) -> None:
        self._stage_pool()
        super().start()

    def _stage_pool(self) -> None:
        import jax

        spec = self.output_spec()
        if spec is None:
            raise NegotiationError(f"{self.name}: no spec/frames given")
        self._pool = []
        if self.frames is not None:
            for f in self.frames[:min(self.pool_size, len(self.frames))]:
                arrays = f if isinstance(f, (list, tuple)) else [f]
                staged = [jax.device_put(np.asarray(a)) for a in arrays]
                for s in staged:
                    s.block_until_ready()  # stage before streaming starts
                self._pool.append(staged)
            return
        # a fresh seed per staging: two pipeline instantiations must not
        # stage byte-identical pools, or repeated (executable, argument)
        # executions can be served from a remote-runtime memo cache and
        # fake near-zero device time in A/B benchmarks
        rng = np.random.default_rng(next(_stage_seed))
        for k in range(self.pool_size):
            staged = []
            for t in spec.tensors:
                if self.pattern == "gradient":
                    flat = np.arange(t.num_elements, dtype=np.int64)
                    host = ((flat + k) % 256).astype(
                        t.dtype.np_dtype).reshape(t.shape)
                else:  # noise
                    if t.dtype.np_dtype == np.uint8:
                        host = rng.integers(
                            0, 256, t.shape, dtype=np.uint8)
                    else:
                        host = rng.standard_normal(t.shape).astype(
                            t.dtype.np_dtype)
                d = jax.device_put(host)
                d.block_until_ready()
                staged.append(d)
            self._pool.append(staged)

    def create(self) -> Optional[Buffer]:
        if 0 <= self.num_buffers <= self._i:
            return None
        slot = self._pool[self._i % len(self._pool)]
        pts = None
        if self.fps:
            pts = int(self._i * 1_000_000_000 / self.fps)
        buf = Buffer(tensors=[Tensor(a) for a in slot], pts=pts,
                     offset=self._i)
        self._i += 1
        return buf

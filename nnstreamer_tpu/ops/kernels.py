"""Pallas TPU kernels: fused normalize/typecast + flash attention.

Parity/role:
- ``scale_bias_cast`` is the tensor_transform arithmetic prologue
  (``typecast:float32,add:B,mul/div:S``) as ONE VPU kernel — the TPU
  form of the reference's Orc-accelerated transform loops
  (gsttensor_transform.c:473-483).  It matters on the standalone
  transform path (transform feeding a host sink); when a jax-xla filter
  follows, the fusion pass already inlines the chain into the filter's
  XLA program.
- ``flash_attention`` is the blockwise-attention block kernel (online
  softmax, never materializing the (S, S) score matrix) — the
  single-chip engine under long-context sequence parallelism
  (parallel/collectives.ring_attention rotates K/V blocks between chips
  with the same math).

Both compile natively on TPU and run under the Pallas interpreter on
CPU backends (tests); callers use the jnp reference automatically when
shapes don't meet the tiling constraints (lane dim multiple of 128,
sublane multiple of 8 for f32).
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

_LANE = 128
_SUBLANE = 8


def _pl():
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    return jax, pl, pltpu


def _interpret() -> bool:
    import jax

    return jax.default_backend() != "tpu"


# -- fused scale/bias/cast ---------------------------------------------------


def scale_bias_cast_available(shape, in_dtype, rows: int = _SUBLANE) -> bool:
    """Kernel eligibility: element count must tile into (8k, 128) blocks
    and the input must not be float64 (the kernel computes in f32; f64
    inputs take the precision-preserving jnp fallback)."""
    if np.dtype(in_dtype) == np.dtype(np.float64):
        return False
    n = int(np.prod(shape))
    return n % (_LANE * rows) == 0


def scale_bias_cast(x, scale: float, bias: float, out_dtype=np.float32,
                    block_rows: int = 256):
    """``((x + bias) * scale).astype(out_dtype)`` as one tiled VPU kernel.

    Accepts any shape whose element count tiles into (8k, 128) blocks;
    otherwise computes the jnp reference.
    """
    import jax.numpy as jnp

    out_dtype = jnp.dtype(out_dtype)
    n = int(np.prod(x.shape))
    if not scale_bias_cast_available(x.shape, x.dtype):
        # fallback computes at the input's precision when it is wider
        ct = jnp.promote_types(x.dtype, jnp.float32)
        return ((x.astype(ct) + bias) * scale).astype(out_dtype)
    jax, pl, pltpu = _pl()
    rows = n // _LANE
    block = min(block_rows, rows)
    while rows % block:
        block //= 2
    block = max(block, _SUBLANE)

    def kernel(in_ref, out_ref):
        v = in_ref[:]
        if v.dtype in (jnp.uint8, jnp.int8, jnp.uint16, jnp.int16):
            # Mosaic has no direct small-int→float cast: widen first
            v = v.astype(jnp.int32)
        v = v.astype(jnp.float32)
        out_ref[:] = ((v + bias) * scale).astype(out_dtype)

    flat = x.reshape(rows, _LANE)
    out = pl.pallas_call(
        kernel,
        grid=(rows // block,),
        in_specs=[pl.BlockSpec((block, _LANE), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((block, _LANE), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, _LANE), out_dtype),
        interpret=_interpret(),
    )(flat)
    return out.reshape(x.shape)


# -- flash attention ---------------------------------------------------------


def flash_attention_reference(q, k, v, scale: Optional[float] = None):
    """jnp reference: softmax(q kᵀ · scale) v, f32 accumulation."""
    import jax.numpy as jnp

    if scale is None:
        scale = 1.0 / float(np.sqrt(q.shape[-1]))
    s = jnp.einsum("...qd,...kd->...qk", q, k).astype(jnp.float32) * scale
    p = jnp.exp(s - jnp.max(s, axis=-1, keepdims=True))
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    return jnp.einsum("...qk,...kd->...qd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def flash_attention(q, k, v, scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128):
    """Blockwise attention, never materializing the (S, S) scores.

    q/k/v: (..., S, D) with D a multiple of 128 and S a multiple of the
    block sizes — otherwise the jnp reference runs.  Leading dims are
    flattened into the grid's outer axis; the kernel keeps a running
    max/normalizer/accumulator in VMEM scratch across K blocks (online
    softmax), so VMEM holds only (block_q + 2·block_k) × D floats.
    """
    import jax.numpy as jnp

    if scale is None:
        scale = 1.0 / float(np.sqrt(q.shape[-1]))
    S, D = q.shape[-2], q.shape[-1]
    Sk = k.shape[-2]
    block_q = min(block_q, S)
    block_k = min(block_k, Sk)
    if (D % _LANE or S % block_q or Sk % block_k
            or block_q % _SUBLANE or block_k % _SUBLANE):
        return flash_attention_reference(q, k, v, scale)
    jax, pl, pltpu = _pl()
    lead = q.shape[:-2]
    B = int(np.prod(lead)) if lead else 1
    qf = q.reshape(B, S, D)
    kf = k.reshape(B, Sk, D)
    vf = v.reshape(B, Sk, D)
    nq, nk = S // block_q, Sk // block_k

    def kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref):
        ik = pl.program_id(2)

        @pl.when(ik == 0)
        def _init():
            m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
            l_ref[:] = jnp.zeros_like(l_ref)
            acc_ref[:] = jnp.zeros_like(acc_ref)

        qb = q_ref[0].astype(jnp.float32)           # (bq, D)
        kb = k_ref[0].astype(jnp.float32)           # (bk, D)
        s = jax.lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (bq, bk)
        # m/l scratch stores the per-row stats broadcast across a full
        # lane so every access stays (8,128)-tile aligned
        m_prev = m_ref[:][:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])             # (bq, bk)
        l_new = l_ref[:][:, 0] * corr + jnp.sum(p, axis=-1)
        l_ref[:] = jnp.broadcast_to(l_new[:, None], l_ref.shape)
        acc_ref[:] = acc_ref[:] * corr[:, None] + jax.lax.dot_general(
            p, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new[:, None], m_ref.shape)

        @pl.when(ik == nk - 1)
        def _finish():
            o_ref[0] = (acc_ref[:] / l_ref[:][:, 0][:, None]
                        ).astype(o_ref.dtype)

    out = pl.pallas_call(
        kernel,
        grid=(B, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, iq, ik: (b, iq, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, iq, ik: (b, ik, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, iq, ik: (b, ik, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, iq, ik: (b, iq, 0)),
        out_shape=jax.ShapeDtypeStruct((B, S, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANE), jnp.float32),   # running max
            pltpu.VMEM((block_q, _LANE), jnp.float32),   # normalizer
            pltpu.VMEM((block_q, D), jnp.float32),       # accumulator
        ],
        interpret=_interpret(),
    )(qf, kf, vf)
    return out.reshape(*lead, S, D) if lead else out[0]

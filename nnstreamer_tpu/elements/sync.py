"""Time synchronization for N-input collect elements (mux/merge/crop).

Parity target: the reference's time-sync engine over GstCollectPads —
mode table and ``gst_tensor_time_sync_buffer_from_collectpad``
(/root/reference/gst/nnstreamer/nnstreamer_plugin_api_impl.c:20-25,203,332)
with the four policies documented in
Documentation/synchronization-policies-at-mux-merge.md:

- ``nosync``   — no timestamp logic; emit whenever every pad has a buffer.
- ``slowest``  — base time is the *oldest* head timestamp among pads (the
  slowest stream); faster pads drop buffers older than the base.
- ``basepad``  — base time comes from a designated pad (option
  ``<pad_index>:<duration_ns>``); other pads match within the duration.
- ``refresh``  — emit on every arrival on any pad, reusing the most recent
  buffer of the quieter pads.

The runtime difference from GStreamer: collection runs inside ``chain()``
on the depositing thread (no dedicated collect thread).  ``deposit()``
returns zero or more complete buffer-sets to emit, so a fast pad can drain
several sets at once.
"""

from __future__ import annotations

import dataclasses
import threading
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from ..core import Buffer


@dataclasses.dataclass(frozen=True)
class SyncPolicy:
    mode: str = "nosync"  # nosync | slowest | basepad | refresh
    base_pad: int = 0
    duration_ns: Optional[int] = None  # basepad match window

    @classmethod
    def parse(cls, mode: str, option: str = "") -> "SyncPolicy":
        mode = (mode or "nosync").strip().lower()
        if mode not in ("nosync", "slowest", "basepad", "refresh"):
            raise ValueError(f"unknown sync mode {mode!r}")
        base_pad, duration = 0, None
        if mode == "basepad" and option:
            head, _, dur = str(option).partition(":")
            base_pad = int(head or 0)
            duration = int(dur) if dur else None
        return cls(mode=mode, base_pad=base_pad, duration_ns=duration)


class Collector:
    """Per-element collect state: one FIFO per sink pad + the sync policy."""

    def __init__(self, policy: SyncPolicy, pad_names: List[str]):
        self.policy = policy
        self._lock = threading.Lock()
        self._queues: Dict[str, Deque[Buffer]] = {
            n: deque() for n in pad_names}
        self._last: Dict[str, Optional[Buffer]] = {n: None for n in pad_names}
        self._eos: set = set()
        self._order: List[str] = list(pad_names)

    def add_pad(self, name: str) -> None:
        with self._lock:
            if name not in self._queues:
                self._queues[name] = deque()
                self._last[name] = None
                self._order.append(name)

    # -- deposit → complete sets ---------------------------------------------

    def deposit(self, pad_name: str, buf: Buffer
                ) -> List[Dict[str, Buffer]]:
        """Add a buffer; return every now-complete synchronized set, in
        emit order.  Each set maps pad name → Buffer."""
        with self._lock:
            self._queues[pad_name].append(buf)
            out = []
            while True:
                s = self._try_collect(arrived=pad_name)
                if s is None:
                    break
                out.append(s)
                if self.policy.mode == "refresh":
                    break  # refresh emits exactly one set per arrival
            return out

    def mark_eos(self, pad_name: str) -> bool:
        """Returns True when every pad has seen EOS."""
        with self._lock:
            self._eos.add(pad_name)
            return self._eos >= set(self._queues)

    def pending(self) -> int:
        with self._lock:
            return sum(len(q) for q in self._queues.values())

    # -- policy cores (call with lock held) ----------------------------------

    def _heads(self) -> Optional[Dict[str, Buffer]]:
        if any(not q for n, q in self._queues.items() if n not in self._eos):
            return None
        heads = {n: q[0] for n, q in self._queues.items() if q}
        return heads or None

    def _try_collect(self, arrived: str) -> Optional[Dict[str, Buffer]]:
        mode = self.policy.mode
        if mode == "refresh":
            # Every pad must have seen at least one buffer; reuse stale ones.
            q = self._queues[arrived]
            if not q:
                return None
            self._last[arrived] = q.popleft()
            if any(self._last[n] is None for n in self._queues):
                return None
            return dict(self._last)

        heads = self._heads()
        if heads is None:
            return None
        if mode == "nosync":
            return {n: self._queues[n].popleft() for n in heads}

        # timestamped modes: pick base time, then per-pad the newest buffer
        # not newer than base (dropping the older ones it supersedes)
        def pts(b: Buffer) -> int:
            return b.pts if b.pts is not None else 0

        if mode == "slowest":
            base = max(pts(b) for b in heads.values())
        else:  # basepad
            idx = min(self.policy.base_pad, len(self._order) - 1)
            base_name = self._order[idx]
            if base_name not in heads:
                return None  # base pad at EOS with empty queue: stop
            base = pts(heads[base_name])
        limit = base if self.policy.duration_ns is None \
            else base + self.policy.duration_ns
        out = {}
        for n, q in self._queues.items():
            if not q:
                continue  # pad at EOS, queue drained: skip it
            # drop buffers superseded by a newer one still within the limit
            while len(q) > 1 and pts(q[1]) <= limit:
                q.popleft()
            if pts(q[0]) <= limit:
                out[n] = q.popleft()
            else:
                # pad ran ahead of the base: contribute its pending buffer
                # without consuming it (it pairs again with the next base)
                out[n] = q[0]
        return out

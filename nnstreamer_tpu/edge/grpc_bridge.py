"""gRPC tensor bridge: ``tensor_src_grpc`` / ``tensor_sink_grpc``.

Parity targets:
- /root/reference/ext/nnstreamer/tensor_source/tensor_src_grpc.c (525
  LoC): props ``server`` (default TRUE), ``blocking`` (default TRUE),
  ``idl={protobuf,flatbuf}``, ``host`` (localhost), ``port`` (55115);
  each element works as either gRPC server or client.
- .../extra/nnstreamer_grpc_protobuf.cc: the ``TensorService`` RPCs —
  ``SendTensors`` (client→server stream) and ``RecvTensors``
  (server→client stream) over the ``nnstreamer.protobuf.Tensors``
  message (ext/nnstreamer/include/nnstreamer.proto).

TPU-native notes: payloads ride the hand-rolled wire codecs
(``nnstreamer_tpu.converters.codecs`` — same field numbers as the
reference .proto, so frames interoperate), and the gRPC methods are
registered as *generic* bytes-in/bytes-out handlers — no protoc/flatc
codegen at build or runtime.  Received frames surface as
``format=flexible`` buffers with fully-typed tensors (self-describing
wire), like the wire converter sub-plugins.

Data flow matrix (matching the reference):
- sink server=True  : serves ``RecvTensors``; every buffer rendered into
  the sink is streamed to all connected receivers.
- sink server=False : client; opens ``SendTensors`` and streams buffers
  to the remote server.
- src  server=True  : serves ``SendTensors``; frames pushed by remote
  clients flow into the pipeline.
- src  server=False : client; calls ``RecvTensors`` and pushes the
  received stream into the pipeline.
"""

from __future__ import annotations

import queue as _q
import threading
import time
from typing import Optional

import grpc
import numpy as np

from ..converters.codecs import (
    flatbuf_decode,
    flatbuf_encode,
    flexbuf_decode,
    flexbuf_encode,
    protobuf_decode,
    protobuf_encode,
)
from ..core import Buffer, Caps, TensorFormat, TensorsSpec
from ..obs import hooks as _hooks
from ..obs import tracectx
from ..obs.tracer import TRACE_META_KEY
from ..runtime.element import SinkElement, SourceElement, StreamError
from ..runtime.registry import register_element

SERVICE = "nnstreamer.protobuf.TensorService"
DEFAULT_PORT = 55115

_CODECS = {
    "protobuf": (protobuf_encode, protobuf_decode),
    "flatbuf": (flatbuf_encode, flatbuf_decode),
    "flexbuf": (flexbuf_encode, flexbuf_decode),
}


def _identity(b):
    return bytes(b)


class _GrpcPeer:
    """Shared server/client plumbing for one element."""

    def __init__(self, host: str, port: int, server: bool, idl: str):
        if idl not in _CODECS:
            raise ValueError(f"unknown idl {idl!r}; one of {list(_CODECS)}")
        self.encode, self.decode = _CODECS[idl]
        self.host, self.port, self.is_server = host, int(port), server
        self._server = None
        self._channel = None
        self.bound_port: Optional[int] = None

    # -- server --------------------------------------------------------------

    def start_server(self, send_handler=None, recv_source=None) -> int:
        """``send_handler(frame_bytes)`` consumes incoming SendTensors
        frames; ``recv_source()`` yields outgoing frames for RecvTensors
        subscribers."""
        rpcs = {}
        if send_handler is not None:
            def send_tensors(request_iterator, context):
                for frame in request_iterator:
                    send_handler(frame)
                return b""  # Empty

            rpcs["SendTensors"] = grpc.stream_unary_rpc_method_handler(
                send_tensors, request_deserializer=_identity,
                response_serializer=_identity)
        if recv_source is not None:
            def recv_tensors(request, context):
                for frame in recv_source(context):
                    yield frame

            rpcs["RecvTensors"] = grpc.unary_stream_rpc_method_handler(
                recv_tensors, request_deserializer=_identity,
                response_serializer=_identity)
        from concurrent import futures

        handler = grpc.method_handlers_generic_handler(SERVICE, rpcs)
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
        self._server.add_generic_rpc_handlers((handler,))
        self.bound_port = self._server.add_insecure_port(
            f"{self.host}:{self.port}")
        if self.bound_port == 0:
            raise StreamError(f"grpc: cannot bind {self.host}:{self.port}")
        self._server.start()
        return self.bound_port

    # -- client --------------------------------------------------------------

    def channel(self):
        if self._channel is None:
            self._channel = grpc.insecure_channel(f"{self.host}:{self.port}")
        return self._channel

    def client_send_stream(self, frame_iter) -> None:
        """SendTensors as a client: stream frames, wait for Empty."""
        ch = self.channel()
        call = ch.stream_unary(
            f"/{SERVICE}/SendTensors",
            request_serializer=_identity, response_deserializer=_identity)
        call(frame_iter)

    def client_recv_stream(self):
        """RecvTensors as a client: yields frames from the server."""
        ch = self.channel()
        call = ch.unary_stream(
            f"/{SERVICE}/RecvTensors",
            request_serializer=_identity, response_deserializer=_identity)
        return call(b"")

    def stop(self) -> None:
        if self._server is not None:
            self._server.stop(grace=0.5)
            self._server = None
        if self._channel is not None:
            self._channel.close()
            self._channel = None


@register_element("tensor_sink_grpc")
class GrpcSink(SinkElement):
    """Pipeline → gRPC (server: serve RecvTensors; client: SendTensors)."""

    FACTORY = "tensor_sink_grpc"

    def __init__(self, name=None, host: str = "localhost",
                 port: int = DEFAULT_PORT, server: bool = True,
                 blocking: bool = True, idl: str = "protobuf",
                 out_queue: int = 64, **props):
        self.host, self.port = host, port
        self.server, self.blocking, self.idl = server, blocking, idl
        self.out_queue = out_queue
        super().__init__(name, **props)
        self._peer: Optional[_GrpcPeer] = None
        self._q: "_q.Queue" = _q.Queue(maxsize=int(out_queue))
        self._subscribers: list = []
        self._sub_lock = threading.Lock()
        self._client_thread: Optional[threading.Thread] = None
        self._running = False

    def start(self) -> None:
        self._peer = _GrpcPeer(self.host, self.port, bool(self.server),
                               str(self.idl))
        self._running = True
        if self._peer.is_server:
            self._peer.start_server(recv_source=self._subscriber_frames)
        else:
            from ..obs import prof as _prof

            self._client_thread = _prof.named_thread(
                "edge-grpc-send", self.name, self._client_loop)
            self._client_thread.start()

    @property
    def bound_port(self) -> Optional[int]:
        return self._peer.bound_port if self._peer else None

    def _subscriber_frames(self, context):
        sub: "_q.Queue" = _q.Queue(maxsize=int(self.out_queue))
        with self._sub_lock:
            self._subscribers.append(sub)
        try:
            while self._running and context.is_active():
                try:
                    frame = sub.get(timeout=0.1)
                except _q.Empty:
                    continue
                if frame is None:
                    return
                yield frame
        finally:
            with self._sub_lock:
                if sub in self._subscribers:
                    self._subscribers.remove(sub)

    def _client_loop(self) -> None:
        def frames():
            while self._running:
                try:
                    f = self._q.get(timeout=0.1)
                except _q.Empty:
                    continue
                if f is None:
                    return
                yield f

        try:
            self._peer.client_send_stream(frames())
        except Exception as e:  # noqa: BLE001 — surface as bus error
            if self._running:
                self.post_error(e)

    def _encode(self, buf: Buffer) -> bytes:
        """Codec bytes, plus the trace trailer for a sampled buffer
        (magic-framed suffix, obs.tracectx — the src side strips it
        before handing the frame to the codec)."""
        frame = self._peer.encode(buf, buf.spec())
        tr = buf.meta.get(TRACE_META_KEY)
        if tr is not None:
            frame = tracectx.append_trailer(
                frame, tracectx.oneway_ctx(tr, int(time.time() * 1e6)))
        return frame

    def render(self, buf: Buffer) -> None:
        if self._peer.is_server:
            with self._sub_lock:
                subs = list(self._subscribers)
            if not subs:
                return  # nobody listening: skip the serialization entirely
            frame = self._encode(buf)
            for sub in subs:
                try:
                    sub.put(frame, timeout=1.0 if self.blocking else 0.0)
                except _q.Full:
                    pass  # slow subscriber: drop (non-blocking semantics)
        else:
            frame = self._encode(buf)
            # blocking mode still re-checks _running so a stalled remote
            # cannot wedge the streaming thread past stop()
            while self._running:
                try:
                    self._q.put(frame, timeout=0.2 if self.blocking else 0.0)
                    return
                except _q.Full:
                    if not self.blocking:
                        return  # drop

    @staticmethod
    def _put_sentinel(q: "_q.Queue") -> None:
        """Enqueue the shutdown sentinel even if the queue is full."""
        while True:
            try:
                q.put_nowait(None)
                return
            except _q.Full:
                try:
                    q.get_nowait()
                except _q.Empty:
                    pass

    def stop(self) -> None:
        self._running = False
        self._put_sentinel(self._q)
        with self._sub_lock:
            for sub in self._subscribers:
                self._put_sentinel(sub)
        if self._client_thread is not None:
            self._client_thread.join(timeout=5)
            self._client_thread = None
        if self._peer is not None:
            self._peer.stop()
            self._peer = None


@register_element("tensor_src_grpc")
class GrpcSrc(SourceElement):
    """gRPC → pipeline (server: serve SendTensors; client: RecvTensors)."""

    FACTORY = "tensor_src_grpc"

    def __init__(self, name=None, host: str = "localhost",
                 port: int = DEFAULT_PORT, server: bool = True,
                 blocking: bool = True, idl: str = "protobuf",
                 num_buffers: int = 0, **props):
        self.host, self.port = host, port
        self.server, self.blocking, self.idl = server, blocking, idl
        self.num_buffers = num_buffers
        super().__init__(name, **props)
        self._peer: Optional[_GrpcPeer] = None
        self._q: "_q.Queue" = _q.Queue(maxsize=256)
        self._recv_thread: Optional[threading.Thread] = None
        self._count = 0

    def output_spec(self) -> TensorsSpec:
        # payloads are self-describing (wire header carries the schema)
        return TensorsSpec(format=TensorFormat.FLEXIBLE)

    def output_caps(self) -> Caps:
        return Caps.from_spec(self.output_spec())

    def start(self) -> None:
        self._peer = _GrpcPeer(self.host, self.port, bool(self.server),
                               str(self.idl))
        self._count = 0
        # _running must be set BEFORE the server can deliver frames:
        # _on_frame drops everything while the element is not running
        super().start()
        if self._peer.is_server:
            self._peer.start_server(send_handler=self._on_frame)
        else:
            from ..obs import prof as _prof

            self._recv_thread = _prof.named_thread(
                "edge-grpc-recv", self.name, self._recv_loop)
            self._recv_thread.start()

    @property
    def bound_port(self) -> Optional[int]:
        return self._peer.bound_port if self._peer else None

    def _on_frame(self, frame: bytes) -> None:
        # bounded, interruptible put: a stalled/stopped pipeline must not
        # wedge the gRPC executor thread (its workers are non-daemon and
        # would hang interpreter exit)
        while self._running.is_set():
            try:
                self._q.put(frame, timeout=0.2)
                return
            except _q.Full:
                continue

    def _recv_loop(self) -> None:
        try:
            for frame in self._peer.client_recv_stream():
                self._q.put(frame)
        except grpc.RpcError as e:
            # remote going away is EOS, not an error (classified by
            # status code, never by message text)
            eos_codes = (grpc.StatusCode.CANCELLED,
                         grpc.StatusCode.UNAVAILABLE)
            if self._running.is_set() and e.code() not in eos_codes:
                self.post_error(e)
        except Exception as e:  # noqa: BLE001
            if self._running.is_set():
                self.post_error(e)
        finally:
            self._q.put(None)

    def create(self) -> Optional[Buffer]:
        n = int(self.num_buffers)
        if n and self._count >= n:
            return None
        while self._running.is_set():
            try:
                frame = self._q.get(timeout=0.05)
            except _q.Empty:
                continue
            if frame is None:
                return None  # EOS
            frame, ctx = tracectx.split_trailer(frame)
            buf, _spec = self._peer.decode(frame)
            buf.format = TensorFormat.FLEXIBLE
            if ctx is not None and _hooks.tracer is not None:
                tracectx.plant_oneway(buf.meta, ctx,
                                      int(time.time() * 1e6),
                                      link=self.name,
                                      source_name=self.name)
            self._count += 1
            return buf
        return None

    def stop(self) -> None:
        super().stop()
        if self._peer is not None:
            self._peer.stop()
            self._peer = None
        if self._recv_thread is not None:
            self._recv_thread.join(timeout=5)
            self._recv_thread = None

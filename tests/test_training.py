"""datareposrc/datareposink + tensor_trainer tests.

Parity model: the reference's datarepo unit tests
(/root/reference/tests/nnstreamer_datarepo/) write→read round-trips, and
the trainer tests drive ``datareposrc ! tensor_trainer`` end-to-end.  The
"done" criterion from the round-1 verdict: that pipeline trains
MobileNet-width-0.25 on the 8-device CPU mesh and saves params loadable
by the jax-xla filter.
"""

import json
import os

import numpy as np
import pytest

from nnstreamer_tpu.core import Buffer, TensorsSpec
from nnstreamer_tpu.elements.basic import AppSink, AppSrc
from nnstreamer_tpu.runtime import Pipeline
from nnstreamer_tpu.runtime.events import MessageKind
from nnstreamer_tpu.runtime.registry import make

SPEC2 = TensorsSpec.parse("4:1,1:1", "float32,int32")


def drain(sink, timeout=0.3):
    out = []
    while True:
        b = sink.pull(timeout=timeout)
        if b is None:
            return out
        out.append(b)


class TestDataRepoRoundTrip:
    def _write(self, tmp_path, n=6):
        data, js = str(tmp_path / "d.dat"), str(tmp_path / "d.json")
        p = Pipeline()
        src = AppSrc(name="src", spec=SPEC2)
        snk = make("datareposink", el_name="dsink", location=data, json=js)
        p.add(src, snk).link(src, snk)
        with p:
            for i in range(n):
                src.push_buffer(Buffer.of(
                    np.full((1, 4), float(i), np.float32),
                    np.full((1, 1), i, np.int32)))
            src.end_of_stream()
            assert p.wait_eos(timeout=10)
        return data, js

    def test_sink_writes_descriptor(self, tmp_path):
        data, js = self._write(tmp_path)
        desc = json.load(open(js))
        assert desc["total_samples"] == 6
        assert desc["sample_size"] == 4 * 4 + 4
        assert "other/tensors" in desc["gst_caps"]
        assert os.path.getsize(data) == 6 * desc["sample_size"]

    def test_src_reads_back_in_order(self, tmp_path):
        data, js = self._write(tmp_path)
        p = Pipeline()
        src = make("datareposrc", el_name="dsrc", location=data, json=js,
                   is_shuffle=False, epochs=1)
        snk = AppSink(name="out")
        p.add(src, snk).link(src, snk)
        with p:
            assert p.wait_eos(timeout=10)
            out = drain(snk)
        assert len(out) == 6
        for i, b in enumerate(out):
            assert float(b.tensors[0].np()[0, 0]) == float(i)
            assert int(b.tensors[1].np()[0, 0]) == i

    def test_sample_window_and_epochs(self, tmp_path):
        data, js = self._write(tmp_path)
        p = Pipeline()
        src = make("datareposrc", el_name="dsrc", location=data, json=js,
                   is_shuffle=False, start_sample_index=1,
                   stop_sample_index=3, epochs=2)
        snk = AppSink(name="out")
        p.add(src, snk).link(src, snk)
        with p:
            assert p.wait_eos(timeout=10)
            out = drain(snk)
        vals = [float(b.tensors[0].np()[0, 0]) for b in out]
        assert vals == [1.0, 2.0, 3.0, 1.0, 2.0, 3.0]

    def test_shuffle_permutes_within_epoch(self, tmp_path):
        data, js = self._write(tmp_path)
        p = Pipeline()
        src = make("datareposrc", el_name="dsrc", location=data, json=js,
                   is_shuffle=True, epochs=1, seed=3)
        snk = AppSink(name="out")
        p.add(src, snk).link(src, snk)
        with p:
            assert p.wait_eos(timeout=10)
            out = drain(snk)
        vals = sorted(float(b.tensors[0].np()[0, 0]) for b in out)
        assert vals == [0.0, 1.0, 2.0, 3.0, 4.0, 5.0]

    def test_tensors_sequence_selects_and_reorders(self, tmp_path):
        data, js = self._write(tmp_path)
        p = Pipeline()
        src = make("datareposrc", el_name="dsrc", location=data, json=js,
                   is_shuffle=False, epochs=1, tensors_sequence="1,0")
        snk = AppSink(name="out")
        p.add(src, snk).link(src, snk)
        with p:
            assert p.wait_eos(timeout=10)
            out = drain(snk)
        b = out[2]
        assert b.tensors[0].spec.dtype.name.lower() == "int32"
        assert float(b.tensors[1].np()[0, 0]) == 2.0

    def test_pattern_mode_teardown_without_eos_writes_descriptor(
            self, tmp_path):
        """Round-2 verdict weak #5: image-pattern mode never opens
        ``_file``, so an early teardown (stop() without EOS) used to skip
        the JSON descriptor, leaving the dataset unreadable."""
        from nnstreamer_tpu.core import TensorFormat

        pat = str(tmp_path / "img_%04d.raw")
        js = str(tmp_path / "imgs.json")
        snk = make("datareposink", el_name="ds", location=pat, json=js)
        snk.start()
        for i in range(3):
            snk.render(Buffer.of(
                np.arange(4 + i, dtype=np.uint8),
                format=TensorFormat.FLEXIBLE))
        snk.stop()  # torn down early — no on_eos()
        desc = json.load(open(js))
        assert desc["total_samples"] == 3
        assert desc["location_pattern"] == pat
        # and the dataset is actually readable back
        src = make("datareposrc", el_name="dr", location=pat, json=js,
                   is_shuffle=False, epochs=1)
        bufs = []
        while True:
            src._running.set()
            b = src.create()
            if b is None:
                break
            bufs.append(b)
        assert [b.tensors[0].shape for b in bufs] == [(4,), (5,), (6,)]

    def test_zero_sample_stop_does_not_clobber_descriptor(self, tmp_path):
        """A run that errors before the first render() must not overwrite
        a pre-existing dataset descriptor with an empty one."""
        data, js = str(tmp_path / "c.dat"), str(tmp_path / "c.json")
        with open(js, "w") as f:
            f.write('{"total_samples": 5, "sample_size": 20}')
        snk = make("datareposink", el_name="ds", location=data, json=js)
        snk.start()
        snk.stop()  # nothing rendered
        assert json.load(open(js))["total_samples"] == 5

    def test_failed_open_does_not_clobber_descriptor(self, tmp_path):
        """render() failing at open() (unwritable location) touched no
        data — stop() must preserve the pre-existing descriptor."""
        from nnstreamer_tpu.core import TensorFormat

        pat = str(tmp_path / "nodir" / "img_%04d.raw")  # missing dir
        js = str(tmp_path / "d.json")
        with open(js, "w") as f:
            f.write('{"total_samples": 100, "location_pattern": "x"}')
        snk = make("datareposink", el_name="ds", location=pat, json=js)
        snk.start()
        with pytest.raises(OSError):
            snk.render(Buffer.of(np.zeros(4, np.uint8),
                                 format=TensorFormat.FLEXIBLE))
        snk.stop()
        assert json.load(open(js))["total_samples"] == 100

    def test_zero_sample_stop_fresh_location_writes_empty(self, tmp_path):
        """A fresh location (no pre-existing descriptor) still gets a
        valid empty descriptor on early teardown, so tooling that opens
        the json sees an empty dataset instead of FileNotFoundError."""
        data, js = str(tmp_path / "e.dat"), str(tmp_path / "e.json")
        snk = make("datareposink", el_name="ds", location=data, json=js)
        snk.start()
        snk.stop()
        assert json.load(open(js))["total_samples"] == 0

    def test_stop_after_eos_does_not_rewrite_descriptor(self, tmp_path):
        data, js = str(tmp_path / "s.dat"), str(tmp_path / "s.json")
        snk = make("datareposink", el_name="ds", location=data, json=js)
        snk.start()
        snk.render(Buffer.of(np.zeros((1, 4), np.float32)))
        snk.on_eos()
        os.remove(js)
        snk.stop()  # already finalized: must not re-write
        assert not os.path.exists(js)

    def test_flexible_roundtrip(self, tmp_path):
        data, js = str(tmp_path / "f.dat"), str(tmp_path / "f.json")
        from nnstreamer_tpu.core import TensorFormat

        snk = make("datareposink", el_name="ds", location=data, json=js)
        for i in range(3):
            snk.render(Buffer.of(
                np.arange(2 + i, dtype=np.float32),
                format=TensorFormat.FLEXIBLE))
        snk.on_eos()
        src = make("datareposrc", el_name="dr", location=data, json=js,
                   is_shuffle=False, epochs=1)
        bufs = []
        while True:
            src._running.set()
            b = src.create()
            if b is None:
                break
            bufs.append(b)
        assert [b.tensors[0].shape for b in bufs] == [(2,), (3,), (4,)]


def _write_dataset(tmp_path, n=16, size=8, classes=4):
    """Tiny labeled image dataset through datareposink."""
    data, js = str(tmp_path / "train.dat"), str(tmp_path / "train.json")
    spec = TensorsSpec.parse(f"3:{size}:{size}:1,1:1", "float32,int32")
    p = Pipeline()
    src = AppSrc(name="src", spec=spec)
    snk = make("datareposink", el_name="dsink", location=data, json=js)
    p.add(src, snk).link(src, snk)
    rng = np.random.default_rng(0)
    with p:
        for i in range(n):
            x = rng.standard_normal((1, size, size, 3)).astype(np.float32)
            y = np.array([[i % classes]], np.int32)
            src.push_buffer(Buffer.of(x, y))
        src.end_of_stream()
        assert p.wait_eos(timeout=10)
    return data, js


class TestTrainerPipeline:
    def test_datareposrc_trains_mobilenet_and_saves(self, tmp_path):
        """The round-1 verdict 'done' criterion: datareposrc !
        tensor_trainer trains MobileNet-w0.25 on the 8-CPU mesh and saves
        params the jax-xla filter can load."""
        import jax

        data, js = _write_dataset(tmp_path, n=16, size=8, classes=4)
        save = str(tmp_path / "model.pkl")
        params = None

        def init(rng):
            from nnstreamer_tpu.models.mobilenet import mobilenet_v1_init

            return mobilenet_v1_init(rng, num_classes=4, width=0.25)

        events = []
        p = Pipeline()
        src = make("datareposrc", el_name="dsrc", location=data, json=js,
                   is_shuffle=False, epochs=2)
        trn = make(
            "tensor_trainer", el_name="trn", framework="jax-optax",
            model_config={
                "apply":
                    "nnstreamer_tpu.models.mobilenet:mobilenet_v1_apply",
                "init": init, "batch_size": 8, "lr": 1e-2,
                "mesh": "data:-1"},
            model_save_path=save, num_inputs=1, num_labels=1,
            num_training_samples=16, num_validation_samples=0, epochs=2)
        snk = AppSink(name="out")
        p.add(src, trn, snk).link(src, trn, snk)
        p.bus.add_watch(
            lambda m: events.append(m.data.get("event"))
            if m.kind == MessageKind.ELEMENT else None)
        with p:
            assert p.wait_eos(timeout=180)
            stats = drain(snk)
        assert events.count("epoch-completion") == 2
        assert "training-completion" in events
        # per-sample status buffers: 5 float64 fields
        assert stats and stats[-1].tensors[0].shape == (1, 5)
        final_loss = float(stats[-1].tensors[0].np()[0, 1])
        assert np.isfinite(final_loss)
        # saved model loads straight into the single-shot filter API
        assert os.path.exists(save)
        from nnstreamer_tpu.elements.filter import FilterSingle

        with FilterSingle(framework="jax-xla", model=save) as f:
            out = f.invoke(
                [np.zeros((8, 8, 8, 3), np.float32)])
            assert np.asarray(out[0]).shape == (8, 4)

    def test_trainer_loss_decreases_on_learnable_data(self, tmp_path):
        """Linear separable toy data: epoch losses must decrease."""
        epoch_losses = []

        def apply_fn(params, x, train=False):
            return x @ params["w"] + params["b"]

        import nnstreamer_tpu  # noqa: F401 - namespace for the trainer

        # register the apply so model-config can reference it importably
        import tests.test_training as me

        me.toy_apply = apply_fn

        data, js = None, None
        spec = TensorsSpec.parse("8:1,1:1", "float32,int32")
        p = Pipeline()
        src = AppSrc(name="src", spec=spec)
        trn = make(
            "tensor_trainer", el_name="trn", framework="jax-optax",
            model_config={
                "apply": "tests.test_training:toy_apply",
                "init": {"w": np.zeros((8, 2), np.float32),
                         "b": np.zeros((2,), np.float32)},
                "batch_size": 8, "lr": 0.5, "optimizer": "sgd",
                "mesh": "data:-1"},
            num_inputs=1, num_labels=1, num_training_samples=32,
            epochs=3)
        # 96 per-sample status buffers flow before the test drains:
        # size the sink above that so the streaming thread never blocks
        snk = AppSink(name="out", max_buffers=128)
        p.add(src, trn, snk).link(src, trn, snk)
        p.bus.add_watch(
            lambda m: epoch_losses.append(m.data["training_loss"])
            if m.kind == MessageKind.ELEMENT
            and m.data.get("event") == "epoch-completion" else None)
        rng = np.random.default_rng(1)
        with p:
            for e in range(3):
                for i in range(32):
                    y = i % 2
                    x = rng.standard_normal(8).astype(np.float32) + \
                        (3.0 if y else -3.0)
                    src.push_buffer(Buffer.of(
                        x.reshape(1, 8), np.array([[y]], np.int32)))
            src.end_of_stream()
            assert p.wait_eos(timeout=120)
            stats = drain(snk)
        assert len(epoch_losses) == 3
        assert epoch_losses[0] > 0
        assert epoch_losses[-1] < epoch_losses[0]
        assert len(stats) == 96  # one status buffer per sample

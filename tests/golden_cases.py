"""Shared golden-pipeline case definitions (SSAT analog).

Parity model: the reference's SSAT tier — ~60 directories of
``gst-launch … ! filesink`` pipelines compared against committed golden
files (/root/reference/tests/nnstreamer_decoder_boundingbox/runTest.sh,
tests/transform_arithmetic/runTest.sh, …).  Here each case is a
string-described pipeline built with ``parse_launch`` ending in a
``filesink``; its byte output is compared against a file committed under
``tests/golden/``.

Inputs are deterministic (seeded ``np.random.default_rng`` or
arithmetic ramps) and filters use deterministic ``custom-easy`` models —
the reference's "passthrough/scaler" custom-filter fixture pattern — so
goldens are stable across devices.  Regenerate with
``python tests/golden_cases.py regen`` after INTENTIONAL behavior
changes, and commit the diff.
"""

import os
import sys
from fractions import Fraction

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from nnstreamer_tpu.core import Buffer, TensorsSpec  # noqa: E402
from nnstreamer_tpu.filters.custom import register_custom_easy  # noqa: E402
from nnstreamer_tpu.runtime import parse_launch  # noqa: E402

GOLDEN_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "golden")


def _rng(seed=42):
    return np.random.default_rng(seed)


def _ensure_scaler():
    """The reference's most load-bearing fixture: a deterministic
    'scaler' custom filter (tests/nnstreamer_example/custom_example_scaler)."""
    spec = TensorsSpec.parse("8:4", "float32")
    register_custom_easy(
        "golden_scaler", lambda xs: [xs[0] * 2.0 + 1.0],
        in_spec=spec, out_spec=spec)


def _push_eos(p, src_name, buffers):
    src = p[src_name]
    for b in buffers:
        src.push_buffer(b)
    src.end_of_stream()
    assert p.wait_eos(timeout=120), "pipeline did not reach EOS"


# -- cases -------------------------------------------------------------------
# each: name -> run(out_path) writing the pipeline's filesink output


def case_transform_arithmetic(out):
    """appsrc ! tensor_transform(arith) ! filesink
    (parity: tests/transform_arithmetic)."""
    p = parse_launch(
        "appsrc name=src ! tensor_transform mode=arithmetic "
        "option=typecast:float32,add:-2.0,mul:0.5 ! "
        f"filesink location={out}")
    p["src"].spec = TensorsSpec.parse("8:2", "uint8", rate=Fraction(10))
    x = np.arange(16, dtype=np.uint8).reshape(2, 8)
    with p:
        _push_eos(p, "src", [Buffer.of(x)])


def case_custom_easy_scaler(out):
    """appsrc ! tensor_filter(custom-easy scaler) ! filesink
    (parity: nnstreamer_filter_custom SSAT)."""
    _ensure_scaler()
    p = parse_launch(
        "appsrc name=src ! tensor_filter framework=custom-easy "
        f"model=golden_scaler ! filesink location={out}")
    p["src"].spec = TensorsSpec.parse("8:4", "float32", rate=Fraction(10))
    x = _rng().standard_normal((4, 8)).astype(np.float32)
    with p:
        _push_eos(p, "src", [Buffer.of(x)])


def case_decoder_direct_video(out):
    p = parse_launch(
        "appsrc name=src ! tensor_decoder mode=direct_video ! "
        f"filesink location={out}")
    p["src"].spec = TensorsSpec.parse("3:16:12:1", "uint8",
                                      rate=Fraction(10))
    x = _rng(1).integers(0, 255, (1, 12, 16, 3), np.uint8)
    with p:
        _push_eos(p, "src", [Buffer.of(x)])


def case_decoder_image_labeling(out, labels_path):
    p = parse_launch(
        "appsrc name=src ! tensor_decoder mode=image_labeling "
        f"option1={labels_path} ! filesink location={out}")
    p["src"].spec = TensorsSpec.parse("5", "float32", rate=Fraction(10))
    x = np.array([0.05, 0.1, 0.7, 0.05, 0.1], np.float32)
    with p:
        _push_eos(p, "src", [Buffer.of(x)])


def case_decoder_boundingbox_pp(out):
    """Post-processed detections → RGBA overlay (no labels: the overlay
    bytes must not depend on the PIL font)."""
    p = parse_launch(
        "appsrc name=src ! tensor_decoder mode=bounding_boxes "
        "option1=mobilenet-ssd-postprocess option4=32:32 option5=32:32 ! "
        f"filesink location={out}")
    p["src"].spec = TensorsSpec.of(
        *TensorsSpec.parse("4:3,3,3,1", "float32,float32,float32,int32"
                           ).tensors, rate=Fraction(10))
    boxes = np.array([[0.1, 0.1, 0.6, 0.5], [0.5, 0.5, 0.9, 0.9],
                      [0, 0, 0, 0]], np.float32)
    classes = np.array([1, 2, 0], np.float32)
    scores = np.array([0.9, 0.8, 0.0], np.float32)
    num = np.array([2], np.int32)
    with p:
        _push_eos(p, "src", [Buffer.of(boxes, classes, scores, num)])


def case_decoder_image_segment(out):
    p = parse_launch(
        "appsrc name=src ! tensor_decoder mode=image_segment "
        "option1=tflite-deeplab ! "
        f"filesink location={out}")
    p["src"].spec = TensorsSpec.parse("4:8:8:1", "float32", rate=Fraction(10))
    x = _rng(2).standard_normal((1, 8, 8, 4)).astype(np.float32)
    with p:
        _push_eos(p, "src", [Buffer.of(x)])


def case_decoder_pose(out):
    p = parse_launch(
        "appsrc name=src ! tensor_decoder mode=pose_estimation "
        "option1=16:16 option2=8:8 ! "
        f"filesink location={out}")
    p["src"].spec = TensorsSpec.parse("14:8:8:1", "float32",
                                      rate=Fraction(10))
    x = _rng(3).standard_normal((1, 8, 8, 14)).astype(np.float32)
    with p:
        _push_eos(p, "src", [Buffer.of(x)])


def case_decoder_tensor_region(out):
    p = parse_launch(
        "appsrc name=src ! tensor_decoder mode=tensor_region "
        "option1=1 ! "
        f"filesink location={out}")
    p["src"].spec = TensorsSpec.of(
        *TensorsSpec.parse("4:2,2,2,1", "float32,float32,float32,int32"
                           ).tensors, rate=Fraction(10))
    boxes = np.array([[0.1, 0.2, 0.5, 0.6], [0.3, 0.3, 0.9, 0.9]],
                     np.float32)
    classes = np.array([1, 2], np.float32)
    scores = np.array([0.9, 0.4], np.float32)
    num = np.array([2], np.int32)
    with p:
        _push_eos(p, "src", [Buffer.of(boxes, classes, scores, num)])


def case_decoder_octet_stream(out):
    p = parse_launch(
        "appsrc name=src ! tensor_decoder mode=octet_stream ! "
        f"filesink location={out}")
    p["src"].spec = TensorsSpec.parse("6,3", "uint8,float32",
                                      rate=Fraction(10))
    with p:
        _push_eos(p, "src", [Buffer.of(
            np.arange(6, dtype=np.uint8),
            np.array([1.5, -2.5, 3.5], np.float32))])


def _wire_case(mode):
    def run(out):
        p = parse_launch(
            f"appsrc name=src ! tensor_decoder mode={mode} ! "
            f"filesink location={out}")
        p["src"].spec = TensorsSpec.parse("4:2,3", "float32,int32",
                                          rate=Fraction(30))
        a = np.linspace(-1, 1, 8, dtype=np.float32).reshape(2, 4)
        b = np.array([7, 8, 9], np.int32)
        with p:
            _push_eos(p, "src", [Buffer.of(a, b)])
    return run


case_decoder_flexbuf = _wire_case("flexbuf")
case_decoder_flatbuf = _wire_case("flatbuf")
case_decoder_protobuf = _wire_case("protobuf")


def case_wire_roundtrip_protobuf(out):
    """decoder(protobuf) ! tensor_converter ! filesink: the full wire
    round-trip re-emits the original payload bytes."""
    p = parse_launch(
        "appsrc name=src ! tensor_decoder mode=protobuf ! "
        "tensor_converter ! tensor_decoder mode=octet_stream ! "
        f"filesink location={out}")
    p["src"].spec = TensorsSpec.parse("4:2", "float32", rate=Fraction(30))
    a = np.linspace(0, 1, 8, dtype=np.float32).reshape(2, 4)
    with p:
        _push_eos(p, "src", [Buffer.of(a)])


def case_converter_octet(out):
    """filesrc ! tensor_converter(octet) ! tensor_transform ! filesink:
    media-file ingestion path (parity: octet SSAT cases)."""
    raw = os.path.join(GOLDEN_DIR, "input_octet.bin")
    p = parse_launch(
        f"filesrc name=src location={raw} blocksize=12 ! "
        "tensor_converter input-dim=4:3 input-type=uint8 ! "
        "tensor_transform mode=typecast option=float32 ! "
        f"filesink location={out}")
    with p:
        assert p.wait_eos(timeout=120)


def case_mux_aggregate(out):
    """two appsrcs ! tensor_mux ! tensor_aggregator ! filesink."""
    p = parse_launch(
        "tensor_mux name=m sync-mode=nosync ! "
        "tensor_aggregator frames-in=1 frames-out=2 frames-flush=2 "
        "frames-dim=1 ! "
        f"filesink location={out} "
        "appsrc name=a ! m.sink_0 appsrc name=b ! m.sink_1")
    p["a"].spec = TensorsSpec.parse("4:1", "float32", rate=Fraction(10))
    p["b"].spec = TensorsSpec.parse("4:1", "float32", rate=Fraction(10))
    with p:
        for i in range(2):
            p["a"].push_buffer(Buffer.of(
                np.full((1, 4), i, np.float32), pts=i * 10**8))
            p["b"].push_buffer(Buffer.of(
                np.full((1, 4), 10 + i, np.float32), pts=i * 10**8))
        p["a"].end_of_stream()
        p["b"].end_of_stream()
        assert p.wait_eos(timeout=120)


def _transform_case(mode, option, dims="4:3", types="float32",
                    data=None, seed=11):
    """One golden per transform mode (parity: the reference's
    tests/transform_{arithmetic,clamp,dimchg,padding,stand,transpose,
    typecast} SSAT directories)."""
    def run(out):
        p = parse_launch(
            f"appsrc name=src ! tensor_transform mode={mode} "
            f"option={option} ! filesink location={out}")
        p["src"].spec = TensorsSpec.parse(dims, types, rate=Fraction(10))
        x = data if data is not None else \
            _rng(seed).standard_normal(
                tuple(reversed([int(d) for d in dims.split(":")]))
            ).astype(np.float32)
        with p:
            _push_eos(p, "src", [Buffer.of(x)])
    return run


case_transform_typecast = _transform_case(
    "typecast", "int16",
    data=np.array([[1.9, -2.9, 100.5, -100.5]], np.float32), dims="4:1")
case_transform_clamp = _transform_case("clamp", "-0.5:0.5")
case_transform_stand = _transform_case("stand", "default")
case_transform_transpose = _transform_case(
    "transpose", "1:0:2:3", dims="4:3:2:1")
case_transform_dimchg = _transform_case("dimchg", "0:2", dims="4:3:2:1")
case_transform_padding = _transform_case("padding", "1:2,value:0.5")


def case_demux_tensorpick(out):
    """Multi-tensor stream → pick/reorder (parity:
    tests/nnstreamer_demux SSAT)."""
    p = parse_launch(
        "appsrc name=src ! tensor_demux name=d tensorpick=1,0 "
        f"d.src_0 ! filesink location={out} "
        "d.src_1 ! fakesink")
    p["src"].spec = TensorsSpec.parse("4:1,2:1", "float32,int32",
                                      rate=Fraction(10))
    with p:
        _push_eos(p, "src", [Buffer.of(
            np.array([[1, 2, 3, 4]], np.float32),
            np.array([[9, 8]], np.int32))])


def case_split_tensorseg(out):
    """One tensor split along a dim (parity: tests/nnstreamer_split)."""
    p = parse_launch(
        "appsrc name=src ! tensor_split name=s tensorseg=2:2 dimension=0 "
        f"s.src_0 ! filesink location={out} "
        "s.src_1 ! fakesink")
    p["src"].spec = TensorsSpec.parse("4:1", "float32", rate=Fraction(10))
    with p:
        _push_eos(p, "src", [Buffer.of(
            np.array([[1, 2, 3, 4]], np.float32))])


def case_if_passthrough_else_fill(out):
    """Data-dependent branch: frame 1 passes (avg>0), frame 2 takes the
    else path and is zero-filled; both branch pads rejoin through
    ``join`` so the golden captures the full then/else routing (parity:
    tests/nnstreamer_if + gst/join usage)."""
    p = parse_launch(
        f"join name=j ! filesink location={out} "
        "appsrc name=src ! tensor_if name=i compared-value=AVERAGE "
        "compared-value-option=0 operator=gt supplied-value=0 "
        "then=PASSTHROUGH else=FILL_ZERO "
        "i.src_then ! j.sink_0  i.src_else ! j.sink_1")
    p["src"].spec = TensorsSpec.parse("4:1", "float32", rate=Fraction(10))
    with p:
        _push_eos(p, "src", [
            Buffer.of(np.array([[1, 2, 3, 4]], np.float32)),
            Buffer.of(np.array([[-5, -6, -7, -8]], np.float32)),
        ])


def case_sparse_roundtrip(out):
    """static → sparse → static re-emits the original payload (parity:
    tests/nnstreamer_filter_extensions sparse SSAT)."""
    p = parse_launch(
        "appsrc name=src ! tensor_sparse_enc ! tensor_sparse_dec ! "
        f"filesink location={out}")
    p["src"].spec = TensorsSpec.parse("8:1", "float32", rate=Fraction(10))
    x = np.zeros((1, 8), np.float32)
    x[0, 2], x[0, 5] = 3.5, -1.25
    with p:
        _push_eos(p, "src", [Buffer.of(x)])


def case_aggregator_window(out):
    """Temporal windowing: 4 frames in, 2-frame windows out (parity:
    tests/nnstreamer_aggregator)."""
    p = parse_launch(
        "appsrc name=src ! tensor_aggregator frames-in=1 frames-out=2 "
        "frames-flush=2 frames-dim=1 ! "
        f"filesink location={out}")
    p["src"].spec = TensorsSpec.parse("3:1", "float32", rate=Fraction(10))
    with p:
        _push_eos(p, "src", [
            Buffer.of(np.full((1, 3), float(i), np.float32),
                      pts=i * 10**8)
            for i in range(4)])


def case_converter_flexible_to_static(out):
    """flexible → static conversion through tensor_converter (parity:
    tests/nnstreamer_converter SSAT)."""
    from nnstreamer_tpu.core import TensorFormat

    p = parse_launch(
        "appsrc name=src ! tensor_converter input-dim=4:1 "
        f"input-type=float32 ! filesink location={out}")
    p["src"].spec = TensorsSpec(format=TensorFormat.FLEXIBLE)
    with p:
        _push_eos(p, "src", [Buffer.of(
            np.array([[0.5, 1.5, -2.5, 4.0]], np.float32),
            format=TensorFormat.FLEXIBLE)])


def case_query_offload(out):
    """Query offload round-trip: a client pipeline sends every frame
    through a SERVER pipeline (custom-easy scaler) and filesinks the
    answers (parity: /root/reference/tests/nnstreamer_edge/query/
    runTest.sh — paired gst-launch client/server with golden compare)."""
    _ensure_scaler()
    srv = parse_launch(
        "tensor_query_serversrc name=qsrc host=golden-query port=7401 "
        "connect-type=inproc id=71 "
        "caps=other/tensors,dimensions=8:4,types=float32 ! "
        "tensor_filter framework=custom-easy model=golden_scaler ! "
        "tensor_query_serversink id=71")
    cli = parse_launch(
        "appsrc name=src ! tensor_query_client host=golden-query "
        "port=7401 connect-type=inproc timeout=30000 ! "
        f"filesink location={out}")
    cli["src"].spec = TensorsSpec.parse("8:4", "float32",
                                        rate=Fraction(10))
    with srv:
        with cli:
            _push_eos(cli, "src", [
                Buffer.of(_rng(7).standard_normal((4, 8)
                                                  ).astype(np.float32)),
                Buffer.of(np.arange(32, dtype=np.float32).reshape(4, 8)),
            ])


def case_trainer_status(out):
    """Trainer status stream: datarepo-style samples through
    tensor_trainer with a DETERMINISTIC numpy trainer sub-plugin; the
    per-sample [epoch, losses…] float64 status tensors are the golden
    (parity: gsttensor_trainer.c:889 status output + the reference's
    nnstreamer_trainer SSAT tier).  A numpy mean-squared trainer keeps
    the bytes identical across jax versions and backends."""
    from nnstreamer_tpu.trainers import (
        EVENT_EPOCH_COMPLETION,
        EVENT_TRAINING_COMPLETION,
        TrainerSubplugin,
        register_trainer,
    )

    @register_trainer
    class GoldenNpTrainer(TrainerSubplugin):
        """Running-MSE 'trainer': pure float64 numpy, bit-deterministic."""

        NAME = "golden-np"

        def __init__(self):
            super().__init__()
            self._n = 0
            self._loss_sum = 0.0
            self._epoch = 0

        def push_data(self, inputs, labels, is_validation=False):
            x = np.asarray(inputs[0], np.float64)
            y = np.asarray(labels[0], np.float64)
            self._loss_sum += float(np.mean((x - y) ** 2))
            self._n += 1
            per = (self.props.num_training_samples
                   + self.props.num_validation_samples)
            if per and self._n % per == 0:
                self._epoch += 1
                if self.notify is not None:
                    self.notify(EVENT_EPOCH_COMPLETION, self.get_status())
                if self._epoch >= self.props.num_epochs:
                    self.finished.set()
                    if self.notify is not None:
                        self.notify(EVENT_TRAINING_COMPLETION,
                                    self.get_status())

        def get_status(self):
            return {"epoch": float(self._epoch),
                    "training_loss": self._loss_sum / max(self._n, 1),
                    "training_accuracy": 1.0 / (1 + self._epoch),
                    "validation_loss": 0.0, "validation_accuracy": 0.0}

        def save(self, path):
            pass

    p = parse_launch(
        "appsrc name=src ! tensor_trainer framework=golden-np "
        "num-inputs=1 num-labels=1 num-training-samples=3 "
        "num-validation-samples=0 epochs=2 ! "
        f"filesink location={out}")
    p["src"].spec = TensorsSpec.parse("4:1,4:1", "float32,float32",
                                      rate=Fraction(10))
    samples = []
    for i in range(6):  # 2 epochs x 3 samples
        x = np.linspace(0, 1, 4, dtype=np.float32).reshape(1, 4) * (i + 1)
        y = np.ones((1, 4), np.float32)
        samples.append(Buffer.of(x, y, pts=i * 10**8))
    with p:
        _push_eos(p, "src", samples)


def case_decoder_yolov8(out):
    """Raw v8 wire tensor (1, 4+C, A) → yolov8 scheme → RGBA overlay
    (parity: box_properties/yolo.cc v8 branch; pixel-space xywh, class
    confidences, host NMS + draw)."""
    C, A = 4, 6
    arr = np.zeros((1, 4 + C, A), np.float32)
    # anchor 0: a confident class-1 box; anchor 3: class-3; rest silent
    arr[0, :4, 0] = [16.0, 16.0, 12.0, 10.0]   # cx, cy, w, h in pixels
    arr[0, 4 + 1, 0] = 0.9
    arr[0, :4, 3] = [24.0, 8.0, 8.0, 8.0]
    arr[0, 4 + 3, 3] = 0.8
    p = parse_launch(
        "appsrc name=src ! tensor_decoder mode=bounding_boxes "
        "option1=yolov8 option4=32:32 option5=32:32 ! "
        f"filesink location={out}")
    p["src"].spec = TensorsSpec.parse(f"{A}:{4 + C}:1", "float32",
                                      rate=Fraction(10))
    with p:
        _push_eos(p, "src", [Buffer.of(arr)])


def case_decoder_yolov5(out):
    """Raw v5 wire tensor (1, A, 5+C) → yolov5 scheme → RGBA overlay
    (parity: box_properties/yolo.cc v5 branch; objectness × class)."""
    C, A = 4, 6
    arr = np.zeros((1, A, 5 + C), np.float32)
    arr[0, 0, :4] = [16.0, 16.0, 12.0, 10.0]
    arr[0, 0, 4] = 0.95                        # objectness
    arr[0, 0, 5 + 2] = 0.9
    arr[0, 4, :4] = [8.0, 24.0, 6.0, 6.0]
    arr[0, 4, 4] = 0.9
    arr[0, 4, 5 + 0] = 0.85
    p = parse_launch(
        "appsrc name=src ! tensor_decoder mode=bounding_boxes "
        "option1=yolov5 option4=32:32 option5=32:32 ! "
        f"filesink location={out}")
    p["src"].spec = TensorsSpec.parse(f"{5 + C}:{A}:1", "float32",
                                      rate=Fraction(10))
    with p:
        _push_eos(p, "src", [Buffer.of(arr)])


def case_rate_downsample(out):
    """10 fps → tensor_rate 5/1 → filesink: every other frame dropped
    (parity: tests/nnstreamer_rate)."""
    SEC = 1_000_000_000
    p = parse_launch(
        "appsrc name=src ! tensor_rate framerate=5/1 ! "
        f"filesink location={out}")
    p["src"].spec = TensorsSpec.parse("4", "float32", rate=Fraction(10))
    bufs = [Buffer.of(np.full((4,), i, np.float32), pts=i * SEC // 10)
            for i in range(10)]
    with p:
        _push_eos(p, "src", bufs)


def case_crop_regions(out):
    """raw + crop-info streams → tensor_crop → filesink (parity:
    tests/nnstreamer_decoder_tensorRegion + tensor_crop SSAT: crop raw
    by regions carried in a flexible second stream)."""
    p = parse_launch(
        f"tensor_crop name=crop ! filesink location={out} "
        "appsrc name=raw ! crop.sink_raw "
        "appsrc name=info ! crop.sink_info")
    p["raw"].spec = TensorsSpec.parse("3:8:8", "uint8", rate=Fraction(10))
    p["info"].spec = TensorsSpec.parse("4:2", "uint32", rate=Fraction(10))
    img = np.arange(8 * 8 * 3, dtype=np.uint8).reshape(8, 8, 3)
    regions = np.array([[1, 2, 4, 3], [0, 0, 2, 2]], np.uint32)
    raw, info = p["raw"], p["info"]
    with p:
        raw.push_buffer(Buffer.of(img))
        info.push_buffer(Buffer.of(regions))
        raw.end_of_stream()
        info.end_of_stream()
        assert p.wait_eos(timeout=120), "crop pipeline did not reach EOS"


def case_repo_loop(out):
    """reposrc → add:1 → tee → reposink + filesink: the cyclic-stream
    counter (parity: tests/nnstreamer_repo_lstm — recurrence via the
    out-of-band tensor repository)."""
    from nnstreamer_tpu.elements.repo import REPO

    REPO.reset()
    p = parse_launch(
        "tensor_reposrc name=loop slot=0 num_buffers=5 "
        "caps=other/tensors,format=static,num_tensors=1,"
        "dimensions=1,types=float32,framerate=0/1 ! "
        "tensor_transform mode=arithmetic option=add:1 ! "
        f"tee name=t ! tensor_reposink slot=0 t. ! filesink location={out}")
    with p:
        assert p.wait_eos(timeout=120), "repo loop did not reach EOS"


def case_mqtt_loopback(out):
    """appsrc ! mqttsink → MiniBroker → mqttsrc ! filesink (parity:
    tests/nnstreamer_mqtt loopback over a real 3.1.1 broker)."""
    import time as _time

    from nnstreamer_tpu.edge.mqtt import MiniBroker
    from nnstreamer_tpu.runtime import Pipeline
    from nnstreamer_tpu.runtime.registry import make

    broker = MiniBroker()  # serving from construction
    try:
        spec = TensorsSpec.parse("4:2", "float32", rate=Fraction(30))
        recv = parse_launch(
            f"mqttsrc name=ms host=127.0.0.1 port={broker.port} "
            f"sub_topic=nns/golden num_buffers=3 ! "
            f"filesink location={out}")
        recv.start()
        send = Pipeline()
        from nnstreamer_tpu.elements.basic import AppSrc

        asrc = AppSrc(name="src", spec=spec)
        msink = make("mqttsink", el_name="mk", host="127.0.0.1",
                     port=broker.port, pub_topic="nns/golden")
        send.add(asrc, msink).link(asrc, msink)
        send.start()
        try:
            _time.sleep(0.3)  # let the subscription settle
            for i in range(3):
                asrc.push_buffer(Buffer.of(
                    np.full((2, 4), i, np.float32), pts=i * 10))
            assert recv.wait_eos(timeout=120), "mqtt loopback stalled"
            asrc.end_of_stream()
        finally:
            send.stop()
            recv.stop()
    finally:
        broker.stop()


def case_grpc_roundtrip(out):
    """tensor_sink_grpc(server) ← tensor_src_grpc(client) ! filesink
    (parity: tests/nnstreamer_grpc protobuf IDL round-trip)."""
    import time as _time

    from nnstreamer_tpu.elements.basic import AppSrc
    from nnstreamer_tpu.runtime import Pipeline
    from nnstreamer_tpu.runtime.registry import make

    spec = TensorsSpec.parse("4:2", "float32", rate=Fraction(30))
    snk = make("tensor_sink_grpc", el_name="gs", server=True, port=0,
               idl="protobuf")
    p1 = Pipeline()
    asrc = AppSrc(name="src", spec=spec)
    p1.add(asrc, snk).link(asrc, snk)
    p1.start()
    try:
        port = snk.bound_port
        recv = parse_launch(
            f"tensor_src_grpc name=gr server=false port={port} "
            f"idl=protobuf num_buffers=3 ! filesink location={out}")
        recv.start()
        try:
            _time.sleep(0.3)  # let the RecvTensors subscription attach
            for i in range(3):
                asrc.push_buffer(Buffer.of(np.full((2, 4), i, np.float32)))
            assert recv.wait_eos(timeout=120), "grpc roundtrip stalled"
            asrc.end_of_stream()
        finally:
            recv.stop()
    finally:
        p1.stop()


#: Accuracy-bearing SEMANTIC golden (round-3 verdict #3): REAL
#: pretrained weights (the reference's mobilenet_v2 quant .tflite,
#: imported through filters/tflite_import.py) classify a REAL image and
#: the committed golden is the literal label text.  Gated on the
#: reference assets being present (they are data inputs, not code).
_SEMANTIC_REF = "/root/reference/tests/test_models"
_SEMANTIC_MODEL = os.path.join(
    _SEMANTIC_REF, "models", "mobilenet_v2_1.0_224_quant.tflite")
_SEMANTIC_IMAGE = os.path.join(_SEMANTIC_REF, "data", "orange.raw")
_SEMANTIC_LABELS = os.path.join(_SEMANTIC_REF, "labels", "labels.txt")


def semantic_assets_present() -> bool:
    return all(os.path.isfile(f) for f in
               (_SEMANTIC_MODEL, _SEMANTIC_IMAGE, _SEMANTIC_LABELS))


def case_semantic_classify_orange(out):
    """filesrc(raw image) → converter → tflite mobilenet_v2 →
    image_labeling → filesink: the golden holds the string "orange".
    Parity: the reference's canonical accuracy pipeline
    (tests/test_models/data/orange.png through
    mobilenet_v2_1.0_224_quant.tflite)."""
    p = parse_launch(
        f"filesrc location={_SEMANTIC_IMAGE} blocksize=0 ! "
        "tensor_converter input_dim=3:224:224:1 input_type=uint8 ! "
        f"tensor_filter framework=tensorflow-lite model={_SEMANTIC_MODEL} "
        f"! tensor_decoder mode=image_labeling option1={_SEMANTIC_LABELS} "
        f"! filesink location={out}")
    with p:
        assert p.wait_eos(timeout=600), "semantic pipeline stalled"


CASES = {
    "transform_arithmetic": case_transform_arithmetic,
    "custom_easy_scaler": case_custom_easy_scaler,
    "decoder_direct_video": case_decoder_direct_video,
    "decoder_boundingbox_pp": case_decoder_boundingbox_pp,
    "decoder_image_segment": case_decoder_image_segment,
    "decoder_pose": case_decoder_pose,
    "decoder_tensor_region": case_decoder_tensor_region,
    "decoder_octet_stream": case_decoder_octet_stream,
    "decoder_flexbuf": case_decoder_flexbuf,
    "decoder_flatbuf": case_decoder_flatbuf,
    "decoder_protobuf": case_decoder_protobuf,
    "wire_roundtrip_protobuf": case_wire_roundtrip_protobuf,
    "converter_octet": case_converter_octet,
    "mux_aggregate": case_mux_aggregate,
    "query_offload": case_query_offload,
    "trainer_status": case_trainer_status,
    "transform_typecast": case_transform_typecast,
    "transform_clamp": case_transform_clamp,
    "transform_stand": case_transform_stand,
    "transform_transpose": case_transform_transpose,
    "transform_dimchg": case_transform_dimchg,
    "transform_padding": case_transform_padding,
    "demux_tensorpick": case_demux_tensorpick,
    "split_tensorseg": case_split_tensorseg,
    "if_passthrough_else_fill": case_if_passthrough_else_fill,
    "sparse_roundtrip": case_sparse_roundtrip,
    "aggregator_window": case_aggregator_window,
    "converter_flexible_to_static": case_converter_flexible_to_static,
    "decoder_yolov8": case_decoder_yolov8,
    "decoder_yolov5": case_decoder_yolov5,
    "rate_downsample": case_rate_downsample,
    "crop_regions": case_crop_regions,
    "repo_loop": case_repo_loop,
    "mqtt_loopback": case_mqtt_loopback,
    "grpc_roundtrip": case_grpc_roundtrip,
}

LABELS = ["cat", "dog", "bird", "fish", "horse"]


#: the speech-commands label set the conv_actions graph was trained on
SPEECH_COMMANDS = ["_silence_", "_unknown_", "yes", "no", "up", "down",
                   "left", "right", "on", "off", "stop", "go"]


def _write_fixtures():
    os.makedirs(GOLDEN_DIR, exist_ok=True)
    with open(os.path.join(GOLDEN_DIR, "labels.txt"), "w") as f:
        f.write("\n".join(LABELS) + "\n")
    with open(os.path.join(GOLDEN_DIR, "input_octet.bin"), "wb") as f:
        f.write(bytes(range(24)))
    with open(os.path.join(GOLDEN_DIR, "speech_commands.txt"), "w") as f:
        f.write("\n".join(SPEECH_COMMANDS) + "\n")
    # mock-IIO sysfs dir for case_sensor_src (committed fixture)
    iio = os.path.join(GOLDEN_DIR, "iio_device0")
    os.makedirs(os.path.join(iio, "scan_elements"), exist_ok=True)
    for name, raw, scale, offset in (("accel_x", 100, 0.5, 10.0),
                                     ("accel_y", -50, 2.0, 0.0)):
        with open(os.path.join(iio, f"in_{name}_raw"), "w") as f:
            f.write(str(raw))
        with open(os.path.join(iio, f"in_{name}_scale"), "w") as f:
            f.write(str(scale))
        with open(os.path.join(iio, f"in_{name}_offset"), "w") as f:
            f.write(str(offset))
        with open(os.path.join(iio, "scan_elements",
                               f"in_{name}_en"), "w") as f:
            f.write("1")
    # python3 converter script for case_python3_converter
    with open(os.path.join(GOLDEN_DIR, "golden_converter.py"), "w") as f:
        f.write(
            "import numpy as np\n"
            "\n\nclass CustomConverter:\n"
            "    def convert(self, input_arrays):\n"
            "        raw = input_arrays[0]\n"
            "        return [raw.view(np.int16).reshape(1, -1)"
            ".astype(np.int16)]\n")


def run_case(name, out_path):
    # fixtures (labels.txt, input_octet.bin) are COMMITTED files written
    # only by regen(): test runs must exercise the committed copies and
    # stay side-effect-free in the source tree
    if name == "decoder_image_labeling":
        case_decoder_image_labeling(
            out_path, os.path.join(GOLDEN_DIR, "labels.txt"))
    else:
        CASES[name](out_path)


def case_transform_per_channel(out):
    """Per-channel arithmetic mini-language (parity:
    transform_arithmetic SSAT per-channel options)."""
    p = parse_launch(
        "appsrc name=src ! tensor_transform mode=arithmetic "
        "option=typecast:float32,per-channel-add:1;2;3 ! "
        f"filesink location={out}")
    p["src"].spec = TensorsSpec.parse("3:4", "uint8", rate=Fraction(10))
    x = np.arange(12, dtype=np.uint8).reshape(4, 3)
    with p:
        _push_eos(p, "src", [Buffer.of(x)])


def case_if_tensor_average(out):
    """tensor_if TENSOR_AVERAGE_VALUE ge branch (parity:
    tests/nnstreamer_if SSAT): the below-threshold frame takes the
    else-branch FILL_ZERO path; both branch pads rejoin through
    ``join`` so the golden captures the full routing."""
    p = parse_launch(
        f"join name=j ! filesink location={out} "
        "appsrc name=src ! tensor_if name=i "
        "compared_value=TENSOR_AVERAGE_VALUE compared_value_option=0 "
        "operator=ge supplied_value=3 then=PASSTHROUGH else=FILL_ZERO "
        "i.src_then ! j.sink_0  i.src_else ! j.sink_1")
    p["src"].spec = TensorsSpec.parse("4", "float32", rate=Fraction(10))
    bufs = [Buffer.of(np.full((4,), v, np.float32)) for v in (1.0, 5.0)]
    with p:
        _push_eos(p, "src", bufs)


def case_datarepo_roundtrip(out):
    """datareposink writes samples + JSON descriptor; datareposrc reads
    them back in order (parity: tests/nnstreamer_datarepo)."""
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        data, js = os.path.join(td, "d.dat"), os.path.join(td, "d.json")
        w = parse_launch(
            f"appsrc name=src ! datareposink location={data} json={js}")
        w["src"].spec = TensorsSpec.parse("4", "float32", rate=Fraction(10))
        with w:
            _push_eos(w, "src", [
                Buffer.of(np.full((4,), float(i), np.float32))
                for i in range(5)])
        r = parse_launch(
            f"datareposrc location={data} json={js} is_shuffle=false "
            f"epochs=1 ! filesink location={out}")
        with r:
            assert r.wait_eos(timeout=120), "datarepo read stalled"


def case_python3_filter(out):
    """framework=python3 script-class filter (parity:
    nnstreamer_filter_python3 SSAT): the script doubles its input."""
    import tempfile

    script = (
        "import numpy as np\n"
        "class CustomFilter:\n"
        "    def getInputDim(self):\n"
        "        return [('4:2', 'float32')]\n"
        "    def getOutputDim(self):\n"
        "        return [('4:2', 'float32')]\n"
        "    def invoke(self, tensors):\n"
        "        return [tensors[0] * 2.0]\n"
    )
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "double.py")
        with open(path, "w") as f:
            f.write(script)
        p = parse_launch(
            f"appsrc name=src ! tensor_filter framework=python3 "
            f"model={path} ! filesink location={out}")
        p["src"].spec = TensorsSpec.parse("4:2", "float32",
                                          rate=Fraction(10))
        x = np.linspace(0, 1, 8, dtype=np.float32).reshape(2, 4)
        with p:
            _push_eos(p, "src", [Buffer.of(x)])


_SPEECH_MODEL = os.path.join(
    _SEMANTIC_REF, "models", "conv_actions_frozen.pb")
_SPEECH_WAV = os.path.join(_SEMANTIC_REF, "data", "yes.wav")


def speech_assets_present() -> bool:
    return os.path.isfile(_SPEECH_MODEL) and os.path.isfile(_SPEECH_WAV)


def case_semantic_speech_yes(out):
    """yes.wav → tensorflow conv_actions graph (imported GraphDef with
    the Hann/FFT/mel/DCT speech front end) → image_labeling over the
    command set → filesink; the golden holds the literal string "yes".
    Parity: the reference's tensor_filter_tensorflow speech pipeline."""
    from nnstreamer_tpu.filters.tf_import import decode_wav_bytes

    pcm, _rate = decode_wav_bytes(open(_SPEECH_WAV, "rb").read())
    commands = os.path.join(GOLDEN_DIR, "speech_commands.txt")
    p = parse_launch(
        f"appsrc name=src ! tensor_filter framework=tensorflow "
        f"model={_SPEECH_MODEL} ! "
        f"tensor_decoder mode=image_labeling option1={commands} ! "
        f"filesink location={out}")
    p["src"].spec = TensorsSpec.parse("1:16000", "float32", rate=0)
    with p:
        _push_eos(p, "src", [Buffer.of(pcm)])


def case_filter_hot_reload(out):
    """Hot reload mid-stream (parity: the reference's
    tests/nnstreamer_filter_reload SSAT dir — model swapped while the
    pipeline runs, frames before/after must show old/new weights).
    The golden holds one frame through model A then one through model
    B after RELOAD_MODEL, so reload SEMANTICS (frame N with old, frame
    N+1 with new, no drops) are pinned, not just 'it didn't crash'."""
    from nnstreamer_tpu.filters.jax_xla import register_model, \
        unregister_model
    from nnstreamer_tpu.runtime.events import Event

    register_model("golden_reload_a", lambda x: x * 2.0 + 1.0,
                   in_shapes=[(2, 4)])
    register_model("golden_reload_b", lambda x: x * 10.0 - 3.0,
                   in_shapes=[(2, 4)])
    try:
        p = parse_launch(
            "appsrc name=src ! tensor_filter framework=jax-xla "
            "model=golden_reload_a is-updatable=true name=f ! "
            f"filesink location={out}")
        src, f = p["src"], p["f"]
        src.spec = TensorsSpec.parse("4:2", "float32")
        x = np.arange(8, dtype=np.float32).reshape(2, 4)
        with p:
            src.push_buffer(Buffer.of(x))
            # drain frame 1 through the filter before swapping
            import time as _time

            for _ in range(200):
                if f.invoke_stats.total_invoke_num >= 1:
                    break
                _time.sleep(0.02)
            f.handle_event(f.sinkpad, Event.reload_model("golden_reload_b"))
            src.push_buffer(Buffer.of(x))
            src.end_of_stream()
            assert p.wait_eos(timeout=120)
    finally:
        unregister_model("golden_reload_a")
        unregister_model("golden_reload_b")


def case_sensor_src(out):
    """tensor_src_sensor against the committed mock-IIO fixture dir
    (parity: tensor_src_iio SSAT coverage — scaled/offset channels
    merged into one frame)."""
    fixture = os.path.join(GOLDEN_DIR, "iio_device0")
    p = parse_launch(
        f"tensor_src_sensor device-dir={fixture} num-buffers=3 "
        f"name=src ! filesink location={out}")
    with p:
        assert p.wait_eos(timeout=120), "sensor pipeline stalled"


def case_python3_converter(out):
    """tensor_converter mode=custom-script:….py (parity:
    tensor_converter_python3.cc + custom_converter.py contract): the
    committed script reinterprets an octet payload as int16 pairs."""
    script = os.path.join(GOLDEN_DIR, "golden_converter.py")
    p = parse_launch(
        f"appsrc name=src ! tensor_converter "
        f"mode=custom-script:{script} ! filesink location={out}")
    src = p["src"]
    src.spec = TensorsSpec.parse("16", "uint8")
    payload = np.arange(16, dtype=np.uint8)
    with p:
        _push_eos(p, "src", [Buffer.of(payload)])


def case_decoder_ov_person(out):
    """ov-person-detection decode through the ELEMENT (parity:
    box_properties/ovdetection.cc): a deterministic (200,7) descriptor
    table with two valid rows and a negative-image-id terminator."""
    rows = np.zeros((200, 7), np.float32)
    rows[0] = [0, 1, 0.95, 0.10, 0.20, 0.30, 0.55]
    rows[1] = [0, 1, 0.85, 0.50, 0.55, 0.80, 0.90]
    rows[2] = [0, 1, 0.30, 0.0, 0.0, 0.1, 0.1]   # below 0.8: dropped
    rows[3][0] = -1                              # terminator
    p = parse_launch(
        "appsrc name=src ! tensor_decoder mode=bounding_boxes "
        "option1=ov-person-detection option4=160:120 option5=300:300 ! "
        f"filesink location={out}")
    p["src"].spec = TensorsSpec.parse("7:200", "float32")
    with p:
        _push_eos(p, "src", [Buffer.of(rows)])


def case_decoder_mp_palm(out):
    """mp-palm-detection decode through the ELEMENT, fed the
    REFERENCE's recorded real palm-model tensors (parity:
    box_properties/mppalmdetection.cc + its SSAT golden — the
    refcompat module separately proves our math matches the reference
    render bit-for-bit)."""
    ref = ("/root/reference/tests/nnstreamer_decoder_boundingbox")
    boxes = np.fromfile(os.path.join(ref, "palm_detection_input_0.0"),
                        np.float32).reshape(2016, 18)
    scores = np.fromfile(os.path.join(ref, "palm_detection_input_1.0"),
                         np.float32).reshape(2016, 1)
    p = parse_launch(
        "tensor_mux name=mux ! tensor_decoder mode=bounding_boxes "
        "option1=mp-palm-detection "
        "option3=0.5:4:1.0:1.0:0.5:0.5:8:16:16:16 "
        "option4=160:120 option5=300:300 ! "
        f"filesink location={out}  "
        "appsrc name=s0 ! mux.sink_0  appsrc name=s1 ! mux.sink_1")
    p["s0"].spec = TensorsSpec.parse("18:2016", "float32")
    p["s1"].spec = TensorsSpec.parse("1:2016", "float32")
    with p:
        p["s0"].push_buffer(Buffer.of(boxes))
        p["s1"].push_buffer(Buffer.of(scores))
        p["s0"].end_of_stream()
        p["s1"].end_of_stream()
        assert p.wait_eos(timeout=120), "palm pipeline stalled"


CASES.update({
    "transform_per_channel": case_transform_per_channel,
    "if_tensor_average": case_if_tensor_average,
    "datarepo_roundtrip": case_datarepo_roundtrip,
    "python3_filter": case_python3_filter,
    "filter_hot_reload": case_filter_hot_reload,
    "sensor_src": case_sensor_src,
    "python3_converter": case_python3_converter,
    "decoder_ov_person": case_decoder_ov_person,
})

if os.path.isfile("/root/reference/tests/nnstreamer_decoder_boundingbox/"
                  "palm_detection_input_0.0"):
    CASES["decoder_mp_palm"] = case_decoder_mp_palm

if semantic_assets_present():
    CASES["semantic_classify_orange"] = case_semantic_classify_orange
if speech_assets_present():
    CASES["semantic_speech_yes"] = case_semantic_speech_yes

ALL_CASES = sorted(list(CASES) + ["decoder_image_labeling"])


def regen():
    _write_fixtures()
    for name in ALL_CASES:
        out = os.path.join(GOLDEN_DIR, f"{name}.golden")
        run_case(name, out)
        print(f"wrote {out} ({os.path.getsize(out)} bytes)")


if __name__ == "__main__" and len(sys.argv) > 1 and sys.argv[1] == "regen":
    regen()

#!/usr/bin/env python
"""Repo-root bench entry point (the driver runs this file in place).

The implementation lives in the installable package
(``nnstreamer_tpu/bench.py``; console script ``nnstreamer-tpu-bench``).
This shim only makes the in-tree copy importable when the package is not
installed.
"""

import os
import sys

try:
    import nnstreamer_tpu  # noqa: F401
except ImportError:
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from nnstreamer_tpu.bench import main

if __name__ == "__main__":
    main()

"""tensorflow filter framework: frozen GraphDef import through XLA.

Parity target: the reference's tensorflow sub-plugin and its frozen
test models (/root/reference/ext/nnstreamer/tensor_filter/
tensor_filter_tensorflow.cc; tests/test_models/models/mnist.pb and
conv_actions_frozen.pb).  Both semantic tests run REAL pretrained
weights on REAL inputs: the MNIST digit image classifies as 9, and
yes.wav classifies as the spoken command "yes" through the
reimplemented DecodeWav → AudioSpectrogram → Mfcc front end.
"""

import os

import numpy as np
import pytest

from nnstreamer_tpu.core import Buffer, TensorsSpec
from nnstreamer_tpu.elements.filter import FilterSingle
from nnstreamer_tpu.filters.api import FilterError
from nnstreamer_tpu.runtime import parse_launch

REF = "/root/reference/tests/test_models"
MNIST = os.path.join(REF, "models", "mnist.pb")
SPEECH = os.path.join(REF, "models", "conv_actions_frozen.pb")
DIGIT = os.path.join(REF, "data", "9.raw")
WAV = os.path.join(REF, "data", "yes.wav")

needs_mnist = pytest.mark.skipif(
    not (os.path.isfile(MNIST) and os.path.isfile(DIGIT)),
    reason="reference test assets not present")
needs_speech = pytest.mark.skipif(
    not (os.path.isfile(SPEECH) and os.path.isfile(WAV)),
    reason="reference test assets not present")

#: the speech-commands label set the conv_actions graph was trained on
COMMANDS = ["_silence_", "_unknown_", "yes", "no", "up", "down", "left",
            "right", "on", "off", "stop", "go"]


class TestGraphImport:
    @needs_mnist
    def test_mnist_graph_structure(self):
        from nnstreamer_tpu.filters.tf_import import TFGraph

        g = TFGraph(MNIST)
        assert {n.op for n in g.order} == {
            "Placeholder", "Const", "Identity", "MatMul", "Add",
            "Softmax"}
        assert g.output().name == "softmax"

    def test_bad_file_raises_filter_error(self, tmp_path):
        bad = tmp_path / "junk.pb"
        bad.write_bytes(b"\x07" * 32)
        with pytest.raises(FilterError):
            FilterSingle(framework="tensorflow", model=str(bad),
                         input_spec=TensorsSpec.parse("784:1", "float32"))


class TestSemantic:
    @needs_mnist
    def test_mnist_digit_nine(self):
        """Real weights, real digit image, real answer."""
        fs = FilterSingle(
            framework="tensorflow", model=MNIST,
            input_spec=TensorsSpec.parse("784:1", "float32"))
        img = np.fromfile(DIGIT, np.uint8).astype(np.float32) / 255.0
        out = np.asarray(fs.invoke([img.reshape(1, 784)])[0])
        assert int(out[0].argmax()) == 9
        assert float(out[0, 9]) > 0.9

    @needs_speech
    def test_speech_command_yes(self):
        """The whole speech front end (WAV container parse on host;
        Hann/FFT spectrogram + HTK mel + DCT Mfcc inside the jitted
        graph) must be faithful enough that the pretrained convnet
        hears "yes"."""
        from nnstreamer_tpu.filters.tf_import import decode_wav_bytes

        fs = FilterSingle(framework="tensorflow", model=SPEECH)
        pcm, rate = decode_wav_bytes(open(WAV, "rb").read())
        assert rate == 16000 and pcm.shape == (16000, 1)
        out = np.asarray(fs.invoke([pcm])[0]).ravel()
        assert COMMANDS[int(out.argmax())] == "yes"
        assert float(out.max()) > 0.9

    @needs_mnist
    def test_mnist_through_pipeline_with_labels(self, tmp_path):
        """Reference-shaped pipeline: raw digit bytes → transform(/255)
        → tensorflow filter (auto-detected from .pb) → image_labeling →
        the literal label string."""
        labels = tmp_path / "digits.txt"
        labels.write_text("\n".join(str(d) for d in range(10)) + "\n")
        p = parse_launch(
            f"appsrc name=src ! tensor_transform mode=arithmetic "
            f"option=typecast:float32,div:255.0 ! "
            f"tensor_filter model={MNIST} input=784:1 inputtype=float32 ! "
            f"tensor_decoder mode=image_labeling option1={labels} ! "
            "appsink name=out")
        p["src"].spec = TensorsSpec.parse("784:1", "uint8", rate=0)
        img = np.fromfile(DIGIT, np.uint8).reshape(1, 784)
        with p:
            p["src"].push_buffer(Buffer.of(img))
            p["src"].end_of_stream()
            assert p.wait_eos(timeout=300)
            out = p["out"].pull(timeout=5)
        label = bytes(out[0].np()).decode("utf-8").strip("\x00").strip()
        assert label == "9", label

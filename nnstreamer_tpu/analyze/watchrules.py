"""NNS510/NNS517/NNS518 — static validation of ``obs/watch.py`` rules
files and the host-profiler environment.

A watch rule that references a metric family the registry never
exports, or that cannot parse at all, fails in the worst possible way:
*silently*, at 3am, by not firing.  This pass loads a TOML/JSON rules
file (the same loader the watchdog uses — one grammar, one error
surface) WITHOUT starting anything and reports:

- malformed grammar (unknown keys/kinds/ops, bad durations, duplicate
  names, unreadable/unparseable files) — the exact :class:`RuleError`
  the watchdog would raise at startup;
- rules that can never fire: unknown metric family, a signal that
  cannot exist for the family's kind (``rate`` on a gauge, ``p99`` on
  a counter), ratio/burn shapes that can never bind (see
  :func:`nnstreamer_tpu.obs.watch.lint_rule`);
- nonsense ``[store]`` sizing (rings too short for any quantile or
  anomaly baseline, a series cap too small to hold one pool) — still
  NNS510, it is the same file;
- NNS517 — forecast rules that cannot predict: a missing or
  non-positive ``horizon`` (the watchdog refuses the set at startup;
  the lint catches it at review time), a forecast bound to a
  histogram family (windowed quantiles re-derive each tick — there is
  no single series to fit a trend through), or a horizon shorter than
  three sampler intervals (a "trend" over fewer than ~3 points of
  lookahead is noise, and the fit's significance gate would suppress
  every firing anyway).

- NNS518 — host-profiler misconfiguration (:func:`prof_env_problems`
  for the pure-env faces; the deep-episode-vs-``for`` face binds here
  against the rules file): ``NNS_TPU_PROF``/``NNS_TPU_PROF_DEEP_DIR``
  set together with ``NNS_TPU_OBS_DISABLE`` (the profiler is strictly
  inert — a silent no-op, the NNS508 family), an unparsable or
  > 250 Hz sampling rate (the sampler walks every thread's stack each
  tick; past ~250 Hz it stops being low-overhead), or
  ``NNS_TPU_PROF_DEEP_SECONDS`` longer than a rule's ``for`` window
  (the capture outlasts the episode that triggered it — the tail of
  the profile records recovery, not the incident).

Invoked by ``nns-lint --watch-rules FILE`` (bare ``--watch-rules``
reads ``$NNS_TPU_WATCH_RULES``, the same env var the runtime loads
from).
"""

from __future__ import annotations

import os
from typing import List, Optional

from .diagnostics import Diagnostic

_HINT = ("rule grammar + the exported-family catalog: "
         "Documentation/observability.md ('Alerting & watchdog'); "
         "known families: nnstreamer_tpu.obs.watch.KNOWN_FAMILIES")

_FC_HINT = ("forecast grammar: horizon = \"<duration>\" > 0 (and >= 3 "
            "sampler intervals), bound to a counter/gauge family — "
            "Documentation/observability.md ('Forecast rules & "
            "capacity headroom')")

#: sampler interval the horizon sanity check assumes when nobody says
#: otherwise (the watchdog's own default)
DEFAULT_INTERVAL_S = 1.0

#: a horizon shorter than this many sampler intervals forecasts over
#: fewer points than any trend needs
MIN_HORIZON_TICKS = 3

_PROF_HINT = ("host-profiler env vars (NNS_TPU_PROF=<hz>, "
              "NNS_TPU_PROF_DEEP_DIR, NNS_TPU_PROF_DEEP_SECONDS): "
              "Documentation/observability.md ('Host execution "
              "profiling')")

#: past this sampling rate the sys._current_frames() walk stops being
#: low-overhead (every tick walks every thread's whole stack)
MAX_PROF_HZ = 250.0

#: deep-capture default when NNS_TPU_PROF_DEEP_SECONDS is unset — must
#: track obs.prof.DeepProfiler's default
DEFAULT_DEEP_SECONDS = 2.0


def _deep_seconds() -> Optional[float]:
    """The armed deep-episode length, or None when deep capture is not
    armed at all (no NNS_TPU_PROF_DEEP_DIR — nothing to check)."""
    if not os.environ.get("NNS_TPU_PROF_DEEP_DIR", "").strip():
        return None
    raw = os.environ.get("NNS_TPU_PROF_DEEP_SECONDS", "").strip()
    if not raw:
        return DEFAULT_DEEP_SECONDS
    try:
        return float(raw)
    except ValueError:
        return DEFAULT_DEEP_SECONDS


def prof_env_problems() -> List[Diagnostic]:
    """The pure-environment NNS518 faces (the ``prof-env`` target —
    only gathered when a profiler env var is set, so default nns-lint
    output stays byte-stable): profiler armed under the obs kill
    switch, and an unparsable or unworkable sampling rate."""
    from ..obs import hooks as obs_hooks

    prof = os.environ.get("NNS_TPU_PROF", "").strip()
    deep = os.environ.get("NNS_TPU_PROF_DEEP_DIR", "").strip()
    diags: List[Diagnostic] = []
    if not prof and not deep:
        return diags
    if obs_hooks.obs_disabled():
        armed = " and ".join(
            n for n, v in (("NNS_TPU_PROF", prof),
                           ("NNS_TPU_PROF_DEEP_DIR", deep)) if v)
        diags.append(Diagnostic.make(
            "NNS518",
            f"{armed} set together with NNS_TPU_OBS_DISABLE: the host "
            "profiler is strictly inert under the kill switch — no "
            "sampler thread, no registry, no export (a silent no-op, "
            "like NNS508)", hint=_PROF_HINT))
    if prof:
        try:
            hz = float(prof)
        except ValueError:
            hz = None
            diags.append(Diagnostic.make(
                "NNS518",
                f"NNS_TPU_PROF={prof!r} is not a sample rate in Hz — "
                "the profiler will not start", hint=_PROF_HINT))
        if hz is not None and hz > MAX_PROF_HZ:
            diags.append(Diagnostic.make(
                "NNS518",
                f"NNS_TPU_PROF={hz:g} Hz exceeds {MAX_PROF_HZ:g} Hz: "
                "each tick walks every thread's whole stack — at this "
                "rate the profiler is no longer low-overhead "
                "(the --hostprof bench gates < 3%)", hint=_PROF_HINT))
    return diags


def _forecast_problems(rule, interval_s: float) -> List[str]:
    """The NNS517 faces of one well-formed forecast rule."""
    from ..obs import watch as _watch

    problems: List[str] = []
    if not rule.horizon_s > 0:
        problems.append(
            "forecast without a horizon (horizon = \"30s\") — the "
            "watchdog refuses the rule set at startup")
    elif rule.horizon_s < MIN_HORIZON_TICKS * interval_s:
        problems.append(
            f"horizon {rule.horizon_s:g}s is shorter than "
            f"{MIN_HORIZON_TICKS} sampler intervals "
            f"({MIN_HORIZON_TICKS * interval_s:g}s at {interval_s:g}s "
            f"sampling) — too little lookahead to beat the reactive "
            f"rules, and the noise gate suppresses it anyway")
    if _watch.KNOWN_FAMILIES.get(rule.metric) == "histogram":
        problems.append(
            f"forecast bound to histogram family {rule.metric!r} — "
            f"windowed quantiles re-derive each tick; trend-forecast "
            f"a counter rate or gauge level instead")
    return problems


def check_watch_rules(path: Optional[str],
                      interval_s: float = DEFAULT_INTERVAL_S
                      ) -> List[Diagnostic]:
    """Diagnostics for one rules file.  ``path=None`` means "use
    ``$NNS_TPU_WATCH_RULES``" — unset is itself a finding (the user
    asked for a check with nothing to check).  ``interval_s`` is the
    sampler interval the horizon sanity check assumes."""
    from ..obs import watch as _watch

    if path is None:
        path = os.environ.get("NNS_TPU_WATCH_RULES", "").strip()
        if not path:
            return [Diagnostic.make(
                "NNS510",
                "--watch-rules given without a file and "
                "NNS_TPU_WATCH_RULES is unset — no rules to validate",
                hint=_HINT)]
    label = os.path.basename(path)
    try:
        rules = _watch.load_rules(path)
        store_cfg = _watch.load_store(path)
    except _watch.RuleError as e:
        return [Diagnostic.make(
            "NNS510", f"{label}: malformed rules file: {e}",
            element=path, hint=_HINT)]
    except OSError as e:
        return [Diagnostic.make(
            "NNS510", f"{label}: cannot read rules file: {e}",
            element=path, hint=_HINT)]
    diags: List[Diagnostic] = []
    deep_s = _deep_seconds()
    for rule in rules:
        for problem in _watch.lint_rule(rule):
            diags.append(Diagnostic.make(
                "NNS510", f"{label}: rule {rule.name!r}: {problem}",
                element=path, pad=rule.name, hint=_HINT))
        if rule.kind == "forecast":
            for problem in _forecast_problems(rule, interval_s):
                diags.append(Diagnostic.make(
                    "NNS517", f"{label}: rule {rule.name!r}: {problem}",
                    element=path, pad=rule.name, hint=_FC_HINT))
        # NNS518 deep-episode face: a deep capture longer than the
        # rule's for= window outlasts the very episode that fires it —
        # the profile's tail records recovery, not the incident
        if deep_s is not None and 0 < rule.for_s < deep_s:
            diags.append(Diagnostic.make(
                "NNS518",
                f"{label}: rule {rule.name!r}: deep-profile episode "
                f"({deep_s:g}s, NNS_TPU_PROF_DEEP_SECONDS) is longer "
                f"than the rule's for= window ({rule.for_s:g}s) — the "
                "capture outlasts the alert episode that triggers it",
                element=path, pad=rule.name, hint=_PROF_HINT))
    for problem in _watch.lint_store(store_cfg):
        diags.append(Diagnostic.make(
            "NNS510", f"{label}: {problem}", element=path,
            hint=_HINT))
    return diags

"""Checkpoint interop (models/params_io.py): npz / safetensors ⇄ zoo
pytrees, and weight files as tensor_filter models.

Parity: the reference loads framework-native checkpoints straight into
tensor_filter (tensor_filter_tensorflow_lite.cc:242-280); here the
interchange formats are npz and the hand-rolled safetensors codec.
"""

import numpy as np
import pytest

import jax

from nnstreamer_tpu.elements.filter import FilterSingle
from nnstreamer_tpu.filters.api import FilterError
from nnstreamer_tpu.models.params_io import (
    flatten_params,
    load_npz,
    load_safetensors,
    save_npz,
    save_safetensors,
    unflatten_params,
)

TREE = {
    "stem": {"w": np.arange(12, dtype=np.float32).reshape(3, 4),
             "b": np.zeros((4,), np.float32)},
    "blocks": [
        {"dw": np.ones((2, 2), np.float32)},
        {"dw": np.full((2, 2), 3.0, np.float32)},
    ],
    "num_classes": 7,
}


def _assert_tree_equal(a, b):
    fa, fb = flatten_params(a), flatten_params(b)
    assert set(fa) == set(fb)
    for k in fa:
        np.testing.assert_array_equal(fa[k], fb[k])


class TestFlatten:
    def test_roundtrip_with_lists_and_scalars(self):
        tree = unflatten_params(flatten_params(TREE))
        _assert_tree_equal(TREE, tree)
        assert isinstance(tree["blocks"], list)
        assert tree["num_classes"] == 7  # scalar restored

    def test_digit_string_dict_keys_stay_dicts(self):
        """torch-style {"0": ...} dicts must NOT come back as lists
        (review finding: the #i list marker keeps the round trip
        structure-exact)."""
        tree = {"layers": {"0": {"w": np.ones((2,), np.float32)},
                           "1": {"w": np.zeros((2,), np.float32)}}}
        back = unflatten_params(flatten_params(tree))
        assert isinstance(back["layers"], dict)
        np.testing.assert_array_equal(back["layers"]["0"]["w"],
                                      tree["layers"]["0"]["w"])


class TestNpz:
    def test_roundtrip_and_metadata(self, tmp_path):
        p = str(tmp_path / "w.npz")
        save_npz(p, TREE, apply="some.module:apply",
                 in_shapes=[(1, 4)], in_dtypes=np.float32)
        tree, meta = load_npz(p)
        _assert_tree_equal(TREE, tree)
        assert meta["apply"] == "some.module:apply"
        assert meta["in_shapes"] == [[1, 4]]


class TestSafetensors:
    def test_roundtrip_and_metadata(self, tmp_path):
        p = str(tmp_path / "w.safetensors")
        save_safetensors(p, TREE, metadata={"apply": "m:f"})
        tree, meta = load_safetensors(p)
        _assert_tree_equal(TREE, tree)
        assert meta["apply"] == "m:f"

    def test_bfloat16_leaf(self, tmp_path):
        import jax.numpy as jnp

        p = str(tmp_path / "bf.safetensors")
        save_safetensors(p, {"w": np.asarray(
            jnp.arange(4, dtype=jnp.bfloat16))})
        tree, _ = load_safetensors(p)
        assert str(tree["w"].dtype) == "bfloat16"

    def test_corrupt_offsets_rejected(self, tmp_path):
        import json
        import struct

        hdr = json.dumps({"w": {"dtype": "F32", "shape": [4],
                                "data_offsets": [0, 999]}}).encode()
        p = tmp_path / "bad.safetensors"
        p.write_bytes(struct.pack("<Q", len(hdr)) + hdr + b"\x00" * 16)
        with pytest.raises(ValueError, match="offsets"):
            load_safetensors(str(p))


def mlp_apply(params, x):
    return x @ params["w"] + params["b"]


class TestWeightsFileAsModel:
    @pytest.mark.parametrize("fmt", ["npz", "safetensors"])
    def test_filter_loads_weights_file(self, fmt, tmp_path):
        rng = np.random.default_rng(3)
        params = {"w": rng.standard_normal((8, 4)).astype(np.float32),
                  "b": rng.standard_normal((4,)).astype(np.float32)}
        path = str(tmp_path / f"mlp.{fmt}")
        if fmt == "npz":
            save_npz(path, params, apply="test_params_io:mlp_apply",
                     in_shapes=[(2, 8)], in_dtypes=np.float32)
        else:
            import json

            save_safetensors(path, params, metadata={
                "apply": "test_params_io:mlp_apply",
                "in_shapes": json.dumps([[2, 8]]),
                "in_dtypes": "float32"})
        fs = FilterSingle(framework="jax-xla", model=path)
        x = rng.standard_normal((2, 8)).astype(np.float32)
        out = np.asarray(fs.invoke([x])[0])
        # reference on the SAME backend: TPU f32 matmul uses bf16
        # passes, so a host-numpy comparison would need sloppy tolerances
        want = np.asarray(jax.jit(mlp_apply)(params, x))
        np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-5)

    def test_missing_apply_metadata_rejected(self, tmp_path):
        path = str(tmp_path / "noapply.safetensors")
        save_safetensors(path, {"w": np.zeros((2, 2), np.float32)})
        with pytest.raises(FilterError, match="apply"):
            FilterSingle(framework="jax-xla", model=path)

    def test_zoo_checkpoint_roundtrip(self, tmp_path):
        """A real zoo model's params survive the trip: save mobilenet_v1
        weights as safetensors, reload, invoke — same logits."""
        from nnstreamer_tpu.models.mobilenet import (
            mobilenet_v1_apply,
            mobilenet_v1_init,
        )

        params = mobilenet_v1_init(jax.random.PRNGKey(0), num_classes=10,
                                   width=0.25)
        path = str(tmp_path / "mnv1.safetensors")
        save_safetensors(path, jax.tree_util.tree_map(np.asarray, params))
        tree, _ = load_safetensors(path)
        x = np.random.default_rng(0).standard_normal(
            (1, 32, 32, 3)).astype(np.float32)
        a = np.asarray(mobilenet_v1_apply(params, x))
        b = np.asarray(mobilenet_v1_apply(tree, x))
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)

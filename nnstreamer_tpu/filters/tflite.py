"""``tensorflow-lite`` filter framework: .tflite files through XLA.

Parity target: the reference's flagship sub-plugin
(/root/reference/ext/nnstreamer/tensor_filter/
tensor_filter_tensorflow_lite.cc — TFLiteInterpreter/TFLiteCore,
:158,242).  Here the model file is *imported* rather than interpreted
(filters/tflite_import.py): the graph compiles into one XLA program, so
a pretrained .tflite gets TPU-resident weights, async invoke, hot
reload, sharing and mesh placement for free by inheriting the jax-xla
execution machinery.
"""

from __future__ import annotations

import os
import struct

import numpy as np

from ..core import TensorsSpec
from .api import FilterError
from .jax_xla import JaxXlaFilter, ModelDef
from .registry import register_filter


@register_filter
class TFLiteFilter(JaxXlaFilter):
    NAME = "tensorflow-lite"
    ACCELERATORS = ("tpu", "cpu")

    def _load_file(self, path: str) -> ModelDef:
        ext = os.path.splitext(path)[1].lower()
        if ext != ".tflite":
            return super()._load_file(path)
        from .tflite_import import TFLiteModel, build_fn

        from .importer_util import parse_custom_prop

        qmode = parse_custom_prop(self.props.custom, "qmode", "auto")
        try:
            fn, weights, in_shape, in_dtype = build_fn(TFLiteModel(path),
                                                       qmode=qmode)
        except (ValueError, NotImplementedError, IndexError, KeyError,
                struct.error) as e:
            raise FilterError(f"tensorflow-lite: {path}: {e}") from e
        in_spec = TensorsSpec.from_shapes([in_shape], np.dtype(in_dtype))
        # weights ride as a params pytree (device-placed by the jax-xla
        # machinery), not baked into the HLO as literals
        return ModelDef(fn, weights, in_spec, name=path)


@register_filter
class TFLite2Filter(TFLiteFilter):
    """Alias: the reference registers both tensorflow-lite and
    tensorflow2-lite names for the same engine."""

    NAME = "tensorflow2-lite"

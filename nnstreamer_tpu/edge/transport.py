"""Edge transports: in-process (zero-copy) and TCP (wire-serialized).

Parity target: the nnstreamer-edge communication library the reference's
L5 layer consumes (``nns_edge_create_handle/start/send/event_cb``,
/root/reference/gst/nnstreamer/tensor_query/tensor_query_client.c:541-557,
gst/edge/edge_sink.c:291-334; connect types TCP/HYBRID/MQTT/AITT).

TPU-native redesign: three connect types.

- ``inproc`` — client and server pipelines share the process: envelopes
  carry :class:`~nnstreamer_tpu.core.Buffer` objects *by reference*, so
  device-resident tensors never leave HBM and offloading a stage costs a
  queue hop, not a serialize/deserialize round-trip.  This is the default
  for same-host stage offload (SURVEY.md §7.6).
- ``tcp`` — cross-host: envelopes serialize through
  :mod:`nnstreamer_tpu.edge.wire` (MetaInfo-headed payloads) over a
  length-prefixed socket stream.  The same element graph works unchanged.
- ``hybrid`` — broker-mediated discovery + TCP data (the reference's
  MQTT-hybrid, tensor_query/README.md:74-99): ``host:port`` addresses an
  MQTT broker where the server advertises its TCP data address under
  ``topic`` as a retained message; reconnecting clients re-query the
  broker, so a server that moved is found again mid-stream.

Both present the same two interfaces: :class:`ServerTransport`
(accept + per-client send + topic publish) and :class:`ClientConn`
(send + blocking receive + caps query).
"""

from __future__ import annotations

import dataclasses
import queue
import socket
import struct
import threading
import time
import uuid
from typing import Callable, Dict, Optional, Tuple

from ..chaos import hooks as _chaos
from ..chaos.plan import apply_wire_op as _apply_wire_op
from ..core import Buffer
from ..utils.log import logd, logw
from . import devicechannel as _devch
from .wire import (
    EdgeMessage,
    MSG_CAPS_REQ,
    MSG_CAPS_RES,
    MSG_DEVCH_REQ,
    MSG_DEVCH_RES,
    MSG_PUBLISH,
    MSG_QUERY,
    MSG_REPLY,
    MSG_SUBSCRIBE,
)


@dataclasses.dataclass
class Envelope:
    """Transport-neutral message: what the elements see.  ``buffer`` is
    by-reference for inproc and (de)serialized at the socket boundary for
    tcp.  ``trace`` is an optional trace context
    (:mod:`nnstreamer_tpu.obs.tracectx`) riding the frame's extension
    area over the wire."""

    mtype: int
    client_id: int = 0
    seq: int = 0
    info: str = ""
    buffer: Optional[Buffer] = None
    trace: Optional[dict] = None


def _to_wire(env: Envelope, devch: bool = False,
             chan: object = "") -> bytes:
    if devch and env.buffer is not None and _devch.eligible(env.buffer):
        # device-channel fast path (edge/devicechannel.py): the frame's
        # tensors stay in HBM, parked under a slot id scoped to this
        # connection's channel; only this control frame — descriptor,
        # routing, trace — rides the socket
        msg = EdgeMessage(mtype=env.mtype, client_id=env.client_id,
                          seq=env.seq, pts=env.buffer.pts, info=env.info)
        msg.devch = _devch.deposit_buffer(env.buffer, chan=chan)
    elif env.buffer is not None:
        msg = EdgeMessage.from_buffer(env.mtype, env.buffer,
                                      client_id=env.client_id, seq=env.seq,
                                      info=env.info)
    else:
        msg = EdgeMessage(mtype=env.mtype, client_id=env.client_id,
                          seq=env.seq, info=env.info)
    msg.trace = env.trace
    return msg.pack()


def _from_wire(data: bytes) -> Envelope:
    msg = EdgeMessage.unpack(data)
    if msg.devch is not None and not msg.payloads:
        # control-only frame: redeem the parked device-resident buffer
        # (None — surfaced upstream as a drop/timeout — when the slot
        # was evicted or the sender's device world is foreign)
        buf = _devch.take_buffer(msg.devch)
        if buf is not None:
            buf.meta["client_id"] = msg.client_id
            buf.meta["query_seq"] = msg.seq
    else:
        buf = msg.to_buffer() if msg.payloads else None
    return Envelope(mtype=msg.mtype, client_id=msg.client_id, seq=msg.seq,
                    info=msg.info, buffer=buf, trace=msg.trace)


# -- server side --------------------------------------------------------------


class ServerTransport:
    """Interface: accept clients, deliver inbound envelopes to
    ``on_message(client_id, env)``, send/publish outbound ones.

    ``metrics`` (an :class:`~nnstreamer_tpu.obs.metrics.LinkMetrics`, or
    None) receives per-frame tx/rx byte counts from transports that
    actually frame bytes; owning elements assign it after construction."""

    def __init__(self):
        self.on_message: Optional[Callable[[int, Envelope], None]] = None
        self.caps_provider: Optional[Callable[[], str]] = None
        self.metrics = None
        # clients that proved (MSG_DEVCH_REQ handshake) they share this
        # process's device world: frames to them may ride the device
        # channel (control metadata only on the socket)
        self._devch_clients: set = set()

    def devch_capable(self, client_id: int) -> bool:
        return client_id in self._devch_clients

    def start(self) -> None:
        raise NotImplementedError

    def stop(self) -> None:
        raise NotImplementedError

    def send(self, client_id: int, env: Envelope) -> bool:
        raise NotImplementedError

    def publish(self, env: Envelope) -> int:
        """Send to every subscriber whose topic matches ``env.info``
        (empty subscription = all topics).  Returns receiver count."""
        raise NotImplementedError

    # shared control-message handling
    def _dispatch(self, client_id: int, env: Envelope,
                  subscribe_cb: Callable[[int, str], None]) -> None:
        if env.mtype == MSG_CAPS_REQ:
            caps = self.caps_provider() if self.caps_provider else ""
            self.send(client_id, Envelope(
                MSG_CAPS_RES, client_id=client_id, seq=env.seq, info=caps))
        elif env.mtype == MSG_DEVCH_REQ:
            # device-channel handshake: ``info`` is the client's device
            # fingerprint — grant the fast path only on an exact match
            # with ours (same process, same pod); the reply tells the
            # client whether ITS sends may ride the channel too
            ok = _devch.handshake_ok(env.info)
            if ok:
                self._devch_clients.add(client_id)
            else:
                self._devch_clients.discard(client_id)
            self.send(client_id, Envelope(
                MSG_DEVCH_RES, client_id=client_id, seq=env.seq,
                info=_devch.DEVCH_OK if ok else ""))
        elif env.mtype == MSG_SUBSCRIBE:
            subscribe_cb(client_id, env.info)
        elif self.on_message is not None:
            self.on_message(client_id, env)


class ClientConn:
    """Interface: one client connection.  ``metrics`` as on
    :class:`ServerTransport`."""

    metrics = None
    #: True once :meth:`request_devch` confirmed the peer shares this
    #: process's device world — device-resident sends then ride the
    #: device channel (control metadata only on the socket)
    devch_ok = False

    def request_devch(self, timeout: float = 2.0) -> bool:
        """Run the device-channel handshake; returns (and records in
        :attr:`devch_ok`) whether the peer granted the fast path.
        Default: transports without a handshake stay on plain framing —
        the transparent-fallback contract."""
        return False

    def send(self, env: Envelope) -> bool:
        raise NotImplementedError

    def is_alive(self) -> bool:
        """False once the peer is gone — lets a pipelined caller
        distinguish "no data yet" from "connection dead" after a
        timed-out recv (mid-stream failover)."""
        return True

    def recv(self, timeout: Optional[float] = None) -> Optional[Envelope]:
        raise NotImplementedError

    def request_caps(self, timeout: float = 5.0) -> Optional[str]:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError


# -- inproc -------------------------------------------------------------------

_HUB_LOCK = threading.Lock()
_HUB: Dict[Tuple[str, int], "InprocServer"] = {}


class InprocServer(ServerTransport):
    """Zero-copy in-process transport: a global hub maps (host, port) to
    the server; envelopes cross as Python references."""

    def __init__(self, host: str, port: int):
        super().__init__()
        self.addr = (host, int(port))
        self._clients: Dict[int, "InprocClientConn"] = {}
        self._subs: Dict[int, str] = {}  # client_id → topic
        self._next_id = 1
        self._lock = threading.Lock()

    def start(self) -> None:
        with _HUB_LOCK:
            if self.addr in _HUB:
                raise OSError(f"inproc address already bound: {self.addr}")
            _HUB[self.addr] = self

    def stop(self) -> None:
        with _HUB_LOCK:
            if _HUB.get(self.addr) is self:
                del _HUB[self.addr]
        with self._lock:
            clients = list(self._clients.values())
            self._clients.clear()
            self._subs.clear()
        for c in clients:
            c._closed.set()

    def _connect(self, conn: "InprocClientConn") -> int:
        with self._lock:
            cid = self._next_id
            self._next_id += 1
            self._clients[cid] = conn
        return cid

    def _disconnect(self, client_id: int) -> None:
        with self._lock:
            self._clients.pop(client_id, None)
            self._subs.pop(client_id, None)

    def _receive(self, client_id: int, env: Envelope) -> None:
        env.client_id = client_id
        self._dispatch(client_id, env, self._subscribe)

    def _subscribe(self, client_id: int, topic: str) -> None:
        with self._lock:
            self._subs[client_id] = topic

    def send(self, client_id: int, env: Envelope) -> bool:
        with self._lock:
            conn = self._clients.get(client_id)
        if conn is None:
            return False
        ch = _chaos.plan
        if ch is not None:
            # inproc frames are the Envelope objects themselves — the
            # same fault schedule applies, minus corrupt (no wire bytes)
            op = ch.wire(_chaos_label(self.metrics, "inproc-server"),
                         "tx", env)
            if op is not None:
                def kill():
                    conn._closed.set()
                    self._disconnect(client_id)

                _apply_wire_op(op, conn._deliver, kill)
                return True
        conn._deliver(env)
        return True

    def publish(self, env: Envelope) -> int:
        with self._lock:
            targets = [cid for cid, topic in self._subs.items()
                       if not topic or topic == env.info]
        return sum(bool(self.send(cid, env)) for cid in targets)


class InprocClientConn(ClientConn):
    def __init__(self, host: str, port: int):
        with _HUB_LOCK:
            server = _HUB.get((host, int(port)))
        if server is None:
            raise ConnectionRefusedError(
                f"no inproc server at {host}:{port}")
        self._server = server
        self._inbox: "queue.Queue[Envelope]" = queue.Queue()
        self._caps: "queue.Queue[str]" = queue.Queue()
        self._closed = threading.Event()
        self.client_id = server._connect(self)

    def request_devch(self, timeout: float = 2.0) -> bool:
        # inproc envelopes already cross by reference — device-resident
        # buffers never leave HBM here, so the channel is trivially on
        # (no wire exchange, no behavior change)
        self.devch_ok = True
        return True

    def _deliver(self, env: Envelope) -> None:
        # route control responses to their own queue so a caps handshake
        # never races with data replies
        if env.mtype == MSG_CAPS_RES:
            self._caps.put(env.info)
        else:
            self._inbox.put(env)

    def send(self, env: Envelope) -> bool:
        if self._closed.is_set():
            return False
        ch = _chaos.plan
        if ch is not None:
            op = ch.wire(_chaos_label(self.metrics, "inproc-client"),
                         "tx", env)
            if op is not None:
                _apply_wire_op(
                    op, lambda e: self._server._receive(self.client_id,
                                                        e),
                    self.close)
                return True
        self._server._receive(self.client_id, env)
        return True

    def recv(self, timeout: Optional[float] = None) -> Optional[Envelope]:
        try:
            return self._inbox.get(timeout=timeout)
        except queue.Empty:
            return None

    def request_caps(self, timeout: float = 5.0) -> Optional[str]:
        self.send(Envelope(MSG_CAPS_REQ))
        try:
            return self._caps.get(timeout=timeout)
        except queue.Empty:
            return None

    def is_alive(self) -> bool:
        return not self._closed.is_set()

    def close(self) -> None:
        self._closed.set()
        self._server._disconnect(self.client_id)


# -- tcp ----------------------------------------------------------------------


def _chaos_label(metrics, fallback: str) -> str:
    """The seam label a FaultPlan's ``match=`` is tested against: the
    owning element + peer address when link metrics are attached, else
    the transport kind."""
    return f"{metrics.link}:{metrics.peer}" if metrics is not None \
        else fallback


def _shutdown_quiet(sock: socket.socket) -> None:
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass


def _send_frame(sock: socket.socket, data: bytes, lock: threading.Lock
                ) -> bool:
    try:
        with lock:
            # the per-connection write lock exists precisely so that
            # concurrent publishers emit whole frames (len-prefix +
            # payload) — interleaving would desync the length framing.
            # Audited (ISSUE 16): no recv ever runs under this or any
            # transport lock; readers live on their own threads and
            # take no lock around recv.
            # nns-lint: disable=NNS602 -- per-conn write leaf lock;
            # sendall under it IS the frame serialization
            sock.sendall(struct.pack("<I", len(data)) + data)
        return True
    except OSError:
        return False


def _recv_frame(sock: socket.socket) -> Optional[bytes]:
    try:
        hdr = _recv_exact(sock, 4)
        if hdr is None:
            return None
        (n,) = struct.unpack("<I", hdr)
        return _recv_exact(sock, n)
    except OSError:
        return None


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    chunks = []
    while n:
        c = sock.recv(n)
        if not c:
            return None
        chunks.append(c)
        n -= len(c)
    return b"".join(chunks)


class TcpServer(ServerTransport):
    """Socket server: one reader thread per client connection."""

    def __init__(self, host: str, port: int):
        super().__init__()
        self.host, self.port = host, int(port)
        self._sock: Optional[socket.socket] = None
        self._conns: Dict[int, Tuple[socket.socket, threading.Lock]] = {}
        self._subs: Dict[int, str] = {}
        self._next_id = 1
        self._lock = threading.Lock()
        self._running = threading.Event()
        self._accept_thread: Optional[threading.Thread] = None

    def start(self) -> None:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self.host, self.port))
        if self.port == 0:
            self.port = s.getsockname()[1]
        s.listen(16)
        self._sock = s
        self._running.set()
        from ..obs import prof as _prof

        self._accept_thread = _prof.named_thread(
            "edge-accept", str(self.port), self._accept_loop)
        self._accept_thread.start()

    def stop(self) -> None:
        self._running.clear()
        if self._sock is not None:
            # shutdown BEFORE close: close() alone does not wake a
            # thread blocked in accept() on Linux — the kernel socket
            # stays referenced by the blocked call, the accept join
            # below times out, and the port cannot be rebound (which
            # breaks restart-on-the-same-port self-healing)
            try:
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
            self._subs.clear()
        for sock, _ in conns:
            # shutdown first, for the same reason as the listener: a
            # bare close() neither wakes this server's blocked reader
            # thread nor sends the peer its FIN (the blocked recv
            # syscall keeps the kernel socket alive), so clients could
            # never detect the shutdown and fail over
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2.0)
            self._accept_thread = None

    def _accept_loop(self) -> None:
        while self._running.is_set():
            try:
                conn, addr = self._sock.accept()
            except OSError:
                break
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                cid = self._next_id
                self._next_id += 1
                self._conns[cid] = (conn, threading.Lock())
            logd("edge: client %d connected from %s", cid, addr)
            from ..obs import prof as _prof

            _prof.named_thread("edge-read", str(cid), self._reader,
                               args=(cid, conn)).start()

    def _reader(self, cid: int, conn: socket.socket) -> None:
        while self._running.is_set():
            data = _recv_frame(conn)
            if data is None:
                break
            m = self.metrics
            if m is not None:
                m.on_rx(4 + len(data))
            ch = _chaos.plan
            if ch is not None:
                op = ch.wire(_chaos_label(m, "tcp-server"), "rx", data)
                if op is not None:
                    _apply_wire_op(op,
                                   lambda f: self._rx_deliver(cid, f))
                    if op.disconnect:
                        break
                    continue
            self._rx_deliver(cid, data)
        with self._lock:
            self._conns.pop(cid, None)
            self._subs.pop(cid, None)
        self._devch_clients.discard(cid)
        # parked device-channel frames for a dead client can never be
        # redeemed — free their HBM now instead of at slot eviction
        _devch.release_chan((id(self), cid))
        try:
            conn.close()
        except OSError:
            pass

    def _rx_deliver(self, cid: int, data: bytes) -> None:
        try:
            env = _from_wire(data)
        except ValueError as e:
            logw("edge: dropping bad frame from client %d: %s", cid, e)
            m = self.metrics
            if m is not None:
                m.on_bad_frame()
            return
        env.client_id = cid
        self._dispatch(cid, env, self._subscribe)

    def _subscribe(self, client_id: int, topic: str) -> None:
        with self._lock:
            self._subs[client_id] = topic

    def send(self, client_id: int, env: Envelope) -> bool:
        with self._lock:
            entry = self._conns.get(client_id)
        if entry is None:
            return False
        data = _to_wire(env, devch=self.devch_capable(client_id),
                        chan=(id(self), client_id))
        ch = _chaos.plan
        if ch is not None:
            op = ch.wire(_chaos_label(self.metrics, "tcp-server"),
                         "tx", data)
            if op is not None:
                return self._apply_tx_op(entry, op)
        ok = _send_frame(entry[0], data, entry[1])
        m = self.metrics
        if ok and m is not None:
            m.on_tx(4 + len(data))
        return ok

    def _apply_tx_op(self, entry, op) -> bool:
        """Injected-fault send: lost frames still LOOK sent at this
        layer (that's the fault being simulated); a disconnect closes
        the client's socket so its reader sees a dead peer."""
        def send_one(f):
            sent = _send_frame(entry[0], f, entry[1])
            m = self.metrics
            if sent and m is not None:
                m.on_tx(4 + len(f))
            return sent

        return _apply_wire_op(op, send_one,
                              lambda: _shutdown_quiet(entry[0]))

    def publish(self, env: Envelope) -> int:
        with self._lock:
            targets = [cid for cid, topic in self._subs.items()
                       if not topic or topic == env.info]
        return sum(bool(self.send(cid, env)) for cid in targets)


class TcpClientConn(ClientConn):
    def __init__(self, host: str, port: int, timeout: float = 5.0):
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=timeout)
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._wlock = threading.Lock()
        self._inbox: "queue.Queue[Envelope]" = queue.Queue()
        self._caps: "queue.Queue[str]" = queue.Queue()
        self._devch_q: "queue.Queue[str]" = queue.Queue()
        self._closed = threading.Event()
        self._dead = threading.Event()
        from ..obs import prof as _prof

        self._reader_thread = _prof.named_thread(
            "edge-client-read", "", self._reader)
        self._reader_thread.start()

    def request_devch(self, timeout: float = 2.0) -> bool:
        """Device-channel handshake over the live socket: send our
        fingerprint, wait for the peer's verdict.  A peer that never
        answers (an old binary dropping the unknown mtype, a dead link)
        leaves ``devch_ok`` False — plain TCP framing continues, the
        transparent fallback."""
        self.devch_ok = False
        if not self.send(Envelope(MSG_DEVCH_REQ,
                                  info=_devch.fingerprint())):
            return False
        try:
            self.devch_ok = self._devch_q.get(
                timeout=timeout) == _devch.DEVCH_OK
        except queue.Empty:
            pass
        return self.devch_ok

    def _reader(self) -> None:
        while not self._closed.is_set():
            data = _recv_frame(self._sock)
            if data is None:
                break
            m = self.metrics
            if m is not None:
                m.on_rx(4 + len(data))
            ch = _chaos.plan
            if ch is not None:
                op = ch.wire(_chaos_label(m, "tcp-client"), "rx", data)
                if op is not None:
                    _apply_wire_op(op, self._rx_deliver)
                    if op.disconnect:
                        break
                    continue
            self._rx_deliver(data)
        self._dead.set()

    def _rx_deliver(self, data: bytes) -> None:
        try:
            env = _from_wire(data)
        except ValueError as e:
            logw("edge: client dropping bad frame: %s", e)
            m = self.metrics
            if m is not None:
                m.on_bad_frame()
            return
        if env.mtype == MSG_CAPS_RES:
            self._caps.put(env.info)
        elif env.mtype == MSG_DEVCH_RES:
            self._devch_q.put(env.info)
        else:
            self._inbox.put(env)

    def send(self, env: Envelope) -> bool:
        if self._closed.is_set():
            return False
        data = _to_wire(env, devch=self.devch_ok, chan=id(self))
        ch = _chaos.plan
        if ch is not None:
            op = ch.wire(_chaos_label(self.metrics, "tcp-client"),
                         "tx", data)
            if op is not None:
                return self._apply_tx_op(op)
        ok = _send_frame(self._sock, data, self._wlock)
        m = self.metrics
        if ok and m is not None:
            m.on_tx(4 + len(data))
        return ok

    def _apply_tx_op(self, op) -> bool:
        """Injected-fault send: a dropped frame still reports success
        (it was lost ON the wire, not refused by it); a disconnect
        kills the socket so both ends see a dead connection."""
        def send_one(f):
            sent = _send_frame(self._sock, f, self._wlock)
            m = self.metrics
            if sent and m is not None:
                m.on_tx(4 + len(f))
            return sent

        return _apply_wire_op(op, send_one,
                              lambda: _shutdown_quiet(self._sock))

    def recv(self, timeout: Optional[float] = None) -> Optional[Envelope]:
        try:
            return self._inbox.get(timeout=timeout)
        except queue.Empty:
            return None

    def request_caps(self, timeout: float = 5.0) -> Optional[str]:
        if not self.send(Envelope(MSG_CAPS_REQ)):
            return None
        try:
            return self._caps.get(timeout=timeout)
        except queue.Empty:
            return None

    def is_alive(self) -> bool:
        return not self._closed.is_set() and not self._dead.is_set()

    def close(self) -> None:
        self._closed.set()
        _devch.release_chan(id(self))
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


# -- MQTT-hybrid: broker-mediated discovery, TCP data plane -------------------

# discovery topic grammar; the retained payload is "host:port" of the
# data-plane TcpServer (parity: nnstreamer-edge HYBRID publishes the
# server's TCP address through the broker, tensor_query/README.md:74-99)
_HYBRID_TOPIC_FMT = "nns-edge/{topic}/address"


class HybridServer(ServerTransport):
    """``connect-type=hybrid``: the broker (at ``host:port``) carries
    only DISCOVERY — a retained MQTT message advertising this server's
    TCP data address under ``topic``; every tensor rides a plain
    :class:`TcpServer`.  Stopping clears the retained advertisement (if
    still ours), so a replacement server that registers the same topic
    takes over and reconnecting clients find it through the broker (the
    reference's reconnect-to-alternates story,
    tensor_query/README.md:74-99)."""

    def __init__(self, host: str, port: int, topic: str = "",
                 data_host: str = "127.0.0.1", data_port: int = 0,
                 advertise_host: str = ""):
        # the data plane must exist before super().__init__, whose
        # on_message/caps_provider defaults route through the proxies
        self._tcp = TcpServer(data_host, int(data_port))
        super().__init__()
        self._broker_addr = (host, int(port))
        self.topic = topic or "tensor-query"
        # cross-host: bind data_host=0.0.0.0 and advertise a reachable
        # address (explicit advertise_host, else the machine's primary
        # IP); the loopback default covers same-host deployments
        self._advertise_host = advertise_host
        self._mqtt = None
        self._adv_thread = None
        self._stop_evt = threading.Event()
        self._adv_addr: str = ""
        # broker outages back off through the shared edge retry policy
        # (one WARNING per outage instead of a logline every 2 s tick;
        # breaker state exports on the LINK row)
        from ..chaos.retrypolicy import RetryPolicy
        from ..obs.metrics import LinkMetrics

        self._retry = RetryPolicy(
            name=f"hybrid-adv:{self.topic}", base_s=2.0, max_s=15.0,
            fail_threshold=5, open_s=10.0,
            metrics=LinkMetrics.get(f"hybrid-adv:{self.topic}",
                                    f"{host}:{port}", kind="hybrid"))

    def _advertised_addr(self) -> str:
        # resolved ONCE (after the data port is bound): a flapping
        # resolver answer mid-life would re-advertise a different
        # address and break stop()'s retained-slot ownership check
        if self._adv_addr:
            return self._adv_addr
        host = self._advertise_host or self._tcp.host
        if host in ("0.0.0.0", "::", ""):
            # the UDP-connect trick: the local address on the route to
            # the broker is what clients (who reach the same broker) can
            # dial.  Preferred over gethostbyname(gethostname()), which
            # Debian-family /etc/hosts maps to 127.0.1.1 — but when the
            # broker itself is local (route → loopback) fall back to the
            # hostname lookup, which may still yield the LAN address.
            host = ""
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            try:
                s.connect((self._broker_addr[0], self._broker_addr[1]
                           or 1))  # no packets are sent
                host = s.getsockname()[0]
            except OSError:
                pass
            finally:
                s.close()
            if not host or host.startswith("127."):
                try:
                    resolved = socket.gethostbyname(socket.gethostname())
                    if not resolved.startswith("127."):
                        host = resolved
                except OSError:
                    pass
            if not host:
                host = "127.0.0.1"
            if host.startswith("127."):
                logw("hybrid server %r: wildcard bind advertises a "
                     "LOOPBACK address (%s) — cross-host clients cannot "
                     "dial it; set advertise-host= to the reachable IP",
                     self.topic, host)
        self._adv_addr = f"{host}:{self._tcp.port}"
        return self._adv_addr

    # the data plane owns dispatch: proxy the element-facing surface
    @property
    def on_message(self):
        return self._tcp.on_message

    @on_message.setter
    def on_message(self, cb) -> None:
        self._tcp.on_message = cb

    @property
    def caps_provider(self):
        return self._tcp.caps_provider

    @caps_provider.setter
    def caps_provider(self, cb) -> None:
        self._tcp.caps_provider = cb

    @property
    def metrics(self):
        return self._tcp.metrics

    @metrics.setter
    def metrics(self, m) -> None:
        self._tcp.metrics = m

    @property
    def port(self) -> int:  # the ephemeral DATA port (host:port is broker)
        return self._tcp.port

    def start(self) -> None:
        self._tcp.start()
        self._stop_evt = threading.Event()
        try:
            self._connect_mqtt_and_advertise()
        except Exception as e:  # noqa: BLE001 - broker briefly down
            # don't fail (and leak the started TcpServer): the advertise
            # loop below reconnects through broker outages, and clients
            # retry discovery — same tolerance at startup as mid-life
            self._retry.failure(e, what=f"broker advertise "
                                        f"({self.topic!r})")
            self._close_mqtt()
        # periodic re-advertisement: a broker restart without retained
        # persistence would otherwise de-advertise a healthy server
        # forever (the keepalive thread dies silently on the first
        # failed ping); this loop re-publishes and reconnects as needed
        from ..obs import prof as _prof

        self._adv_thread = _prof.named_thread(
            "edge-hybrid-adv", self.topic, self._advertise_loop)
        self._adv_thread.start()

    def _connect_mqtt_and_advertise(self) -> None:
        from .mqtt import MqttClient

        m = MqttClient(
            self._broker_addr[0], self._broker_addr[1],
            client_id=f"nns-hybrid-srv-{uuid.uuid4().hex[:12]}")
        try:
            m.publish(_HYBRID_TOPIC_FMT.format(topic=self.topic),
                      self._advertised_addr().encode(), retain=True)
        except Exception:
            m.close()
            raise
        self._mqtt = m

    def _close_mqtt(self) -> None:
        # atomic swap-then-close: stop() and the advertise thread both
        # call this concurrently — each takes its own reference, so
        # neither can observe a half-closed None and raise
        m, self._mqtt = self._mqtt, None
        if m is not None:
            try:
                m.close()
            except OSError:
                pass

    def _advertise_loop(self, interval: float = 2.0) -> None:
        while not self._stop_evt.wait(interval):
            try:
                if self._mqtt is None:
                    if not self._retry.allow():
                        continue  # breaker open: probe after open_s,
                        # not on every 2 s tick
                    self._connect_mqtt_and_advertise()
                    self._retry.success()
                else:
                    # refresh the retained slot (no-op for a healthy
                    # broker; restores it after a broker restart); local
                    # ref — stop() may swap self._mqtt to None mid-call
                    m = self._mqtt
                    if m is not None and not self._stop_evt.is_set():
                        m.publish(
                            _HYBRID_TOPIC_FMT.format(topic=self.topic),
                            self._advertised_addr().encode(), retain=True)
                # a reconnect or a blocked publish can outlive stop()
                # (socket calls block up to the client timeout, longer
                # than stop's join): never leave a fresh advertisement
                # for a dead server — or clobber a replacement's —
                # after teardown began
                if self._stop_evt.is_set():
                    self._clear_if_mine()
                    return
            except Exception as e:  # noqa: BLE001 - broker down: retry
                # first failure of the outage logs at WARNING, the rest
                # at debug (no per-tick spam); the breaker slows probes
                # on a dead broker
                self._retry.failure(e, what=f"broker advertise "
                                            f"({self.topic!r})")
                self._close_mqtt()

    def _clear_if_mine(self) -> None:
        """Clear the retained advertisement iff it is still OURS.  Uses
        a dedicated local MqttClient (one connection: subscribe → read
        retained → compare → clear) so it never races the advertise
        loop's ``self._mqtt`` — the loop may still be mid-reconnect when
        stop() runs, and its revival path calls this too.

        Rolling restarts are last-writer-wins by design: while old and
        new servers overlap, their 2 s refreshes alternate the retained
        slot, but every address advertised belongs to a then-healthy
        server, the ownership check here keeps the LAST stop from
        clearing the survivor, and the survivor's next refresh (≤2 s,
        well under the 5 s discovery timeout) converges the slot."""
        self._close_mqtt()  # best-effort; the loop's client is not used
        from .mqtt import MqttClient

        try:
            chk = MqttClient(self._broker_addr[0], self._broker_addr[1],
                             client_id=f"nns-hyb-clr-{uuid.uuid4().hex[:8]}",
                             timeout=1.0)
        except Exception:  # noqa: BLE001 - broker gone: nothing to clear
            return
        try:
            topic = _HYBRID_TOPIC_FMT.format(topic=self.topic)
            chk.subscribe(topic)
            got = chk.recv_publish()
            if got is not None and \
                    got[1].decode() == self._advertised_addr():
                chk.publish(topic, b"", retain=True)
        except Exception:  # noqa: BLE001
            pass
        finally:
            chk.close()

    def stop(self) -> None:
        self._stop_evt.set()
        if self._adv_thread is not None:
            self._adv_thread.join(timeout=3)
            self._adv_thread = None
        # clear the retained advertisement — but only if it is still
        # OURS: in a rolling restart the replacement server has already
        # overwritten the slot, and clearing it would de-advertise the
        # healthy successor
        self._clear_if_mine()
        self._tcp.stop()

    def send(self, client_id: int, env: Envelope) -> bool:
        return self._tcp.send(client_id, env)

    def publish(self, env: Envelope) -> int:
        return self._tcp.publish(env)


def _hybrid_discover(host: str, port: int, topic: str,
                     timeout: float) -> Tuple[str, int]:
    """Ask the broker who serves ``topic``; returns the data address.
    All broker-level failures surface as OSError — connect callers
    (e.g. the query client's failover loop) treat them like any other
    unreachable-server condition."""
    from .mqtt import MqttClient

    try:
        mqtt = MqttClient(host, int(port),
                          client_id=f"nns-hybrid-cli-{uuid.uuid4().hex[:12]}",
                          timeout=timeout)
    except Exception as e:  # noqa: BLE001 - CONNACK refused is StreamError
        if isinstance(e, OSError):
            raise
        raise OSError(f"hybrid: broker handshake failed: {e}") from e
    try:
        mqtt.subscribe(_HYBRID_TOPIC_FMT.format(topic=topic))
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            # cap each blocking read to the remaining budget, else a
            # stray publish near the deadline lets the next recv block a
            # full extra timeout
            mqtt.set_recv_timeout(deadline - time.monotonic())
            got = mqtt.recv_publish()
            if got is None:
                continue
            _t, payload = got
            if payload:
                h, _, p = payload.decode().rpartition(":")
                return h, int(p)
        raise OSError(
            f"hybrid: no server registered for topic {topic!r} at "
            f"broker {host}:{port} within {timeout}s")
    except OSError:
        raise
    except Exception as e:  # noqa: BLE001 - e.g. "no SUBACK" StreamError
        raise OSError(f"hybrid: discovery failed: {e}") from e
    finally:
        mqtt.close()


def connect_hybrid(host: str, port: int, topic: str = "",
                   timeout: float = 5.0) -> ClientConn:
    """Discover via broker, then open the TCP data connection.  Called
    again after a disconnect (the query client's failover path), the
    broker is re-queried — a server that moved re-registers its topic
    and the client finds the new address."""
    data_host, data_port = _hybrid_discover(
        host, port, topic or "tensor-query", timeout)
    return TcpClientConn(data_host, data_port, timeout=timeout)


# -- factories ----------------------------------------------------------------


def make_server(host: str, port: int, connect_type: str = "tcp",
                topic: str = "", data_host: str = "127.0.0.1",
                data_port: int = 0,
                advertise_host: str = "") -> ServerTransport:
    if connect_type == "inproc":
        return InprocServer(host, port)
    if connect_type == "tcp":
        return TcpServer(host, port)
    if connect_type == "hybrid":
        return HybridServer(host, port, topic=topic, data_host=data_host,
                            data_port=data_port,
                            advertise_host=advertise_host)
    raise ValueError(f"unknown connect-type {connect_type!r}")


def connect(host: str, port: int, connect_type: str = "tcp",
            timeout: float = 5.0, topic: str = "") -> ClientConn:
    if connect_type == "inproc":
        return InprocClientConn(host, port)
    if connect_type == "tcp":
        return TcpClientConn(host, port, timeout=timeout)
    if connect_type == "hybrid":
        return connect_hybrid(host, port, topic=topic, timeout=timeout)
    raise ValueError(f"unknown connect-type {connect_type!r}")

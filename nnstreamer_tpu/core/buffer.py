"""Stream buffers: frames of tensors flowing through a pipeline.

TPU-native replacement for GstBuffer + the reference's tensor-buffer helpers
(/root/reference/gst/nnstreamer/nnstreamer_plugin_api_impl.c:1586-1813,
``gst_tensor_buffer_get_nth_memory`` / ``append_memory`` / ``get_count``).

A :class:`Tensor` holds its payload in exactly one of three residences —
``jax.Array`` (device HBM), ``np.ndarray`` (host), or raw ``bytes`` (wire) —
and converts lazily.  Device→host conversions are the expensive edge; the
pipeline keeps hot-path tensors device-resident end-to-end, and jax's async
dispatch means a Buffer can hold *futures* (not-yet-computed arrays) so
pipeline stages overlap with TPU execution.

Timestamps (``pts``/``duration``) are integer nanoseconds as in GStreamer;
``None`` means "no timestamp" (GST_CLOCK_TIME_NONE).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..obs import transfer as _xfer
from .meta import MetaInfo
from .spec import TensorSpec, TensorsSpec
from .types import DType, MediaType, TensorFormat

ArrayLike = Any  # jax.Array | np.ndarray | bytes

SECOND = 1_000_000_000  # ns, parity with GST_SECOND
MSECOND = 1_000_000
USECOND = 1_000


class DonatedTensorError(RuntimeError):
    """A tensor's device payload was donated to an XLA dispatch
    (``donate_argnums``) and then read again.  XLA has already reused
    the HBM buffer, so the bytes behind the old handle are garbage —
    jax itself raises only lazily (and on some backends not at all),
    which is why the runtime marks donated tensors eagerly and fails
    the *read*, at the exact line that would have consumed stale
    data."""


def _jnp():
    import jax.numpy as jnp

    return jnp


class Tensor:
    """One tensor payload with lazy device/host/wire conversion."""

    __slots__ = ("_dev", "_host", "_raw", "_spec", "_donated")

    def __init__(self, data: ArrayLike, spec: Optional[TensorSpec] = None):
        self._dev = None
        self._host = None
        self._raw = None
        self._donated = False
        if isinstance(data, (bytes, bytearray, memoryview)):
            if spec is None:
                raise ValueError("raw bytes tensor requires an explicit spec")
            self._raw = bytes(data)
            if len(self._raw) != spec.nbytes:
                raise ValueError(
                    f"payload size {len(self._raw)} != spec size {spec.nbytes}")
            self._spec = spec
        elif isinstance(data, np.ndarray):
            self._host = data
            self._spec = spec or TensorSpec.from_shape(data.shape, data.dtype)
        else:  # jax.Array (or anything array-like living on device)
            self._dev = data
            self._spec = spec or TensorSpec.from_shape(
                data.shape, np.dtype(data.dtype))

    # -- residence conversions ---------------------------------------------

    def _check_donated(self) -> None:
        """Raise if the only payload this tensor ever had was donated.
        Donation consumes the DEVICE buffer; an independent host/raw
        copy (if one exists) stays valid and readable."""
        if self._donated and self._host is None and self._raw is None:
            raise DonatedTensorError(
                f"tensor {self._spec} was donated to an XLA dispatch and "
                f"cannot be read again (its HBM buffer has been reused)")

    def mark_donated(self) -> None:
        """Record that this tensor's device array was handed to a
        donating dispatch (``donate_argnums``): the device handle is
        dropped so no code path can read the reused HBM buffer, and a
        read with no surviving host/raw copy raises
        :class:`DonatedTensorError` instead of returning garbage.
        Host-resident tensors are unaffected (XLA copies host args; it
        cannot donate what it does not own)."""
        if self._dev is not None:
            self._donated = True
            self._dev = None

    @property
    def is_donated(self) -> bool:
        return self._donated

    def jax(self):
        """Device-resident jax.Array (uploads host data on first call).
        The upload is a host→device crossing: counted byte-exact into
        the transfer ledger (obs/transfer.py) when obs is enabled."""
        if self._dev is None:
            self._check_donated()
        if self._dev is None:
            if _xfer.ACTIVE:
                t0 = time.perf_counter()
                self._dev = _jnp().asarray(self.np())
                _xfer.record("h2d", "input", self._spec.nbytes,
                             time.perf_counter() - t0)
            else:
                self._dev = _jnp().asarray(self.np())
        return self._dev

    def np(self) -> np.ndarray:
        """Host ndarray (blocks on device computation if needed).  The
        device→host drain is counted byte-exact into the transfer
        ledger (its duration includes any wait for the async
        computation to finish — that IS the drain cost the pipeline
        pays here)."""
        if self._host is None:
            self._check_donated()
            if self._dev is not None:
                if _xfer.ACTIVE:
                    t0 = time.perf_counter()
                    self._host = np.asarray(self._dev)
                    _xfer.record("d2h", "drain", self._spec.nbytes,
                                 time.perf_counter() - t0)
                else:
                    self._host = np.asarray(self._dev)
            else:
                self._host = np.frombuffer(
                    self._raw, dtype=self._spec.dtype.np_dtype
                ).reshape(self._spec.shape)
        return self._host

    def tobytes(self) -> bytes:
        if self._raw is None:
            self._raw = np.ascontiguousarray(self.np()).tobytes()
        return self._raw

    # -- accessors ----------------------------------------------------------

    @property
    def spec(self) -> TensorSpec:
        return self._spec

    @property
    def shape(self) -> Tuple[int, ...]:
        return self._spec.shape

    @property
    def dtype(self) -> DType:
        return self._spec.dtype

    @property
    def nbytes(self) -> int:
        return self._spec.nbytes

    @property
    def is_device(self) -> bool:
        return self._dev is not None

    def seed_host(self, arr: np.ndarray) -> None:
        """Install an already-drained host copy (shape/size-checked) so
        later ``np()`` calls read it for free instead of paying — and
        the ledger counting — another device→host crossing.  Used by
        the decoders' single-packed-drain path (decoders/__init__.py
        ``drain_once``): N tensors cross once as one packed array, then
        each tensor's host cache is seeded from the split."""
        if arr.nbytes != self._spec.nbytes:
            raise ValueError(
                f"seed_host size mismatch: {arr.nbytes} != "
                f"{self._spec.nbytes}")
        self._host = arr.reshape(self._spec.shape)

    def prefetch_host(self) -> None:
        """Start an async device→host copy (no-op for host tensors).
        Issued at dispatch/enqueue time, a later ``np()`` finds the
        payload already on host instead of paying a blocking device
        round-trip — the output-drain pattern for host-bound stages."""
        if self._dev is not None:
            try:
                self._dev.copy_to_host_async()
            except AttributeError:
                pass  # non-jax array backend

    def with_spec(self, spec: TensorSpec) -> "Tensor":
        """Reinterpret payload under a different spec (sizes must match)."""
        if spec.nbytes != self._spec.nbytes:
            raise ValueError(
                f"cannot reinterpret {self._spec} as {spec}: size mismatch")
        t = Tensor.__new__(Tensor)
        t._dev, t._host, t._raw = None, None, None
        t._donated = False
        if self._dev is not None:
            t._dev = self._dev.reshape(spec.shape) \
                if np.dtype(self._dev.dtype) == spec.dtype.np_dtype else None
        if t._dev is None:
            host = np.ascontiguousarray(self.np())
            t._host = host.view(spec.dtype.np_dtype).reshape(spec.shape)
        t._spec = spec
        return t

    def __repr__(self) -> str:
        res = "dev" if self._dev is not None else (
            "host" if self._host is not None else "raw")
        return f"Tensor({self._spec}, {res})"


@dataclasses.dataclass
class Buffer:
    """One frame of the stream: N tensors + timing + routing metadata.

    ``meta`` carries out-of-band routing info; key ``"client_id"`` is the
    parity of GstMetaQuery (/root/reference/gst/nnstreamer/tensor_meta.c:23).
    """

    tensors: List[Tensor]
    pts: Optional[int] = None
    duration: Optional[int] = None
    offset: Optional[int] = None  # frame index
    format: TensorFormat = TensorFormat.STATIC
    meta: Dict[str, Any] = dataclasses.field(default_factory=dict)

    # -- construction -------------------------------------------------------

    @classmethod
    def of(cls, *arrays, pts: Optional[int] = None, **kw) -> "Buffer":
        return cls(tensors=[a if isinstance(a, Tensor) else Tensor(a)
                            for a in arrays], pts=pts, **kw)

    @classmethod
    def from_bytes_list(cls, payloads: Sequence[bytes], spec: TensorsSpec,
                        pts: Optional[int] = None) -> "Buffer":
        if len(payloads) != spec.num_tensors:
            raise ValueError("payload count mismatch")
        return cls(tensors=[Tensor(p, s) for p, s in zip(payloads, spec.tensors)],
                   pts=pts, format=spec.format)

    # -- accessors ----------------------------------------------------------

    @property
    def num_tensors(self) -> int:
        return len(self.tensors)

    def __len__(self) -> int:
        return len(self.tensors)

    def __getitem__(self, i: int) -> Tensor:
        return self.tensors[i]

    @property
    def nbytes(self) -> int:
        return sum(t.nbytes for t in self.tensors)

    @property
    def residency(self) -> str:
        """Where this frame's payload lives at this moment: ``device``
        when every tensor holds a device array, ``host`` when none
        does (host ndarray or raw wire bytes), ``mixed`` otherwise.
        The tracer samples this at element boundaries to derive the
        per-pipeline crossings-per-frame metric (obs/transfer.py)."""
        if not self.tensors:
            return "host"
        n_dev = sum(1 for t in self.tensors if t.is_device)
        if n_dev == 0:
            return "host"
        return "device" if n_dev == len(self.tensors) else "mixed"

    def spec(self, rate=None) -> TensorsSpec:
        from fractions import Fraction

        return TensorsSpec(tensors=tuple(t.spec for t in self.tensors),
                           format=self.format,
                           rate=Fraction(rate) if rate is not None else Fraction(0, 1))

    def replace_tensors(self, tensors: Sequence[Tensor]) -> "Buffer":
        return dataclasses.replace(self, tensors=list(tensors))

    def mark_donated(self) -> None:
        """Mark every device-resident tensor of this frame donated (see
        :meth:`Tensor.mark_donated`) — called by donating dispatch sites
        AFTER the XLA call so an accidental re-read upstream (a tee
        branch, a retained reference) fails loudly instead of reading
        reused HBM."""
        for t in self.tensors:
            t.mark_donated()

    # -- wire form (flexible/sparse streams & inter-host transport) ---------

    def pack_flexible(self, media_type: MediaType = MediaType.TENSOR) -> List[bytes]:
        """Each tensor as ``meta-header || payload`` (parity:
        flexible-tensor memories, nnstreamer_plugin_api_impl.c flex path)."""
        out = []
        for t in self.tensors:
            mi = MetaInfo.from_spec(t.spec, format=TensorFormat.FLEXIBLE,
                                    media_type=media_type)
            out.append(mi.pack() + t.tobytes())
        return out

    @classmethod
    def unpack_flexible(cls, payloads: Sequence[bytes],
                        pts: Optional[int] = None) -> "Buffer":
        tensors = []
        for p in payloads:
            mi = MetaInfo.unpack(p)
            body = p[mi.header_size:]
            if len(body) != mi.data_nbytes():
                raise ValueError(
                    f"flexible payload size {len(body)} != {mi.data_nbytes()}")
            tensors.append(Tensor(body, mi.to_spec()))
        return cls(tensors=tensors, pts=pts, format=TensorFormat.FLEXIBLE)


# -- sparse codec -----------------------------------------------------------
# Parity: gst_tensor_sparse_from_dense / gst_tensor_sparse_to_dense
# (/root/reference/gst/nnstreamer/elements/gsttensor_sparseutil.c:31,116).
# Layout: sparse meta header (with nnz), then u32 flat indices, then values.


def sparse_from_dense(t: Tensor) -> bytes:
    arr = np.ascontiguousarray(t.np()).reshape(-1)
    idx = np.nonzero(arr)[0].astype(np.uint32)
    vals = arr[idx]
    mi = MetaInfo.from_spec(t.spec, format=TensorFormat.SPARSE, nnz=len(idx))
    return mi.pack() + idx.tobytes() + vals.tobytes()


def sparse_to_dense(payload: bytes) -> Tensor:
    mi = MetaInfo.unpack(payload)
    if mi.format != TensorFormat.SPARSE:
        raise ValueError("payload is not sparse")
    off = mi.header_size
    idx = np.frombuffer(payload, dtype=np.uint32, count=mi.nnz, offset=off)
    off += mi.nnz * 4
    vals = np.frombuffer(payload, dtype=mi.dtype.np_dtype, count=mi.nnz,
                         offset=off)
    dense = np.zeros(mi.shape, dtype=mi.dtype.np_dtype).reshape(-1)
    dense[idx] = vals
    return Tensor(dense.reshape(mi.shape), mi.to_spec())

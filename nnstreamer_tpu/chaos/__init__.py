"""Chaos engineering for the serving stack: deterministic fault
injection (:mod:`.plan`), the shared reconnect backoff + circuit
breaker every edge transport uses (:mod:`.retrypolicy`), and the
process-wide hook the seams read (:mod:`.hooks`).

See ``Documentation/robustness.md`` for the fault model, the spec
grammar, and the recovery machinery the plans exercise.
"""

from __future__ import annotations

from typing import Optional

from . import hooks as _hooks
from .plan import (
    ChaosInvokeError,
    FAULTS,
    FaultPlan,
    FaultSpec,
    INVOKE_FAULTS,
    QUEUE_FAULTS,
    WIRE_FAULTS,
    WireOp,
)
from .retrypolicy import BreakerOpen, RetryPolicy

__all__ = [
    "ChaosInvokeError", "FAULTS", "FaultPlan", "FaultSpec",
    "INVOKE_FAULTS", "QUEUE_FAULTS", "WIRE_FAULTS", "WireOp",
    "BreakerOpen", "RetryPolicy",
    "install_plan", "uninstall_plan", "active_plan",
]


def install_plan(plan: FaultPlan) -> FaultPlan:
    """Install ``plan`` process-wide: every seam (edge transports, pool
    dispatch, batching windows) starts consulting it immediately."""
    _hooks.plan = plan
    return plan


def uninstall_plan() -> None:
    """Detach the process-wide plan (the seams go back to zero-cost)."""
    _hooks.plan = None


def active_plan() -> Optional[FaultPlan]:
    return _hooks.plan

"""Wire-format converter sub-plugins: flexbuf / flatbuf / protobuf.

Parity targets:
- /root/reference/ext/nnstreamer/tensor_converter/tensor_converter_flexbuf.cc
  (mime ``other/flexbuf``)
- .../tensor_converter_flatbuf.cc (mime ``other/flatbuf-tensor``)
- .../tensor_converter_protobuf.cc (mime ``other/protobuf-tensor``)

Each converts one self-describing wire payload into a tensor buffer.
Because the schema rides inside the payload, the negotiated out-caps are
``format=flexible``; the emitted buffers carry fully-typed tensors, so a
downstream ``tensor_converter`` (flexible→static) or any flexible-capable
element consumes them directly.
"""

from __future__ import annotations

from typing import Callable, Tuple

from ..core import (
    Buffer,
    CapsStruct,
    TensorFormat,
    TensorsSpec,
)
from . import ExternalConverter, register_converter
from .codecs import flatbuf_decode, flexbuf_decode, protobuf_decode


class _WireConverter(ExternalConverter):
    DECODE: Callable[[bytes], Tuple[Buffer, TensorsSpec]] = None

    def get_out_config(self, caps: CapsStruct) -> TensorsSpec:
        rate = caps.get("framerate", None) if caps is not None else None
        return TensorsSpec(format=TensorFormat.FLEXIBLE,
                           rate=rate or TensorsSpec().rate)

    def convert(self, buf: Buffer, caps: CapsStruct) -> Buffer:
        payload = buf.tensors[0].tobytes()
        out, _spec = type(self).DECODE(payload)
        out.pts, out.duration = buf.pts, buf.duration
        out.meta.update(buf.meta)
        out.format = TensorFormat.FLEXIBLE
        return out


@register_converter
class FlexbufConverter(_WireConverter):
    NAME = "flexbuf"
    MIMES = ("other/flexbuf",)
    DECODE = staticmethod(flexbuf_decode)


@register_converter
class FlatbufConverter(_WireConverter):
    NAME = "flatbuf"
    MIMES = ("other/flatbuf-tensor",)
    DECODE = staticmethod(flatbuf_decode)


@register_converter
class ProtobufConverter(_WireConverter):
    NAME = "protobuf"
    MIMES = ("other/protobuf-tensor",)
    DECODE = staticmethod(protobuf_decode)

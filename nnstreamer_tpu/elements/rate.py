"""``tensor_rate`` — framerate control + QoS throttling.

Parity target: /root/reference/gst/nnstreamer/elements/gsttensor_rate.c
(props ``in``/``out``/``duplicate``/``drop``/``throttle``/``framerate``
:81-88): adjusts the stream to a target framerate by dropping or
duplicating frames against the PTS clock, and — with ``throttle=true`` —
sends a QoS event upstream that tensor_filter/sources honor by skipping
invokes (the tensor_rate → tensor_filter interplay, tensor_filter.c:511).
"""

from __future__ import annotations

from fractions import Fraction
from typing import Optional

from ..core import Buffer, Caps, SECOND
from ..runtime.element import NegotiationError, Pad, TransformElement
from ..runtime.registry import register_element
from ..runtime.events import Event


@register_element("tensor_rate")
class TensorRate(TransformElement):
    FACTORY = "tensor_rate"

    def __init__(self, name=None, framerate: str = "0/1",
                 throttle: bool = False, silent: bool = True, **props):
        self.framerate = framerate
        self.throttle = throttle
        self.silent = silent
        super().__init__(name, **props)
        self.in_count = 0
        self.out_count = 0
        self.dup_count = 0
        self.drop_count = 0
        self._next_ts: Optional[int] = None
        self._prev: Optional[Buffer] = None

    def _target(self) -> Fraction:
        s = str(self.framerate)
        if "/" in s:
            n, d = s.split("/")
            return Fraction(int(n), int(d or 1))
        return Fraction(s)

    def propose_src_caps(self, pad: Pad) -> Caps:
        in_spec = self.sinkpad.spec
        if in_spec is None:
            raise NegotiationError(f"{self.name}: no input caps")
        target = self._target()
        return Caps.from_spec(
            in_spec.with_rate(target if target else in_spec.rate))

    def start(self) -> None:
        if self.throttle and self._target():
            # ask upstream to not produce faster than the target
            self.sinkpad.push_upstream_event(
                Event.qos_throttle(self._target()))

    def transform(self, buf: Buffer) -> Optional[Buffer]:
        self.in_count += 1
        target = self._target()
        if not target or buf.pts is None:
            self.out_count += 1
            return buf  # passthrough without a clock
        interval = int(SECOND / target)
        if self._next_ts is None:
            self._next_ts = buf.pts
        # fill slots the stream skipped over with whichever of the
        # previous/current frame is closer to the slot time (videorate /
        # gsttensor_rate semantics — always using prev would hand buffers
        # arriving just after a slot boundary one-frame-stale output)
        while self._prev is not None and self._next_ts < buf.pts:
            src = self._prev
            if (self._prev.pts is not None
                    and abs(buf.pts - self._next_ts)
                    < abs(self._next_ts - self._prev.pts)):
                src = buf
            self.push(Buffer(tensors=src.tensors, pts=self._next_ts,
                             duration=interval, meta=dict(src.meta)))
            self._next_ts += interval
            self.out_count += 1
            self.dup_count += 1
        if buf.pts >= self._next_ts:
            self.push(Buffer(tensors=buf.tensors, pts=self._next_ts,
                             duration=interval, meta=dict(buf.meta)))
            self._next_ts += interval
            self.out_count += 1
        else:
            self.drop_count += 1  # more input frames than slots
        self._prev = buf
        return None

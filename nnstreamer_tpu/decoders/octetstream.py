"""``octet_stream`` decoder: tensors → raw byte stream.

Parity target: /root/reference/ext/nnstreamer/tensor_decoder/
tensordec-octetstream.c (130 LoC): concatenates tensor payloads into an
``application/octet-stream`` buffer (the inverse of the converter's octet
ingestion).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..core import Buffer, Caps, CapsStruct, Tensor, TensorSpec, TensorsSpec
from . import Decoder, register_decoder


@register_decoder
class OctetStream(Decoder):
    MODE = "octet_stream"

    def out_caps(self, in_spec: TensorsSpec) -> Caps:
        return Caps.new(CapsStruct.make(
            "application/octet-stream", framerate=in_spec.rate))

    def decode(self, buf: Buffer, in_spec: Optional[TensorsSpec]) -> Buffer:
        payload = b"".join(t.tobytes() for t in buf.tensors)
        arr = np.frombuffer(payload, np.uint8)
        return Buffer(
            tensors=[Tensor(arr, TensorSpec.from_shape(arr.shape, np.uint8))],
            pts=buf.pts, duration=buf.duration, meta=dict(buf.meta))

"""Runtime lock-order witness (`nnstreamer_tpu.utils.lockdep`) tests.

enable() patches the *process-wide* lock constructors, so every armed
scenario runs in a subprocess; the parent suite never sees a patched
``threading.Lock``.  Covers: inertness without the env var, edge and
cycle recording on a deliberate A->B / B->A inversion, the
Condition-over-RLock protocol, held-across-dispatch at the pool fence,
witness dumping via NNS_TPU_LOCKDEP_OUT, and the baseline diff tool
(non-empty witness required; cycles fail with readable paths; --update
regenerates the baseline).
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

INVERSION_SCRIPT = '''
import threading
from nnstreamer_tpu.utils import lockdep

def mk_a():
    a = threading.Lock()
    return a

def mk_b():
    b = threading.RLock()
    return b

a = mk_a()
b = mk_b()
with a:
    with b:
        pass
with b:
    with a:
        pass
'''


def run_lockdep(body, tmp_path, env_extra=None, out_name="witness.json"):
    """Run a snippet in a subprocess with lockdep armed; return
    (completed-process, witness-dict-or-None)."""
    out = tmp_path / out_name
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "NNS_TPU_LOCKDEP": "1",
        "NNS_TPU_LOCKDEP_SCOPE": "all",
        "NNS_TPU_LOCKDEP_OUT": str(out),
        "PYTHONPATH": REPO,
    })
    if env_extra:
        env.update(env_extra)
    script = tmp_path / "scenario.py"
    script.write_text(
        "from nnstreamer_tpu.utils import lockdep\n"
        "lockdep.maybe_enable_from_env()\n" + body)
    cp = subprocess.run([sys.executable, str(script)], env=env,
                        capture_output=True, text=True, timeout=120)
    wit = None
    if out.exists():
        with open(out) as f:
            wit = json.load(f)
    return cp, wit


def test_inert_without_env(tmp_path):
    """Importing the package without NNS_TPU_LOCKDEP leaves
    threading.Lock untouched and the witness disabled."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    env.pop("NNS_TPU_LOCKDEP", None)
    cp = subprocess.run([sys.executable, "-c", (
        "import threading\n"
        "orig = threading.Lock\n"
        "import nnstreamer_tpu\n"
        "from nnstreamer_tpu.utils import lockdep\n"
        "assert threading.Lock is orig, 'constructor was patched'\n"
        "assert not lockdep.enabled()\n"
        "assert not lockdep.check_dispatch('x')\n"
        "print('inert-ok')")],
        env=env, capture_output=True, text=True, timeout=120)
    assert cp.returncode == 0, cp.stderr
    assert "inert-ok" in cp.stdout


def test_witness_records_edges_and_cycle(tmp_path):
    """A deliberate A->B / B->A inversion yields both order edges, a
    cycle, and a cycle violation recorded the moment the second edge
    lands — no deadlock needed."""
    cp, wit = run_lockdep(INVERSION_SCRIPT, tmp_path)
    assert cp.returncode == 0, cp.stderr
    assert wit is not None, "NNS_TPU_LOCKDEP_OUT produced no witness"
    labels = {n["label"] for n in wit["nodes"]}
    a = next(l for l in labels if l.endswith("mk_a.a"))
    b = next(l for l in labels if l.endswith("mk_b.b"))
    edges = {(e["src"], e["dst"]) for e in wit["edges"]}
    assert (a, b) in edges and (b, a) in edges
    assert wit["cycles"], "inversion must close a cycle"
    kinds = [v["kind"] for v in wit["violations"]]
    assert "cycle" in kinds
    cyc = next(v for v in wit["violations"] if v["kind"] == "cycle")
    assert cyc["path"][0] == cyc["path"][-1], "path must close"
    assert {a, b} <= set(cyc["path"])


def test_consistent_order_is_clean(tmp_path):
    body = INVERSION_SCRIPT.replace(
        "with b:\n    with a:\n        pass", "with a:\n    with b:\n        pass")
    cp, wit = run_lockdep(body, tmp_path)
    assert cp.returncode == 0, cp.stderr
    assert wit["cycles"] == [] and wit["violations"] == []
    assert {(e["src"], e["dst"]) for e in wit["edges"]}, \
        "the nested acquisition must still record its order edge"


def test_condition_over_wrapped_rlock(tmp_path):
    """Condition(RLock()) must keep working under the proxy (the
    private _release_save/_acquire_restore protocol) and wait() must
    not leave stale held-stack entries behind."""
    body = '''
import threading

def mk_r():
    r = threading.RLock()
    return r

r = mk_r()
cond = threading.Condition(r)
with cond:
    cond.wait(timeout=0.01)
# after the wait the held stack must be balanced: a dispatch fence
# outside any lock reports nothing
from nnstreamer_tpu.utils import lockdep as ld
assert not ld.check_dispatch("post-wait"), "held stack unbalanced"
print("cond-ok")
'''
    cp, wit = run_lockdep(body, tmp_path)
    assert cp.returncode == 0, cp.stderr
    assert "cond-ok" in cp.stdout
    assert wit["violations"] == []


def test_held_across_dispatch(tmp_path):
    body = '''
import threading
from nnstreamer_tpu.utils import lockdep as ld

def mk():
    lk = threading.Lock()
    return lk

lk = mk()
with lk:
    assert ld.check_dispatch("pool:test")
'''
    cp, wit = run_lockdep(body, tmp_path)
    assert cp.returncode == 0, cp.stderr
    v = [v for v in wit["violations"]
         if v["kind"] == "held-across-dispatch"]
    assert v and v[0]["what"] == "pool:test"
    assert any(h.endswith("mk.lk") for h in v[0]["held"])


def test_pool_dispatch_fence_fires(tmp_path):
    """The serving-pool fence is wired: the REAL PoolEntry._dispatch
    body (run here on a stub entry) reports a held-across-dispatch
    violation when the flushing thread holds a witnessed lock."""
    body = '''
import threading
from nnstreamer_tpu.runtime import serving

class StubEntry(serving.PoolEntry):
    def __init__(self):  # skip the pool plumbing, keep _dispatch
        pass

    def label(self):
        return "jax-xla:stub"

    def _dispatch_inner(self, items):
        pass

def mk():
    guard = threading.Lock()
    return guard

guard = mk()
with guard:
    StubEntry()._dispatch([])
print("dispatched")
'''
    cp, wit = run_lockdep(body, tmp_path)
    assert cp.returncode == 0, cp.stderr
    assert "dispatched" in cp.stdout
    v = [v for v in wit["violations"]
         if v["kind"] == "held-across-dispatch"]
    assert v, "flush under a held lock must trip the dispatch fence"
    assert v[0]["what"] == "pool:jax-xla:stub"


def test_package_smoke_witness_nonempty(tmp_path):
    """Driving a real pipeline under lockdep yields a non-empty witness
    with zero violations — the live half of the CI gate."""
    body = '''
import numpy as np
from nnstreamer_tpu.core import Buffer
from nnstreamer_tpu.runtime import parse_launch

caps = ("other/tensors,format=static,num_tensors=1,"
        "dimensions=3:4:4:1,types=uint8,framerate=30/1")
p = parse_launch(f"appsrc name=src caps={caps} ! tensor_converter "
                 "! tensor_sink name=sink")
p.start()
src = p["src"]
for i in range(4):
    src.push_buffer(Buffer.of(np.zeros((1, 4, 4, 3), np.uint8), pts=i))
src.end_of_stream()
p.wait_eos(timeout=30)
p.stop()
'''
    cp, wit = run_lockdep(body, tmp_path)
    assert cp.returncode == 0, cp.stderr
    assert wit["nodes"], "running a pipeline must witness package locks"
    assert wit["violations"] == [], wit["violations"]
    assert wit["cycles"] == []


# -- nns-lockdep-diff --------------------------------------------------------


def run_diff(args):
    from nnstreamer_tpu.utils.lockdep import diff_main
    import io
    from contextlib import redirect_stdout, redirect_stderr

    out, err = io.StringIO(), io.StringIO()
    with redirect_stdout(out), redirect_stderr(err):
        rc = diff_main(args)
    return rc, out.getvalue(), err.getvalue()


def test_diff_fails_on_inversion_and_prints_cycle(tmp_path):
    """The CI failure mode end-to-end: deliberate inversion fixture ->
    witness -> diff exits nonzero and prints the cycle path."""
    cp, wit = run_lockdep(INVERSION_SCRIPT, tmp_path)
    assert cp.returncode == 0, cp.stderr
    baseline = tmp_path / "baseline.json"
    baseline.write_text(json.dumps(
        {"version": 1, "edges": [], "allowed_cycles": []}))
    rc, out, err = run_diff([str(tmp_path / "witness.json"),
                             "--baseline", str(baseline)])
    assert rc == 1
    assert "LOCK-ORDER CYCLE" in out
    assert "mk_a.a" in out and "mk_b.b" in out and "->" in out
    assert "FAIL" in err


def test_diff_empty_witness_fails(tmp_path):
    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps(
        {"version": 1, "nodes": [], "edges": [],
         "violations": [], "cycles": []}))
    rc, out, err = run_diff([str(empty)])
    assert rc == 1
    assert "empty" in err


def test_diff_clean_witness_and_update_roundtrip(tmp_path):
    body = INVERSION_SCRIPT.replace(
        "with b:\n    with a:\n        pass", "")
    cp, wit = run_lockdep(body, tmp_path)
    assert cp.returncode == 0, cp.stderr
    witness = str(tmp_path / "witness.json")
    baseline = tmp_path / "baseline.json"
    # --update writes a fresh baseline from a violation-free witness
    rc, out, err = run_diff([witness, "--baseline", str(baseline),
                             "--update"])
    assert rc == 0 and baseline.exists()
    # diffing against it is then clean, with zero new edges
    rc, out, err = run_diff([witness, "--baseline", str(baseline)])
    assert rc == 0
    assert "OK" in out and "0 new" in out
    # a never-seen edge is informational, not fatal
    baseline.write_text(json.dumps(
        {"version": 1, "edges": [], "allowed_cycles": []}))
    rc, out, err = run_diff([witness, "--baseline", str(baseline)])
    assert rc == 0
    assert "not in baseline" in out


def test_diff_update_refuses_dirty_witness(tmp_path):
    cp, wit = run_lockdep(INVERSION_SCRIPT, tmp_path)
    assert cp.returncode == 0, cp.stderr
    baseline = tmp_path / "baseline.json"
    rc, out, err = run_diff([str(tmp_path / "witness.json"),
                             "--baseline", str(baseline), "--update"])
    assert rc == 1 and not baseline.exists()
    assert "refusing" in err


def test_committed_baseline_is_valid_json():
    """The committed baseline parses and has the expected shape (the
    lockdep CI step diffs the live witness against it)."""
    path = os.path.join(REPO, "tests", "lockdep_baseline.json")
    with open(path) as f:
        base = json.load(f)
    assert base["version"] == 1
    assert base["allowed_cycles"] == []
    assert isinstance(base["edges"], list)


@pytest.mark.slow
def test_concurrency_suite_under_lockdep(tmp_path):
    """The full dynamic gate: run the concurrency-heavy test modules
    with the witness armed and diff against the committed baseline
    (CI runs this same recipe as a dedicated step)."""
    out = tmp_path / "witness.json"
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "NNS_TPU_LOCKDEP": "1",
                "NNS_TPU_LOCKDEP_OUT": str(out), "PYTHONPATH": REPO})
    cp = subprocess.run(
        [sys.executable, "-m", "pytest", "-q", "-m", "not slow",
         "-p", "no:cacheprovider", "-p", "no:randomly",
         "tests/test_chaos.py", "tests/test_watch.py",
         "tests/test_control.py", "tests/test_lifecycle.py"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=900)
    assert cp.returncode == 0, cp.stdout[-4000:] + cp.stderr[-4000:]
    rc, diff_out, diff_err = run_diff([str(out)])
    assert rc == 0, diff_out + diff_err

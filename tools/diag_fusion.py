"""Diagnose the fused-vs-unfused composite inversion (BENCH_r04 recorded
fused 0.832x of unfused while the docs claimed neutrality).

Two independent measurements:
1. Pipeline-level interleaved A/B with per-rep samples (not best-of-2),
   so drift shows up as spread instead of corrupting a point estimate.
2. Program-level chained-dispatch timing of the exact device programs
   each mode runs: one fused program (norm+detect+overlay) vs the
   three-program chain — isolates XLA-program cost from runtime cost.
"""
import json
import statistics
import sys
import time

sys.path.insert(0, "/root/repo")

import numpy as np  # noqa: E402

from nnstreamer_tpu import bench  # noqa: E402


def pipeline_ab(reps: int = 5):
    model = "bench_ssd_mobilenet_v2"
    bench._register_ssd_pp(model, bench.SSD_BATCH)
    samples = {"fused": [], "unfused": []}
    for r in range(reps):
        for mode, fuse in (("fused", True), ("unfused", False)):
            fps, _, _ = bench._run_composite_once(fuse, model)
            samples[mode].append(round(fps, 1))
            print(f"rep {r} {mode}: {fps:.1f} fps", flush=True)
    return samples


def program_level():
    import jax
    import jax.numpy as jnp

    from nnstreamer_tpu.decoders.boxutil import device_render_fn
    from nnstreamer_tpu.models.ssd import ssd_detect_apply

    params, anchors = bench._ssd_params_anchors()
    dev = jax.devices()[0]
    params_d = jax.device_put(params, dev)
    B, S = bench.SSD_BATCH, bench.SSD_SIZE

    def norm(x):
        return (x.astype(jnp.float32) - 127.5) / 127.5

    def detect(x):
        boxes, scores, classes = ssd_detect_apply(
            params_d, x, anchors, max_out=10)
        num = jnp.sum((scores > 0.25).astype(jnp.int32), axis=-1)
        return boxes, classes, scores, num

    render = device_render_fn(B, 10, S, S, 0.25)

    f_norm = jax.jit(norm)
    f_detect_f32 = jax.jit(detect)
    f_fused_all = jax.jit(lambda x: render(*detect(norm(x))))
    f_fused_nodec = jax.jit(lambda x: detect(norm(x)))

    rng = np.random.default_rng(0)
    xs = [jax.device_put(
        rng.integers(0, 255, (B, S, S, 3), dtype=np.uint8), dev)
        for _ in range(32)]
    xf = [f_norm(x) for x in xs]
    det_outs = [f_detect_f32(x) for x in xf]
    bench._fetch_sync(det_outs[-1])

    import itertools

    _chain_no = itertools.count(1)

    def chained(fn, argsets, n):
        # fresh args per chain (x + c) so no (executable, argument)
        # pair repeats across reps — the memo-cache defense
        c = next(_chain_no)
        salted = [tuple(a + np.asarray(c).astype(a.dtype) for a in args)
                  for args in argsets]
        bench._fetch_sync(salted[-1])
        out = None
        t0 = time.perf_counter()
        for i in range(n):
            out = fn(*salted[i % len(salted)])
        bench._fetch_sync(out)  # completion, not dispatch-ack
        return time.perf_counter() - t0

    def per_call_ms(fn, argsets, n=16, reps=4):
        bench._fetch_sync(fn(*argsets[0]))
        t1 = min(chained(fn, argsets, n) for _ in range(reps))
        t2 = min(chained(fn, argsets, 2 * n) for _ in range(reps))
        return max((t2 - t1) / n * 1e3, 0.0)

    out = {
        "fused_all_ms": per_call_ms(f_fused_all, [(x,) for x in xs]),
        "fused_nodec_ms": per_call_ms(f_fused_nodec, [(x,) for x in xs]),
        "norm_ms": per_call_ms(f_norm, [(x,) for x in xs]),
        "detect_f32_ms": per_call_ms(f_detect_f32, [(x,) for x in xf]),
        "render_ms": per_call_ms(render, det_outs),
    }
    out["unfused_chain_ms"] = round(
        out["norm_ms"] + out["detect_f32_ms"] + out["render_ms"], 3)
    for k in list(out):
        out[k] = round(out[k], 3)
    return out


if __name__ == "__main__":
    prog = program_level()
    print("program-level:", json.dumps(prog), flush=True)
    pipe = pipeline_ab()
    summary = {m: {"median": statistics.median(v), "min": min(v),
                   "max": max(v)} for m, v in pipe.items()}
    print("pipeline A/B samples:", json.dumps(pipe))
    print("pipeline A/B summary:", json.dumps(summary))
    print("program-level:", json.dumps(prog))

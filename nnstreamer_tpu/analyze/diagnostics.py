"""Diagnostic model + stable code catalog for the static pipeline verifier.

Every finding of every pass is a :class:`Diagnostic` with a stable code:

- ``NNS1xx`` — graph structure (links, cycles, reachability, sinks)
- ``NNS2xx`` — caps dry-run (negotiation without starting anything)
- ``NNS3xx`` — concurrency lint over the runtime sources
- ``NNS4xx`` — codebase lint over the whole package
- ``NNS5xx`` — performance-shape checks (micro-batching topology)
- ``NNS6xx`` — whole-package concurrency analysis (lock-order graph,
  deadlock cycles, hold-and-block, shared state, leaf locks)

Codes are append-only: a released code never changes meaning, so CI
suppressions and golden files stay valid across versions.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple


class Severity:
    ERROR = "error"
    WARNING = "warning"
    INFO = "info"

    ORDER = {ERROR: 0, WARNING: 1, INFO: 2}


#: code -> (default severity, one-line title).  The catalog drives the
#: docs (Documentation/analyze.md) and the every-code-covered test.
CODES: Dict[str, Tuple[str, str]] = {
    "NNS100": (Severity.ERROR, "pipeline description does not parse"),
    "NNS101": (Severity.ERROR, "sink pad is not linked"),
    "NNS102": (Severity.WARNING,
               "src pad is not linked (data will be dropped)"),
    "NNS103": (Severity.ERROR,
               "double link: pad is already connected"),
    "NNS104": (Severity.ERROR, "cycle in the pipeline graph"),
    "NNS105": (Severity.WARNING,
               "element unreachable from any source"),
    "NNS106": (Severity.WARNING, "pipeline has no sink element"),
    "NNS107": (Severity.ERROR, "pipeline has no source element"),
    "NNS108": (Severity.WARNING,
               "fan-in element inputs disagree on framerate"),
    "NNS201": (Severity.ERROR, "empty caps intersection at link"),
    "NNS202": (Severity.ERROR, "caps cannot be fixated at link"),
    "NNS203": (Severity.INFO,
               "source output caps unknown at analysis time"),
    "NNS204": (Severity.ERROR,
               "element rejected caps during negotiation"),
    "NNS205": (Severity.INFO,
               "filter sub-plugin could not be opened statically"),
    "NNS206": (Severity.INFO, "negotiation did not reach this pad"),
    "NNS301": (Severity.ERROR,
               "blocking call inside a bus-watch handler"),
    "NNS302": (Severity.ERROR,
               "bus post while holding a lock (handler reentrancy)"),
    "NNS303": (Severity.WARNING, "blocking call while holding a lock"),
    "NNS401": (Severity.ERROR, "registered element declares no pads"),
    "NNS402": (Severity.WARNING, "host numpy op in device hot path"),
    "NNS403": (Severity.ERROR, "bare except"),
    "NNS501": (Severity.WARNING,
               "tensor_filter batch>1 with no upstream queue "
               "(no thread boundary: the window cannot fill)"),
    "NNS502": (Severity.WARNING,
               "tensor_filter batch>1 with latency=1 "
               "(per-invoke sync defeats coalescing)"),
    "NNS503": (Severity.WARNING,
               "same jax-xla model opened by multiple filters without "
               "share-model (duplicated params/executables in HBM)"),
    "NNS504": (Severity.WARNING,
               "share-model=true on a stateful/custom framework "
               "(one host-side instance across pipelines is unsafe)"),
    "NNS505": (Severity.INFO,
               "tensor_filter latency=1 behind a queue (the reported "
               "latency excludes queue residency and can mislead)"),
    "NNS506": (Severity.INFO,
               "tensor_query_client tracing a cross-host link without "
               "NTP sync (span alignment relies on the in-band "
               "symmetric-delay estimate alone)"),
    "NNS507": (Severity.WARNING,
               "tensor_query_client on a cross-host link with "
               "timeout=0 or max-request=0 (unbounded in-flight "
               "growth against a dead or stalled server)"),
    "NNS508": (Severity.WARNING,
               "observability props (stat-sample-interval-ms / "
               "latency=1 / latency-report / trace) set while obs is "
               "globally disabled (NNS_TPU_OBS_DISABLE) — the props "
               "silently no-op"),
    "NNS509": (Severity.WARNING,
               "mesh placement whose batch (or a micro-batch bucket) "
               "is not divisible by the mesh data-axis size — the "
               "window cannot shard evenly, so pad slots (or full "
               "replication) burn device time on every dispatch"),
    "NNS510": (Severity.WARNING,
               "watch rules file problem: malformed rule grammar, or "
               "a rule referencing a metric family the registry never "
               "exports (the alert can never fire)"),
    "NNS511": (Severity.WARNING,
               "controller playbook file problem: malformed grammar, "
               "an unknown rule name or actuator, or an actuation "
               "target (pool/link) no element in the analyzed "
               "pipeline creates (the playbook can never act)"),
    "NNS512": (Severity.WARNING,
               "share-model pool placement problem (pool-level "
               "NNS509): the pool's effective batch/batch-buckets "
               "are not divisible by the mesh data-axis size (every "
               "coalesced cross-pipeline window pads or replicates), "
               "or sharing filters declare provably conflicting "
               "placements (the pool refuses them at start with a "
               "PoolConflictError)"),
    "NNS513": (Severity.WARNING,
               "model lifecycle misconfiguration "
               "(runtime/lifecycle.py): canary= with bad grammar, on "
               "a non-shared filter, or without any watch rule "
               "binding the version-labelled series (the canary "
               "verdict would never trigger); is-updatable on a "
               "framework without reload support; or "
               "NNS_TPU_COMPILE_CACHE_DIR pointing at a missing/"
               "unwritable directory (the persistent AOT cache "
               "silently disables)"),
    "NNS514": (Severity.WARNING,
               "residency fence: a host-only element sandwiched "
               "between two device-resident stages — the frame drains "
               "device→host to feed it and re-uploads host→device to "
               "leave it, one full round-trip pair per frame in a "
               "chain that would otherwise stay in HBM "
               "(Documentation/dataflow.md)"),
    "NNS515": (Severity.WARNING,
               "fusion blocked: a linear transform→filter→decoder "
               "segment cannot collapse into one XLA dispatch for a "
               "breakable reason — an interposed queue/tee, "
               "share-model=true or invoke-dynamic on the filter, or "
               "a decoder configuration without a device scheme; each "
               "window pays one dispatch per stage instead of one "
               "total (Documentation/fusion.md)"),
    "NNS516": (Severity.WARNING,
               "pipeline-split misconfiguration: stage device subsets "
               "overlap or index past the inventory, a tensor_if "
               "offload branch reaches its cross-subset stage only "
               "through a host-only element (the per-branch face of "
               "NNS514 — the device-channel handoff degrades to a "
               "d2h+h2d pair per offloaded frame), or the cascade's "
               "heavy-stage filter lacks share-model=true "
               "(Documentation/serving.md)"),
    "NNS517": (Severity.WARNING,
               "tenancy/forecast misconfiguration: tenant= on a "
               "filter without share-model=true (attribution splits "
               "the SHARED pool's device-seconds — a private filter "
               "never bills), or a forecast watch rule that cannot "
               "predict: missing/non-positive horizon, bound to a "
               "histogram family, or a horizon shorter than 3 sampler "
               "intervals (Documentation/observability.md)"),
    "NNS518": (Severity.WARNING,
               "host-profiler misconfiguration: NNS_TPU_PROF / "
               "NNS_TPU_PROF_DEEP_DIR set together with "
               "NNS_TPU_OBS_DISABLE (the profiler is strictly inert — "
               "silent no-op, the NNS508 family), an unparsable or "
               "unworkable sampling rate (> 250 Hz: the sampler walks "
               "every thread's stack per tick and stops being "
               "low-overhead), or a deep-profile episode "
               "(NNS_TPU_PROF_DEEP_SECONDS) longer than a watch "
               "rule's for= window (the capture outlasts the episode "
               "that triggered it; Documentation/observability.md)"),
    "NNS601": (Severity.ERROR,
               "lock-order cycle across the package: two code paths "
               "take the same locks in opposite orders (potential "
               "deadlock; both acquisition paths printed)"),
    "NNS602": (Severity.WARNING,
               "hold-and-block: a blocking call (socket recv/accept/"
               "sendall, Event.wait, join, block_until_ready, "
               "registry snapshot) made — directly or through package "
               "calls — while a lock is held"),
    "NNS603": (Severity.WARNING,
               "unguarded shared state: a field written both from a "
               "Thread(target=...) entry point and from a public "
               "method with no guarding lock"),
    "NNS604": (Severity.ERROR,
               "leaf-lock discipline: a lock declared '# nns-lock: "
               "leaf' is held while another lock is acquired"),
}


@dataclasses.dataclass(frozen=True)
class Diagnostic:
    """One finding.  ``element``/``pad`` name the pipeline location for
    NNS1xx/NNS2xx; for source lint (NNS3xx/NNS4xx) ``element`` is the
    file path and ``pad`` the ``L<line>`` location."""

    code: str
    severity: str
    element: Optional[str]
    pad: Optional[str]
    message: str
    hint: Optional[str] = None

    @classmethod
    def make(cls, code: str, message: str, element: Optional[str] = None,
             pad: Optional[str] = None, hint: Optional[str] = None,
             severity: Optional[str] = None) -> "Diagnostic":
        sev = severity or CODES[code][0]
        return cls(code=code, severity=sev, element=element, pad=pad,
                   message=message, hint=hint)

    def to_dict(self) -> dict:
        return {"code": self.code, "severity": self.severity,
                "element": self.element, "pad": self.pad,
                "message": self.message, "hint": self.hint}

    def sort_key(self):
        return (Severity.ORDER.get(self.severity, 9), self.code,
                self.element or "", self.pad or "", self.message)

    def __str__(self):
        loc = ""
        if self.element:
            loc = f" [{self.element}" + (f".{self.pad}" if self.pad
                                         else "") + "]"
        s = f"{self.code} {self.severity:<7}{loc} {self.message}"
        if self.hint:
            # identical prefix per hint line keeps caret markers aligned
            for line in self.hint.split("\n"):
                s += f"\n        hint| {line}"
        return s


def sort_diagnostics(diags: List[Diagnostic]) -> List[Diagnostic]:
    return sorted(diags, key=lambda d: d.sort_key())


def counts(diags: List[Diagnostic]) -> Dict[str, int]:
    out = {Severity.ERROR: 0, Severity.WARNING: 0, Severity.INFO: 0}
    for d in diags:
        out[d.severity] = out.get(d.severity, 0) + 1
    return out

"""Reference-exact bounding-box decode semantics (host compat path).

The reference pins its box decoders in CI against RECORDED outputs of
genuinely trained detectors (tests/nnstreamer_decoder_boundingbox/:
yolov5/yolov8 tensors from real COCO models, mobilenet-ssd anchors,
palm detection) and byte-compares the rendered overlay with golden
frames.  This module reimplements, from the reference's documented
behavior, the EXACT decode semantics needed to reproduce those golden
renders bit-for-bit on the box geometry:

- integer truncation of box coords in input-image space
  (box_properties/yolo.cc:193-196 ``object.x = (int)(MAX(0, cx-w/2))``);
- STRICT ``>`` confidence threshold (yolo.cc:178 v5 includes the
  objectness product, :320 v8 class conf only);
- GLOBAL prob-sorted greedy NMS with the +1-inclusive integer IoU and
  strict ``>`` suppression (tensordec-boundingbox.cc:317-365);
- output scaling by integer division and 1-px red (0xFF0000FF RGBA)
  borders (tensordec-boundingbox.cc:594-640 draw()).

Label glyphs (the 8x13 ``rasters`` font, tensordec-font.c) are NOT
reproduced — that table is verbatim font data we intentionally do not
copy; :func:`label_mask` returns the glyph regions so golden
comparisons exclude exactly those pixels and nothing else.
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence, Tuple

import numpy as np

PIXEL_VALUE = np.uint32(0xFF0000FF)  # RED 100% in RGBA, as the ref


@dataclasses.dataclass
class RefDetection:
    """Integer-pixel detection in INPUT image space (the reference's
    ``detectedObject``)."""

    x: int
    y: int
    width: int
    height: int
    class_id: int
    prob: float
    tracking_id: int = 0


def ref_iou(a: RefDetection, b: RefDetection) -> float:
    """Integer, +1-inclusive IoU (tensordec-boundingbox.cc:317)."""
    x1 = max(a.x, b.x)
    y1 = max(a.y, b.y)
    x2 = min(a.x + a.width, b.x + b.width)
    y2 = min(a.y + a.height, b.y + b.height)
    w = max(0, x2 - x1 + 1)
    h = max(0, y2 - y1 + 1)
    inter = float(w * h)
    union = float(a.width * a.height + b.width * b.height) - inter
    o = inter / union if union else 0.0
    return o if o >= 0 else 0.0


def ref_nms(dets: List[RefDetection], threshold: float
            ) -> List[RefDetection]:
    """Global (class-agnostic) greedy NMS, prob-descending, STRICT
    ``>`` suppression (tensordec-boundingbox.cc:336)."""
    dets = sorted(dets, key=lambda d: -d.prob)
    alive = [True] * len(dets)
    for i, a in enumerate(dets):
        if not alive[i]:
            continue
        for j in range(i + 1, len(dets)):
            if alive[j] and ref_iou(a, dets[j]) > threshold:
                alive[j] = False
    return [d for d, ok in zip(dets, alive) if ok]


def yolo_decode(arr: np.ndarray, v8: bool, conf_threshold: float,
                iou_threshold: float, in_w: int, in_h: int,
                scaled_output: bool) -> List[RefDetection]:
    """Decode a yolov5 (A, 5+C) or yolov8 (A, 4+C) float array with the
    reference's exact semantics (box_properties/yolo.cc decode)."""
    arr = np.asarray(arr, np.float32)
    start = 4 if v8 else 5
    confs = arr[:, start:]
    max_idx = confs.argmax(axis=1)
    max_val = confs[np.arange(len(arr)), max_idx]
    eff = max_val if v8 else max_val * arr[:, 4]
    dets: List[RefDetection] = []
    for b in np.nonzero(eff > conf_threshold)[0]:
        cx, cy, w, h = (float(v) for v in arr[b, :4])
        if not scaled_output:
            cx *= in_w
            cy *= in_h
            w *= in_w
            h *= in_h
        dets.append(RefDetection(
            x=int(max(0.0, cx - w / 2.0)),
            y=int(max(0.0, cy - h / 2.0)),
            width=int(min(float(in_w), w)),
            height=int(min(float(in_h), h)),
            class_id=int(max_idx[b]),
            prob=float(eff[b])))
    return ref_nms(dets, iou_threshold)


def mobilenet_ssd_decode(loc: np.ndarray, scores: np.ndarray,
                         priors: np.ndarray, threshold: float,
                         iou_threshold: float, in_w: int, in_h: int,
                         y_scale: float = 10.0, x_scale: float = 10.0,
                         h_scale: float = 5.0, w_scale: float = 5.0
                         ) -> List[RefDetection]:
    """Decode the raw 2-tensor mobilenet-ssd layout against a prior
    table (box_properties/mobilenetssd.cc _get_object_i_mobilenet_ssd):
    per box, the best class c >= 1 whose LOGIT passes
    ``logit(threshold)`` (inclusive >=) wins; float32 prior box math
    with the 10/10/5/5 scales, C-truncation to int pixels with only
    x/y clamped at 0, then the global reference NMS."""
    loc = np.asarray(loc, np.float32).reshape(-1, 4)
    scores = np.asarray(scores, np.float32)
    scores = scores.reshape(-1, scores.shape[-1])
    priors = np.asarray(priors, np.float32)
    # threshold compares in the LOGIT domain (mobilenetssd.cc:84,152)
    sig_thresh = np.float32(np.log(threshold / (1.0 - threshold)))
    dets: List[RefDetection] = []
    logits = scores[:, 1:]
    best = logits.argmax(axis=1)
    best_logit = logits[np.arange(len(logits)), best]
    for b in np.nonzero(best_logit >= sig_thresh)[0]:
        f = np.float32
        # priors rows: [ycenter, xcenter, h, w] normalized
        ycenter = loc[b, 0] / f(y_scale) * priors[b, 2] + priors[b, 0]
        xcenter = loc[b, 1] / f(x_scale) * priors[b, 3] + priors[b, 1]
        hh = f(np.exp(loc[b, 2] / f(h_scale))) * priors[b, 2]
        ww = f(np.exp(loc[b, 3] / f(w_scale))) * priors[b, 3]
        ymin = ycenter - hh / f(2.0)
        xmin = xcenter - ww / f(2.0)
        score = 1.0 / (1.0 + np.exp(-float(best_logit[b])))
        dets.append(RefDetection(
            x=max(0, int(xmin * in_w)), y=max(0, int(ymin * in_h)),
            width=int(ww * in_w), height=int(hh * in_h),
            class_id=int(best[b]) + 1, prob=float(score)))
    return ref_nms(dets, iou_threshold)


def ssd_pp_decode(boxes: np.ndarray, classes: np.ndarray,
                  scores: np.ndarray, num: int, in_w: int, in_h: int,
                  threshold: float = float(np.finfo(np.float32).tiny)
                  ) -> List[RefDetection]:
    """Post-processed SSD layout (box_properties/mobilenetssdpp.cc
    _get_objects_mobilenet_ssd_pp): rows [ymin, xmin, ymax, xmax]
    clamped to [0,1], strict ``< threshold`` skip (default G_MINFLOAT —
    a score of exactly 0 is dropped), C truncation, NO nms (the model
    already suppressed)."""
    boxes = np.asarray(boxes, np.float32).reshape(-1, 4)
    dets: List[RefDetection] = []
    for d in range(min(int(num), len(boxes))):
        if scores[d] < threshold:
            continue
        y1 = min(max(float(boxes[d, 0]), 0.0), 1.0)
        x1 = min(max(float(boxes[d, 1]), 0.0), 1.0)
        y2 = min(max(float(boxes[d, 2]), 0.0), 1.0)
        x2 = min(max(float(boxes[d, 3]), 0.0), 1.0)
        dets.append(RefDetection(
            x=int(x1 * in_w), y=int(y1 * in_h),
            width=int((x2 - x1) * in_w), height=int((y2 - y1) * in_h),
            class_id=int(classes[d]), prob=float(scores[d])))
    return dets


def palm_anchors(min_scale: float = 1.0, max_scale: float = 1.0,
                 offset_x: float = 0.5, offset_y: float = 0.5,
                 strides: Sequence[int] = (8, 16, 16, 16),
                 input_size: int = 192) -> np.ndarray:
    """MediaPipe SSD anchor table [x_center, y_center, w, h] per row
    (box_properties/mppalmdetection.cc
    mp_palm_detection_generate_anchors)."""
    n = len(strides)

    def calc_scale(i):
        if n == 1:
            return (min_scale + max_scale) * 0.5
        return min_scale + (max_scale - min_scale) * i / (n - 1.0)

    rows = []
    layer = 0
    while layer < n:
        scales = []
        last = layer
        while last < n and strides[last] == strides[layer]:
            scales.append(calc_scale(last))
            scales.append(calc_scale(last + 1))
            last += 1
        fm = int(np.ceil(input_size / strides[layer]))
        for y in range(fm):
            for x in range(fm):
                for s in scales:
                    rows.append([(x + offset_x) / fm,
                                 (y + offset_y) / fm, s, s])
        layer = last
    return np.asarray(rows, np.float32)


def palm_decode(boxes: np.ndarray, scores: np.ndarray,
                anchors: np.ndarray, threshold: float,
                in_w: int, in_h: int) -> List[RefDetection]:
    """mp-palm-detection decode (mppalmdetection.cc
    _get_objects_mp_palm_detection): score clamped to +-100 then
    sigmoid, strict ``< threshold`` skip, anchor box math dividing by
    the INPUT size, x/y clamped at 0, then the reference nms at the
    hard-coded 0.05 IoU."""
    boxes = np.asarray(boxes, np.float32)
    boxes = boxes.reshape(len(anchors), -1)
    dets: List[RefDetection] = []
    for d in range(len(anchors)):
        score = float(np.clip(float(scores.reshape(-1)[d]),
                              -100.0, 100.0))
        score = 1.0 / (1.0 + np.exp(-score))
        if score < threshold:
            continue
        ax, ay, aw, ah = (float(v) for v in anchors[d])
        y_center = float(boxes[d, 0]) / in_h * ah + ay
        x_center = float(boxes[d, 1]) / in_w * aw + ax
        h = float(boxes[d, 2]) / in_h * ah
        w = float(boxes[d, 3]) / in_w * aw
        dets.append(RefDetection(
            x=max(0, int((x_center - w / 2.0) * in_w)),
            y=max(0, int((y_center - h / 2.0) * in_h)),
            width=int(w * in_w), height=int(h * in_h),
            class_id=0, prob=score))
    return ref_nms(dets, 0.05)


def load_box_priors(path: str) -> np.ndarray:
    """box_priors.txt: 4 lines x A columns of floats — rows are
    [ycenter, xcenter, h, w] per anchor (tensordecutil.c
    _init_anchors layout used by mobilenetssd.cc)."""
    rows = []
    with open(path, "r", encoding="utf-8") as f:
        for ln in f:
            ln = ln.strip()
            if ln:
                rows.append([float(v) for v in ln.split()])
    a = np.asarray(rows, np.float32)
    if a.shape[0] == 4:
        a = a.T  # (A, 4)
    return a


def draw_reference(dets: Sequence[RefDetection], out_w: int, out_h: int,
                   in_w: int, in_h: int) -> np.ndarray:
    """Render the reference's exact border geometry: returns an
    (out_h, out_w) uint32 RGBA-word canvas with 1-px PIXEL_VALUE
    borders, background 0 (tensordec-boundingbox.cc draw(), box part
    only — label glyphs are excluded by design, see module doc)."""
    frame = np.zeros((out_h, out_w), np.uint32)
    for a in dets:
        x1 = (out_w * a.x) // in_w
        x2 = min(out_w - 1, (out_w * (a.x + a.width)) // in_w)
        y1 = (out_h * a.y) // in_h
        y2 = min(out_h - 1, (out_h * (a.y + a.height)) // in_h)
        if x1 > x2 or y1 > y2 or y1 >= out_h or x1 >= out_w:
            # a box fully past the canvas: the reference's C writes out
            # of bounds here (silent corruption); we skip instead —
            # valid inputs are unaffected, hostile ones can't crash
            continue
        frame[y1, x1:x2 + 1] = PIXEL_VALUE
        frame[y2, x1:x2 + 1] = PIXEL_VALUE
        for yy in range(y1 + 1, y2):
            frame[yy, x1] = PIXEL_VALUE
            frame[yy, x2] = PIXEL_VALUE
    return frame


def label_mask(dets: Sequence[RefDetection], labels: Sequence[str],
               out_w: int, out_h: int, in_w: int, in_h: int,
               track: bool = False) -> np.ndarray:
    """(out_h, out_w) bool mask of the glyph blocks the reference's
    label pass writes (8x13 per char, 9-px advance, anchored 14 rows
    above the box top; chars stop at the right edge) — the pixels a
    golden comparison must exclude because we do not reproduce the
    font table."""
    mask = np.zeros((out_h, out_w), bool)
    for a in dets:
        if a.class_id < 0 or a.class_id >= len(labels):
            continue
        text = labels[a.class_id]
        if track:
            text = f"{text}-{a.tracking_id}"
        x1 = (out_w * a.x) // in_w
        y1 = (out_h * a.y) // in_h
        y1 = max(0, y1 - 14)
        for _ch in text:
            if x1 + 8 > out_w:
                break
            mask[y1:y1 + 13, x1:x1 + 8] = True
            x1 += 9
    return mask

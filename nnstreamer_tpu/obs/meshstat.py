"""Per-shard mesh attribution — who on the mesh actually did the work.

``MESH_SCALING.json`` showed the sharded filter collapsing to 50%
weak-scaling efficiency at n=2 with only a hand-written note guessing
why: nothing recorded how frames were split across shards, how many
micro-batch slots were padding, or even what topology a dispatch ran
over.  This module closes that gap: every mesh dispatch (the jax-xla
single-frame mesh path, ``invoke_batched`` windows with a sharding
constraint, and direct :class:`~nnstreamer_tpu.parallel.sharded.
ShardedModel` calls) records into the process-wide :data:`MESH_STATS`:

- the **topology** it ran over (axis names/sizes, device list, the
  data axis batches shard along);
- the **per-shard useful-frame split**: micro-batch slots fill in
  stack order, so with ``frames`` real frames in a ``slots``-slot
  window over ``S`` shards, shard *i* holds the overlap of its slot
  range with ``[0, frames)`` — equal on an even split, front-loaded
  when the window is short.  The cumulative per-shard counts drive
  ``nns_shard_imbalance`` (``max/mean - 1``: 0.0 on even splits);
- **pad-slot waste** per window (``slots - frames``): pad slots run
  the full computation and burn device time on every window — the
  figure nns-lint NNS509 warns about statically;
- dispatches whose batch could not shard at all (not divisible by the
  data axis: the input is **replicated**, every chip computes every
  frame).

Pulled by the metrics registry at scrape time like every other
collected stat: the snapshot's ``mesh`` table (v5), the
``nns_shard_imbalance`` / ``nns_mesh_*`` families, and the MESH
section of ``nns-top`` (one row per device).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from . import hooks as _hooks

#: fast-path flag (same contract as obs/transfer.py)
ACTIVE = not _hooks.DISABLED


class _Row:
    __slots__ = ("axes", "devices", "data_axis", "shards", "dispatches",
                 "frames", "slots", "pad_slots", "replicated_dispatches",
                 "shard_frames")

    def __init__(self, axes, devices, data_axis, shards):
        self.axes: Tuple[Tuple[str, int], ...] = axes
        self.devices: Tuple[str, ...] = devices
        self.data_axis = data_axis
        self.shards = shards
        self.dispatches = 0
        self.frames = 0
        self.slots = 0
        self.pad_slots = 0
        self.replicated_dispatches = 0
        self.shard_frames = [0] * shards


def shard_device_label(row: dict, shard: int, empty: str = "") -> str:
    """Device label of one data-shard of a snapshot ``mesh`` row.
    A shard is a GROUP of devices on a 2D mesh (data x model): label
    with the group's first device plus a ``+N`` suffix for the rest.
    The device list is the mesh array flattened in C order; a device's
    data-shard index combines its coordinates along every data axis
    (``row["data_axis"]`` may name several, ``+``-joined — a
    multi-host ``dcn.data+data`` window shards over both tiers)
    row-major in mesh-axis order, exactly how ``PartitionSpec``
    spreads a leading batch dim over an axis tuple.  For
    ``mesh=model:2,data:2`` shard 0 is devices {0, 2}, a strided
    column of the array.  Shared by the registry's
    ``nns_mesh_shard_frames_total`` exposition and the nns-top MESH
    section — one definition, one DEVICE column."""
    devices = row["devices"]
    names = {n for n in str(row["data_axis"]).split("+") if n}
    # C-order strides: product of the axis sizes after each axis
    dims = []  # (size, stride) of each data axis, mesh order
    stride = 1
    for name, size in reversed(list(row["axes"])):
        if name in names:
            dims.append((int(size), stride))
        stride *= int(size)
    dims.reverse()
    if not dims:  # data axis absent: the whole mesh is one shard
        devs = list(devices)
    else:
        def shard_of(f: int) -> int:
            idx = 0
            for size, st in dims:
                idx = idx * size + (f // st) % size
            return idx

        devs = [d for f, d in enumerate(devices) if shard_of(f) == shard]
    if not devs:
        return empty
    return devs[0] + (f"+{len(devs) - 1}" if len(devs) > 1 else "")


def shard_split(slots: int, frames: int, shards: int) -> List[int]:
    """Useful frames per shard of one window: ``slots`` micro-batch
    slots spread evenly over ``shards`` (callers guarantee
    divisibility on the sharded path), filled with ``frames`` real
    frames in stack order — the trailing ``slots - frames`` pad slots
    land on the highest shards."""
    per = slots // max(shards, 1)
    return [max(0, min(frames - i * per, per)) for i in range(shards)]


class MeshStats:
    """Process-wide, thread-safe per-source mesh dispatch attribution."""

    def __init__(self):
        self._lock = threading.Lock()
        self._rows: Dict[str, _Row] = {}

    def record_dispatch(self, source: str, topology: dict,
                        data_axis, slots: int, frames: int,
                        sharded: bool) -> None:
        """Count one mesh dispatch.  ``slots`` is the physical
        micro-batch size the executable ran (bucket for a batched
        window, the batch dim for the single-frame path), ``frames``
        the real frames it carried; ``sharded=False`` means the input
        could not split over the data axis and was replicated.
        ``data_axis`` is one axis name or a tuple of them (a placement
        batch-sharding over several tiers, e.g. ``dcn.data`` x
        ``data``): the shard count is the product and the row stores
        the ``+``-joined label."""
        names = (data_axis,) if isinstance(data_axis, str) \
            else tuple(data_axis)
        axes = tuple((str(n), int(s)) for n, s in topology["axes"])
        devices = tuple(topology["devices"])
        shards = 1
        for name, size in axes:
            if name in names:
                shards *= size
        data_axis = "+".join(names)
        key = str(source)
        with self._lock:
            row = self._rows.get(key)
            if row is None or row.axes != axes or row.devices != devices:
                # topology changed (new mesh/devices): fresh attribution
                row = self._rows[key] = _Row(axes, devices,
                                             str(data_axis), shards)
            row.dispatches += 1
            row.frames += int(frames)
            row.slots += int(slots)
            if not sharded:
                row.replicated_dispatches += 1
                # every chip computes every slot: attribute the full
                # load to each shard (imbalance 0 — the waste shows in
                # replicated_dispatches, not in the split)
                for i in range(row.shards):
                    row.shard_frames[i] += int(frames)
                return
            row.pad_slots += max(int(slots) - int(frames), 0)
            for i, n in enumerate(shard_split(int(slots), int(frames),
                                              row.shards)):
                row.shard_frames[i] += n

    # -- pull side -----------------------------------------------------------

    def snapshot(self) -> List[dict]:
        """Rows for the registry's ``mesh`` table (v5), sorted by
        source."""
        out: List[dict] = []
        with self._lock:
            items = sorted(self._rows.items())
        for source, row in items:
            sf = list(row.shard_frames)
            mean = sum(sf) / len(sf) if sf else 0.0
            imbalance = (max(sf) / mean - 1.0) if mean > 0 else 0.0
            out.append({
                "source": source,
                "axes": [[n, s] for n, s in row.axes],
                "devices": list(row.devices),
                "data_axis": row.data_axis,
                "shards": row.shards,
                "dispatches": row.dispatches,
                "frames": row.frames,
                "slots": row.slots,
                "pad_slots": row.pad_slots,
                "pad_frac": row.pad_slots / row.slots
                if row.slots else 0.0,
                "replicated_dispatches": row.replicated_dispatches,
                "shard_frames": sf,
                "imbalance": imbalance,
            })
        return out

    def get(self, source: str) -> Optional[dict]:
        for row in self.snapshot():
            if row["source"] == str(source):
                return row
        return None

    def reset(self) -> None:
        """Tests/bench only: drop every row."""
        with self._lock:
            self._rows.clear()


#: the process-wide store every mesh dispatch seam feeds
MESH_STATS = MeshStats()

#: topology is invariant for a built mesh — cache it per mesh object
#: so the per-dispatch hot path stops re-stringifying every device
#: (weak keys: a dropped mesh must not be pinned by its telemetry)
_topo_cache: "weakref.WeakKeyDictionary" = None  # type: ignore[assignment]


def _topology_of(mesh) -> dict:
    global _topo_cache
    if _topo_cache is None:
        import weakref

        _topo_cache = weakref.WeakKeyDictionary()
    from ..parallel.mesh import mesh_topology

    try:
        topo = _topo_cache.get(mesh)
    except TypeError:  # unhashable/unweakrefable mesh stand-in
        return mesh_topology(mesh)
    if topo is None:
        topo = mesh_topology(mesh)
        try:
            _topo_cache[mesh] = topo
        except TypeError:
            pass
    return topo


def record_dispatch(source: str, mesh, data_axis: str, slots: int,
                    frames: int, sharded: bool) -> None:
    """Module-level shim: extract the topology and record (inert under
    the global obs kill switch; never raises into the hot path)."""
    if not ACTIVE:
        return
    try:
        MESH_STATS.record_dispatch(str(source), _topology_of(mesh),
                                   data_axis, slots, frames, sharded)
    except Exception:  # noqa: BLE001 - telemetry must not kill a dispatch
        pass

"""tensor_filter + sub-plugin tests, and the minimum end-to-end slice.

Modeled on the reference's unittest_filter_single.cc and the custom-filter
scaffold tests (/root/reference/tests/nnstreamer_example/ — passthrough/
scaler fakes exercising the full filter path, SURVEY.md §4).
"""

import os

import numpy as np
import pytest
from fractions import Fraction

import jax
import jax.numpy as jnp

from nnstreamer_tpu.core import Buffer, TensorsSpec
from nnstreamer_tpu.elements.basic import AppSink, AppSrc, TensorSink
from nnstreamer_tpu.elements.filter import FilterSingle, TensorFilter
from nnstreamer_tpu.filters import (
    register_custom_easy,
    register_model,
    unregister_model,
)
from nnstreamer_tpu.filters.jax_xla import export_model
from nnstreamer_tpu.runtime import (
    Event,
    NegotiationError,
    Pipeline,
    parse_launch,
)


@pytest.fixture(autouse=True)
def _models():
    register_model("t_add1", lambda x: x + 1.0, in_shapes=[(2, 3)])
    register_model("t_mlp", lambda p, x: jnp.dot(x, p["w"]) + p["b"],
                   params={"w": jnp.ones((4, 8)), "b": jnp.zeros((8,))},
                   in_shapes=[(1, 4)])
    yield
    unregister_model("t_add1")
    unregister_model("t_mlp")


class TestFilterSingle:
    def test_invoke_and_specs(self):
        fs = FilterSingle(framework="jax-xla", model="t_add1")
        assert fs.in_spec.dimensions_string() == "3:2"
        out = fs.invoke([jnp.zeros((2, 3), jnp.float32)])
        np.testing.assert_allclose(np.asarray(out[0]), 1.0)
        assert fs.stats.latency_us >= 0

    def test_params_model(self):
        fs = FilterSingle(framework="jax-xla", model="t_mlp")
        out = fs.invoke([jnp.ones((1, 4), jnp.float32)])
        np.testing.assert_allclose(np.asarray(out[0]), 4.0)
        assert fs.out_spec.tensors[0].shape == (1, 8)

    def test_set_input_info_recompiles(self):
        fs = FilterSingle(framework="jax-xla", model="t_add1")
        fs.set_input_info(TensorsSpec.parse("5:4", "float32"))
        out = fs.invoke([jnp.zeros((4, 5), jnp.float32)])
        assert np.asarray(out[0]).shape == (4, 5)

    def test_custom_easy(self):
        register_custom_easy(
            "scaler2x", lambda xs: [xs[0] * 2],
            TensorsSpec.parse("3:2", "float32"),
            TensorsSpec.parse("3:2", "float32"))
        fs = FilterSingle(framework="custom-easy", model="scaler2x")
        out = fs.invoke([np.full((2, 3), 3.0, np.float32)])
        np.testing.assert_allclose(out[0], 6.0)

    def test_jaxexp_file_roundtrip(self, tmp_path):
        path = str(tmp_path / "double.jaxexp")
        export_model(lambda x: x * 2.0, [jnp.zeros((2, 2), jnp.float32)], path)
        fs = FilterSingle(framework="jax-xla", model=path)
        out = fs.invoke([jnp.full((2, 2), 3.0, jnp.float32)])
        np.testing.assert_allclose(np.asarray(out[0]), 6.0)

    def test_auto_detect_from_extension(self, tmp_path):
        path = str(tmp_path / "m.jaxexp")
        export_model(lambda x: x, [jnp.zeros((1,), jnp.float32)], path)
        fs = FilterSingle(framework="auto", model=path)
        assert fs.subplugin.NAME == "jax-xla"

    def test_python3_script(self, tmp_path):
        script = tmp_path / "pyfilter.py"
        script.write_text(
            "import numpy as np\n"
            "class CustomFilter:\n"
            "    def getInputDim(self): return ('4:1', 'float32')\n"
            "    def getOutputDim(self): return ('4:1', 'float32')\n"
            "    def invoke(self, xs): return [xs[0][:, ::-1].copy()]\n")
        fs = FilterSingle(framework="python3", model=str(script))
        out = fs.invoke([np.arange(4, dtype=np.float32).reshape(1, 4)])
        np.testing.assert_array_equal(out[0].reshape(-1), [3, 2, 1, 0])


class TestFilterElement:
    def _pipe(self, **fkw):
        p = Pipeline()
        src = AppSrc(name="src",
                     spec=TensorsSpec.parse("3:2", "float32", rate=0))
        f = TensorFilter(name="f", framework="jax-xla", model="t_add1", **fkw)
        sink = AppSink(name="out")
        p.add(src, f, sink).link(src, f, sink)
        return p, src, f, sink

    def test_invoke_in_pipeline(self):
        p, src, f, sink = self._pipe()
        with p:
            src.push_buffer(Buffer.of(np.zeros((2, 3), np.float32), pts=5))
            src.end_of_stream()
            assert p.wait_eos(timeout=10)
            out = sink.pull(timeout=1)
        np.testing.assert_allclose(out[0].np(), 1.0)
        assert out.pts == 5
        assert f.latency_us >= 0

    def test_mismatched_input_reshapes_model(self):
        # jax-xla supports set_input_info → a 4:5 stream reshapes the model
        p = Pipeline()
        src = AppSrc(name="src", spec=TensorsSpec.parse("4:5", "float32"))
        f = TensorFilter(name="f", framework="jax-xla", model="t_add1")
        sink = AppSink(name="out")
        p.add(src, f, sink).link(src, f, sink)
        with p:
            src.push_buffer(Buffer.of(np.zeros((5, 4), np.float32)))
            src.end_of_stream()
            assert p.wait_eos(timeout=10)
            out = sink.pull(timeout=1)
        assert out[0].np().shape == (5, 4)

    def test_incompatible_input_fails_negotiation(self):
        register_custom_easy(
            "rigid", lambda xs: xs,
            TensorsSpec.parse("7:7", "float32"),
            TensorsSpec.parse("7:7", "float32"))
        p = Pipeline()
        src = AppSrc(name="src", spec=TensorsSpec.parse("3:2", "float32"))
        f = TensorFilter(name="f", framework="custom-easy", model="rigid")
        sink = AppSink(name="out")
        p.add(src, f, sink).link(src, f, sink)
        with pytest.raises(NegotiationError):
            p.start()
        p.stop()

    def test_output_combination(self):
        p = Pipeline()
        src = AppSrc(name="src", spec=TensorsSpec.parse("3:2", "float32"))
        f = TensorFilter(name="f", framework="jax-xla", model="t_add1",
                         output_combination="i0,o0")
        sink = AppSink(name="out")
        p.add(src, f, sink).link(src, f, sink)
        with p:
            src.push_buffer(Buffer.of(np.zeros((2, 3), np.float32)))
            src.end_of_stream()
            assert p.wait_eos(timeout=10)
            out = sink.pull(timeout=1)
        assert out.num_tensors == 2
        np.testing.assert_allclose(out[0].np(), 0.0)  # input passthrough
        np.testing.assert_allclose(out[1].np(), 1.0)  # model output

    def test_hot_reload(self):
        p, src, f, sink = self._pipe(is_updatable=True)
        register_model("t_add2", lambda x: x + 2.0, in_shapes=[(2, 3)])
        try:
            with p:
                src.push_buffer(Buffer.of(np.zeros((2, 3), np.float32)))
                a = sink.pull(timeout=10)  # frame 1 fully through the filter
                f.handle_event(f.sinkpad, Event.reload_model("t_add2"))
                src.push_buffer(Buffer.of(np.zeros((2, 3), np.float32)))
                src.end_of_stream()
                assert p.wait_eos(timeout=10)
                b = sink.pull(timeout=1)
            np.testing.assert_allclose(a[0].np(), 1.0)
            np.testing.assert_allclose(b[0].np(), 2.0)
        finally:
            unregister_model("t_add2")


class TestEndToEndSlice:
    """The SURVEY.md §7 stage-3 minimum slice: video source → converter →
    transform (normalize) → jax-xla classifier → image_labeling → sink."""

    def test_video_classification_pipeline(self, tmp_path):
        labels = tmp_path / "labels.txt"
        labels.write_text("cat\ndog\nbird\n")

        # toy "classifier": 8x8 RGB float input → 3 scores favoring channel
        # sums; deterministic so the golden label is known
        def classify(x):
            flat = x.reshape(-1, 3)
            sums = flat.sum(axis=0)
            return sums * jnp.array([1.0, 2.0, 0.5])

        register_model("toy_cls", classify, in_shapes=[(1, 8, 8, 3)])
        try:
            p = parse_launch(
                "appsrc name=src "
                "caps=video/x-raw,format=RGB,width=8,height=8,framerate=30/1 "
                "! tensor_converter ! "
                "tensor_transform mode=arithmetic "
                "option=typecast:float32,div:255.0 ! "
                "tensor_filter framework=jax-xla model=toy_cls ! "
                f"tensor_decoder mode=image_labeling option1={labels} ! "
                "tensor_sink name=out")
            out = p["out"]
            frame = np.zeros((8, 8, 3), np.uint8)
            frame[:, :, 1] = 200  # green dominant → label index 1 → dog
            with p:
                p["src"].push_buffer(Buffer.of(frame))
                p["src"].end_of_stream()
                assert p.wait_eos(timeout=10)
            assert out.buffers_rendered == 1
            assert out.last_buffer.meta["label"] == "dog"
            assert bytes(out.last_buffer[0].np().tobytes()) == b"dog"
        finally:
            unregister_model("toy_cls")

    def test_video_stride_padding_stripped(self):
        # width 3 RGB → row = 9 bytes, padded to 12: converter must strip
        p = Pipeline()
        src = AppSrc(name="src",
                     caps="video/x-raw,format=RGB,width=3,height=2,"
                          "framerate=30/1")
        from nnstreamer_tpu.runtime import make

        conv = make("tensor_converter", el_name="c")
        sink = AppSink(name="out")
        p.add(src, conv, sink).link(src, conv, sink)
        rows = []
        for r in range(2):
            rows.append(bytes(range(r * 9, r * 9 + 9)) + b"\x00\x00\x00")
        payload = b"".join(rows)
        from nnstreamer_tpu.core import Tensor, TensorSpec

        with p:
            src.push_buffer(Buffer(tensors=[Tensor(
                payload, TensorSpec.from_shape((len(payload),), np.uint8))]))
            src.end_of_stream()
            assert p.wait_eos(timeout=10)
            out = sink.pull(timeout=1)
        arr = out[0].np()
        assert arr.shape == (1, 2, 3, 3)
        assert arr.reshape(-1)[0] == 0 and arr.reshape(-1)[9] == 9

"""Golden end-to-end pipeline tests (SSAT analog, SURVEY §4 tier 2).

Each case in ``golden_cases.py`` runs a string-described pipeline
(``parse_launch``) ending in a ``filesink`` and the output bytes must
equal the committed golden file — the reference's
``gst-launch … ! filesink`` + golden comparison shape
(/root/reference/tests/nnstreamer_decoder_boundingbox/runTest.sh).
"""

import os

import numpy as np
import pytest

from golden_cases import ALL_CASES, GOLDEN_DIR, LABELS, run_case


@pytest.mark.parametrize("name", ALL_CASES)
def test_golden_pipeline(name, tmp_path):
    golden = os.path.join(GOLDEN_DIR, f"{name}.golden")
    assert os.path.isfile(golden), \
        f"missing golden file for {name}: run `python tests/golden_cases.py regen`"
    out = tmp_path / f"{name}.out"
    run_case(name, str(out))
    got = out.read_bytes()
    want = open(golden, "rb").read()
    assert got == want, (
        f"{name}: output ({len(got)}B) differs from golden ({len(want)}B)")


class TestGoldenContentSanity:
    """The goldens themselves encode the expected semantics — spot-check
    a few so a bad regen can't silently bless wrong behavior."""

    def test_image_labeling_golden_is_top1_label(self):
        data = open(os.path.join(
            GOLDEN_DIR, "decoder_image_labeling.golden"), "rb").read()
        assert data.decode().strip() == LABELS[2]  # argmax of the input

    def test_transform_arithmetic_golden_values(self):
        data = np.frombuffer(open(os.path.join(
            GOLDEN_DIR, "transform_arithmetic.golden"), "rb").read(),
            np.float32)
        want = (np.arange(16, dtype=np.float32) - 2.0) * 0.5
        np.testing.assert_allclose(data, want)

    def test_custom_scaler_golden_values(self):
        data = np.frombuffer(open(os.path.join(
            GOLDEN_DIR, "custom_easy_scaler.golden"), "rb").read(),
            np.float32)
        x = np.random.default_rng(42).standard_normal((4, 8)
                                                      ).astype(np.float32)
        np.testing.assert_allclose(data.reshape(4, 8), x * 2.0 + 1.0,
                                   rtol=1e-6)

    def test_wire_roundtrip_golden_is_original_payload(self):
        data = np.frombuffer(open(os.path.join(
            GOLDEN_DIR, "wire_roundtrip_protobuf.golden"), "rb").read(),
            np.float32)
        np.testing.assert_allclose(
            data.reshape(2, 4), np.linspace(0, 1, 8, dtype=np.float32
                                            ).reshape(2, 4))

    def test_boundingbox_golden_has_box_pixels(self):
        data = np.frombuffer(open(os.path.join(
            GOLDEN_DIR, "decoder_boundingbox_pp.golden"), "rb").read(),
            np.uint8).reshape(32, 32, 4)
        assert data.any()                      # boxes drawn
        assert (data.sum(axis=-1) == 0).any()  # transparent background left

"""Host-execution profiler: where the HOST CPU goes (``nns-prof``).

The tracer/metrics stack accounts for where a *buffer* spends time;
this module accounts for where the *host CPU* spends time — the
evidence layer for ROADMAP item 3 (the kilostream event-loop runtime):
before rewriting the thread-per-element scheduler we need to know what
the current one costs, per element, split run-vs-wait.

Three cooperating pieces:

**Thread registry + deterministic names.**  Every runtime thread is
spawned through :func:`named_thread` (or :func:`element_thread`), which
names it ``nns:<role>:<owner>`` — element loops get
``nns:<pipeline>:<element>`` — and registers the ident → (pipeline,
element, role, owner) mapping in :data:`THREADS`.  The name is the join
key: the sampling profiler, lockdep site labels and external ``py-spy``
output all attribute samples to the same strings.

**Sampling profiler** (:data:`PROFILER`).  A daemon thread walks
``sys._current_frames()`` at ``NNS_TPU_PROF=<hz>`` (default off,
strictly inert under ``NNS_TPU_OBS_DISABLE``), attributes each sampled
stack to its thread's registry entry, and aggregates collapsed stacks
into a bounded table (lowest-count eviction) plus a bounded ring of
recent samples — the ring is what a flight-recorder dump embeds
(``host_stacks``) and what the Perfetto export renders.  The sampler's
own ticks double as a GIL-pressure proxy: threads whose leaf frame is
not a known wait are *runnable*; ``runnable - 1`` of them are waiting
for the GIL (``nns_gil_waiters``).

**Exact run/wait accounting** (:data:`ACCOUNTS`).  Element loops
(``Queue._loop``, ``SourceElement._loop``) bracket the queue-pop (wait)
vs chain (run) boundary with ``time.monotonic()`` + ``time.thread_time()``
reads and feed per-element accumulators, exported as
``nns_element_cpu_seconds_total`` / ``nns_element_run_seconds_total`` /
``nns_element_wait_seconds_total`` and the snapshot-v10 ``profile``
table.  Unlike sampling this is exact: per-element cpu_seconds sum to
the process CPU delta (minus unaccounted threads) — the
``bench.py --hostprof`` attribution-exactness gate.

**Deep profiles** (:data:`DEEP`).  ``NNS_TPU_PROF_DEEP_DIR`` arms
alert-triggered capture episodes: on a watch rule's rising edge
(``obs/watch.py`` ``_act_fire``) a short-lived thread samples densely
for ``NNS_TPU_PROF_DEEP_SECONDS`` and writes a collapsed-stack file
next to the flight-recorder dump, optionally wrapping the episode in a
``jax.profiler`` device trace.  Same discipline as the flight recorder:
rising-edge only (once per alert episode), internally rate-limited,
never on the sampler thread.

See Documentation/observability.md, "Host execution profiling".
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from . import hooks as _hooks

# -- thread registry ----------------------------------------------------------


class ThreadRegistry:
    """ident → {role, owner, pipeline, element, name}: who each runtime
    thread belongs to.  Populated at thread spawn (inside the
    :func:`named_thread` wrapper, so registration and the thread's own
    lifetime coincide exactly); the profiler joins samples against it.
    Inert under ``NNS_TPU_OBS_DISABLE`` (nothing registers)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._by_ident: Dict[int, Dict[str, str]] = {}

    def register(self, role: str, owner: str, pipeline: str = "",
                 element: str = "",
                 ident: Optional[int] = None) -> None:
        if _hooks.DISABLED:
            return
        if ident is None:
            ident = threading.get_ident()
            name = threading.current_thread().name
        else:
            name = ""
        with self._lock:
            self._by_ident[ident] = {
                "role": role, "owner": owner, "pipeline": pipeline,
                "element": element, "name": name,
            }

    def unregister(self, ident: Optional[int] = None) -> None:
        ident = threading.get_ident() if ident is None else ident
        with self._lock:
            self._by_ident.pop(ident, None)

    def lookup(self, ident: int) -> Optional[Dict[str, str]]:
        with self._lock:
            info = self._by_ident.get(ident)
            return dict(info) if info is not None else None

    def snapshot(self) -> List[Dict[str, str]]:
        with self._lock:
            return [dict(v) for v in self._by_ident.values()]

    def __len__(self) -> int:
        with self._lock:
            return len(self._by_ident)

    def clear(self) -> None:
        """Tests only."""
        with self._lock:
            self._by_ident.clear()


THREADS = ThreadRegistry()


def _label(info: Optional[Dict[str, str]], fallback: str) -> str:
    """One attribution string per thread — ``pipeline:element`` for
    element loops, ``role:owner`` for infrastructure threads, the raw
    thread name for anything unregistered."""
    if info is None:
        return fallback
    if info.get("pipeline") and info.get("element"):
        return f"{info['pipeline']}:{info['element']}"
    if info.get("owner"):
        return f"{info['role']}:{info['owner']}"
    return info.get("role") or fallback


def thread_name(role: str, owner: str = "", pipeline: str = "",
                element: str = "") -> str:
    """The deterministic name scheme: ``nns:<pipeline>:<element>`` for
    element loops, ``nns:<role>:<owner>`` (owner optional) otherwise."""
    if pipeline and element:
        return f"nns:{pipeline}:{element}"
    return f"nns:{role}:{owner}" if owner else f"nns:{role}"


def named_thread(role: str, owner: str, target, *, pipeline: str = "",
                 element: str = "", daemon: bool = True,
                 args: tuple = (), kwargs: Optional[dict] = None
                 ) -> threading.Thread:
    """A ``threading.Thread`` with the deterministic ``nns:`` name AND
    registry coverage: the wrapper registers the ident on entry and
    unregisters on exit, so the registry never holds a dead thread.
    The NAME is always applied (py-spy reads it regardless of obs
    state); the REGISTRATION no-ops under ``NNS_TPU_OBS_DISABLE``."""
    name = thread_name(role, owner, pipeline, element)

    def _run(*a, **kw):
        THREADS.register(role, owner, pipeline=pipeline, element=element)
        try:
            target(*a, **kw)
        finally:
            THREADS.unregister()

    return threading.Thread(target=_run, name=name, daemon=daemon,
                            args=args, kwargs=kwargs or {})


def element_thread(element: Any, target, role: str) -> threading.Thread:
    """The element-loop spawn helper: derives the pipeline name from
    the element's back-reference (set by ``Pipeline.add``; ``-`` for a
    bare element in tests) so the thread is ``nns:<pipeline>:<element>``."""
    pipe = getattr(element, "pipeline", None)
    pname = getattr(pipe, "name", "") or "-"
    return named_thread(role, element.name, target,
                        pipeline=pname, element=element.name)


# -- exact per-element run/wait/CPU accounting --------------------------------


class ElementAccount:
    """Per-element accumulator, fed by exactly ONE loop thread (writes
    are unsynchronized by design — single writer, racy readers see an
    at-most-one-iteration-stale float)."""

    __slots__ = ("pipeline", "element", "cpu_s", "run_s", "wait_s",
                 "iters")

    def __init__(self, pipeline: str, element: str):
        self.pipeline = pipeline
        self.element = element
        self.cpu_s = 0.0
        self.run_s = 0.0
        self.wait_s = 0.0
        self.iters = 0

    def add(self, wait_s: float, run_s: float, cpu_s: float) -> None:
        if wait_s > 0:
            self.wait_s += wait_s
        if run_s > 0:
            self.run_s += run_s
        if cpu_s > 0:
            self.cpu_s += cpu_s
        self.iters += 1


_accounts_lock = threading.Lock()
ACCOUNTS: Dict[Tuple[str, str], ElementAccount] = {}


def element_account(pipeline: str, element: str
                    ) -> Optional[ElementAccount]:
    """The element loop's handle, fetched once at loop start.  Returns
    None under ``NNS_TPU_OBS_DISABLE`` — the loop then skips its clock
    reads entirely (the whole accounting path costs nothing)."""
    if _hooks.DISABLED:
        return None
    key = (pipeline, element)
    with _accounts_lock:
        acct = ACCOUNTS.get(key)
        if acct is None:
            acct = ACCOUNTS[key] = ElementAccount(pipeline, element)
        return acct


def account_rows() -> List[dict]:
    """The accounting table as export rows (registry ``profile`` table
    + ``nns_element_*_seconds_total`` families)."""
    with _accounts_lock:
        accts = list(ACCOUNTS.values())
    return [{
        "pipeline": a.pipeline, "element": a.element,
        "cpu_s": round(a.cpu_s, 6), "run_s": round(a.run_s, 6),
        "wait_s": round(a.wait_s, 6), "iters": a.iters,
    } for a in sorted(accts, key=lambda a: (a.pipeline, a.element))]


def _reset_accounts() -> None:
    """Tests only."""
    with _accounts_lock:
        ACCOUNTS.clear()


# -- stack collapse + wait classification -------------------------------------

#: leaf co_names that mean "this thread is blocked, not contending for
#: the GIL" — the sampler's runnable/waiting split (the GIL proxy) and
#: nothing else; attribution does not depend on this list being complete
_WAIT_LEAVES = frozenset({
    "wait", "sleep", "select", "poll", "epoll", "kqueue", "accept",
    "recv", "recvfrom", "recv_into", "read", "readinto", "readline",
    "acquire", "get", "join", "pull", "park", "_wait_for_tstate_lock",
    "wait_for", "settle",
})

#: leaf files that mean the same (stdlib blocking primitives)
_WAIT_FILES = frozenset({
    "threading.py", "selectors.py", "socket.py", "queue.py", "ssl.py",
    "connection.py", "subprocess.py",
})


#: per-code-object frame-string memo: code objects are module-level
#: and long-lived, so the basename split + format runs once per code
#: object instead of once per frame per tick — the difference between
#: a ~250us and a ~100us sampling pass.  Bounded by a dump-and-restart
#: (id() reuse after a code object dies can mislabel one line of one
#: sample; a profiler tolerates that, a leak it would not)
_CODE_STRS: Dict[int, str] = {}


def _frame_str(code) -> str:
    s = _CODE_STRS.get(id(code))
    if s is None:
        if len(_CODE_STRS) > 8192:
            _CODE_STRS.clear()
        s = f"{os.path.basename(code.co_filename)}:{code.co_name}"
        _CODE_STRS[id(code)] = s
    return s


def _collapse(frame, limit: int = 48) -> str:
    """One sampled stack as collapsed text, root first, leaf last:
    ``file.py:func;file.py:func;...`` — the flamegraph.pl input format
    (prefixed with the thread label by the exporters)."""
    parts: List[str] = []
    f = frame
    while f is not None and len(parts) < limit:
        parts.append(_frame_str(f.f_code))
        f = f.f_back
    parts.reverse()
    return ";".join(parts)


def _is_waiting(frame) -> bool:
    code = frame.f_code
    return (code.co_name in _WAIT_LEAVES
            or os.path.basename(code.co_filename) in _WAIT_FILES)


# -- the sampling profiler ----------------------------------------------------


class SamplingProfiler:
    """Continuous low-overhead wall-clock sampler over
    ``sys._current_frames()``.

    One daemon thread (``nns:prof:sampler``), one bounded collapsed-
    stack table (lowest-count eviction when full — heavy stacks are by
    construction the high-count ones, so eviction loses tail noise),
    one bounded ring of recent samples for the flight-recorder embed
    and the Perfetto export.  Everything here tolerates being read
    while ticking; exports copy under the lock and render outside it."""

    def __init__(self, hz: float = 0.0, max_stacks: int = 512,
                 ring_len: int = 4096, ring_s: float = 30.0):
        self.hz = float(hz)
        self.max_stacks = int(max_stacks)
        self.ring_s = float(ring_s)
        self._lock = threading.Lock()
        self._table: Dict[Tuple[str, str], int] = {}
        self._ring: deque = deque(maxlen=int(ring_len))
        self._element_samples: Dict[Tuple[str, str], int] = {}
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self.ticks_total = 0
        self.samples_total = 0
        self.evicted_total = 0
        self.errors_total = 0
        self.runnable_last = 0
        self.gil_waiters = 0
        #: the sampler's OWN cpu time — the deterministic overhead
        #: bound bench.py --hostprof reports next to the A/B figure
        self.self_cpu_s = 0.0

    # -- lifecycle -----------------------------------------------------------

    def configure(self, hz: float) -> "SamplingProfiler":
        self.hz = float(hz)
        return self

    @property
    def running(self) -> bool:
        return self._running

    def start(self) -> bool:
        """Start the sampler thread.  Refuses (returns False) when
        already running, unconfigured (hz <= 0), or the obs kill
        switch is set — under ``NNS_TPU_OBS_DISABLE`` the profiler is
        fully inert: no thread, no registry, no export."""
        if self._running or self.hz <= 0 or _hooks.obs_disabled():
            return False
        self._running = True
        self._thread = named_thread("prof", "sampler", self._run)
        self._thread.start()
        return True

    def stop(self) -> None:
        self._running = False
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        interval = 1.0 / self.hz
        while self._running:
            c0 = time.thread_time()
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - a sampler hiccup must
                # never take the process down; it is counted instead
                self.errors_total += 1
            self.self_cpu_s += time.thread_time() - c0
            time.sleep(interval)

    # -- sampling ------------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> int:
        """One sampling pass over every live thread (public so tests —
        and the deep profiler — can drive it without the thread).
        Returns the number of threads sampled."""
        now = time.monotonic() if now is None else now
        me = threading.get_ident()
        names = {t.ident: t.name for t in threading.enumerate()}
        frames = sys._current_frames()
        runnable = 0
        sampled = 0
        for ident, frame in frames.items():
            if ident == me:
                continue
            info = THREADS.lookup(ident)
            label = _label(info, names.get(ident, f"tid-{ident}"))
            ekey = None
            if info and info.get("pipeline") and info.get("element"):
                ekey = (info["pipeline"], info["element"])
            self._record(label, _collapse(frame), now, ekey)
            if not _is_waiting(frame):
                runnable += 1
            sampled += 1
        self.runnable_last = runnable
        # of the threads that could run, at most one holds the GIL;
        # the rest are (to first order) waiting for it
        self.gil_waiters = max(0, runnable - 1)
        self.ticks_total += 1
        return sampled

    def _record(self, label: str, stack: str, ts: float = 0.0,
                ekey: Optional[Tuple[str, str]] = None) -> None:
        with self._lock:
            key = (label, stack)
            self._table[key] = self._table.get(key, 0) + 1
            if len(self._table) > self.max_stacks:
                victim = min(self._table, key=self._table.get)
                del self._table[victim]
                self.evicted_total += 1
            self._ring.append((ts, label, stack))
            if ekey is not None:
                self._element_samples[ekey] = \
                    self._element_samples.get(ekey, 0) + 1
            self.samples_total += 1

    # -- exports -------------------------------------------------------------

    def collapsed(self) -> str:
        """The whole aggregate table as flamegraph-ready collapsed
        text: one ``label;frame;frame count`` line per distinct stack."""
        with self._lock:
            items = sorted(self._table.items())
        return "\n".join(f"{label};{stack} {n}"
                         for (label, stack), n in items)

    def ring_collapsed(self, last_s: Optional[float] = None,
                       now: Optional[float] = None) -> str:
        """Collapsed text of the last ``last_s`` (default ring_s)
        seconds only — what a flight-recorder dump embeds."""
        now = time.monotonic() if now is None else now
        cutoff = now - (self.ring_s if last_s is None else last_s)
        agg: Dict[Tuple[str, str], int] = {}
        with self._lock:
            for ts, label, stack in self._ring:
                if ts >= cutoff:
                    key = (label, stack)
                    agg[key] = agg.get(key, 0) + 1
        return "\n".join(f"{label};{stack} {n}"
                         for (label, stack), n in sorted(agg.items()))

    def chrome_trace(self) -> dict:
        """The ring as Perfetto/Chrome trace events: one lane per
        thread label (metadata-named), consecutive identical samples
        merged into one ``X`` slice of ``n / hz`` duration."""
        with self._lock:
            samples = list(self._ring)
        interval = 1.0 / self.hz if self.hz > 0 else 0.01
        by_label: Dict[str, List[Tuple[float, str]]] = {}
        for ts, label, stack in samples:
            by_label.setdefault(label, []).append((ts, stack))
        events: List[dict] = []
        for tid, label in enumerate(sorted(by_label), start=1):
            events.append({"ph": "M", "name": "thread_name", "pid": 1,
                           "tid": tid, "args": {"name": label}})
            run_start, run_stack, run_n = None, None, 0
            for ts, stack in sorted(by_label[label]):
                if stack == run_stack:
                    run_n += 1
                    continue
                if run_stack is not None:
                    events.append(self._slice(tid, run_start, run_n,
                                              run_stack, interval))
                run_start, run_stack, run_n = ts, stack, 1
            if run_stack is not None:
                events.append(self._slice(tid, run_start, run_n,
                                          run_stack, interval))
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    @staticmethod
    def _slice(tid: int, ts: float, n: int, stack: str,
               interval: float) -> dict:
        leaf = stack.rsplit(";", 1)[-1]
        return {"name": leaf, "cat": "hostprof", "ph": "X", "pid": 1,
                "tid": tid, "ts": round(ts * 1e6, 1),
                "dur": round(n * interval * 1e6, 1),
                "args": {"stack": stack, "samples": n}}

    def element_samples(self) -> Dict[Tuple[str, str], int]:
        with self._lock:
            return dict(self._element_samples)

    def top_stacks(self, n: int = 20) -> List[dict]:
        with self._lock:
            items = sorted(self._table.items(),
                           key=lambda kv: (-kv[1], kv[0]))[:n]
        return [{"label": label, "stack": stack, "count": cnt}
                for (label, stack), cnt in items]

    def summary(self) -> dict:
        """Cheap (no table walk) — the ``/healthz`` ``prof`` block."""
        with self._lock:
            stacks = len(self._table)
        return {
            "running": self._running, "hz": self.hz,
            "ticks": self.ticks_total, "samples": self.samples_total,
            "stacks": stacks, "evicted": self.evicted_total,
            "errors": self.errors_total,
            "gil_waiters": self.gil_waiters,
            "runnable": self.runnable_last,
            "self_cpu_s": round(self.self_cpu_s, 4),
        }

    def clear(self) -> None:
        """Tests only."""
        with self._lock:
            self._table.clear()
            self._ring.clear()
            self._element_samples.clear()
            self.ticks_total = self.samples_total = 0
            self.evicted_total = self.errors_total = 0
            self.gil_waiters = self.runnable_last = 0
            self.self_cpu_s = 0.0


PROFILER = SamplingProfiler()


# -- alert-triggered deep profiles --------------------------------------------


class DeepProfiler:
    """Bounded dense-capture episodes, triggered from watch-rule rising
    edges (``obs/watch.py`` ``_act_fire``) — the flight recorder's
    once-per-episode + rate-limit discipline, applied to profiling:
    the rising edge gives once-per-alert-episode for free, the internal
    ``min_interval_s`` bounds an alert storm, and the capture runs on
    its own short-lived thread, never the watch sampler's."""

    def __init__(self):
        self._dir: Optional[str] = None
        self.seconds = 2.0
        self.hz = 200.0
        self.min_interval_s = 30.0
        #: wrap the host episode in a ``jax.profiler`` device trace —
        #: OPT-IN (``NNS_TPU_PROF_DEEP_DEVICE=1``): on some builds
        #: ``start_trace`` drags in tensorflow (a multi-second import
        #: on the capture thread) and an in-flight trace at interpreter
        #: exit can crash the process, so an alert-triggered background
        #: capture must not pay that by default
        self.device = False
        self._lock = threading.Lock()
        self._last_ts = 0.0
        self._seq = 0
        self.episodes = 0
        self.skipped = 0
        #: paths of written collapsed-stack files (tests / tooling)
        self.captures: List[str] = []

    def arm(self, directory: str, seconds: Optional[float] = None,
            hz: Optional[float] = None,
            min_interval_s: Optional[float] = None,
            device: Optional[bool] = None) -> None:
        os.makedirs(directory, exist_ok=True)
        self._dir = directory
        if seconds is not None:
            self.seconds = float(seconds)
        if hz is not None:
            self.hz = float(hz)
        if min_interval_s is not None:
            self.min_interval_s = float(min_interval_s)
        if device is not None:
            self.device = bool(device)

    def disarm(self) -> None:
        self._dir = None

    @property
    def armed(self) -> bool:
        return self._dir is not None

    def trigger(self, reason: str) -> bool:
        """Rate-limited episode start.  Returns True when a capture
        thread was launched."""
        if self._dir is None or _hooks.obs_disabled():
            return False
        with self._lock:
            now = time.monotonic()
            if now - self._last_ts < self.min_interval_s:
                self.skipped += 1
                return False
            self._last_ts = now
            self._seq += 1
            seq = self._seq
        self.episodes += 1
        named_thread("prof", "deep", self._capture,
                     args=(reason, seq)).start()
        return True

    def _capture(self, reason: str, seq: int) -> None:
        directory = self._dir
        if directory is None:
            return
        interval = 1.0 / max(self.hz, 1.0)
        me = threading.get_ident()
        agg: Dict[Tuple[str, str], int] = {}
        ticks = 0
        device = self.device and self._start_device_trace(directory, seq)
        t0 = time.monotonic()
        deadline = t0 + self.seconds
        while time.monotonic() < deadline:
            for ident, frame in sys._current_frames().items():
                if ident == me:
                    continue
                info = THREADS.lookup(ident)
                key = (_label(info, f"tid-{ident}"), _collapse(frame))
                agg[key] = agg.get(key, 0) + 1
            ticks += 1
            time.sleep(interval)
        if device:
            self._stop_device_trace()
        path = os.path.join(directory,
                            f"deepprof-{seq:03d}-{reason}.txt")
        lines = [f"# nns-prof deep capture: reason={reason} "
                 f"seconds={self.seconds:g} hz={self.hz:g} "
                 f"ticks={ticks} device_trace={int(device)}"]
        lines += [f"{label};{stack} {n}"
                  for (label, stack), n in sorted(agg.items())]
        try:
            with open(path, "w") as f:
                f.write("\n".join(lines) + "\n")
        except OSError:
            return
        with self._lock:
            self.captures.append(path)

    def _start_device_trace(self, directory: str, seq: int) -> bool:
        """Best-effort ``jax.profiler`` device capture around the host
        episode — entirely optional (import- and runtime-guarded: a
        backend without profiler support must not kill the capture)."""
        try:
            import jax.profiler  # noqa: PLC0415

            jax.profiler.start_trace(
                os.path.join(directory, f"device-{seq:03d}"))
            return True
        except Exception:  # noqa: BLE001
            return False

    def _stop_device_trace(self) -> None:
        try:
            import jax.profiler  # noqa: PLC0415

            jax.profiler.stop_trace()
        except Exception:  # noqa: BLE001
            pass

    def clear(self) -> None:
        """Tests only."""
        with self._lock:
            self._last_ts = 0.0
            self._seq = 0
            self.captures.clear()
        self.episodes = self.skipped = 0


DEEP = DeepProfiler()


def deep_trigger(reason: str) -> bool:
    """The watch-action entry point: no-op unless armed."""
    return DEEP.trigger(reason)


# -- registry / health export -------------------------------------------------


def profile_table() -> dict:
    """The snapshot-v10 ``profile`` table: exact per-element accounting
    rows (cpu/run/wait seconds + sample shares joined from the
    profiler), the top sampled stacks, and the profiler's own state."""
    rows = account_rows()
    samples = PROFILER.element_samples()
    total_samples = sum(samples.values())
    for row in rows:
        n = samples.get((row["pipeline"], row["element"]), 0)
        row["samples"] = n
        row["sample_share"] = round(n / total_samples, 4) \
            if total_samples else 0.0
        busy = row["run_s"] + row["wait_s"]
        row["wait_share"] = round(row["wait_s"] / busy, 4) if busy \
            else 0.0
    return {
        "elements": rows,
        "stacks": PROFILER.top_stacks(),
        "gil_waiters": PROFILER.gil_waiters,
        "profiler": PROFILER.summary(),
    }


def prof_health() -> dict:
    """The ``/healthz`` summary: cheap profiler + deep-capture state."""
    s = PROFILER.summary()
    s["deep_armed"] = DEEP.armed
    s["deep_episodes"] = DEEP.episodes
    return s


# -- env activation -----------------------------------------------------------

_env_checked = False


def maybe_start_from_env() -> None:
    """``NNS_TPU_PROF=<hz>`` starts the sampler on first pipeline start
    (same activation hook as the flight recorder / watchdog);
    ``NNS_TPU_PROF_DEEP_DIR`` arms alert-triggered deep captures
    (``NNS_TPU_PROF_DEEP_SECONDS`` / ``_HZ`` / ``_INTERVAL`` tune the
    episode; ``NNS_TPU_PROF_DEEP_DEVICE=1`` opts into the
    ``jax.profiler`` device trace around it).  Both strictly inert under ``NNS_TPU_OBS_DISABLE``
    (nns-lint NNS518 warns about that combination)."""
    global _env_checked
    if _env_checked:
        return
    _env_checked = True
    if _hooks.obs_disabled():
        return
    from ..utils.log import logw

    hz_raw = os.environ.get("NNS_TPU_PROF", "").strip()
    if hz_raw:
        try:
            hz = float(hz_raw)
        except ValueError:
            logw("NNS_TPU_PROF=%r is not a sample rate (hz); profiler "
                 "not started", hz_raw)
            hz = 0.0
        if hz > 0:
            PROFILER.configure(hz).start()
    directory = os.environ.get("NNS_TPU_PROF_DEEP_DIR", "").strip()
    if directory:
        try:
            DEEP.arm(
                directory,
                seconds=_env_float("NNS_TPU_PROF_DEEP_SECONDS"),
                hz=_env_float("NNS_TPU_PROF_DEEP_HZ"),
                min_interval_s=_env_float("NNS_TPU_PROF_DEEP_INTERVAL"),
                device=os.environ.get(
                    "NNS_TPU_PROF_DEEP_DEVICE", "").strip() == "1")
        except OSError as e:
            logw("cannot arm deep profiler on NNS_TPU_PROF_DEEP_DIR=%s:"
                 " %s", directory, e)


def _env_float(var: str) -> Optional[float]:
    raw = os.environ.get(var, "").strip()
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        return None


# -- the nns-prof CLI ---------------------------------------------------------


def build_parser():
    import argparse

    p = argparse.ArgumentParser(
        prog="nns-prof",
        description="Fetch host-execution profiles from a running "
                    "nnstreamer-tpu process (the metrics server's "
                    "/prof endpoint) as flamegraph-ready collapsed "
                    "stacks or a Perfetto-loadable trace.")
    p.add_argument("--connect", metavar="HOST:PORT", default=None,
                   help="metrics endpoint to scrape; defaults to "
                        "127.0.0.1:$NNS_TPU_METRICS_PORT, else the "
                        "in-process profiler")
    p.add_argument("--format", choices=("collapsed", "trace"),
                   default="collapsed",
                   help="collapsed-stack text (flamegraph.pl) or "
                        "Chrome/Perfetto trace JSON")
    p.add_argument("--last", type=float, default=None, metavar="S",
                   help="only the last S seconds (the profiler ring) "
                        "instead of the whole aggregate table")
    p.add_argument("--out", default=None,
                   help="write to this file instead of stdout")
    return p


def fetch_prof(connect: str, fmt: str = "collapsed",
               last_s: Optional[float] = None) -> str:
    import urllib.request

    qs = []
    if fmt == "trace":
        qs.append("format=trace")
    if last_s is not None:
        qs.append(f"last={last_s:g}")
    url = f"http://{connect}/prof" + ("?" + "&".join(qs) if qs else "")
    with urllib.request.urlopen(url, timeout=5.0) as resp:
        return resp.read().decode()


def main(argv=None, out=None) -> int:
    import json as _json

    args = build_parser().parse_args(argv)
    out = out or sys.stdout
    connect = args.connect
    if connect is None:
        port = os.environ.get("NNS_TPU_METRICS_PORT", "").strip()
        if port:
            connect = f"127.0.0.1:{port}"
    if connect:
        try:
            text = fetch_prof(connect, args.format, args.last)
        except OSError as e:
            print(f"nns-prof: cannot scrape {connect}: {e}",
                  file=sys.stderr)
            return 1
    elif args.format == "trace":
        text = _json.dumps(PROFILER.chrome_trace(), indent=1)
    elif args.last is not None:
        text = PROFILER.ring_collapsed(args.last)
    else:
        text = PROFILER.collapsed()
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + ("\n" if text and not text.endswith("\n")
                            else ""))
    else:
        print(text, file=out)
    return 0


if __name__ == "__main__":
    sys.exit(main())

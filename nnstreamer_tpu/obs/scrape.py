"""Shared fleet scrape client — ONE snapshot-over-HTTP implementation.

``nns-top --connect`` and the ``obs/watch.py`` watchdog's fleet mode
observe the same endpoints (``serve_metrics`` / ``NNS_TPU_METRICS_PORT``
``/json``); this module holds the one fetch/parse implementation both
share, including the failure-tolerance contract that used to live
inline in ``top.py``: a process dying MID-response surfaces as
``http.client`` errors or a truncated-JSON ``ValueError`` rather than
an ``OSError`` — every one of those is captured per endpoint, never
raised, so one flapping endpoint cannot kill a dashboard or a watchdog
sampler.
"""

from __future__ import annotations

import json
from typing import Callable, List, Optional


def fetch_snapshot(connect: Optional[str] = None) -> dict:
    """One registry snapshot: scraped over HTTP when ``connect``
    (``host:port``) is given, read from the in-process global registry
    otherwise."""
    if connect:
        import urllib.request

        url = f"http://{connect}/json"
        with urllib.request.urlopen(url, timeout=5.0) as resp:
            return json.loads(resp.read().decode())
    from .metrics import REGISTRY

    return REGISTRY.snapshot()


def fetch_fleet(endpoints: List[Optional[str]],
                fetch: Optional[Callable[[Optional[str]], dict]] = None
                ) -> List[dict]:
    """One sample per endpoint: ``{"endpoint", "snap"|None, "error"}``.
    Scrape failures are captured, not raised — the caller decides
    whether a dead endpoint is fatal (``nns-top --once``), transient
    (live top), or an alertable condition (``nns-watch``
    ``endpoint-down``).  ``fetch`` overrides the per-endpoint fetch
    function (tests, and ``top.py``'s monkeypatchable re-export)."""
    from http.client import HTTPException

    fetch = fetch or fetch_snapshot
    out = []
    for ep in endpoints:
        entry = {"endpoint": ep or "local", "snap": None, "error": None}
        try:
            entry["snap"] = fetch(ep)
        except (OSError, HTTPException, ValueError) as e:
            entry["error"] = str(e) or type(e).__name__
        out.append(entry)
    return out

"""Dynamic micro-batching (`runtime/batching.py` + `tensor_filter batch=`).

Covers the ISSUE-2 acceptance surface: order/pts preservation (incl.
concurrent producers), partial-batch flush on EOS with no frame loss,
bucket-executable cache hit/miss accounting, batch-occupancy stats, the
batch=1 default staying on the single-buffer path, and the satellite
fixes that ride along (StreamError before QoS throttle, event-driven
wait_eos, locked flow counters).
"""

import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.core import Buffer, TensorsSpec
from nnstreamer_tpu.elements.basic import AppSink, AppSrc, Queue
from nnstreamer_tpu.elements.filter import TensorFilter
from nnstreamer_tpu.filters.jax_xla import register_model, unregister_model
from nnstreamer_tpu.runtime import Pipeline, StreamError
from nnstreamer_tpu.runtime.batching import (
    MicroBatcher,
    parse_buckets,
    pick_bucket,
)

SHAPE = (4,)


@pytest.fixture(scope="module", autouse=True)
def _model():
    register_model("_t_batching", lambda x: x * 2.0 + 1.0,
                   in_shapes=[SHAPE], in_dtypes=np.float32)
    yield
    unregister_model("_t_batching")


def _frame(i: int) -> Buffer:
    return Buffer.of(np.full(SHAPE, float(i), np.float32), pts=i)


def _pipeline(batch, timeout_ms=1000.0, buckets="", with_queue=True,
              n_bufs=64):
    spec = TensorsSpec.from_shapes([SHAPE], np.float32)
    p = Pipeline()
    src = AppSrc(name="src", spec=spec, max_buffers=n_bufs + 4)
    flt = TensorFilter(name="net", framework="jax-xla", model="_t_batching",
                       batch=batch, batch_timeout_ms=timeout_ms,
                       batch_buckets=buckets)
    sink = AppSink(name="out", max_buffers=n_bufs + 4)
    if with_queue:
        q = Queue(name="q", max_size_buffers=n_bufs + 4)
        p.add(src, q, flt, sink).link(src, q, flt, sink)
    else:
        p.add(src, flt, sink).link(src, flt, sink)
    return p, src, flt, sink


def _pull_all(sink, n, timeout=10.0):
    out = []
    for _ in range(n):
        b = sink.pull(timeout=timeout)
        assert b is not None, f"stream stalled after {len(out)}/{n} buffers"
        out.append(b)
    return out


# -- bucket helpers ----------------------------------------------------------


def test_parse_buckets_default_powers_of_two():
    assert parse_buckets("", 8) == (1, 2, 4, 8)
    assert parse_buckets("", 6) == (1, 2, 4, 6)
    assert parse_buckets("", 1) == (1,)


def test_parse_buckets_explicit():
    assert parse_buckets("2, 5", 8) == (2, 5, 8)  # max always a bucket
    with pytest.raises(ValueError):
        parse_buckets("16", 8)  # a bucket beyond batch can never fill
    with pytest.raises(ValueError):
        parse_buckets("0", 8)


def test_pick_bucket():
    buckets = (1, 2, 4, 8)
    assert pick_bucket(1, buckets) == 1
    assert pick_bucket(3, buckets) == 4
    assert pick_bucket(8, buckets) == 8
    with pytest.raises(ValueError):
        pick_bucket(9, buckets)


# -- MicroBatcher unit: ordering under concurrent producers ------------------


def test_microbatcher_concurrent_producers_preserve_order():
    """Items from racing producers are flushed exactly once, in arrival
    order — per-producer FIFO holds across window boundaries."""
    flushed = []
    mb = MicroBatcher(max_batch=4, timeout_s=0.005,
                      flush_fn=flushed.extend)
    mb.start()
    n_producers, per = 4, 50

    def produce(pid):
        for i in range(per):
            mb.submit((pid, i))

    threads = [threading.Thread(target=produce, args=(pid,))
               for pid in range(n_producers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    mb.flush()
    mb.stop()
    assert len(flushed) == n_producers * per
    assert len(set(flushed)) == n_producers * per  # no dup, no loss
    for pid in range(n_producers):
        seq = [i for q, i in flushed if q == pid]
        assert seq == sorted(seq), f"producer {pid} reordered"


def test_microbatcher_deadline_flush():
    flushed = []
    mb = MicroBatcher(max_batch=16, timeout_s=0.02,
                      flush_fn=flushed.extend)
    mb.start()
    mb.submit("a")
    mb.submit("b")
    deadline = time.monotonic() + 5.0
    while len(flushed) < 2 and time.monotonic() < deadline:
        time.sleep(0.005)
    mb.stop()
    assert flushed == ["a", "b"]
    assert mb.flushes_deadline >= 1
    assert mb.flushes_full == 0


def test_microbatcher_timer_error_routed():
    errors = []

    def boom(items):
        raise RuntimeError("flush failed")

    mb = MicroBatcher(max_batch=16, timeout_s=0.01, flush_fn=boom,
                      error_fn=errors.append)
    mb.start()
    mb.submit("x")
    deadline = time.monotonic() + 5.0
    while not errors and time.monotonic() < deadline:
        time.sleep(0.005)
    mb.stop()
    assert errors and "flush failed" in str(errors[0])


# -- pipeline integration ----------------------------------------------------


def test_batched_pipeline_order_pts_and_values():
    n = 25
    p, src, flt, sink = _pipeline(batch=4, n_bufs=n)
    with p:
        for i in range(n):
            src.push_buffer(_frame(i))
        src.end_of_stream()
        assert p.wait_eos(timeout=30)
        outs = _pull_all(sink, n)
    for i, b in enumerate(outs):
        assert b.pts == i
        np.testing.assert_allclose(b.tensors[0].np(),
                                   np.full(SHAPE, i * 2.0 + 1.0))
    # real coalescing: strictly fewer dispatches than frames
    st = flt.invoke_stats
    assert st.total_frame_num == n
    assert st.total_invoke_num < n


def test_partial_batch_flushes_on_eos_no_frame_loss():
    # 10 frames, batch 4, long deadline: windows close full-full-EOS —
    # the 2-frame tail must drain BEFORE the sink sees EOS
    n = 10
    p, src, flt, sink = _pipeline(batch=4, timeout_ms=60_000.0, n_bufs=n)
    with p:
        for i in range(n):
            src.push_buffer(_frame(i))
        src.end_of_stream()
        assert p.wait_eos(timeout=30)
        st = flt.invoke_stats
        assert st.total_frame_num == n
        assert st.total_invoke_num == 3  # 4 + 4 + 2(EOS partial)
        outs = _pull_all(sink, n, timeout=1.0)
    assert [b.pts for b in outs] == list(range(n))


def test_bucket_cache_hits_and_misses():
    n = 10  # windows 4, 4, 2 -> buckets {4, 2}: 2 misses, 1 hit
    p, src, flt, sink = _pipeline(batch=4, timeout_ms=60_000.0, n_bufs=n)
    with p:
        for i in range(n):
            src.push_buffer(_frame(i))
        src.end_of_stream()
        assert p.wait_eos(timeout=30)
        sp = flt.subplugin
        assert sp.batch_cache_misses == 2
        assert sp.batch_cache_hits == 1
        _pull_all(sink, n, timeout=1.0)


def test_batch_occupancy_stats():
    n = 10
    p, src, flt, sink = _pipeline(batch=4, timeout_ms=60_000.0, n_bufs=n)
    with p:
        for i in range(n):
            src.push_buffer(_frame(i))
        src.end_of_stream()
        assert p.wait_eos(timeout=30)
        st = flt.invoke_stats
        assert st.avg_batch_occupancy == pytest.approx(n / 3)
        # frames/s >= dispatches/s, both derived from the same window
        if st.throughput_milli_fps > 0:
            assert st.throughput_milli_fps >= st.dispatch_milli_fps
        _pull_all(sink, n, timeout=1.0)


def test_deadline_flush_in_pipeline():
    """Frames below the window size still come out: the deadline closes
    the window without EOS."""
    p, src, flt, sink = _pipeline(batch=8, timeout_ms=30.0, n_bufs=8)
    with p:
        for i in range(3):
            src.push_buffer(_frame(i))
        outs = _pull_all(sink, 3, timeout=10.0)
        assert [b.pts for b in outs] == [0, 1, 2]
        src.end_of_stream()
        assert p.wait_eos(timeout=30)


def test_explicit_buckets_respected():
    n = 5  # windows 4 + 1(EOS); buckets "4" -> pad the tail up to 4
    p, src, flt, sink = _pipeline(batch=4, timeout_ms=60_000.0,
                                  buckets="4", n_bufs=n)
    with p:
        for i in range(n):
            src.push_buffer(_frame(i))
        src.end_of_stream()
        assert p.wait_eos(timeout=30)
        sp = flt.subplugin
        assert flt._buckets == (4,)
        assert sp.batch_cache_misses == 1  # one executable total
        assert sp.batch_cache_hits == 1
        outs = _pull_all(sink, n, timeout=1.0)
    assert [b.pts for b in outs] == list(range(n))


def test_batch1_default_stays_single_buffer_path():
    n = 6
    p, src, flt, sink = _pipeline(batch=1, n_bufs=n)
    with p:
        assert flt._batcher is None  # no coalescer, no timer thread
        for i in range(n):
            src.push_buffer(_frame(i))
        src.end_of_stream()
        assert p.wait_eos(timeout=30)
        st = flt.invoke_stats
        assert st.total_invoke_num == n  # one dispatch per frame
        assert st.total_frame_num == n
        assert st.avg_batch_occupancy == 1.0
        sp = flt.subplugin
        assert sp.batch_cache_misses == 0  # batched compile never ran
        outs = _pull_all(sink, n, timeout=1.0)
    assert [b.pts for b in outs] == list(range(n))


def test_batch_with_invoke_dynamic_rejected():
    p, src, flt, sink = _pipeline(batch=4)
    flt.invoke_dynamic = True
    with pytest.raises(ValueError, match="invoke-dynamic"):
        p.start()
    p.stop()


def test_batch_restart_recreates_batcher():
    p, src, flt, sink = _pipeline(batch=4, n_bufs=8)
    with p:
        assert flt._batcher is not None
        src.push_buffer(_frame(0))
        src.end_of_stream()
        assert p.wait_eos(timeout=30)
    assert flt._batcher is None  # stop() tears the coalescer down
    with p:
        assert flt._batcher is not None
        src.push_buffer(_frame(1))
        src.end_of_stream()
        assert p.wait_eos(timeout=30)


def test_batched_over_mesh_data_axis():
    """batch>1 + mesh: the micro-batch axis shards over the data axis
    (one SPMD dispatch per window) and per-frame outputs come back
    intact."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device backend (conftest forces 8)")
    n = 16
    spec = TensorsSpec.from_shapes([SHAPE], np.float32)
    p = Pipeline()
    src = AppSrc(name="src", spec=spec, max_buffers=n + 4)
    q = Queue(name="q", max_size_buffers=n + 4)
    flt = TensorFilter(name="net", framework="jax-xla",
                       model="_t_batching", batch=8,
                       batch_timeout_ms=60_000.0, mesh="data:-1")
    sink = AppSink(name="out", max_buffers=n + 4)
    p.add(src, q, flt, sink).link(src, q, flt, sink)
    with p:
        for i in range(n):
            src.push_buffer(_frame(i))
        src.end_of_stream()
        assert p.wait_eos(timeout=30)
        st = flt.invoke_stats
        assert st.total_frame_num == n
        assert st.total_invoke_num == 2
        outs = _pull_all(sink, n, timeout=1.0)
    for i, b in enumerate(outs):
        assert b.pts == i
        np.testing.assert_allclose(b.tensors[0].np(),
                                   np.full(SHAPE, i * 2.0 + 1.0))


# -- satellite fixes ---------------------------------------------------------


def test_no_subplugin_reports_before_throttle():
    """A misconfigured filter raises StreamError even while a QoS
    throttle is active (the old order silently dropped every buffer)."""
    flt = TensorFilter(name="net", framework="jax-xla",
                       model="_t_batching")
    flt._throttle_interval = 10.0
    flt._last_invoke_ts = time.monotonic()
    with pytest.raises(StreamError, match="no sub-plugin"):
        flt.chain(flt.sinkpad, _frame(0))


def test_wait_eos_is_event_driven():
    """wait_eos with no timeout returns promptly once sinks see EOS (one
    combined event, no poll loop)."""
    n = 3
    p, src, flt, sink = _pipeline(batch=1, n_bufs=n)
    got = []

    def waiter():
        got.append(p.wait_eos())

    with p:
        t = threading.Thread(target=waiter, daemon=True)
        t.start()
        for i in range(n):
            src.push_buffer(_frame(i))
        src.end_of_stream()
        t.join(timeout=30)
        assert not t.is_alive() and got == [True]


def test_wait_eos_state_resets_on_restart():
    """A restarted pipeline must not report the previous run's EOS."""
    p, src, flt, sink = _pipeline(batch=1, n_bufs=4)
    with p:
        src.push_buffer(_frame(0))
        src.end_of_stream()
        assert p.wait_eos(timeout=30)
    with p:
        assert p.wait_eos(timeout=0.3) is False  # stale EOS cleared
        src.push_buffer(_frame(1))
        src.end_of_stream()
        assert p.wait_eos(timeout=30)


def test_concurrent_chain_counters_are_exact():
    """buffers_in under racing upstream threads (the fan-in case the
    unlocked += lost increments on)."""
    from nnstreamer_tpu.runtime.element import Element

    class _Null(Element):
        def __init__(self):
            super().__init__("null")
            self.add_sink_pad()

        def chain(self, pad, buf):
            pass

    e = _Null()
    n_threads, per = 8, 500
    buf = _frame(0)

    def hammer():
        for _ in range(per):
            e._chain_guarded(e.sinkpad, buf)

    threads = [threading.Thread(target=hammer) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert e.stats["buffers_in"] == n_threads * per

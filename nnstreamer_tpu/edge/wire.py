"""Edge wire codec: framed messages for cross-host tensor streams.

Parity target: the nnstreamer-edge data wire the reference's L5 layer
sends over TCP/MQTT — ``nns_edge_data_create/add/set_info/send`` usage at
/root/reference/gst/nnstreamer/tensor_query/tensor_query_client.c:673-741
and gst/edge/edge_sink.c:291-322.  One message carries N tensor payloads,
each self-described by the :class:`~nnstreamer_tpu.core.meta.MetaInfo`
header (the same header flexible streams use on-pipe), plus routing info
(client id, sequence, topic) and the buffer timestamp.

Frame layout (little-endian):

    magic u32 | version u8 | mtype u8 | flags u16 |
    client_id u64 | seq u64 | pts u64 (NONE = 2^64-1) |
    info_len u32 | npayloads u32 | info bytes |
    npayloads × (len u32 | payload) |
    [extension area]

``info`` is a small UTF-8 string whose meaning depends on ``mtype``:
topic for SUBSCRIBE/PUBLISH, a caps string for CAPS_RES, empty otherwise.

The **extension area** (new in the distributed-observability PR) sits
AFTER the payload table, where decoders that predate it never look —
a version-1 decoder stops reading at the last payload, so frames
carrying extensions interoperate with old binaries in both directions.
``flags`` bit 0 (:data:`FLAG_EXT`) announces the area; it holds zero or
more self-describing blocks ``tag u16 | len u32 | bytes``.  Known tags:
:data:`EXT_TRACE` (1) — a JSON trace context
(:mod:`nnstreamer_tpu.obs.tracectx`).  Decoders skip unknown tags and
tolerate a truncated area (forward compatibility); unknown ``flags``
bits pass through untouched rather than raising.
"""

from __future__ import annotations

import dataclasses
import json
import struct
from typing import List, Optional, Sequence

from ..core import Buffer, MediaType

WIRE_MAGIC = 0x5451E55A
WIRE_VERSION = 1
PTS_NONE = (1 << 64) - 1

#: flags bit 0: an extension area follows the payload table
FLAG_EXT = 0x0001

#: extension-block tag: JSON trace context (obs.tracectx)
EXT_TRACE = 1
#: extension-block tag: device-channel descriptor (edge.devicechannel)
#: — a JSON dict {fp, slot, nbytes} standing in for the payload table
#: when the frame's tensors stayed in HBM; old decoders skip it
EXT_DEVCH = 2

_EXT_HDR = struct.Struct("<HI")

# message types
MSG_QUERY = 1      # client → server: run this buffer through the pipeline
MSG_REPLY = 2      # server → client: the pipeline's answer
MSG_SUBSCRIBE = 3  # edge client → edge sink server: topic subscription
MSG_PUBLISH = 4    # edge sink server → subscribers: one stream buffer
MSG_CAPS_REQ = 5   # client → server: what caps does your output have?
MSG_CAPS_RES = 6   # server → client: info = caps string
MSG_DEVCH_REQ = 7  # either side: info = sender's device fingerprint
MSG_DEVCH_RES = 8  # reply: info = "ok" iff fingerprints match

_HDR_FMT = "<IBBHQQQII"
_HDR_SIZE = struct.calcsize(_HDR_FMT)


@dataclasses.dataclass
class EdgeMessage:
    """One framed edge message."""

    mtype: int
    client_id: int = 0
    seq: int = 0
    pts: Optional[int] = None
    info: str = ""
    payloads: List[bytes] = dataclasses.field(default_factory=list)
    #: header flag bits MINUS the representational FLAG_EXT (derived
    #: from ``trace`` at pack time); unknown bits round-trip untouched
    flags: int = 0
    #: optional trace context (obs.tracectx dict) carried as an
    #: EXT_TRACE extension block
    trace: Optional[dict] = None
    #: optional device-channel descriptor (edge.devicechannel dict)
    #: carried as an EXT_DEVCH block — present on control-only frames
    #: whose tensors stayed in HBM (payload table empty)
    devch: Optional[dict] = None

    # -- tensor-buffer bridging ---------------------------------------------

    @classmethod
    def from_buffer(cls, mtype: int, buf: Buffer, client_id: int = 0,
                    seq: int = 0, info: str = "") -> "EdgeMessage":
        return cls(mtype=mtype, client_id=client_id, seq=seq, pts=buf.pts,
                   info=info, payloads=buf.pack_flexible(MediaType.TENSOR))

    def to_buffer(self) -> Buffer:
        buf = Buffer.unpack_flexible(self.payloads, pts=self.pts)
        buf.meta["client_id"] = self.client_id
        buf.meta["query_seq"] = self.seq
        return buf

    # -- framing -------------------------------------------------------------

    def pack(self) -> bytes:
        info_b = self.info.encode("utf-8")
        flags = self.flags & 0xFFFF & ~FLAG_EXT
        ext = b""
        if self.trace is not None:
            blob = json.dumps(self.trace,
                              separators=(",", ":")).encode("utf-8")
            ext = _EXT_HDR.pack(EXT_TRACE, len(blob)) + blob
            flags |= FLAG_EXT
        if self.devch is not None:
            blob = json.dumps(self.devch,
                              separators=(",", ":")).encode("utf-8")
            ext += _EXT_HDR.pack(EXT_DEVCH, len(blob)) + blob
            flags |= FLAG_EXT
        parts = [struct.pack(
            _HDR_FMT, WIRE_MAGIC, WIRE_VERSION, self.mtype, flags,
            self.client_id, self.seq,
            PTS_NONE if self.pts is None else self.pts,
            len(info_b), len(self.payloads)), info_b]
        for p in self.payloads:
            parts.append(struct.pack("<I", len(p)))
            parts.append(p)
        parts.append(ext)
        return b"".join(parts)

    @classmethod
    def unpack(cls, data: bytes) -> "EdgeMessage":
        if len(data) < _HDR_SIZE:
            raise ValueError(f"edge frame truncated: {len(data)}")
        (magic, version, mtype, flags, client_id, seq, pts, info_len,
         npay) = struct.unpack_from(_HDR_FMT, data)
        if magic != WIRE_MAGIC:
            raise ValueError(f"bad edge magic 0x{magic:08x}")
        if version != WIRE_VERSION:
            raise ValueError(f"unsupported edge version {version}")
        off = _HDR_SIZE
        info = data[off:off + info_len].decode("utf-8")
        off += info_len
        payloads = []
        for _ in range(npay):
            if off + 4 > len(data):
                raise ValueError("edge frame payload table truncated")
            (n,) = struct.unpack_from("<I", data, off)
            off += 4
            if off + n > len(data):
                raise ValueError("edge frame payload truncated")
            payloads.append(data[off:off + n])
            off += n
        trace = devch = None
        if flags & FLAG_EXT:
            trace, devch = cls._parse_ext(data, off)
        return cls(mtype=mtype, client_id=client_id, seq=seq,
                   pts=None if pts == PTS_NONE else pts, info=info,
                   payloads=payloads, flags=flags & ~FLAG_EXT,
                   trace=trace, devch=devch)

    @staticmethod
    def _parse_ext(data: bytes, off: int):
        """Walk the extension area: pick out EXT_TRACE / EXT_DEVCH,
        SKIP unknown tags, and stop (never raise) on truncation — a
        newer peer's extensions must not break this decoder."""
        trace = devch = None
        while off + _EXT_HDR.size <= len(data):
            tag, blen = _EXT_HDR.unpack_from(data, off)
            off += _EXT_HDR.size
            if off + blen > len(data):
                break  # truncated block: ignore the rest
            if tag in (EXT_TRACE, EXT_DEVCH):
                try:
                    doc = json.loads(data[off:off + blen].decode("utf-8"))
                except (ValueError, UnicodeDecodeError):
                    doc = None
                if isinstance(doc, dict):
                    if tag == EXT_TRACE and trace is None:
                        trace = doc
                    elif tag == EXT_DEVCH and devch is None:
                        devch = doc
            off += blen
        return trace, devch

"""TWO-PROCESS jax.distributed validation (round-4 verdict #5): spawn a
pair of CPU worker processes that form a real process group through
``multihost.initialize``, build the hybrid ICI/DCN mesh with a
cross-process ``replica`` axis, run a global psum over all 8 devices
(4 per process), and invoke a mesh-sharded tensor_filter whose batch
axis spans BOTH processes.

Parity: the reference validates its cross-process layer with paired
gst-launch processes (/root/reference/tests/nnstreamer_edge/query/
unittest_query.cc, runTest.sh); the DCN axis is the TPU-native
equivalent and gets the same treatment here.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent("""\
    import sys
    sys.path.insert(0, {repo!r})
    import numpy as np

    pid = int(sys.argv[1])
    port = sys.argv[2]

    from nnstreamer_tpu.parallel import multihost

    multihost.initialize(coordinator_address="127.0.0.1:" + port,
                         num_processes=2, process_id=pid)

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    idx, cnt = multihost.process_info()
    assert cnt == 2, cnt
    assert idx == pid, (idx, pid)
    assert len(jax.devices()) == 8, jax.devices()
    assert len(jax.local_devices()) == 4

    mesh = multihost.hybrid_mesh([("data", 4)], [("replica", 2)])
    assert mesh.axis_names == ("replica", "data")
    assert mesh.shape == {{"replica": 2, "data": 4}}

    # -- global psum across BOTH processes --------------------------------
    from jax.experimental.shard_map import shard_map

    x = np.arange(16, dtype=np.float32).reshape(8, 2)
    sharding = NamedSharding(mesh, P(("replica", "data")))
    xd = jax.device_put(x, sharding)
    f = jax.jit(shard_map(
        lambda a: jax.lax.psum(a.sum(), ("replica", "data")),
        mesh=mesh, in_specs=P(("replica", "data")), out_specs=P()))
    y = f(xd)
    got = float(np.asarray(y.addressable_shards[0].data))
    assert got == float(x.sum()), (got, x.sum())
    print(f"psum ok process={{pid}} value={{got}}", flush=True)

    # -- mesh-sharded filter invoke spanning the process group ------------
    from nnstreamer_tpu.elements.filter import FilterSingle
    from nnstreamer_tpu.filters.jax_xla import register_model

    def double(a):
        return a * 2.0 + 1.0

    register_model("twoproc_double", double,
                   in_shapes=[(8, 4)], in_dtypes=np.float32)
    flt = FilterSingle(framework="jax-xla", model="twoproc_double",
                       mesh="replica:2,data:4")
    xin = np.arange(32, dtype=np.float32).reshape(8, 4)
    out = flt.invoke([xin])[0]
    arr = out.jax() if hasattr(out, "jax") else out
    # the result is a GLOBAL array: verify this process's addressable
    # shards carry the right slices
    for sh in arr.addressable_shards:
        lo = sh.index[0].start or 0
        np.testing.assert_allclose(
            np.asarray(sh.data), xin[lo:lo + sh.data.shape[0]] * 2.0 + 1.0)
    print(f"filter ok process={{pid}} shards="
          f"{{len(arr.addressable_shards)}}", flush=True)
""")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_group_psum_and_sharded_filter(tmp_path):
    port = _free_port()
    script = tmp_path / "worker.py"
    script.write_text(WORKER.format(repo=REPO))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env.pop("PYTHONPATH", None)  # keep the axon site hook intact
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(i), str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for i in range(2)]
    outs = []
    try:
        for pr in procs:
            out, _ = pr.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for pr in procs:
            pr.kill()
        pytest.fail("two-process workers timed out:\n" +
                    "\n".join(outs))
    for i, (pr, out) in enumerate(zip(procs, outs)):
        if pr.returncode != 0 and (
                "UNIMPLEMENTED" in out or "not supported" in out):
            pytest.skip(f"jax.distributed unsupported here: {out[-400:]}")
        assert pr.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"psum ok process={i}" in out, out
        assert f"filter ok process={i}" in out, out

"""Shared-model serving runtime: cross-pipeline batch coalescing.

PR 2's :class:`~nnstreamer_tpu.runtime.batching.MicroBatcher` coalesces
the in-flight buffers of ONE ``tensor_filter``.  At serving scale that
is the wrong granularity: 100 concurrent pipelines running the same
jax-xla model mean 100 params copies in HBM, 100 per-bucket executable
caches, and 100 independent batch windows that each dispatch
nearly-empty buckets.  Continuous-batching servers (Orca, OSDI '22) and
prediction-serving systems that share one model replica across request
streams (Clipper, NSDI '17) coalesce at the MODEL, not the element.

This module lifts the window machinery to per-model:

- :class:`ModelPool` — a process-wide table of opened sub-plugin
  instances, ref-counted and keyed by ``(framework, model,
  accelerator/mesh config)``.  N filters with ``share-model=true``
  referencing the same model share ONE instance: one params copy, one
  per-bucket executable cache (``filters/jax_xla.py`` ``open_shared`` /
  ``close_shared`` back this at the framework level).
- :class:`PoolEntry` — one pooled model plus its cross-stream batcher
  and :class:`~nnstreamer_tpu.utils.stats.InvokeStats` (dispatches,
  frames, and *distinct streams per dispatch*).
- :class:`SharedBatcher` — a MicroBatcher over ``(stream, buffer)``
  pairs from MANY pipelines.  Per-stream FIFO order is preserved (one
  FIFO window, serialized flushes); results are demuxed back to each
  owning filter's downstream pad on that filter's flush context (a
  broken downstream in pipeline A errors on A's bus without killing
  B's demux); per-stream EOS flushes only that stream's parked frames;
  and the **adaptive window** flushes early whenever the device is idle
  instead of always waiting out the deadline — coalescing happens
  exactly while a dispatch is in flight, so an idle device never sits
  out a ``batch-timeout-ms``.

Frameworks without ``SUPPORTS_BATCH`` still share the instance (one
params copy); their streams fall back to per-frame dispatch through the
element's normal chain path — no frames are parked, none are lost.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..chaos import hooks as _chaos
from ..obs import hooks as _obs_hooks
from ..obs import tenantstat as _tenantstat
from ..obs import transfer as _xfer
from ..obs.tracer import TRACE_META_KEY
from ..utils import lockdep as _lockdep
from ..utils.log import logw
from ..utils.stats import InvokeStats
from .admission import (
    INGRESS_TS_META,
    AdmissionController,
    StreamPolicy,
    _controller_armed,
    _controller_disarmed,
    parse_priority,
    priority_name,
)
from .batching import MicroBatcher, parse_buckets, pick_bucket
from .events import Message, MessageKind

#: sampling cadence of pool-level dispatch stats (same policy as
#: TensorFilter.STAT_SAMPLE_INTERVAL: at most one blocking sample per
#: interval, so stats never throttle the shared hot path)
POOL_STAT_SAMPLE_INTERVAL = 1.0


def block_all(outs) -> None:
    """Block until every array in ``outs`` finished executing on the
    device (arrays without ``block_until_ready`` pass through)."""
    for o in outs:
        if hasattr(o, "block_until_ready"):
            o.block_until_ready()


class PoolConflictError(ValueError):
    """Sharers of one pool entry disagree on pool-level settings
    (``batch`` / ``batch-timeout-ms`` / ``batch-buckets`` are properties
    of the SHARED window, not of one element)."""


class SharedBatcher(MicroBatcher):
    """Deadline + max-batch coalescer over ``(stream, item, deadline,
    enqueue-ts)`` tuples.

    Inherits the MicroBatcher contract — serialized FIFO flushes,
    full/deadline/forced window closes — and adds per-stream draining:
    :meth:`flush_stream` dispatches windows from the head of the FIFO
    until none of one stream's frames are parked, leaving frames other
    streams parked *after* that point untouched.  Runs with the adaptive
    window on by default (idle device ⇒ flush now; busy device ⇒ keep
    coalescing until full/deadline).

    With :attr:`edf` armed (the pool's admission controller is on),
    window formation turns earliest-deadline-first: the dispatched
    window carries the frames whose deadlines expire soonest rather
    than the oldest arrivals, so a latency-critical stream never waits
    behind a bulk stream's backlog.  The selection sort is stable and
    per-stream deadlines are monotonic, so per-stream FIFO order is
    preserved.
    """

    def __init__(self, max_batch: int, timeout_s: float,
                 flush_fn: Callable[[List[Any]], None],
                 error_fn: Optional[Callable[[BaseException], None]] = None,
                 adaptive: bool = True, name: str = ""):
        super().__init__(max_batch, timeout_s, flush_fn, error_fn,
                         adaptive=adaptive, name=name)
        self.edf = False  # armed by PoolEntry when admission is on

    def submit_from(self, stream: Any, item: Any,
                    deadline_s: float = 0.0,
                    enq: Optional[float] = None) -> None:
        """Enqueue one frame of ``stream``; dispatches inline when the
        cross-stream window fills.  ``deadline_s`` (relative, 0 = none)
        drives EDF formation when armed; ``enq`` (the admission entry
        time — BEFORE any backpressure wait) anchors the latency signal
        and the deadline."""
        if enq is None:
            enq = time.monotonic()
        dl = enq + deadline_s if deadline_s > 0 else float("inf")
        self.submit((stream, item, dl, enq))

    def pending_of(self, stream: Any) -> int:
        with self._cv:
            return sum(1 for it in self._pending if it[0] is stream)

    def wait_below(self, stream: Any, limit: int,
                   timeout_s: float) -> bool:
        """Block (backpressure) until ``stream`` parks fewer than
        ``limit`` frames.  False when the window never drained within
        ``timeout_s`` — a wedged device must not wedge the producer
        forever; the caller sheds visibly instead."""
        if limit <= 0:
            return True
        deadline = time.monotonic() + timeout_s
        with self._cv:
            while sum(1 for it in self._pending
                      if it[0] is stream) >= limit:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    return False
                self._cv.wait(min(remain, 0.05))
        return True

    def _take_batch_locked(self) -> List[Any]:
        if not self.edf or len(self._pending) <= self.max_batch:
            return super()._take_batch_locked()
        # earliest-deadline-first: pick (and order) the window by
        # (deadline, arrival index) — stable, so per-stream FIFO holds;
        # the un-picked remainder keeps its arrival order
        sel = sorted(range(len(self._pending)),
                     key=lambda i: (self._pending[i][2], i)
                     )[:self.max_batch]
        batch = [self._pending[i] for i in sel]
        chosen = set(sel)
        self._pending = [it for i, it in enumerate(self._pending)
                         if i not in chosen]
        return batch

    def flush_stream(self, stream: Any) -> None:
        """Drain windows (FIFO from the head) until no frame of
        ``stream`` is parked — the per-stream EOS/stop path.  Frames of
        other streams that arrived before this stream's last frame ride
        along (order is preserved); frames parked after it stay for
        their own window.  Returns only after any in-flight window that
        may carry this stream's frames completed."""
        while True:
            with self._cv:
                mine = any(it[0] is stream for it in self._pending)
            if not mine:
                break
            if self._drain() == 0:
                break
            self.flushes_forced += 1
        with self._flush_serial_lock:
            pass  # barrier: flushes are FIFO-serialized, so once this
            # lock is free every window taken before now has demuxed


class PoolEntry:
    """One pooled model: the shared sub-plugin instance, the attached
    streams, the cross-stream batcher, and pool-level stats."""

    def __init__(self, pool: "ModelPool", key: Tuple,
                 subplugin: Any, close_fn: Callable[[Any], None]):
        self.pool = pool
        self.key = key
        self.subplugin = subplugin
        self._close_fn = close_fn
        self.refcount = 0  # managed by ModelPool under the pool lock
        self.stats = InvokeStats()
        self._lock = threading.Lock()
        self._streams: Dict[int, Any] = {}  # id(owner) -> owner element
        self.batcher: Optional[SharedBatcher] = None
        self.buckets: Tuple[int, ...] = (1,)
        self._batch_cfg: Optional[Tuple] = None
        # SLO-aware admission (runtime/admission.py): armed when any
        # sharer sets slo-ms > 0 (pool-level, conflict-checked like the
        # batch settings); per-stream policies keyed like _streams
        self.admission: Optional[AdmissionController] = None
        self._policies: Dict[int, StreamPolicy] = {}
        # id(owner) -> tenant, read lock-free on the dispatch path
        # (same discipline as the unlocked self.admission read there:
        # plain dict lookups, rebuilt only under self._lock)
        self._tenants: Dict[int, str] = {}
        self._shed_warn_ts: Dict[int, float] = {}
        # dispatch sampling state (serialized by the batcher flush lock)
        self._seq = 0
        self._last_sample_ts = 0.0
        self._last_out: Any = None
        # sampling cadence: the pool default, tightened by any attached
        # filter's stat-sample-interval-ms (the pool keeps the minimum
        # so the most latency-curious sharer wins)
        self.sample_interval = POOL_STAT_SAMPLE_INTERVAL
        # actuator set (runtime/actuators.py), built lazily and kept
        # for the entry's lifetime: cooldown state must survive
        # rebuilds, and the closures read batcher/admission through
        # self so a torn-down window fails the actuation cleanly
        # instead of steering a dead object
        self._actuators: Dict[str, Any] = {}
        # model lifecycle (runtime/lifecycle.py): version registry +
        # hot-swap/canary state machine, built on first use (a pool
        # that never swaps pays nothing on the dispatch path)
        self._lifecycle = None

    # -- streams -------------------------------------------------------------

    @property
    def attached_streams(self) -> int:
        with self._lock:
            return len(self._streams)

    @property
    def placement(self):
        """The resolved placement (``parallel.ResolvedPlacement``) the
        pooled sub-plugin compiled over; None on a single-device pool.
        THE join point between the serving pool and the mesh: the
        window divisibility rule, the shard count the obs layer
        attributes against, and the multi-process fan-out all read
        from here."""
        return getattr(self.subplugin, "_placement", None)

    def label(self) -> str:
        """Stable short pool label (``framework:model-tail``) — the
        ``pool=`` value on every metric this entry exports."""
        from ..obs.metrics import pool_label

        return pool_label(self)

    # -- model lifecycle (runtime/lifecycle.py) -------------------------------

    @property
    def lifecycle(self):
        """The entry's version registry / hot-swap state machine,
        built on first use — a pool that never swaps or canaries pays
        nothing for it on the dispatch path."""
        with self._lock:
            if self._lifecycle is None:
                from .lifecycle import VersionManager

                self._lifecycle = VersionManager(self)
            return self._lifecycle

    def subplugin_for(self, owner: Any) -> Any:
        """The instance serving ``owner``'s per-frame dispatches: the
        canary shadow for canary-routed streams, the shared instance
        otherwise (the batched path partitions whole windows instead —
        see ``_dispatch_inner``)."""
        lc = self._lifecycle
        if lc is not None and lc.canary_active:
            return lc.subplugin_for(owner)
        return self.subplugin

    def reload_model(self, model: Any, version: str = "") -> dict:
        """RELOAD_MODEL for a share-model pool: stage the replacement
        OFF the dispatch path (load + compile + warm while the old
        executable serves), then either start the declared canary
        split (pool-level ``canary=``) or hot-swap at the next window
        boundary.  This is what lifts PR 3's share-model refusal of
        ``is-updatable``: the reload steers the POOL, never one
        sharer's private instance."""
        lc = self.lifecycle
        ver = lc.stage(model, version=version)
        tag, n = lc.default_canary
        # the declared tag GATES the split: `canary=next:1/N` canaries
        # whatever gets staged; a concrete tag (`canary=v7:1/N`)
        # canaries only that version — anything else cuts over
        # directly, as an undeclared version would
        if n >= 2 and (tag in ("", "next") or ver.tag == tag):
            return lc.start_canary(n, ver)
        return lc.swap(ver)

    def _serve_hist(self):
        """The registry's per-pool serve-latency histogram the admission
        controller feeds AND reads its p99 from — the exported signal
        and the shed signal are one and the same."""
        from ..obs.metrics import admission_latency_hist

        return admission_latency_hist(self.label())

    def attach(self, owner: Any, batch: int, timeout_ms: float,
               buckets_spec: str, slo_ms: float = 0.0,
               priority: Any = "normal", deadline_ms: float = 0.0,
               queue_limit: int = 0, canary: str = "",
               tenant: str = "") -> bool:
        """Register ``owner`` as a live stream of this entry.  The first
        attach fixes the pool-level window settings (``batch*``,
        ``slo-ms`` and the ``canary=`` routing declaration); later
        attaches with different settings raise
        :class:`PoolConflictError`.  ``priority`` / ``deadline-ms`` /
        ``queue-limit`` / ``tenant`` are PER-STREAM
        (runtime/admission.py; the tenant names who this stream's
        frames are attributed to — obs/tenantstat.py).  Returns
        True when the owner must submit through the shared batcher,
        False for shared-instance/per-frame dispatch (``batch<=1`` or a
        framework without ``SUPPORTS_BATCH``)."""
        from .lifecycle import parse_canary

        batch = int(batch or 1)
        batched = batch > 1 and bool(
            getattr(self.subplugin, "SUPPORTS_BATCH", False))
        slo_ms = float(slo_ms or 0.0)
        canary = str(canary or "").strip()
        canary_cfg = parse_canary(canary)  # validates the grammar
        cfg = (batch, float(timeout_ms), str(buckets_spec or "").strip(),
               slo_ms, canary)
        prio = parse_priority(priority)
        policy = StreamPolicy(
            tenant=str(tenant or "").strip() or _tenantstat.DEFAULT_TENANT,
            priority=prio,
            # EDF deadline: explicit per-stream deadline, else the pool
            # SLO (a frame older than the SLO is the one to save first)
            deadline_s=(float(deadline_ms) if float(deadline_ms or 0.0) > 0
                        else slo_ms) / 1e3,
            # bounded per-stream queue: explicit, else 16 windows'
            # worth — deep enough that overload backlog lives INSIDE
            # the window (where the latency signal sees it), still a
            # hard bound backpressure enforces
            queue_limit=int(queue_limit) if int(queue_limit or 0) > 0
            else (16 * batch if slo_ms > 0 else 0))
        owner_ms = getattr(owner, "stat_sample_interval_ms", None)
        mn = getattr(self.subplugin, "model_name", None)
        if callable(mn):
            # obs join key: the pool's nns_invoke_device_seconds series
            # measures executables of this model (obs/xlacost.py)
            from ..obs import xlacost as _xlacost

            _xlacost.map_source(self.label(), mn())
        start = None
        with self._lock:
            if owner_ms is not None:
                self.sample_interval = min(self.sample_interval,
                                           float(owner_ms) / 1e3)
            if self._streams and self._batch_cfg is not None \
                    and cfg != self._batch_cfg:
                raise PoolConflictError(
                    f"{getattr(owner, 'name', owner)}: batch settings "
                    f"{cfg} conflict with the pool's {self._batch_cfg} — "
                    f"batch/batch-timeout-ms/batch-buckets/slo-ms are "
                    f"pool-level for share-model filters and must agree "
                    f"across all {len(self._streams)} sharer(s)")
            self._streams[id(owner)] = owner
            self._policies[id(owner)] = policy
            self._tenants[id(owner)] = policy.tenant
            self._batch_cfg = cfg
            if slo_ms > 0 and self.admission is None:
                self.admission = AdmissionController(
                    slo_ms / 1e3, hist=self._serve_hist())
                _controller_armed()  # sources start stamping ingress
            if batched and self.batcher is None:
                self.buckets = parse_buckets(cfg[2], batch)
                self.batcher = SharedBatcher(
                    max_batch=batch, timeout_s=cfg[1] / 1e3,
                    flush_fn=self._dispatch, error_fn=self._error_all,
                    name=f"pool:{self.key[0]}")
                self.batcher.edf = slo_ms > 0
                start = self.batcher
            n = len(self._streams)
        self.stats.attached_streams = n
        if canary_cfg[1] >= 2:
            # the pool declares canary routing: reloads stage + canary
            # at this split instead of cutting the whole pool over
            self.lifecycle.default_canary = canary_cfg
        lc = self._lifecycle
        if lc is not None:
            lc.on_attach(owner)
        if start is not None:
            start.start()
        return batched

    def detach(self, owner: Any) -> None:
        """Unregister one stream: flush ITS parked frames first (no
        frame loss on a mid-stream stop), then — if it was the last
        stream out — drain and tear the batcher down so a later
        attach can bring new window settings."""
        with self._lock:
            present = self._streams.pop(id(owner), None) is not None
            self._policies.pop(id(owner), None)
            self._tenants.pop(id(owner), None)
            self._shed_warn_ts.pop(id(owner), None)
            batcher = self.batcher
            n = len(self._streams)
            last = not self._streams
            if last:
                self.batcher = None
                self._batch_cfg = None
                if self.admission is not None:
                    self.admission = None
                    _controller_disarmed()
        self.stats.attached_streams = n
        lc = self._lifecycle
        if lc is not None:
            lc.on_detach(owner)
        if batcher is None:
            return
        if present and not last:
            batcher.flush_stream(owner)
        elif last:
            batcher.flush()  # nothing can be parked but a survivor's
            # tail; drain everything before the timer dies
            batcher.stop()

    def flush_stream(self, owner: Any) -> None:
        """Per-stream EOS: dispatch this stream's parked frames (other
        streams' windows are untouched past that point)."""
        with self._lock:
            batcher = self.batcher
        if batcher is not None:
            batcher.flush_stream(owner)

    def submit(self, owner: Any, buf: Any) -> None:
        with self._lock:
            batcher = self.batcher
            adm = self.admission
            pol = self._policies.get(id(owner))
        if batcher is None:
            raise RuntimeError(
                f"{getattr(owner, 'name', owner)}: stream is not "
                f"attached to a shared batcher (start() not run?)")
        # deadline/latency anchor: the buffer's pipeline-INGRESS stamp
        # when present (a full window dispatches inline on the producer
        # thread, so overload backlog queues UPSTREAM of this call —
        # only the ingress anchor lets the controller see that wait),
        # else now (covers un-stamped buffers, e.g. pushed before the
        # controller armed)
        enq = time.monotonic()
        if adm is not None and pol is not None:
            t_in = buf.meta.get(INGRESS_TS_META)
            if t_in is not None:
                enq = t_in
            if not adm.admit(pol.priority):
                # p99 over SLO and this stream is sheddable: dropped at
                # the cheapest point — before any queueing — and LOUDLY
                # (counter + rate-limited bus warning)
                _tenantstat.record_shed(self.label(), pol.tenant, "slo")
                self._warn_shed(owner, pol, adm, reason="slo")
                return
            if pol.queue_limit > 0 and not batcher.wait_below(
                    owner, pol.queue_limit,
                    timeout_s=max(1.0, 8 * batcher.timeout_s)):
                # bounded queue never drained (wedged device): shed
                # rather than wedge the producer thread forever
                adm.count_queue_full(pol.priority)
                _tenantstat.record_shed(self.label(), pol.tenant,
                                        "queue-full")
                self._warn_shed(owner, pol, adm, reason="queue-full")
                return
        batcher.submit_from(owner, buf,
                            deadline_s=pol.deadline_s if pol else 0.0,
                            enq=enq)

    def _warn_shed(self, owner: Any, pol: StreamPolicy,
                   adm: AdmissionController, reason: str) -> None:
        """Every shed is counted; the bus warning is rate-limited to
        one per stream per second (it carries the cumulative count, so
        nothing is lost — the bus just isn't flooded under overload)."""
        now = time.monotonic()
        with self._lock:
            last = self._shed_warn_ts.get(id(owner), 0.0)
            if now - last < 1.0:
                return
            self._shed_warn_ts[id(owner)] = now
        total = adm.total_shed
        owner.post_message(Message(
            MessageKind.WARNING, getattr(owner, "name", str(owner)),
            data={"shed": True, "reason": reason,
                  "priority": priority_name(pol.priority),
                  "pool": f"{self.key[0]}", "total_shed": total}))
        logw("%s: load-shedding %s-priority frames (%s; %d shed so far "
             "on this pool)", getattr(owner, "name", owner),
             priority_name(pol.priority), reason, total)
        # black box: every (rate-limited) shed episode is recorded; the
        # shed ramp saturating at 1.0 is the HARD-shed threshold that
        # triggers a flight-recorder dump (obs/flightrec.py)
        from ..obs.flightrec import FLIGHT

        FLIGHT.shed(self.label(), priority_name(pol.priority), reason,
                    total, hard=adm.shed_probability >= 1.0)

    # -- the actuator API (runtime/actuators.py) ------------------------------

    def _live_batcher(self) -> SharedBatcher:
        from .actuators import ActuationError

        b = self.batcher
        if b is None:
            raise ActuationError(
                f"{self.label()}: no live cross-stream window "
                f"(no batched stream attached, or the pool is "
                f"tearing down)")
        return b

    def _live_admission(self) -> Any:
        from .actuators import ActuationError

        adm = self.admission
        if adm is None:
            raise ActuationError(
                f"{self.label()}: no admission controller armed "
                f"(no sharer set slo-ms)")
        return adm

    def actuators(self) -> Dict[str, Any]:
        """The pool's named, bounded, reversible knobs: window
        deadline, window size, coalescing pause, admission shed ramp,
        per-stream queue limits.  Built once per entry (cooldown and
        revert state persist); every knob reads its target through the
        entry, so an actuation racing ``Pipeline.stop()`` raises a
        clean ``ActuationError`` instead of steering a dead window."""
        with self._lock:
            acts = self._actuators
        if acts:
            # the window bound follows the live bucket set (a pool
            # re-attached with new settings keeps its knobs' cooldown/
            # revert state but must clamp against the NEW ceiling)
            acts["max-batch"].hi = float(self.buckets[-1])
            return acts
        from .actuators import Actuator

        label = self.label()

        def _set_window_ms(v: float) -> None:
            b = self._live_batcher()
            b.timeout_s = v / 1e3
            b.settle_s = min(b.settle_s, b.timeout_s)

        def _window_cfg():
            # snapshot BOTH knobs the setter touches: settle_s only
            # ever shrinks under _set_window_ms, so a scalar prior
            # could not restore it and "revert restores the exact
            # prior config" would silently lie
            b = self._live_batcher()
            return (b.timeout_s, b.settle_s)

        def _restore_window(prior) -> None:
            b = self._live_batcher()
            b.timeout_s, b.settle_s = prior

        def _set_max_batch(v: float) -> None:
            self._live_batcher().max_batch = int(round(v))

        def _set_coalescing(v: float) -> None:
            b = self._live_batcher()
            if v >= 0.5:
                b.resume()
            else:
                b.pause()

        def _queue_limits() -> Dict[int, int]:
            with self._lock:
                return {sid: pol.queue_limit
                        for sid, pol in self._policies.items()}

        def _set_queue_limit(v: float) -> None:
            self._live_admission()  # queue limits are an admission knob
            with self._lock:
                for pol in self._policies.values():
                    pol.queue_limit = int(round(v))

        def _restore_queue_limits(prior: Dict[int, int]) -> None:
            # exact per-stream restore; streams that detached since the
            # snapshot are simply gone (their policy died with them)
            with self._lock:
                for sid, pol in self._policies.items():
                    if sid in prior:
                        pol.queue_limit = prior[sid]

        # max-batch upper bound: the LARGEST configured bucket — every
        # window size up to it pads onto an already-compiled
        # executable; growing past it would demand a recompile the
        # guard exists to forbid
        built = {
            "window-ms": Actuator(
                "window-ms", "pool", label,
                get_fn=lambda: self._live_batcher().timeout_s * 1e3,
                set_fn=_set_window_ms, lo=0.1, hi=1000.0, unit="ms",
                snapshot_fn=_window_cfg, restore_fn=_restore_window),
            "max-batch": Actuator(
                "max-batch", "pool", label,
                get_fn=lambda: float(self._live_batcher().max_batch),
                set_fn=_set_max_batch, lo=1.0,
                hi=float(self.buckets[-1]), unit="frames"),
            "coalescing": Actuator(
                "coalescing", "pool", label,
                get_fn=lambda: 0.0 if self._live_batcher().paused
                else 1.0,
                set_fn=_set_coalescing, lo=0.0, hi=1.0, unit="on"),
            "ramp-start": Actuator(
                "ramp-start", "pool", label,
                get_fn=lambda: self._live_admission().ramp_start,
                set_fn=lambda v: self._live_admission()
                .set_ramp_start(v),
                lo=0.3, hi=0.99, unit="xSLO"),
            "queue-limit": Actuator(
                "queue-limit", "pool", label,
                get_fn=lambda: float(max(
                    _queue_limits().values(), default=0)),
                set_fn=_set_queue_limit, lo=1.0, hi=65536.0,
                unit="frames", snapshot_fn=_queue_limits,
                restore_fn=_restore_queue_limits),
        }
        with self._lock:
            # two concurrent first builds must converge on ONE set —
            # split sets would split the cooldown/revert state the
            # module promises to persist
            if not self._actuators:
                self._actuators = built
            return self._actuators

    # -- the cross-stream dispatch -------------------------------------------

    def _dispatch(self, items: List[Tuple[Any, Any, float, float]]
                  ) -> None:
        """Window flush: ONE invoke for frames from every attached
        stream, then demux each result back to its owner's downstream
        pad.  Serialized by the batcher (never concurrent); items are
        ``(owner, buf, deadline, enqueue-ts)`` in window order (arrival
        order, or EDF order under admission control)."""
        # lockdep fence: a window flush is a device-dispatch point — a
        # thread that reaches it holding any witnessed lock stalls
        # every pooled stream for the invoke (utils/lockdep.py)
        if _lockdep.ENABLED:
            _lockdep.check_dispatch(f"pool:{self.label()}")
        # transfer-label context: the pool dispatch runs on whichever
        # producer/timer thread closed the window — its crossings
        # (batched feeds, pads, drains) belong to the POOL, not to the
        # thread's own element
        xctx = None
        pushed = _xfer.ACTIVE
        if pushed:
            traces = tuple(
                tr for tr in (buf.meta.get(TRACE_META_KEY)
                              for _o, buf, _dl, _enq in items)
                if tr is not None) or None
            xctx = _xfer.push_context("", self.label(), traces)
        try:
            self._dispatch_inner(items)
        finally:
            if pushed:
                _xfer.pop_context(xctx)

    def _dispatch_inner(self, items: List[Tuple[Any, Any, float, float]]
                        ) -> None:
        if _obs_hooks.DISABLED:
            # NNS_TPU_OBS_DISABLE: fully async pool dispatch — no
            # seq/interval bookkeeping, no backlog drain, no _last_out
            # retention (mirrors TensorFilter._sample_gate)
            sample = False
        else:
            self._seq += 1
            now = time.monotonic()
            sample = (self._seq == 1 or
                      now - self._last_sample_ts >= self.sample_interval)
            if sample and self._last_out is not None:
                # drain the async backlog first, so t0→done times ONE
                # window
                block_all([self._last_out])
        lc = self._lifecycle
        if lc is not None and lc.canary_active:
            # canary split: the window partitions by the owners'
            # version assignment (every stream maps to exactly ONE
            # version, so per-stream FIFO survives the split) and each
            # part dispatches through its version's own executable —
            # a failing canary errors only its own streams' buses and
            # only its version's error counter
            for ver, sp, part in lc.partition(items):
                self._dispatch_group(part, sp, ver, sample)
            return
        self._dispatch_group(items, self.subplugin, None, sample)

    def _dispatch_group(self, items: List[Tuple[Any, Any, float, float]],
                        sp: Any, version: Any, sample: bool) -> None:
        """Dispatch one version-homogeneous group of window items
        through ``sp`` (the shared instance, or a canary shadow) —
        invoke, per-owner demux, stats, cost attribution.  ``version``
        (a ``lifecycle.ModelVersion``) collects per-version stats and
        errors when the window was split."""
        owners: Dict[int, List[Any]] = {}
        for owner, _buf, _dl, _enq in items:
            owners.setdefault(id(owner), [owner, 0])[1] += 1
        t0 = time.monotonic()
        bucket = len(items)
        try:
            ch = _chaos.plan
            if ch is not None:
                # model-path fault seam: slow-invoke sleeps here (the
                # whole window pays, like a real device stall);
                # fail-invoke raises into the guard below, exercising
                # the every-owner error fan-out
                from ..chaos.plan import apply_invoke_fault

                apply_invoke_fault(ch, f"pool:{self.key[0]}:{self.key[1]}")
            # frame prep inside the guard: items already left the
            # pending queue, so ANY failure from here on loses the
            # window and must surface on every owner's bus
            frames = [owner._pool_frame_inputs(buf)
                      for owner, buf, _dl, _enq in items]
            t1 = time.monotonic()  # host-prep done, device phase begins
            if getattr(sp, "SUPPORTS_BATCH", False):
                bucket = pick_bucket(len(frames), self.buckets)
                outs = sp.invoke_batched(frames, bucket)
            else:
                # shared instance without a batched entry point: the
                # window still coalesces (ordering, EOS semantics) but
                # each frame dispatches separately
                outs = [sp.invoke(list(f)) for f in frames]
        except Exception as e:  # noqa: BLE001 - a failed shared window
            # affects EVERY stream that parked a frame in it: the error
            # must land on each owner's bus, not only on whichever
            # producer happened to trigger the flush.  A split window
            # scopes that blast radius to THIS version's streams.
            if version is not None:
                # direct attribute read: version non-None implies the
                # manager exists, and the lifecycle PROPERTY takes the
                # entry lock — needless contention on the hot path
                self._lifecycle.record_error(version)
            for owner, _n in owners.values():
                owner.post_error(e)
            return
        if getattr(sp, "SUPPORTS_BATCH", False) and \
                getattr(sp, "_donate", False):
            # donation bookkeeping, mirroring the element paths
            # (elements/filter.py): the batched executable consumed the
            # device-resident inputs it was fed — mark exactly the
            # input-combination subset each owner dispatched, so a
            # retained reference raises DonatedTensorError instead of
            # reading reused HBM
            for owner, buf, _dl, _enq in items:
                ts = buf.tensors
                combi = getattr(owner, "_in_combi", None)
                if combi is not None:
                    ts = [ts[i] for i in combi]
                for t in ts:
                    t.mark_donated()
        flat = [o for out in outs for o in out]
        if sample:
            block_all(flat)
            t2 = time.monotonic()
            self.stats.record(t2 - t0, frames=len(items),
                              streams=len(owners))
            self._last_sample_ts = t2
        else:
            t2 = time.monotonic()
            self.stats.count(frames=len(items), streams=len(owners))
        if version is not None:
            # per-version serving stats: the canary-vs-baseline
            # comparator series (nns_model_canary/baseline_latency_us);
            # attribute read, not the lock-taking property (hot path)
            self._lifecycle.record(
                version, (t2 - t0) if sample else None,
                frames=len(items), streams=len(owners))
        self._last_out = (flat[-1] if flat else None) \
            if not _obs_hooks.DISABLED else None
        for owner, n in owners.values():
            owner.invoke_stats.count(frames=n)
        if sample:
            tracer = _obs_hooks.tracer
            if tracer is not None:
                # marks BEFORE the demux (sinks reached inline finalize
                # the trace records); each buffer's demux mark closes
                # its own drain span
                tracer.invoke_split(
                    [(getattr(owner, "name", str(owner)), buf)
                     for owner, buf, _dl, _enq in items], t0, t1, t2)
        adm = self.admission
        done = time.monotonic()
        tstats = _tenantstat.ACTIVE
        label = self.label() if (tstats or sample) else ""
        tenants = self._tenants
        for (owner, buf, _dl, enq), out in zip(items, outs):
            if adm is not None:
                # the admission controller's latency signal: window
                # park → results demuxed (sampled windows blocked on
                # the device above, so they include execution time;
                # under overload the queueing term dominates either
                # way — that's the term admission must react to)
                lat = done - enq
                adm.observe(lat)
                if tstats:
                    # per-tenant SLO attainment, graded on the SAME
                    # per-frame latency the shed decision reads
                    _tenantstat.record_latency(
                        label, tenants.get(id(owner), "default"),
                        lat, adm.slo_s)
            try:
                # the owner's flush context: push through ITS pads, so
                # a broken downstream errors on ITS bus only
                owner._pool_emit(buf, out)
            except Exception as e:  # noqa: BLE001 - keep demuxing the
                # other streams' frames of this window
                owner.post_error(e)
        if sample:
            # cost attribution: host-prep (t0→t1) / device (t1→t2) /
            # host-drain (t2→now: unbatch + per-owner demux) into the
            # pool stats and the registry's nns_invoke_* histograms
            from ..obs.metrics import observe_invoke_phases

            t3 = time.monotonic()
            self.stats.record_phases(t1 - t0, t2 - t1, t3 - t2)
            observe_invoke_phases("pool", label, bucket,
                                  t1 - t0, t2 - t1, t3 - t2)
        if tstats:
            # tenant attribution: split this window's device phase by
            # useful-frame occupancy, from the SAME t1/t2 clock reads
            # the histogram above observed — unsampled dispatches
            # count frames only (they take no honest device timing,
            # exactly like the histogram)
            tenant_frames: Dict[str, int] = {}
            for owner, n in owners.values():
                t = tenants.get(id(owner), "default")
                tenant_frames[t] = tenant_frames.get(t, 0) + n
            _tenantstat.record_window(
                label, tenant_frames,
                round((t2 - t1) * 1e9) if sample else None)

    def _error_all(self, err: BaseException) -> None:
        with self._lock:
            owners = list(self._streams.values())
        for o in owners:  # post outside the lock: bus handlers reenter
            o.post_error(err)

    # -- teardown (pool-internal) --------------------------------------------

    def _close(self) -> None:
        batcher, self.batcher = self.batcher, None
        if self.admission is not None:
            # pool torn down without a last detach (e.g. test clear())
            self.admission = None
            _controller_disarmed()
        if batcher is not None:
            batcher.flush()
            batcher.stop()
        self._close_fn(self.subplugin)


class ModelPool:
    """Process-wide ref-counted table of opened sub-plugin instances.

    ``acquire`` returns the existing entry for a key (refcount+1) or
    opens a new one via ``open_fn``; ``release`` closes the instance
    when the last reference drops.  Keys must carry everything that
    makes two opens non-interchangeable — the helper :func:`pool_key`
    builds them from FilterProps.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[Tuple, PoolEntry] = {}

    def acquire(self, key: Tuple, open_fn: Callable[[], Any],
                close_fn: Callable[[Any], None]) -> PoolEntry:
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                # same model, DIFFERENT placement: opening a second
                # pool would silently defeat the sharing the filter
                # asked for (two params copies, two windows) — surface
                # it as the pool-level conflict it is.  Equivalent
                # placement spellings never get here: they resolve to
                # one canonical key and join the existing entry.
                base = _key_base(key)
                for other in self._entries.values():
                    pk, ok = _key_placement(key), \
                        _key_placement(other.key)
                    kinds = {pk[0] if isinstance(pk, tuple) and pk
                             else "?",
                             ok[0] if isinstance(ok, tuple) and ok
                             else "?"}
                    if "raw" in kinds or "mesh" not in kinds:
                        # the conflict is about MESH placements: two
                        # resolved meshes of one model, or a meshed
                        # and an unmeshed sharer, cannot share one
                        # pool's story.  A "raw" key is an
                        # unresolvable spec whose own configure error
                        # must surface, and two "device"
                        # (null-placement) keys differ legitimately —
                        # accelerator auto vs explicit simply opens
                        # separate single-device pools, as it always
                        # did.
                        continue
                    if len(other.key) == len(key) \
                            and _key_base(other.key) == base \
                            and ok != pk:
                        if _disjoint_mesh_subsets(pk, ok):
                            # pipeline-split serving: the SAME model
                            # deliberately staged more than once over
                            # DISJOINT device subsets (``devices=0-3``
                            # and ``devices=4-7``) is not a sharing
                            # mistake — each stage gets its own pool,
                            # window and params copy on its own chips,
                            # and frames move between them over the
                            # device channel.  Only overlapping or
                            # whole-inventory re-placements stay a
                            # conflict.
                            continue
                        raise PoolConflictError(
                            f"share-model filters disagree on placement "
                            f"for {key[0]}:{key[1]}: this open resolves "
                            f"to {_key_placement(key)!r} but a live pool "
                            f"of the same model runs "
                            f"{_key_placement(other.key)!r} — placement "
                            f"(mesh/sharding/devices/accelerator) is "
                            f"pool-level for sharing filters; align the "
                            f"properties, or stop the other sharers "
                            f"before re-placing the model")
                entry = PoolEntry(self, key, open_fn(), close_fn)
                self._entries[key] = entry
            entry.refcount += 1
            return entry

    def release(self, entry: PoolEntry) -> None:
        close = False
        with self._lock:
            entry.refcount -= 1
            if entry.refcount <= 0:
                self._entries.pop(entry.key, None)
                close = True
        if close:
            entry._close()

    def get(self, key: Tuple) -> Optional[PoolEntry]:
        with self._lock:
            return self._entries.get(key)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def clear(self) -> None:
        """Drop every entry regardless of refcount (test teardown)."""
        with self._lock:
            entries = list(self._entries.values())
            self._entries.clear()
        for e in entries:
            e._close()


def pool_key(framework: str, props: Any) -> Tuple:
    """Build the ModelPool key from a framework name + FilterProps:
    everything that makes two opens non-interchangeable (model identity,
    placement, custom options, forced I/O specs).  Non-string models
    (callables, ModelDef, lists) key by object identity — two filters
    share only when handed the very same object.

    The placement component is the CANONICAL resolved key from
    ``parallel.Placement`` — equivalent spellings (``mesh=data:-1`` vs
    ``mesh=data:8`` on an 8-device host, ``sharding=dp`` vs
    ``sharding=replicated``, ``accelerator=cpu`` vs ``true:cpu``) join
    ONE pool instead of silently opening two and defeating sharing."""
    from ..parallel import Placement

    model = props.model
    if isinstance(model, (list, tuple)):
        mkey = tuple(m if isinstance(m, str) else f"obj:{id(m)}"
                     for m in model)
    elif isinstance(model, str):
        mkey = model
    else:
        mkey = f"obj:{id(model)}"
    return (str(framework), mkey,
            Placement.from_props(props).key(),
            str(props.custom or ""),
            str(props.input_spec or ""), str(props.output_spec or ""),
            str(props.shared_key or ""))


def _disjoint_mesh_subsets(a, b) -> bool:
    """Two canonical mesh keys (``("mesh", platform, axes, device-ids,
    rules)``) name non-overlapping device subsets — the legitimate
    coexistence case pipeline-split serving runs on.  False for any
    shared chip (or malformed keys), which keeps the conflict error."""
    try:
        ida, idb = set(a[3]), set(b[3])
    except Exception:  # noqa: BLE001 - malformed/foreign key: conflict
        return False
    return bool(ida) and bool(idb) and not (ida & idb)


def _key_placement(key: Tuple):
    """The placement component of a :func:`pool_key` tuple."""
    return key[2] if len(key) > 2 else None


def _key_base(key: Tuple) -> Tuple:
    """A :func:`pool_key` tuple with the placement removed — the model
    identity two conflicting placements collide on."""
    return key[:2] + key[3:]


#: the process-wide pool `tensor_filter share-model=true` attaches to
MODEL_POOL = ModelPool()

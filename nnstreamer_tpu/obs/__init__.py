"""``nnstreamer_tpu.obs`` — unified observability layer.

The runtime introspection the reference ecosystem delegates to external
tooling (gst-top / gst-instruments wall-time attribution, NNShark's
GstTracer-fed per-element view, GstTracer latency tracers), built in as
one subsystem (Documentation/observability.md):

- :mod:`.metrics` — process-wide registry of labeled counters / gauges /
  histograms that absorbs the runtime's existing stats at *scrape* time
  (``Element.count_stat`` flow counters, ``InvokeStats.snapshot()``,
  MicroBatcher/SharedBatcher window state, ``queue`` depth, the serving
  ``ModelPool``), with Prometheus text exposition, a JSON snapshot API
  and an optional stdlib-http endpoint (``serve_metrics`` /
  ``NNS_TPU_METRICS_PORT``).
- :mod:`.tracer` — GstTracer-style per-buffer latency tracer fed by
  hook points in the runtime core (pre/post chain, queue in/out,
  batching park → dispatch → demux), sampled 1-in-N, exporting
  per-element residency breakdowns and Chrome trace-event JSON
  (Perfetto-loadable) for the host-side time a JAX device trace can't
  see.
- :mod:`.hooks` — the one-global-read dispatch point the runtime hot
  path checks; strictly a no-op while no tracer is attached.
- :mod:`.tracectx` — cross-device trace propagation: the wire contexts
  that carry a sampled trace over a tensor_query/edge/MQTT/gRPC hop and
  the clock math that places remote spans on the local timeline.
- :mod:`.top` — ``nns-top``: the gst-top/NNShark parity tool, a
  live/``--once`` terminal table of per-element frames/s, queue depth,
  invoke latency, host/device cost attribution (DEV/HOST columns),
  batch/stream occupancy per pipeline and per pool — plus LINK rows
  for the edge links and a COMPILE section (XLA compile telemetry),
  aggregated across a fleet of ``--connect`` endpoints.
- :mod:`.benchgate` — the continuous-bench regression gate:
  ``bench.py --history`` appends normalized run records to
  ``BENCH_history.jsonl`` and ``nns-bench-diff`` compares the latest
  record against a committed per-metric-tolerance baseline
  (pass/regression/missing-baseline — the CI gate) or, with
  ``--against``, any two history records.
- :mod:`.transfer` — the byte-exact host↔device transfer ledger:
  every crossing at the jax seams counted with exact ``nbytes``,
  labeled ``{pipeline, source, direction, reason}``, exported as
  ``nns_transfer_*`` + ``nns-top`` XFER columns and, for sampled
  buffers, Chrome-trace ``xfer`` sub-spans (the crossings-per-frame
  measurement substrate for the device-resident-dataflow rework).
- :mod:`.devicemem` — scrape-time device-memory accounting
  (``nns_device_memory_bytes{device,kind}``; graceful empty table on
  the CPU backend) plus per-pool model weight footprints.
- :mod:`.flightrec` — the always-on flight recorder: a bounded ring
  of control-plane events dumped (Perfetto trace + registry snapshot)
  on admission hard-shed, breaker open, element error, ``/dump`` or
  SIGUSR2.
- :mod:`.scrape` — the shared fleet scrape client (one
  snapshot-over-HTTP fetch/parse + failure-tolerance implementation
  behind both ``nns-top --connect`` and the watchdog's fleet mode).
- :mod:`.watch` — ``nns-watch``: the alerting watchdog; a background
  sampler folding registry snapshots into bounded per-series rings
  (rate / level / windowed quantiles) and evaluating declarative
  threshold / SLO-burn / drift-anomaly rules, with bus-WARNING +
  flight-recorder + ``nns_alert_state`` export actions
  ("Alerting & watchdog" in the docs).
- :mod:`.control` — ``nns-ctl``: the closed-loop controller; watch
  alert state mapped through declarative playbooks onto the bounded,
  cooldown-guarded, reversible actuator API
  (``runtime/actuators.py``) on serving pools, admission and link
  breakers — every decision audited (ring + ``nns_control_*`` export,
  snapshot-v6 ``control`` table, ``nns-top`` CONTROL section,
  ``/healthz`` summary) and the fault → alert → actuation →
  recovered-SLO loop gated as MTTR (``bench.py --mttr``).
"""

from __future__ import annotations

from . import hooks
from .metrics import REGISTRY, LinkMetrics, MetricsRegistry, serve_metrics
from .tracer import TRACE_META_KEY, LatencyTracer

__all__ = [
    "REGISTRY",
    "LinkMetrics",
    "MetricsRegistry",
    "serve_metrics",
    "LatencyTracer",
    "TRACE_META_KEY",
    "hooks",
]

"""examples/ as smoke tests (round-3 verdict #9: the example scripts
were exercised by no test).

Parity model: the reference's nnstreamer_example repos double as its
living documentation AND its SSAT smoke surface; likewise each script
here must run end to end — on the CPU backend with a small buffer
count — and exit 0.
"""

import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = os.path.join(ROOT, "examples")


def _run(script, *args, timeout=600):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    # binary-safe capture: detect_overlay dumps raw RGBA to stdout
    r = subprocess.run(
        [sys.executable, os.path.join(EXAMPLES, script), *args],
        capture_output=True, timeout=timeout, cwd=ROOT, env=env)
    out = r.stdout.decode("utf-8", errors="replace")
    err = r.stderr.decode("utf-8", errors="replace")
    assert r.returncode == 0, (
        f"{script} failed ({r.returncode}):\n{out[-2000:]}\n{err[-2000:]}")
    return out


@pytest.mark.parametrize("script,args", [
    ("classify_stream.py", ("2",)),                 # arg = num_buffers
    ("detect_overlay.py", ("{tmp}/overlay.raw",)),  # arg = output path
    ("query_offload.py", ()),
    ("train_pipeline.py", ()),
    ("pretrained_imports.py", ()),
])
def test_example_runs(script, args, tmp_path):
    args = tuple(a.format(tmp=tmp_path) for a in args)
    out = _run(script, *args)
    assert out.strip(), f"{script} printed nothing"

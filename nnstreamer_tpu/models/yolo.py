"""YOLO detection family: v8 wire layout + on-device decode/NMS head.

Parity target: the reference's YOLO decoder strategies
(/root/reference/ext/nnstreamer/tensor_decoder/box_properties/yolo.cc:384
— v5 ``(1, A, 5+C)`` and v8 ``(1, 4+C, A)`` output layouts, pixel-space
xywh, class-confidence thresholding + NMS on the host).  The reference
treats YOLO models as opaque backend files; here the family is a
jittable JAX program whose *raw* variant emits the exact v8 wire layout
the ``bounding_boxes`` decoder's ``yolov8`` scheme parses, and whose
*end-to-end* variant keeps decode + class-aware NMS ON the accelerator
(one XLA computation, fixed shapes) and emits the postprocess 4-tensor
contract (boxes/classes/scores/num) — so it composes with the device
overlay renderer exactly like the SSD family.

Architecture note: a compact anchor-free v8-STYLE network (stride
8/16/32 pyramid, per-cell xywh + class scores).  It is layout- and
pipeline-compatible with YOLOv8, not weight-compatible — the zoo's
models are initialized, not pretrained (the reference's test models are
likewise tiny stand-ins, tests/test_models/).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from .mobilenet import _conv_bn, _conv_init, _rng_of
from .ssd import batched_nms

Params = Dict[str, Any]

_STRIDES = (8, 16, 32)


def _block_init(rng, cin, cout):
    """conv(s2) + depthwise + pointwise refine (CSP-lite)."""
    return {
        "down": _conv_init(rng, 3, 3, cin, cout),
        "dw": _conv_init(rng, 3, 3, cout, cout, groups=cout),
        "pw": _conv_init(rng, 1, 1, cout, cout),
    }


def _refine_init(rng, c):
    return {
        "dw": _conv_init(rng, 3, 3, c, c, groups=c),
        "pw": _conv_init(rng, 1, 1, c, c),
    }


def _block(p, x, dtype):
    x = _conv_bn(p["down"], x, stride=2, dtype=dtype)
    y = _conv_bn(p["dw"], x, stride=1, groups=x.shape[-1], dtype=dtype)
    y = _conv_bn(p["pw"], y, stride=1, dtype=dtype)
    x = x + y
    for r in p.get("refines", []):
        y = _conv_bn(r["dw"], x, stride=1, groups=x.shape[-1],
                     dtype=dtype)
        y = _conv_bn(r["pw"], y, stride=1, dtype=dtype)
        x = x + y
    return x


def yolo_init(key, num_classes: int = 80, width: int = 32,
              depth: int = 1) -> Params:
    """Init the v8-style pyramid network.  ``width`` scales channels;
    ``depth`` adds residual dw+pw refinement blocks per stage (the C2f
    repeat analog) — width=64, depth=2 at 640px lands in real
    yolov8n FLOPs territory (~9 GFLOP/frame vs yolov8n's 8.7)."""
    rng = _rng_of(key)
    c = [width, width * 2, width * 4, width * 8]
    p: Params = {
        "stem": _conv_init(rng, 3, 3, 3, c[0]),
        "num_classes": num_classes,
    }
    for i in range(3):  # stages to strides 8, 16, 32 (stem is s2, b0 s4)
        p[f"b{i}"] = _block_init(rng, c[i], c[i + 1])
        if depth > 1:
            p[f"b{i}"]["refines"] = [
                _refine_init(rng, c[i + 1]) for _ in range(depth - 1)]
    # extra early downsample so stage outputs land on strides 8/16/32
    p["early"] = _block_init(rng, c[0], c[0])
    for i, _s in enumerate(_STRIDES):
        p[f"head{i}"] = _conv_init(rng, 1, 1, c[i + 1], 4 + num_classes)
    return p


def _pyramid(params: Params, x, dtype):
    x = x.astype(dtype)
    x = _conv_bn(params["stem"], x, stride=2, dtype=dtype)   # s2
    x = _block(params["early"], x, dtype)                    # s4
    feats = []
    for i in range(3):
        x = _block(params[f"b{i}"], x, dtype)                # s8/s16/s32
        feats.append(x)
    return feats


def yolo_raw_apply(params: Params, x, dtype=jnp.bfloat16):
    """(B,H,W,3) float input → the v8 WIRE layout ``(B, 4+C, A)``:
    rows 0..3 are xywh in INPUT PIXELS, rows 4.. are per-class
    confidences in [0,1] — exactly what the ``yolov8`` decoder scheme
    expects (yolo.cc v8 branch; decoder divides by option5's in-dim)."""
    feats = _pyramid(params, x, dtype)
    outs = []
    for i, (f, stride) in enumerate(zip(feats, _STRIDES)):
        h = _conv_bn(params[f"head{i}"], f, stride=1, relu6=False,
                     dtype=dtype).astype(jnp.float32)        # (B,h,w,4+C)
        gh, gw = h.shape[1], h.shape[2]
        gy, gx = jnp.mgrid[0:gh, 0:gw]
        # anchor-free decode: cell center + sigmoid offset, exp size
        cx = (gx + jax.nn.sigmoid(h[..., 0])) * stride
        cy = (gy + jax.nn.sigmoid(h[..., 1])) * stride
        w = jnp.minimum(jnp.exp(h[..., 2]), 8.0) * stride
        hh = jnp.minimum(jnp.exp(h[..., 3]), 8.0) * stride
        cls = jax.nn.sigmoid(h[..., 4:])
        out = jnp.concatenate(
            [jnp.stack([cx, cy, w, hh], axis=-1), cls], axis=-1)
        outs.append(out.reshape(x.shape[0], gh * gw, -1))
    cat = jnp.concatenate(outs, axis=1)                      # (B,A,4+C)
    return jnp.swapaxes(cat, 1, 2)                           # (B,4+C,A)


def yolo_detect_apply(params: Params, x, max_out: int = 100,
                      iou_thresh: float = 0.5,
                      score_thresh: float = 0.25,
                      dtype=jnp.bfloat16):
    """End-to-end on-device: raw head → corner-form normalized boxes →
    class-aware fast NMS (ssd.batched_nms) → the postprocess contract
    (boxes (B,N,4) ymin..xmax normalized, classes, scores, num) consumed
    by ``mobilenet-ssd-postprocess`` decoding and the device overlay."""
    size_h, size_w = float(x.shape[1]), float(x.shape[2])
    raw = jnp.swapaxes(yolo_raw_apply(params, x, dtype=dtype), 1, 2)
    cx, cy = raw[..., 0] / size_w, raw[..., 1] / size_h
    w, h = raw[..., 2] / size_w, raw[..., 3] / size_h
    boxes = jnp.stack([cy - h / 2, cx - w / 2,
                       cy + h / 2, cx + w / 2], axis=-1)     # (B,A,4)
    # batched_nms treats column 0 as background: prepend a zero column
    # so YOLO's class 0 stays a real class (ids come back 1-based)
    scores = raw[..., 4:]
    padded = jnp.concatenate(
        [jnp.zeros_like(scores[..., :1]), scores], axis=-1)
    b, s, c = jax.vmap(
        lambda bb, ss: batched_nms(bb, ss, max_out=max_out,
                                   iou_thresh=iou_thresh,
                                   score_thresh=score_thresh))(
        boxes, padded)
    num = jnp.sum((s > score_thresh).astype(jnp.int32), axis=-1)
    return b, (c - 1).astype(jnp.float32), s, num


def register_yolo(name: str = "yolo_v8n", batch: int = 1,
                  image_size: int = 256, num_classes: int = 80,
                  raw: bool = False, max_out: int = 100,
                  seed: int = 0, width: int = 32, depth: int = 1) -> str:
    """Register with the jax-xla filter.  ``raw=True`` emits the v8 wire
    layout for the host ``yolov8`` decoder scheme; default is the
    end-to-end on-device variant in the postprocess contract."""
    from ..filters.jax_xla import register_model

    params = yolo_init(jax.random.PRNGKey(seed), num_classes=num_classes,
                       width=width, depth=depth)
    if raw:
        fn = lambda p, x: yolo_raw_apply(p, x)  # noqa: E731
    else:
        fn = lambda p, x: yolo_detect_apply(p, x, max_out=max_out)  # noqa: E731
    register_model(name, fn, params=params,
                   in_shapes=[(batch, image_size, image_size, 3)],
                   in_dtypes=np.float32)
    return name

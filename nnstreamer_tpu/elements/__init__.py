"""Stream-graph elements (L4). Importing this package registers all
built-in elements with the runtime registry."""

from . import basic  # noqa: F401
from . import filter  # noqa: F401

for _mod in ("transform", "converter", "decoder", "devicesrc", "combiners",
             "aggregator", "condition", "crop", "sparse", "rate", "repo",
             "datarepo", "trainer", "sensorsrc"):
    __import__(f"{__name__}.{_mod}")

"""Pallas kernels (ops/): fused scale/bias/cast and flash attention.
On non-TPU backends the kernels run under the Pallas interpreter."""

import numpy as np
import pytest

from nnstreamer_tpu.ops import (
    flash_attention,
    flash_attention_reference,
    scale_bias_cast,
)


class TestScaleBiasCast:
    def test_uint8_normalize_matches_numpy(self):
        x = np.random.default_rng(0).integers(
            0, 255, (2, 224, 224, 3), np.uint8)
        y = scale_bias_cast(x, 1 / 127.5, -127.5)
        np.testing.assert_allclose(
            np.asarray(y), (x.astype(np.float32) - 127.5) / 127.5,
            rtol=1e-6)

    def test_float_input(self):
        x = np.linspace(-1, 1, 8 * 128, dtype=np.float32).reshape(8, 128)
        y = scale_bias_cast(x, 2.0, 0.5)
        np.testing.assert_allclose(np.asarray(y), (x + 0.5) * 2.0,
                                   rtol=1e-6)

    def test_non_tiling_shape_falls_back(self):
        x = np.ones((3, 5), np.uint8)
        y = scale_bias_cast(x, 2.0, 1.0)
        np.testing.assert_allclose(np.asarray(y), np.full((3, 5), 4.0))

    def test_bfloat16_output(self):
        import jax.numpy as jnp

        x = np.full((8, 128), 4.0, np.float32)
        y = scale_bias_cast(x, 0.5, 0.0, out_dtype=jnp.bfloat16)
        assert y.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(y, np.float32), 2.0)


class TestFlashAttention:
    def test_matches_reference(self):
        rng = np.random.default_rng(1)
        shape = (2, 2, 256, 128)
        q = rng.standard_normal(shape).astype(np.float32)
        k = rng.standard_normal(shape).astype(np.float32)
        v = rng.standard_normal(shape).astype(np.float32)
        o = flash_attention(q, k, v)
        ref = flash_attention_reference(q, k, v)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   rtol=2e-2, atol=2e-3)

    def test_cross_attention_kv_longer(self):
        rng = np.random.default_rng(2)
        q = rng.standard_normal((1, 128, 128)).astype(np.float32)
        k = rng.standard_normal((1, 512, 128)).astype(np.float32)
        v = rng.standard_normal((1, 512, 128)).astype(np.float32)
        o = flash_attention(q, k, v)
        ref = flash_attention_reference(q, k, v)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   rtol=2e-2, atol=2e-3)

    def test_odd_shapes_fall_back(self):
        rng = np.random.default_rng(3)
        q = rng.standard_normal((1, 100, 64)).astype(np.float32)
        k = rng.standard_normal((1, 100, 64)).astype(np.float32)
        v = rng.standard_normal((1, 100, 64)).astype(np.float32)
        o = flash_attention(q, k, v)  # D=64 not 128-multiple: reference
        ref = flash_attention_reference(q, k, v)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   rtol=1e-5)


class TestTransformAcceleration:
    """acceleration=true folds affine arithmetic chains into the kernel
    (the reference's Orc acceleration analog)."""

    def run_transform(self, accel, arr):
        from fractions import Fraction

        from nnstreamer_tpu.core import Buffer, TensorsSpec
        from nnstreamer_tpu.elements.basic import AppSink, AppSrc
        from nnstreamer_tpu.elements.transform import TensorTransform
        from nnstreamer_tpu.runtime import Pipeline

        p = Pipeline(fuse=False)
        src = AppSrc(name="src", spec=TensorsSpec.from_shapes(
            [arr.shape], arr.dtype, rate=Fraction(10)))
        t = TensorTransform(name="t", mode="arithmetic",
                            option="typecast:float32,add:-127.5,div:127.5",
                            acceleration=accel,
                            backend="pallas" if accel else "xla")
        sink = AppSink(name="out")
        p.add(src, t, sink).link(src, t, sink)
        with p:
            src.push_buffer(Buffer.of(arr))
            src.end_of_stream()
            assert p.wait_eos(timeout=60)
            return sink.pull(timeout=1).tensors[0].np()

    def test_accelerated_matches_plain(self):
        arr = np.random.default_rng(0).integers(
            0, 255, (2, 8, 128), np.uint8)
        fast = self.run_transform(True, arr)
        plain = self.run_transform(False, arr)
        np.testing.assert_allclose(fast, plain, rtol=1e-6)

    def test_fold_affine_guards(self):
        from nnstreamer_tpu.elements.transform import (
            _fold_affine,
            parse_arith_ops,
        )

        a, b, dt = _fold_affine(parse_arith_ops(
            "typecast:float32,add:-127.5,div:127.5"))
        assert a == pytest.approx(1 / 127.5)
        assert b == pytest.approx(-1.0)
        # non-affine chains refuse to fold
        assert _fold_affine(parse_arith_ops("pow:2.0")) is None
        assert _fold_affine(parse_arith_ops(
            "add:1.0,typecast:float32")) is None  # mid-chain cast
        assert _fold_affine(parse_arith_ops("mul:0.0")) is None
        # no leading typecast: f16/bf16/f64 inputs keep their dtype on
        # the plain path, so folding (always f32) must refuse
        import jax.numpy as jnp

        ops = parse_arith_ops("mul:2.0")
        assert _fold_affine(ops, np.dtype(np.float16)) is None
        assert _fold_affine(ops, np.dtype(np.float64)) is None
        assert _fold_affine(ops, jnp.bfloat16) is None
        assert _fold_affine(ops, np.dtype(np.uint8)) is not None
        assert _fold_affine(ops, np.dtype(np.float32)) is not None

    def test_f64_direct_call_keeps_precision(self):
        from nnstreamer_tpu.ops import scale_bias_cast_available

        x = np.full((8, 128), 1.0 + 1e-12, np.float64)
        assert not scale_bias_cast_available(x.shape, x.dtype)
        y = scale_bias_cast(x, 1.0, 0.0, out_dtype=np.float64)
        assert np.asarray(y).dtype == np.float64

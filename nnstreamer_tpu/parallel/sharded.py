"""Sharded model execution: data/model-parallel invoke and training.

The TPU-native equivalent of "scale the pipeline out" — where the reference
fans work across devices with tensor_query client/server processes over TCP
(/root/reference/gst/nnstreamer/tensor_query/), here ONE jitted computation
spans the mesh: batches shard over the ``data`` axis, weight matrices over
``model``, and XLA lowers the resulting resharding onto ICI collectives
(all-gather/reduce-scatter) — no sockets, no serialization.

The scaling recipe (pick a mesh → annotate shardings → let XLA insert
collectives → profile) follows the public How-to-Scale-Your-Model
methodology; nothing here hand-schedules a collective.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import numpy as np


def _jax():
    import jax

    return jax


def _P(*args):
    from jax.sharding import PartitionSpec

    return PartitionSpec(*args)


def replicated(mesh):
    from jax.sharding import NamedSharding

    return NamedSharding(mesh, _P())


def batch_sharding(mesh, axis: str = "data"):
    """Shard the leading (batch) dimension over ``axis``."""
    from jax.sharding import NamedSharding

    return NamedSharding(mesh, _P(axis))


# -- parameter sharding rules ------------------------------------------------


def replicated_param_rules(path: Tuple, leaf) -> Tuple:
    """Pure data-parallel layout: every param replicated on every chip."""
    return _P()


def mobilenet_param_rules(path: Tuple, leaf) -> Tuple:
    """Tensor-parallel rules for the MobileNet/SSD param pytrees
    (models/mobilenet.py): shard output channels of pointwise convs and the
    classifier matmul over ``model``; keep depthwise convs and BN vectors
    replicated (they are tiny; channel-sharding them buys nothing but
    collectives)."""
    keys = [getattr(p, "key", getattr(p, "idx", None)) for p in path]
    leaf_name = keys[-1] if keys else None
    if leaf_name == "w" and hasattr(leaf, "ndim"):
        if leaf.ndim == 2:  # dense head: (cin, cout)
            return _P(None, "model")
        if leaf.ndim == 4 and leaf.shape[0] == 1 and leaf.shape[1] == 1:
            return _P(None, None, None, "model")  # pointwise conv
    return _P()


#: Named parameter-layout rules selectable from the element graph: the
#: ``tensor_filter sharding=`` property resolves here, so pipeline strings
#: can pick a tensor-parallel layout by name (parity with the reference's
#: string-valued accelerator/custom properties rather than code handles).
PARAM_RULES: Dict[str, Callable] = {
    "replicated": replicated_param_rules,
    "dp": replicated_param_rules,
    "mobilenet": mobilenet_param_rules,
    "tp": mobilenet_param_rules,
}


def register_param_rules(name: str, rules: Callable) -> str:
    """Register a ``(path, leaf) -> PartitionSpec`` rule set under ``name``
    for use via ``tensor_filter sharding=name``."""
    PARAM_RULES[name] = rules
    return name


def get_param_rules(name: str) -> Callable:
    try:
        return PARAM_RULES[name or "replicated"]
    except KeyError:
        raise ValueError(
            f"unknown sharding rules {name!r}; known: "
            f"{sorted(PARAM_RULES)}") from None


def shard_params(mesh, params, rules: Callable = mobilenet_param_rules,
                 model_axis: str = "model"):
    """Place a param pytree on the mesh per ``rules``; falls back to
    replication for leaves whose sharded dim isn't divisible by the axis."""
    jax = _jax()
    from jax.sharding import NamedSharding

    has_axis = model_axis in mesh.axis_names
    axis_size = dict(zip(mesh.axis_names, mesh.devices.shape)).get(
        model_axis, 1)

    def place(path, leaf):
        spec = rules(path, leaf)
        if any(s is not None for s in spec):
            dim = next(i for i, s in enumerate(spec) if s is not None)
            # replicate when the mesh has no model axis (pure-dp mesh) or
            # the sharded dim doesn't divide over it
            if not has_axis or not hasattr(leaf, "shape") \
                    or leaf.shape[dim] % axis_size:
                spec = _P()
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(place, params)


# -- sharded inference -------------------------------------------------------


class ShardedModel:
    """A model pjit-sharded over a mesh: params laid out by ``rules``,
    inputs sharded on batch over ``data``.

    This is what a "distributed tensor_filter" is on TPU: one invoke spans
    every chip on the mesh, replacing the reference's N query-server
    processes with ICI-backed SPMD.
    """

    def __init__(self, mesh, fn: Callable, params: Any = None,
                 rules: Callable = mobilenet_param_rules,
                 data_axis: str = "data", donate: bool = False,
                 name: str = ""):
        jax = _jax()
        self.mesh = mesh
        # per-shard attribution label (obs/meshstat.py); falls back to
        # the wrapped callable's name
        self.name = name or getattr(fn, "__name__", "sharded")
        self._data_axis = data_axis
        self.params = (shard_params(mesh, params, rules)
                       if params is not None else None)
        in_shard = batch_sharding(mesh, data_axis)

        if self.params is not None:
            def flat(params, *xs):
                return fn(params, *xs)

            self._jitted = jax.jit(
                flat,
                in_shardings=(
                    jax.tree_util.tree_map(lambda x: x.sharding, self.params),
                    in_shard),
                donate_argnums=(1,) if donate else ())
        else:
            self._jitted = jax.jit(
                fn, in_shardings=(in_shard,),
                donate_argnums=(0,) if donate else ())

    def __call__(self, *inputs):
        self._record_dispatch(inputs)
        if self.params is not None:
            return self._jitted(self.params, *inputs)
        return self._jitted(*inputs)

    def _record_dispatch(self, inputs) -> None:
        """Per-shard mesh attribution (obs/meshstat.py): the leading
        dim of the first input is the batch this dispatch spreads over
        the data axis."""
        from ..obs import meshstat as _meshstat

        b = 1
        if inputs and getattr(inputs[0], "shape", None):
            b = int(inputs[0].shape[0] or 1)
        axis = dict(zip(self.mesh.axis_names,
                        self.mesh.devices.shape)).get(self._data_axis, 1)
        _meshstat.record_dispatch(self.name, self.mesh, self._data_axis,
                                  slots=b, frames=b,
                                  sharded=b % max(axis, 1) == 0)


# -- sharded training step ---------------------------------------------------


def softmax_xent(logits, labels):
    import jax.numpy as jnp

    logp = _jax().nn.log_softmax(logits)
    onehot = _jax().nn.one_hot(labels, logits.shape[-1])
    return -jnp.mean(jnp.sum(onehot * logp, axis=-1))


def train_step(mesh, apply_fn: Callable, params, optimizer=None,
               loss_fn: Callable = softmax_xent,
               rules: Callable = mobilenet_param_rules,
               data_axis: str = "data"):
    """Build a jitted sharded training step.

    Returns ``(step, params, opt_state)`` where
    ``step(params, opt_state, x, y) -> (params, opt_state, loss)`` is ONE
    XLA computation over the whole mesh: forward, backward, gradient
    all-reduce (inserted by XLA along ``data``), and optimizer update.

    Parity: the reference's tensor_trainer delegates training to the
    nntrainer sub-plugin on one device (/root/reference/gst/nnstreamer/
    elements/gsttensor_trainer.c); this is its many-chip equivalent.
    """
    jax = _jax()
    import optax

    if optimizer is None:
        optimizer = optax.sgd(1e-2, momentum=0.9)
    params = shard_params(mesh, params, rules)
    opt_state = optimizer.init(params)
    param_shardings = jax.tree_util.tree_map(lambda x: x.sharding, params)
    opt_shardings = jax.tree_util.tree_map(
        lambda x: x.sharding if hasattr(x, "sharding") else replicated(mesh),
        opt_state)
    in_shard = batch_sharding(mesh, data_axis)

    def _step(params, opt_state, x, y):
        def loss_of(p):
            logits = apply_fn(p, x, train=True)
            return loss_fn(logits, y)

        loss, grads = jax.value_and_grad(loss_of)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    step = jax.jit(
        _step,
        in_shardings=(param_shardings, opt_shardings, in_shard, in_shard),
        out_shardings=(param_shardings, opt_shardings, replicated(mesh)),
        donate_argnums=(0, 1))
    return step, params, opt_state

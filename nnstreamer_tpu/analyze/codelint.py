"""Pass 3 — concurrency lint (NNS3xx) + codebase lint (NNS4xx).

Pure-AST analysis, no imports of the linted code:

- **NNS301** blocking call inside a bus-watch handler.  Watch handlers
  run synchronously inside ``Bus.post`` on whatever thread posted (often
  a streaming thread) — a handler that sleeps/joins/waits stalls the
  stream.
- **NNS302** bus post while holding a lock.  ``post`` runs handlers
  re-entrantly; a handler that takes the same lock deadlocks.
- **NNS303** blocking call while holding a lock (sleep/join/queue
  get-put/Event.wait/imports/file IO under ``with <lock>``).  Waiting on
  the *same* condition object the ``with`` holds is exempt —
  ``Condition.wait`` releases the lock.
- **NNS401** a ``@register_element`` class that never declares pads:
  neither it nor any base in the package calls
  ``add_sink_pad``/``add_src_pad`` or overrides ``request_pad`` — such an
  element can never be linked.
- **NNS402** host ``numpy`` array ops in device hot-path code (the fused
  kernels/fusion modules and any ``jit``-decorated function).  Trace-time
  shape/dtype math (``np.prod(x.shape)``) is exempt; array math must be
  ``jax.numpy`` or it forces a device sync per buffer.
- **NNS403** bare ``except:`` — swallows ``KeyboardInterrupt`` and hides
  real failures from the bus.

Suppressions: ``# nns-lint: disable=NNS303 -- <reason>`` on the flagged
line, or ``# nns-lint: disable-file=NNS303 -- <reason>`` anywhere for the
whole file.  Always give the reason.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Set

from .diagnostics import Diagnostic

_SUPPRESS_RE = re.compile(
    r"#\s*nns-lint:\s*disable(?P<scope>-file)?\s*=\s*"
    r"(?P<codes>NNS\d{3}(?:\s*,\s*NNS\d{3})*)")

#: attribute calls that block the calling thread
_BLOCKING_ATTRS = {"join", "wait", "wait_for", "acquire", "accept",
                   "recv", "recvfrom", "select", "import_module"}
#: bare-name calls that block
_BLOCKING_NAMES = {"sleep", "input", "open"}
#: bus-post entry points (NNS302)
_POST_ATTRS = {"post", "post_message", "post_error"}
#: numpy array ops that belong to jax.numpy in hot paths (NNS402)
_NP_ARRAY_OPS = {
    "sum", "mean", "exp", "log", "sqrt", "matmul", "dot", "concatenate",
    "stack", "transpose", "reshape", "einsum", "maximum", "minimum",
    "argmax", "argmin", "where", "tanh", "clip", "abs", "add", "multiply",
    "subtract", "divide", "power", "cumsum", "sort", "take", "pad",
}
#: modules whose every function is a device hot path
_HOT_MODULES = (os.path.join("ops", "kernels.py"),
                os.path.join("runtime", "fusion.py"))


def _unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - very old ast nodes
        return ""


class _Suppressions:
    """``disable=`` applies to its own line; when written on a pure
    comment line it applies to the next code line instead (so a long
    reason can precede the suppressed statement)."""

    def __init__(self, source: str):
        self.by_line: Dict[int, Set[str]] = {}
        self.file_wide: Set[str] = set()
        lines = source.splitlines()
        for ln, line in enumerate(lines, 1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            codes = {c.strip() for c in m.group("codes").split(",")}
            if m.group("scope"):
                self.file_wide |= codes
                continue
            target = ln
            if line.lstrip().startswith("#"):  # standalone comment line
                for nxt in range(ln, len(lines)):
                    stripped = lines[nxt].strip()
                    if stripped and not stripped.startswith("#"):
                        target = nxt + 1
                        break
            self.by_line.setdefault(target, set()).update(codes)

    def active(self, code: str, line: int) -> bool:
        return code in self.file_wide or code in self.by_line.get(line,
                                                                  ())


def _lockish(text: str) -> bool:
    low = text.lower()
    return ("lock" in low or low.endswith("_cv") or "cond" in low
            or "mutex" in low)


def _with_texts(stmt) -> List[str]:
    """Source text of each with-item's context expression (sans call
    parens, so ``with self._lock:`` and ``with lock():`` both yield the
    lock name)."""
    out = []
    for item in stmt.items:
        expr = item.context_expr
        if isinstance(expr, ast.Call):
            expr = expr.func
        out.append(_unparse(expr))
    return out


def _own_exprs(stmt: ast.stmt) -> List[ast.expr]:
    """Expressions attached directly to ``stmt`` (its test/targets/value),
    excluding nested statement bodies, which the caller recurses into."""
    out: List[ast.expr] = []
    for field, value in ast.iter_fields(stmt):
        if field in ("body", "orelse", "finalbody", "handlers"):
            continue
        if isinstance(value, ast.expr):
            out.append(value)
        elif isinstance(value, list):
            out += [v for v in value if isinstance(v, ast.expr)]
    return out


def _blocking_desc(call: ast.Call, held: Sequence[str]) -> Optional[str]:
    """Describe why ``call`` blocks, or None.  ``held`` is the with-expr
    text of currently held locks (for the Condition.wait exemption)."""
    f = call.func
    if isinstance(f, ast.Attribute):
        if isinstance(f.value, ast.Constant):
            return None  # "sep".join(...), b"".join(...): string ops
        recv = _unparse(f.value)
        if f.attr == "sleep":
            return f"{recv}.sleep()"
        if f.attr in ("wait", "wait_for"):
            if recv in held:
                return None  # Condition.wait releases the lock it holds
            return f"{recv}.{f.attr}()"
        if f.attr in _BLOCKING_ATTRS:
            return f"{recv}.{f.attr}()"
        if f.attr in ("get", "put") and _queueish(recv, call):
            return f"{recv}.{f.attr}() (blocking queue op)"
        return None
    if isinstance(f, ast.Name):
        if f.id in _BLOCKING_NAMES:
            return f"{f.id}()"
        if f.id == "__import__":
            return "__import__()"
    return None


def _queueish(recv: str, call: ast.Call) -> bool:
    tail = recv.rsplit(".", 1)[-1].lower()
    if re.fullmatch(r"_?d?q(ueue)?\d*", tail) or "queue" in tail:
        return True
    # an explicit timeout/block kwarg marks a blocking queue-style call
    return any(kw.arg in ("timeout", "block") for kw in call.keywords)


class _FileLint:
    """All per-file checks for one source file."""

    def __init__(self, source: str, path: str, display_path: str):
        self.source = source
        self.path = path
        self.display = display_path
        self.tree = ast.parse(source, filename=path)
        self.suppress = _Suppressions(source)
        self.diags: List[Diagnostic] = []

    def _emit(self, code: str, line: int, message: str,
              hint: Optional[str] = None) -> None:
        if self.suppress.active(code, line):
            return
        self.diags.append(Diagnostic.make(
            code, message, element=self.display, pad=f"L{line}",
            hint=hint))

    # -- NNS3xx --------------------------------------------------------------

    def concurrency(self) -> "_FileLint":
        handlers = self._watch_handler_names()
        for fn in self._functions(self.tree):
            if fn.name in handlers:
                self._lint_handler(fn)
            self._walk_locked(fn, fn.body, [])
        return self

    def _watch_handler_names(self) -> Set[str]:
        names: Set[str] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "add_watch":
                for arg in node.args:
                    if isinstance(arg, ast.Attribute):
                        names.add(arg.attr)
                    elif isinstance(arg, ast.Name):
                        names.add(arg.id)
        return names

    def _functions(self, root: ast.AST) -> List[ast.FunctionDef]:
        return [n for n in ast.walk(root)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]

    def _lint_handler(self, fn: ast.FunctionDef) -> None:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                desc = _blocking_desc(node, held=[])
                if desc:
                    self._emit(
                        "NNS301", node.lineno,
                        f"{fn.name} is a bus-watch handler but makes the "
                        f"blocking call {desc}; handlers run synchronously "
                        f"in the posting (streaming) thread",
                        hint="hand work off to a queue/thread; handlers "
                             "must only inspect the message and return")

    def _walk_locked(self, fn: ast.FunctionDef, body: Sequence[ast.stmt],
                     held: List[str]) -> None:
        """Recursive statement walk tracking the set of held locks."""
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested def runs later; locks not held then
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                if held:
                    # the with-items themselves run under the outer lock
                    # (e.g. `with lock: with open(p) as f:`)
                    for item in stmt.items:
                        for node in ast.walk(item.context_expr):
                            if isinstance(node, ast.Call):
                                self._check_locked_call(fn, node, held)
                locks = [t for t in _with_texts(stmt) if _lockish(t)]
                self._walk_locked(fn, stmt.body, held + locks)
                continue
            if held:
                for expr in _own_exprs(stmt):
                    for node in ast.walk(expr):
                        if isinstance(node, ast.Call):
                            self._check_locked_call(fn, node, held)
            for key in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, key, None)
                if sub:
                    self._walk_locked(fn, sub, held)
            for h in getattr(stmt, "handlers", None) or []:
                self._walk_locked(fn, h.body, held)

    def _check_locked_call(self, fn: ast.FunctionDef, node: ast.Call,
                           held: Sequence[str]) -> None:
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _POST_ATTRS:
            self._emit(
                "NNS302", node.lineno,
                f"{fn.name} posts to the bus while holding "
                f"{'/'.join(held)}; Bus.post runs watch handlers "
                f"synchronously — a handler taking the same lock "
                f"deadlocks",
                hint="collect the message under the lock, post after "
                     "releasing it")
            return
        desc = _blocking_desc(node, held)
        if desc:
            self._emit(
                "NNS303", node.lineno,
                f"{fn.name} makes the blocking call {desc} while holding "
                f"{'/'.join(held)}",
                hint="move the blocking call outside the lock, or use a "
                     "timeout-free non-blocking variant")

    # -- NNS4xx --------------------------------------------------------------

    def code(self) -> "_FileLint":
        self._bare_excepts()
        self._hot_numpy()
        return self

    def _bare_excepts(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ExceptHandler) and node.type is None:
                self._emit(
                    "NNS403", node.lineno,
                    "bare 'except:' swallows KeyboardInterrupt/SystemExit "
                    "and hides failures from the bus",
                    hint="catch Exception (with a reason comment) or the "
                         "specific errors expected")

    def _hot_numpy(self) -> None:
        module_hot = any(self.display.replace("/", os.sep).endswith(m)
                         for m in _HOT_MODULES)
        for fn in self._functions(self.tree):
            if not (module_hot or _jit_decorated(fn)):
                continue
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id in ("np", "numpy")
                        and node.func.attr in _NP_ARRAY_OPS):
                    continue
                if _trace_time_args(node):
                    continue  # shape/dtype math is fine at trace time
                self._emit(
                    "NNS402", node.lineno,
                    f"host numpy op np.{node.func.attr}(...) in device "
                    f"hot path '{fn.name}' — forces host transfer + "
                    f"blocks XLA async dispatch",
                    hint="use jax.numpy (jnp.) so the op fuses into the "
                         "device program")


def _jit_decorated(fn: ast.FunctionDef) -> bool:
    return any("jit" in _unparse(d) for d in fn.decorator_list)


def _trace_time_args(call: ast.Call) -> bool:
    """True when every argument derives from shapes/dims/constants —
    trace-time scalar math, not array math."""
    args = list(call.args) + [kw.value for kw in call.keywords]
    return all(_trace_time_expr(a) for a in args)


def _trace_time_expr(arg: ast.expr) -> bool:
    for node in ast.walk(arg):
        if isinstance(node, ast.Attribute) \
                and node.attr in ("shape", "ndim", "dtype"):
            return True
        if isinstance(node, ast.Name) \
                and re.search(r"shape|dim|size|rank", node.id.lower()):
            return True
    # no names at all -> pure constants
    return not any(isinstance(n, ast.Name) for n in ast.walk(arg))


# -- NNS401: package-wide pad-declaration check ------------------------------


class _ClassInfo:
    __slots__ = ("name", "bases", "declares_pads", "registered", "lineno",
                 "display")

    def __init__(self, name, bases, declares_pads, registered, lineno,
                 display):
        self.name = name
        self.bases = bases
        self.declares_pads = declares_pads
        self.registered = registered
        self.lineno = lineno
        self.display = display


def _collect_classes(tree: ast.AST, display: str) -> List[_ClassInfo]:
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        bases = []
        for b in node.bases:
            if isinstance(b, ast.Name):
                bases.append(b.id)
            elif isinstance(b, ast.Attribute):
                bases.append(b.attr)
        registered = any("register_element" in _unparse(d)
                         for d in node.decorator_list)
        declares = any(isinstance(n, ast.FunctionDef)
                       and n.name == "request_pad"
                       for n in node.body)
        for n in ast.walk(node):
            if isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr in ("add_sink_pad", "add_src_pad"):
                declares = True
        out.append(_ClassInfo(node.name, bases, declares, registered,
                              node.lineno, display))
    return out


def _check_pad_declarations(classes: List[_ClassInfo],
                            suppressions: Dict[str, _Suppressions]
                            ) -> List[Diagnostic]:
    by_name = {c.name: c for c in classes}

    def declares(name: str, seen: Set[str]) -> bool:
        c = by_name.get(name)
        if c is None or name in seen:
            return False
        if c.declares_pads:
            return True
        seen.add(name)
        return any(declares(b, seen) for b in c.bases)

    diags: List[Diagnostic] = []
    for c in classes:
        if not c.registered:
            continue
        if declares(c.name, set()):
            continue
        sup = suppressions.get(c.display)
        if sup is not None and sup.active("NNS401", c.lineno):
            continue
        diags.append(Diagnostic.make(
            "NNS401",
            f"element class {c.name} is registered but neither it nor "
            f"any base declares pads (no add_sink_pad/add_src_pad call, "
            f"no request_pad override) — it can never be linked",
            element=c.display, pad=f"L{c.lineno}",
            hint="create pads in __init__ or subclass Source/Sink/"
                 "TransformElement"))
    return diags


# -- public API --------------------------------------------------------------


def lint_source(source: str, path: str = "<string>",
                concurrency: bool = True, code: bool = True
                ) -> List[Diagnostic]:
    """Lint one source text (used by tests and single-file runs).  The
    NNS401 package-wide check runs with just this file's classes."""
    fl = _FileLint(source, path, path)
    if concurrency:
        fl.concurrency()
    if code:
        fl.code()
        fl.diags += _check_pad_declarations(
            _collect_classes(fl.tree, path), {path: fl.suppress})
    return fl.diags


def lint_package(pkg_root: str) -> List[Diagnostic]:
    """Run the self-lint over an ``nnstreamer_tpu`` checkout:
    NNS3xx over ``runtime/``, NNS4xx over every module, NNS401 resolved
    package-wide."""
    pkg_root = os.path.abspath(pkg_root)
    base = os.path.dirname(pkg_root)
    diags: List[Diagnostic] = []
    classes: List[_ClassInfo] = []
    suppressions: Dict[str, _Suppressions] = {}
    runtime_dir = os.path.join(pkg_root, "runtime")
    for dirpath, dirnames, filenames in os.walk(pkg_root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", "build", "native")]
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            display = os.path.relpath(path, base).replace(os.sep, "/")
            with open(path, encoding="utf-8") as f:
                source = f.read()
            try:
                fl = _FileLint(source, path, display)
            except SyntaxError as e:
                diags.append(Diagnostic.make(
                    "NNS403", f"{display}: does not parse: {e}",
                    element=display, pad=f"L{e.lineno or 0}"))
                continue
            if os.path.abspath(dirpath) == runtime_dir:
                fl.concurrency()
            fl.code()
            diags += fl.diags
            classes += _collect_classes(fl.tree, display)
            suppressions[display] = fl.suppress
    diags += _check_pad_declarations(classes, suppressions)
    return diags

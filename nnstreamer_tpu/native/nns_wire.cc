// Native wire codec for the nnstreamer-tpu framework.
//
// Implements the proto3 wire format of the reference's Tensors message
// (/root/reference/ext/nnstreamer/include/nnstreamer.proto — field
// numbers are the wire contract) as a C ABI loaded via ctypes.  This is
// the host-side hot path of the L5 layer (gRPC bridge, edge offload):
// every cross-process tensor frame is encoded/decoded once, and the
// Python fallback (converters/codecs.py) parses varints byte-by-byte.
//
// Byte-exact with the Python codec: same field order on encode
// (num_tensor, fr, tensor..., format; per tensor: name?, type, packed
// 16-entry dims, data), same tolerance on decode (any field order,
// packed or unpacked dims, unknown fields skipped).

#include <cstdint>
#include <cstring>

namespace {

constexpr int kRankLimit = 16;
constexpr int kTensorLimit = 256;

inline size_t varint_size(uint64_t v) {
  size_t n = 1;
  while (v >= 0x80) {
    v >>= 7;
    ++n;
  }
  return n;
}

inline size_t write_varint(uint8_t* out, uint64_t v) {
  size_t n = 0;
  while (v >= 0x80) {
    out[n++] = static_cast<uint8_t>(v) | 0x80;
    v >>= 7;
  }
  out[n++] = static_cast<uint8_t>(v);
  return n;
}

// returns bytes consumed, 0 on truncation/overflow
inline size_t read_varint(const uint8_t* p, size_t len, uint64_t* v) {
  uint64_t acc = 0;
  for (size_t i = 0; i < len && i < 10; ++i) {
    acc |= static_cast<uint64_t>(p[i] & 0x7F) << (7 * i);
    if (!(p[i] & 0x80)) {
      *v = acc;
      return i + 1;
    }
  }
  return 0;
}

inline size_t tag_size(uint32_t field) {
  return varint_size(static_cast<uint64_t>(field) << 3);
}

inline size_t write_tag(uint8_t* out, uint32_t field, uint32_t wire) {
  return write_varint(out, (static_cast<uint64_t>(field) << 3) | wire);
}

size_t skip_field(const uint8_t* p, size_t len, uint32_t wire) {
  uint64_t v;
  size_t n;
  switch (wire) {
    case 0:
      return read_varint(p, len, &v);
    case 1:
      return len >= 8 ? 8 : 0;
    case 2:
      n = read_varint(p, len, &v);
      // subtractive form: n <= len here, and v can be near 2^64 from an
      // adversarial 10-byte varint — `n + v` would wrap
      if (!n || v > len - n) return 0;
      return n + static_cast<size_t>(v);
    case 5:
      return len >= 4 ? 4 : 0;
    default:
      return 0;  // unsupported wire type
  }
}

}  // namespace

extern "C" {

// Size bound for the encoded frame (exact header accounting).
uint64_t nns_pb_encode_bound(const uint64_t* payload_sizes,
                             const uint32_t* name_lens, uint32_t ntensors) {
  uint64_t total = 0;
  total += tag_size(1) + varint_size(ntensors);
  // fr submessage: 2 int32 varints (<= 5 bytes each as non-negative)
  total += tag_size(2) + 1 + 2 * (1 + 10);
  for (uint32_t i = 0; i < ntensors; ++i) {
    uint64_t t = 0;
    if (name_lens[i])
      t += tag_size(1) + varint_size(name_lens[i]) + name_lens[i];
    t += tag_size(2) + varint_size(32);                 // type
    t += tag_size(3) + varint_size(kRankLimit * 5) + kRankLimit * 5;
    t += tag_size(4) + varint_size(payload_sizes[i]) + payload_sizes[i];
    total += tag_size(3) + varint_size(t) + t;
  }
  total += tag_size(4) + varint_size(2);  // format
  return total;
}

// Encode one frame.  dims: ntensors x kRankLimit uint32 (innermost
// first, zero-padded).  Returns written length, or 0 on overflow.
uint64_t nns_pb_encode(const uint8_t* const* payloads,
                       const uint64_t* payload_sizes,
                       const uint32_t* dtypes,
                       const uint32_t* dims,
                       const uint8_t* const* names,
                       const uint32_t* name_lens, uint32_t ntensors,
                       int32_t rate_n, int32_t rate_d, uint32_t fmt,
                       uint8_t* out, uint64_t out_cap) {
  uint8_t* p = out;
  uint8_t* end = out + out_cap;
  if (ntensors > kTensorLimit) return 0;
#define NEED(n)                                    \
  do {                                             \
    if (static_cast<uint64_t>(end - p) < (n)) return 0; \
  } while (0)
  NEED(tag_size(1) + 10);
  p += write_tag(p, 1, 0);
  p += write_varint(p, ntensors);
  // fr { rate_n, rate_d } — proto3 int32 encodes negatives as 10-byte
  uint8_t frbuf[24];
  size_t frn = 0;
  frn += write_tag(frbuf + frn, 1, 0);
  frn += write_varint(frbuf + frn, static_cast<uint64_t>(
                                       static_cast<int64_t>(rate_n)));
  frn += write_tag(frbuf + frn, 2, 0);
  frn += write_varint(frbuf + frn, static_cast<uint64_t>(
                                       static_cast<int64_t>(rate_d)));
  NEED(tag_size(2) + varint_size(frn) + frn);
  p += write_tag(p, 2, 2);
  p += write_varint(p, frn);
  std::memcpy(p, frbuf, frn);
  p += frn;
  for (uint32_t i = 0; i < ntensors; ++i) {
    // dims: packed varints, always kRankLimit entries (reference
    // readers consume all 16)
    uint8_t dimbuf[kRankLimit * 5];
    size_t dn = 0;
    for (int d = 0; d < kRankLimit; ++d)
      dn += write_varint(dimbuf + dn, dims[i * kRankLimit + d]);
    uint64_t t = 0;
    if (name_lens[i])
      t += tag_size(1) + varint_size(name_lens[i]) + name_lens[i];
    t += tag_size(2) + varint_size(dtypes[i]);
    t += tag_size(3) + varint_size(dn) + dn;
    t += tag_size(4) + varint_size(payload_sizes[i]) + payload_sizes[i];
    NEED(tag_size(3) + varint_size(t) + t);
    p += write_tag(p, 3, 2);
    p += write_varint(p, t);
    if (name_lens[i]) {
      p += write_tag(p, 1, 2);
      p += write_varint(p, name_lens[i]);
      std::memcpy(p, names[i], name_lens[i]);
      p += name_lens[i];
    }
    p += write_tag(p, 2, 0);
    p += write_varint(p, dtypes[i]);
    p += write_tag(p, 3, 2);
    p += write_varint(p, dn);
    std::memcpy(p, dimbuf, dn);
    p += dn;
    p += write_tag(p, 4, 2);
    p += write_varint(p, payload_sizes[i]);
    std::memcpy(p, payloads[i], payload_sizes[i]);
    p += payload_sizes[i];
  }
  if (fmt) {
    NEED(tag_size(4) + varint_size(fmt));
    p += write_tag(p, 4, 0);
    p += write_varint(p, fmt);
  }
#undef NEED
  return static_cast<uint64_t>(p - out);
}

// Decode one frame in place: fills per-tensor views into `data`.
// Returns the number of tensors, or -1 on malformed input.
int32_t nns_pb_decode(const uint8_t* data, uint64_t len,
                      uint32_t max_tensors,
                      uint64_t* payload_offs, uint64_t* payload_lens,
                      uint32_t* dtypes, uint32_t* dims /*16 per tensor*/,
                      uint64_t* name_offs, uint64_t* name_lens,
                      int32_t* rate, uint32_t* fmt) {
  uint64_t i = 0;
  uint32_t count = 0;
  rate[0] = rate[1] = 0;
  *fmt = 0;
  while (i < len) {
    uint64_t key;
    size_t n = read_varint(data + i, len - i, &key);
    if (!n) return -1;
    i += n;
    uint32_t field = static_cast<uint32_t>(key >> 3);
    uint32_t wire = static_cast<uint32_t>(key & 7);
    if (field == 2 && wire == 2) {  // fr submessage
      uint64_t sub;
      n = read_varint(data + i, len - i, &sub);
      // all length checks below are subtractive (sub > remaining) so an
      // adversarial near-2^64 length can't wrap the addition
      if (!n || sub > len - i - n) return -1;
      i += n;
      uint64_t j = i, subend = i + sub;
      while (j < subend) {
        uint64_t k2;
        n = read_varint(data + j, subend - j, &k2);
        if (!n) return -1;
        j += n;
        if ((k2 >> 3) >= 1 && (k2 >> 3) <= 2 && (k2 & 7) == 0) {
          uint64_t v;
          n = read_varint(data + j, subend - j, &v);
          if (!n) return -1;
          j += n;
          rate[(k2 >> 3) - 1] = static_cast<int32_t>(v);
        } else {
          n = skip_field(data + j, subend - j, k2 & 7);
          if (!n) return -1;
          j += n;
        }
      }
      i = subend;
    } else if (field == 3 && wire == 2) {  // one Tensor
      uint64_t sub;
      n = read_varint(data + i, len - i, &sub);
      if (!n || sub > len - i - n) return -1;
      i += n;
      if (count >= max_tensors) return -1;
      uint64_t j = i, subend = i + sub;
      payload_offs[count] = payload_lens[count] = 0;
      name_offs[count] = name_lens[count] = 0;
      dtypes[count] = 11;  // NNS_END default
      int rank = 0;
      for (int d = 0; d < kRankLimit; ++d)
        dims[count * kRankLimit + d] = 0;
      while (j < subend) {
        uint64_t k2;
        n = read_varint(data + j, subend - j, &k2);
        if (!n) return -1;
        j += n;
        uint32_t f2 = static_cast<uint32_t>(k2 >> 3);
        uint32_t w2 = static_cast<uint32_t>(k2 & 7);
        uint64_t v;
        if (f2 == 1 && w2 == 2) {  // name
          n = read_varint(data + j, subend - j, &v);
          if (!n || v > subend - j - n) return -1;
          name_offs[count] = j + n;
          name_lens[count] = v;
          j += n + v;
        } else if (f2 == 2 && w2 == 0) {  // type
          n = read_varint(data + j, subend - j, &v);
          if (!n) return -1;
          dtypes[count] = static_cast<uint32_t>(v);
          j += n;
        } else if (f2 == 3 && w2 == 2) {  // packed dims
          n = read_varint(data + j, subend - j, &v);
          if (!n || v > subend - j - n) return -1;
          uint64_t dend = j + n + v;
          j += n;
          while (j < dend) {
            n = read_varint(data + j, dend - j, &v);
            if (!n) return -1;
            j += n;
            if (rank < kRankLimit)
              dims[count * kRankLimit + rank++] = static_cast<uint32_t>(v);
          }
        } else if (f2 == 3 && w2 == 0) {  // unpacked dim
          n = read_varint(data + j, subend - j, &v);
          if (!n) return -1;
          j += n;
          if (rank < kRankLimit)
            dims[count * kRankLimit + rank++] = static_cast<uint32_t>(v);
        } else if (f2 == 4 && w2 == 2) {  // payload
          n = read_varint(data + j, subend - j, &v);
          if (!n || v > subend - j - n) return -1;
          payload_offs[count] = j + n;
          payload_lens[count] = v;
          j += n + v;
        } else {
          n = skip_field(data + j, subend - j, w2);
          if (!n) return -1;
          j += n;
        }
      }
      i = subend;
      ++count;
    } else if (field == 4 && wire == 0) {  // format
      uint64_t v;
      n = read_varint(data + i, len - i, &v);
      if (!n) return -1;
      i += n;
      *fmt = static_cast<uint32_t>(v);
    } else if (field == 1 && wire == 0) {  // num_tensor (len(tensor) wins)
      uint64_t v;
      n = read_varint(data + i, len - i, &v);
      if (!n) return -1;
      i += n;
    } else {
      n = skip_field(data + i, len - i, wire);
      if (!n) return -1;
      i += n;
    }
  }
  return static_cast<int32_t>(count);
}

}  // extern "C"

"""CLI for the static analyzer: ``python -m nnstreamer_tpu.analyze``.

Modes (combinable; at least one target is required):

- positional ``PIPELINE`` strings and/or ``--file PATH`` — analyze
  descriptions (graph verifier + caps dry-run);
- ``--examples [DIR]`` — analyze every pipeline extracted from
  ``examples/*.py`` plus the element-doc example pipelines;
- ``--self [PKG_DIR]`` — concurrency lint (NNS3xx) over ``runtime/`` and
  codebase lint (NNS4xx) over the whole package;
- ``--concurrency [PKG_DIR]`` — whole-package lock-order/deadlock
  analysis (NNS6xx) with the lock graph in ``--json``/``--dot``.

Output: human text (default) or ``--json`` (stable: targets and
diagnostics sorted, fixed key set).  Exit status: 0 clean, 1 findings at
error severity (or warning severity with ``--strict``), 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import List, Optional, Tuple

from .diagnostics import Diagnostic, Severity, counts, sort_diagnostics

JSON_VERSION = 1


def _repo_root() -> str:
    # nnstreamer_tpu/analyze/cli.py -> repo checkout root
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m nnstreamer_tpu.analyze",
        description="Static pipeline verifier + codebase lint "
                    "(gst-validate analog). Diagnostic catalog: "
                    "Documentation/analyze.md")
    p.add_argument("pipelines", nargs="*", metavar="PIPELINE",
                   help="gst-launch-style description(s) to analyze")
    p.add_argument("--file", action="append", default=[],
                   metavar="PATH", help="read a description from a file")
    p.add_argument("--examples", nargs="?", const="__default__",
                   metavar="DIR",
                   help="analyze pipelines extracted from examples/*.py "
                        "and the element-doc examples")
    p.add_argument("--self", dest="self_lint", nargs="?",
                   const="__default__", metavar="PKG_DIR",
                   help="run the NNS3xx/NNS4xx source passes over the "
                        "package")
    p.add_argument("--concurrency", nargs="?", const="__default__",
                   metavar="PKG_DIR",
                   help="run the whole-package concurrency analysis "
                        "(NNS6xx): lock inventory, inter-procedural "
                        "lock-order graph, deadlock cycles, "
                        "hold-and-block, leaf-lock discipline.  "
                        "--json includes the lock graph; --dot dumps "
                        "it alongside pipeline graphs")
    p.add_argument("--watch-rules", dest="watch_rules", nargs="?",
                   const="__env__", metavar="FILE",
                   help="validate an obs/watch.py alert-rules file "
                        "(NNS510: malformed grammar, metric families "
                        "the registry never exports); bare "
                        "--watch-rules reads $NNS_TPU_WATCH_RULES")
    p.add_argument("--ctl-playbooks", dest="ctl_playbooks", nargs="?",
                   const="__env__", metavar="FILE",
                   help="validate an obs/control.py playbook file "
                        "(NNS511: malformed grammar, unknown rule/"
                        "actuator, targets no analyzed pipeline "
                        "creates); bare --ctl-playbooks reads "
                        "$NNS_TPU_CTL_PLAYBOOKS")
    p.add_argument("--dot", nargs="?", const="-", metavar="DIR",
                   help="emit Pipeline.to_dot() for every parsed "
                        "description — the static graph dump (parity: "
                        "GST_DEBUG_DUMP_DOT_DIR on a never-started "
                        "pipeline).  Bare --dot prints to stdout; "
                        "--dot DIR writes one .dot file per target")
    p.add_argument("--fragment", action="store_true",
                   help="treat descriptions as pipeline fragments "
                        "(incomplete graphs downgrade to info)")
    p.add_argument("--json", dest="as_json", action="store_true",
                   help="machine-readable output")
    p.add_argument("--strict", action="store_true",
                   help="exit nonzero on warnings too")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="hide info-severity diagnostics")
    return p


def _gather(args) -> List[Tuple[str, List[Diagnostic], Optional[object]]]:
    """``(label, diagnostics, pipeline-or-None)`` per target — the
    pipeline rides along (never started) so ``--dot`` can dump it."""
    from . import analyze_description, lint_package
    from .pipelines import default_corpus

    targets: List[Tuple[str, List[Diagnostic], Optional[object]]] = []
    for desc in args.pipelines:
        diags, pipe = analyze_description(desc, fragment=args.fragment)
        targets.append((desc, diags, pipe))
    for path in args.file:
        try:
            with open(path, encoding="utf-8") as f:
                desc = f.read().strip()
        except OSError as e:
            targets.append((path, [Diagnostic.make(
                "NNS100", f"cannot read description file: {e}")], None))
            continue
        diags, pipe = analyze_description(desc, fragment=args.fragment)
        targets.append((path, diags, pipe))
    if args.examples is not None:
        ex_dir = args.examples
        if ex_dir == "__default__":
            ex_dir = os.path.join(_repo_root(), "examples")
        for entry in default_corpus(ex_dir):
            diags, pipe = analyze_description(entry.description,
                                              fragment=entry.fragment)
            targets.append((entry.label, diags, pipe))
    if args.self_lint is not None:
        pkg = args.self_lint
        if pkg == "__default__":
            pkg = os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))
        targets.append(
            (f"self:{os.path.basename(os.path.abspath(pkg))}",
             sort_diagnostics(lint_package(pkg)), None))
    if args.concurrency is not None:
        from .concurrency import analyze_package_concurrency

        pkg = args.concurrency
        if pkg == "__default__":
            pkg = os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))
        diags, graph = analyze_package_concurrency(pkg)
        # the LockGraph rides in the pipeline slot: it has to_dot()
        # (--dot) and as_graph_dict() (--json) of its own
        targets.append(
            (f"concurrency:{os.path.basename(os.path.abspath(pkg))}",
             diags, graph))
    if args.watch_rules is not None:
        from .watchrules import check_watch_rules

        path = None if args.watch_rules == "__env__" else args.watch_rules
        label = path or os.environ.get("NNS_TPU_WATCH_RULES", "") \
            or "$NNS_TPU_WATCH_RULES"
        targets.append((f"watch-rules:{label}",
                        sort_diagnostics(check_watch_rules(path)), None))
    if args.ctl_playbooks is not None:
        from .ctlplaybooks import check_playbooks

        path = None if args.ctl_playbooks == "__env__" \
            else args.ctl_playbooks
        label = path or os.environ.get("NNS_TPU_CTL_PLAYBOOKS", "") \
            or "$NNS_TPU_CTL_PLAYBOOKS"
        # bind rule names against the SAME invocation's rules file when
        # one was given (the deployment's actual rule set), and check
        # concrete targets against the pipelines analyzed above
        rule_names = None
        if args.watch_rules is not None \
                and args.watch_rules != "__env__":
            try:
                from ..obs import watch as _watch

                rule_names = [r.name
                              for r in _watch.load_rules(
                                  args.watch_rules)]
            except Exception:  # noqa: BLE001 - the rules file's own
                # problems are already NNS510 findings above
                rule_names = None
        pipes = [p for _label, _diags, p in targets
                 if p is not None and hasattr(p, "elements")]
        targets.append((f"ctl-playbooks:{label}",
                        sort_diagnostics(check_playbooks(
                            path, rule_names=rule_names,
                            pipelines=pipes)), None))
    _canary_rules_target(args, targets)
    _prof_env_target(targets)
    return targets


def _prof_env_target(targets) -> None:
    """NNS518 pure-env faces: the target only appears when a profiler
    env var is actually set, so default nns-lint output stays
    byte-stable (same pattern as the canary-rules target).  The
    deep-episode-vs-``for`` face binds in check_watch_rules instead —
    it needs the rules file."""
    if not (os.environ.get("NNS_TPU_PROF", "").strip()
            or os.environ.get("NNS_TPU_PROF_DEEP_DIR", "").strip()):
        return
    from .watchrules import prof_env_problems

    targets.append(("prof-env",
                    sort_diagnostics(prof_env_problems()), None))


def _canary_rules_target(args, targets) -> None:
    """NNS513 rules face: when any analyzed pipeline declares a
    ``canary=`` split, bind it against the active watch rule set (the
    same-invocation ``--watch-rules`` file, else $NNS_TPU_WATCH_RULES,
    else the default pack) — a canary nothing judges never promotes or
    rolls back.  The target only appears when a canary was analyzed,
    so non-lifecycle corpora keep their output byte-stable."""
    pipes = [p for _label, _diags, p in targets
             if p is not None and hasattr(p, "elements")]
    has_canary = any(
        getattr(e, "FACTORY", "") == "tensor_filter"
        and str(getattr(e, "canary", "") or "").strip()
        and bool(getattr(e, "share_model", False))
        for p in pipes for e in p.elements.values())
    if not has_canary:
        return
    from ..obs import watch as _watch
    from .graph import canary_watch_checks

    label = "(default pack)"
    try:
        if args.watch_rules is not None \
                and args.watch_rules != "__env__":
            rules = _watch.load_rules(args.watch_rules)
            label = args.watch_rules
        else:
            rules = _watch.rules_from_env()
            label = os.environ.get("NNS_TPU_WATCH_RULES", "") \
                or label
    except Exception:  # noqa: BLE001 - a broken rules file is already
        # an NNS510 finding; the canary face can't bind against it
        return
    targets.append((f"canary-rules:{label}",
                    sort_diagnostics(canary_watch_checks(pipes, rules)),
                    None))


def _dot_name(label: str) -> str:
    return re.sub(r"[^A-Za-z0-9._-]+", "_", label).strip("_")[:80] \
        or "pipeline"


def _emit_dot(targets, dest: str, out) -> None:
    """``--dot``: the static graph dump for every target that parsed.
    The pipeline was assembled but never started — caps on the edges are
    whatever the dry-run left fixed, '?' otherwise (parity with a
    GST_DEBUG_DUMP_DOT_DIR dump taken at NULL)."""
    used: dict = {}
    for label, _diags, pipe in targets:
        if pipe is None:
            continue
        dot = pipe.to_dot()
        if dest == "-":
            print(f"// dot: {label}", file=out)
            print(dot, file=out)
        else:
            os.makedirs(dest, exist_ok=True)
            stem = _dot_name(label)
            # two labels may sanitize/truncate to one stem: suffix a
            # counter so no target's graph is silently clobbered
            n = used.get(stem, 0)
            used[stem] = n + 1
            if n:
                stem = f"{stem}.{n}"
            path = os.path.join(dest, stem + ".dot")
            with open(path, "w", encoding="utf-8") as f:
                f.write(dot + "\n")
            print(f"wrote {path}", file=out)


def _print_text(targets, quiet: bool, out) -> None:
    for label, diags, _pipe in targets:
        shown = [d for d in diags
                 if not (quiet and d.severity == Severity.INFO)]
        head = label if len(label) <= 72 else label[:69] + "..."
        print(f"=== {head}", file=out)
        if not shown:
            print("    clean", file=out)
        for d in shown:
            print("    " + str(d).replace("\n", "\n    "), file=out)
    total = counts([d for _, diags, _ in targets for d in diags])
    print(f"{total[Severity.ERROR]} error(s), "
          f"{total[Severity.WARNING]} warning(s), "
          f"{total[Severity.INFO]} info", file=out)


def _print_json(targets, out) -> None:
    doc = {
        "version": JSON_VERSION,
        "targets": [],
        "summary": counts([d for _, diags, _ in targets for d in diags]),
    }
    for label, diags, obj in targets:
        entry = {"target": label,
                 "diagnostics": [d.to_dict() for d in diags]}
        # the --concurrency target carries its LockGraph: nodes/edges/
        # sites ride in the document for tools/ consumers
        if hasattr(obj, "as_graph_dict"):
            entry["lock_graph"] = obj.as_graph_dict()
        doc["targets"].append(entry)
    json.dump(doc, out, indent=2, sort_keys=True)
    out.write("\n")


def main(argv=None, out=None) -> int:
    out = out or sys.stdout
    args = build_parser().parse_args(argv)
    if not (args.pipelines or args.file or args.examples is not None
            or args.self_lint is not None
            or args.concurrency is not None
            or args.watch_rules is not None
            or args.ctl_playbooks is not None):
        build_parser().print_usage(sys.stderr)
        print("error: nothing to analyze (give a PIPELINE, --file, "
              "--examples, --self, --concurrency, --watch-rules or "
              "--ctl-playbooks)",
              file=sys.stderr)
        return 2
    targets = _gather(args)
    if args.dot is not None:
        # dot text / "wrote" lines go to stderr under --json so the
        # JSON document on stdout stays machine-parseable
        _emit_dot(targets, args.dot, sys.stderr if args.as_json else out)
    if args.as_json:
        _print_json(targets, out)
    else:
        _print_text(targets, args.quiet, out)
    all_diags = [d for _, diags, _ in targets for d in diags]
    c = counts(all_diags)
    if c[Severity.ERROR] or (args.strict and c[Severity.WARNING]):
        return 1
    return 0

"""``datareposrc`` / ``datareposink`` — MLOps dataset reader/writer.

Parity targets: /root/reference/gst/datarepo/gstdatareposrc.c (props
``location``, ``json``, ``start-sample-index``, ``stop-sample-index``,
``epochs``, ``is-shuffle``, ``tensors-sequence``, ``caps`` — :81-141) and
gstdatareposink.c (``location``, ``json``; writes the JSON descriptor on
EOS).  The JSON descriptor keeps the reference's field names so datasets
interoperate: ``gst_caps`` (caps string), ``total_samples``,
``sample_size`` (static streams), and for flexible streams
``sample_offset`` / ``tensor_size`` / ``tensor_count`` arrays
(gstdatareposrc.c:1437-1506).

Storage layout:
- static tensors: samples are fixed-size records — every tensor's raw
  payload concatenated in declaration order, ``sample_size`` bytes each.
- flexible tensors: each tensor is stored in its self-describing
  MetaInfo-headed wire form; ``sample_offset[i]`` is the file offset of
  sample i, ``tensor_count[i]`` its tensor count, and ``tensor_size``
  the flat list of per-tensor byte sizes (headers included).
- image mode: ``location`` contains a printf-style index pattern
  (e.g. ``img_%04d.png``) — one file per sample, read/written as one
  uint8 octet tensor per buffer (flexible caps).

TPU note: datareposrc is the training-feed element — downstream
tensor_trainer micro-batches its samples onto the mesh, so reads are
plain sequential host I/O off the hot path.
"""

from __future__ import annotations

import json as _json
import os
from typing import List, Optional

import numpy as np

from ..core import Buffer, Caps, Tensor, TensorFormat, TensorSpec, TensorsSpec
from ..runtime.element import NegotiationError, SinkElement, SourceElement
from ..runtime.registry import register_element


def _is_pattern(location: str) -> bool:
    return "%" in (location or "")


@register_element("datareposink")
class DataRepoSink(SinkElement):
    FACTORY = "datareposink"

    def __init__(self, name=None, location: str = "", json: str = "",
                 **props):
        self.location = location
        self.json = json
        super().__init__(name, **props)
        self._file = None
        self._count = 0
        self._offsets: List[int] = []
        self._tensor_sizes: List[int] = []
        self._tensor_counts: List[int] = []
        self._sample_size: Optional[int] = None
        self._flexible = False
        self._finalized = False
        self._touched = False  # any output file opened (even if the
        #                        write then failed): data may be clobbered

    def start(self) -> None:
        if not self.location or not self.json:
            raise NegotiationError(
                f"{self.name}: datareposink needs location= and json=")

    def render(self, buf: Buffer) -> None:
        if _is_pattern(self.location):
            path = self.location % self._count
            with open(path, "wb") as f:
                # opened (truncated) — existing data may be clobbered
                # even if a write below fails
                self._touched = True
                for t in buf.tensors:
                    f.write(t.tobytes())
            self._count += 1
            return
        if self._file is None:
            self._file = open(self.location, "wb")
        self._touched = True
        self._flexible = self._flexible or \
            buf.format != TensorFormat.STATIC
        if self._flexible:
            self._offsets.append(self._file.tell())
            self._tensor_counts.append(buf.num_tensors)
            for p in buf.pack_flexible():
                self._tensor_sizes.append(len(p))
                self._file.write(p)
        else:
            start = self._file.tell()
            for t in buf.tensors:
                self._file.write(t.tobytes())
            size = self._file.tell() - start
            if self._sample_size is None:
                self._sample_size = size
            elif self._sample_size != size:
                raise ValueError(
                    f"{self.name}: static stream produced varying sample "
                    f"sizes ({self._sample_size} then {size})")
        self._count += 1

    def _write_json(self) -> None:
        desc = {
            "gst_caps": str(self.sinkpad.caps) if self.sinkpad.caps else "",
            "total_samples": self._count,
        }
        if _is_pattern(self.location):
            desc["location_pattern"] = self.location
        elif self._flexible:
            desc["sample_offset"] = self._offsets
            desc["tensor_size"] = self._tensor_sizes
            desc["tensor_count"] = self._tensor_counts
        else:
            desc["sample_size"] = self._sample_size or 0
        with open(self.json, "w") as f:
            _json.dump(desc, f, indent=2)

    def on_eos(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None
        self._write_json()
        self._finalized = True

    def stop(self) -> None:
        # No EOS seen (early teardown): still finalize the descriptor, in
        # every mode — image-pattern mode never opens self._file, but its
        # dataset is unreadable without the JSON (reference writes it on
        # EOS, gstdatareposink.c).  Zero-sample exception: a pipeline
        # that errored before the first render() must not clobber a
        # PRE-EXISTING descriptor with an empty one — UNLESS render ran
        # at all (it opens/truncates output files — in pattern mode too —
        # before it can fail): then the old descriptor may describe
        # bytes that no longer exist, and rewriting it (total_samples =
        # what was actually completed) keeps the pair consistent.  A
        # fresh location always gets a valid empty descriptor.
        if not self._finalized and self.json and (
                self._touched or not os.path.exists(self.json)):
            self.on_eos()


@register_element("datareposrc")
class DataRepoSrc(SourceElement):
    FACTORY = "datareposrc"

    def __init__(self, name=None, location: str = "", json: str = "",
                 start_sample_index: int = 0,
                 stop_sample_index: Optional[int] = None,
                 epochs: int = 1, is_shuffle: bool = True,
                 tensors_sequence: str = "", caps=None, seed: int = 0,
                 **props):
        self.location = location
        self.json = json
        self.start_sample_index = start_sample_index
        self.stop_sample_index = stop_sample_index
        self.epochs = epochs
        self.is_shuffle = is_shuffle
        self.tensors_sequence = tensors_sequence
        self.caps = caps
        self.seed = seed
        super().__init__(name, **props)
        if isinstance(self.caps, str):
            from ..runtime.parser import parse_caps_string

            self.caps = parse_caps_string(self.caps)
        self._desc = None
        self._spec: Optional[TensorsSpec] = None
        self._file = None
        self._epoch = 0
        self._pos = 0
        self._order: List[int] = []
        self._rng = np.random.default_rng(seed)
        self._count_prefix: Optional[List[int]] = None

    # -- descriptor -----------------------------------------------------------

    def _load_desc(self) -> dict:
        if self._desc is None:
            if self.json:
                with open(self.json) as f:
                    self._desc = _json.load(f)
            else:
                self._desc = {}
        return self._desc

    def _sequence(self) -> Optional[List[int]]:
        s = str(self.tensors_sequence or "").strip()
        if not s:
            return None
        return [int(x) for x in s.split(",") if x.strip() != ""]

    def output_spec(self) -> TensorsSpec:
        if self._spec is not None:
            return self._spec
        desc = self._load_desc()
        spec: Optional[TensorsSpec] = None
        if self.caps is not None:
            spec = self.caps.to_spec()
        elif desc.get("gst_caps"):
            from ..runtime.parser import parse_caps_string

            spec = parse_caps_string(desc["gst_caps"]).to_spec()
        elif "sample_offset" in desc or "location_pattern" in desc:
            # self-describing storage (MetaInfo-headed / per-file): the
            # schema travels per sample, no caps needed
            spec = TensorsSpec(format=TensorFormat.FLEXIBLE)
        else:
            raise NegotiationError(
                f"{self.name}: need json= descriptor or caps= to know the "
                "sample format")
        seq = self._sequence()
        if seq is not None and spec.is_static():
            spec = TensorsSpec(
                tensors=tuple(spec.tensors[i] for i in seq),
                format=spec.format, rate=spec.rate)
        self._spec = spec
        return spec

    # -- sample window --------------------------------------------------------

    def _window(self) -> List[int]:
        desc = self._load_desc()
        total = int(desc.get("total_samples", 0))
        if not total and not self.json:
            # raw mode without JSON: derive from file size / sample size
            total = os.path.getsize(self.location) // self._static_size()
        start = int(self.start_sample_index)
        # None = read to the end; an explicit 0 selects exactly sample 0
        stop = total - 1 if self.stop_sample_index is None \
            else int(self.stop_sample_index)
        if not (0 <= start <= stop < total):
            raise NegotiationError(
                f"{self.name}: sample window [{start},{stop}] outside "
                f"dataset of {total} samples")
        return list(range(start, stop + 1))

    def _static_size(self) -> int:
        desc = self._load_desc()
        if "sample_size" in desc:
            return int(desc["sample_size"])
        spec = self.output_spec()
        if not spec.is_static():
            raise NegotiationError(f"{self.name}: unknown sample size")
        # sequence-selected specs still read the FULL stored sample
        full = self.caps.to_spec() if self.caps is not None else spec
        return sum(t.nbytes for t in full.tensors)

    def _next_index(self) -> Optional[int]:
        if not self._order:
            self._order = self._window()
            if self.is_shuffle:
                self._rng.shuffle(self._order)
        if self._pos >= len(self._order):
            self._epoch += 1
            if 0 <= int(self.epochs) <= self._epoch:
                return None
            self._pos = 0
            if self.is_shuffle:
                self._rng.shuffle(self._order)
        i = self._order[self._pos]
        self._pos += 1
        return i

    # -- reading --------------------------------------------------------------

    def _read_static(self, index: int) -> Buffer:
        if self._file is None:
            self._file = open(self.location, "rb")
        size = self._static_size()
        self._file.seek(index * size)
        data = self._file.read(size)
        if len(data) != size:
            raise IOError(
                f"{self.name}: short read at sample {index}")
        desc_spec = self.caps.to_spec() if self.caps is not None else None
        if desc_spec is None:
            from ..runtime.parser import parse_caps_string

            desc_spec = parse_caps_string(
                self._load_desc()["gst_caps"]).to_spec()
        tensors, off = [], 0
        for t in desc_spec.tensors:
            tensors.append(Tensor(data[off:off + t.nbytes], t))
            off += t.nbytes
        seq = self._sequence()
        if seq is not None:
            tensors = [tensors[i] for i in seq]
        return Buffer(tensors=tensors, offset=index)

    def _read_flexible(self, index: int) -> Buffer:
        desc = self._load_desc()
        if self._file is None:
            self._file = open(self.location, "rb")
        if self._count_prefix is None:
            # prefix sums: O(1) first-tensor lookup per read instead of
            # O(index) summing per sample
            acc, pref = 0, [0]
            for c in desc["tensor_count"]:
                acc += c
                pref.append(acc)
            self._count_prefix = pref
        counts = desc["tensor_count"]
        sizes = desc["tensor_size"]
        first_tensor = self._count_prefix[index]
        self._file.seek(desc["sample_offset"][index])
        payloads = []
        for k in range(counts[index]):
            payloads.append(self._file.read(sizes[first_tensor + k]))
        buf = Buffer.unpack_flexible(payloads)
        buf.offset = index
        return buf

    def _read_image(self, index: int) -> Buffer:
        path = (self._load_desc().get("location_pattern")
                or self.location) % index
        with open(path, "rb") as f:
            data = f.read()
        t = Tensor(data, TensorSpec.from_shape((len(data),), np.uint8))
        return Buffer(tensors=[t], offset=index,
                      format=TensorFormat.FLEXIBLE)

    def create(self) -> Optional[Buffer]:
        index = self._next_index()
        if index is None:
            return None
        if _is_pattern(self.location) or \
                "location_pattern" in self._load_desc():
            buf = self._read_image(index)
        elif self.output_spec().is_static():
            buf = self._read_static(index)
        else:
            buf = self._read_flexible(index)
        buf.meta["epoch"] = self._epoch
        return buf

    def stop(self) -> None:
        super().stop()
        if self._file is not None:
            self._file.close()
            self._file = None

    @property
    def current_epoch(self) -> int:
        return self._epoch

"""``jax-xla`` — the flagship filter sub-plugin.

The TPU-native answer to the reference's accelerator sub-plugins (tensorrt,
edgetpu, tflite — /root/reference/ext/nnstreamer/tensor_filter/): a model is
an XLA computation resident on the device.  Where TensorRT builds a CUDA
engine and keeps outputs in ``cudaMallocManaged`` memory
(tensor_filter_tensorrt.cc:292-358,396), jax-xla compiles a jitted function
once per input schema and keeps params *and* activations in TPU HBM;
``invoke`` is an async XLA dispatch, so the pipeline thread runs ahead of the
device (the framework's allocate-in-invoke is structural, not opt-in).

Model sources:
- in-process registration: ``register_model("name", fn, params=...)`` then
  ``model="name"`` (the TPU analog of the reference's in-process custom-easy
  registration, generalized to any jittable callable)
- ``.jaxexp`` file: a serialized ``jax.export.Exported`` computation (the
  StableHLO interchange format — parity with loading a compiled .tflite/.uff)
- a raw Python callable passed as ``model=``

Hot reload (``is-updatable``): RELOAD_MODEL events compile the replacement
*before* atomically swapping it in — parity with the tflite sub-plugin's
double-interpreter reload (tensor_filter_tensorflow_lite.cc:269-274).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core import DType, TensorSpec, TensorsSpec
from ..obs import meshstat as _meshstat
from ..obs import transfer as _xfer
from ..obs import xlacost as _xlacost
from ..runtime.events import Event, EventKind
from ..utils.stats import COMPILE_STATS, DISPATCH_STATS
from .api import FilterError, FilterProps, FilterSubplugin, SHARED_MODELS
from .registry import register_filter


def _jax():
    import jax

    return jax


def _timed_first_call(fn: Callable, stats_key) -> Callable:
    """Attribute the executable's FIRST invocation to its compile-stats
    row: ``jax.jit`` compiles lazily, so the first call is where XLA
    actually builds the program — timing only the trace/lower at the
    compile site would miss almost all of the cold-start cost.  After
    the first call the wrapper is one bool check per dispatch."""
    done = [False]

    def wrapped(*args):
        if done[0]:
            return fn(*args)
        t0 = time.perf_counter()
        out = fn(*args)
        if not done[0]:
            done[0] = True
            COMPILE_STATS.add_seconds(stats_key,
                                      time.perf_counter() - t0)
        return out

    return wrapped


def _aot_call(lowered, jitted: Callable, pkey: Optional[str] = None,
              bucket: int = 0) -> Callable:
    """Serve dispatches from an already-traced ``Lowered``: AOT-compile
    it on first use so the whole path costs one trace, falling back to
    the jit wrapper if the AOT build or its stricter call signature
    (exact avals, no weak-type promotion) rejects this program.  A
    rejected *call* cannot have consumed donated buffers, so retrying
    through ``jitted`` is safe.

    ``pkey`` (``runtime/compilecache.py``) arms the persistent AOT
    cache for this executable: the first use tries to DESERIALIZE the
    program from ``NNS_TPU_COMPILE_CACHE_DIR`` (counted as a
    ``persist_hit`` compile) before paying the XLA build, and a fresh
    build is serialized back for the next process — the cold-start
    removal ROADMAP item 3 asks for, measured by ``bench.py
    --lifecycle``."""
    # the Lowered (traced jaxpr + IR) lives in state, not the closure's
    # free variables, so it can be dropped the moment the executable or
    # the fallback is resolved — a long-running serving process must
    # not pin megabytes of IR per (model, bucket)
    state: Dict[str, Any] = {"lowered": lowered}
    del lowered

    def call(*args):
        fb = state.get("fb")
        if fb is not None:
            return fb(*args)
        compiled = state.get("c")
        if compiled is None:
            from ..runtime import compilecache as _pcache

            try:
                compiled = state["c"] = _pcache.load_or_compile(
                    pkey, state["lowered"], bucket=bucket)
            except Exception:  # noqa: BLE001 - backend-dependent AOT API
                state["fb"] = jitted
                state.pop("lowered", None)
                return jitted(*args)
            state.pop("lowered", None)
        try:
            return compiled(*args)
        except (TypeError, ValueError):
            # signature mismatch (AOT is stricter than jit dispatch):
            # permanently fall back before any execution happened
            state["fb"] = jitted
            return jitted(*args)

    return call


def _avals_nbytes(avals) -> int:
    """Total payload bytes of a flat list of ShapeDtypeStructs."""
    return sum(int(np.prod(a.shape, dtype=np.int64))
               * np.dtype(a.dtype).itemsize for a in avals)


def _chain_sha(chain: str) -> str:
    """Short stable hash of a fused-chain digest string for the
    persistent-cache key (keeps filenames bounded; the full ordered
    digest string is what gets hashed, so order matters)."""
    import hashlib

    return hashlib.sha256(chain.encode()).hexdigest()[:16]


# -- in-process model registry ----------------------------------------------

_models: Dict[str, "ModelDef"] = {}
_models_lock = threading.Lock()


class ModelDef:
    """A jittable model: ``fn(params, *inputs) -> output(s)`` (or
    ``fn(*inputs)`` when params is None) plus its input schema."""

    def __init__(self, fn: Callable, params: Any = None,
                 in_spec: Optional[TensorsSpec] = None,
                 name: str = "<anonymous>"):
        self.fn = fn
        self.params = params
        self.in_spec = in_spec
        self.name = name
        self._dev_params: Dict[Any, Any] = {}  # device → placed pytree
        self._mesh_params: Dict[Any, Any] = {}  # (mesh, rules) → pytree

    def flat_fn(self, device=None) -> Callable:
        if self.params is None:
            return self.fn
        if device not in self._dev_params:
            # Params must be device arrays before they are closed over:
            # host (numpy) leaves would be baked into the HLO as literals.
            # Committing them to ``device`` also pins the whole computation
            # there (the accelerator= property).
            t0 = time.perf_counter()
            self._dev_params[device] = _jax().device_put(self.params, device)
            _xfer.record("h2d", "weights",
                         _xfer.params_nbytes(self.params),
                         time.perf_counter() - t0, source=self.name)
        params = self._dev_params[device]

        def fn(*inputs):
            return self.fn(params, *inputs)

        return fn

    def mesh_fn(self, mesh, rules) -> Callable:
        """Like :meth:`flat_fn` but params laid out over ``mesh`` per the
        named ``rules`` (parallel.shard_params) — the multi-chip placement,
        cached per (mesh, rules) so shared/hot-reloaded instances don't
        re-transfer weights."""
        if self.params is None:
            return self.fn
        key = (mesh, rules)
        if key not in self._mesh_params:
            from ..parallel import shard_params

            t0 = time.perf_counter()
            self._mesh_params[key] = shard_params(mesh, self.params, rules)
            _xfer.record("h2d", "weights",
                         _xfer.params_nbytes(self.params),
                         time.perf_counter() - t0, source=self.name)
        params = self._mesh_params[key]

        def fn(*inputs):
            return self.fn(params, *inputs)

        return fn


def register_model(name: str, fn: Callable, params: Any = None,
                   in_spec: Optional[TensorsSpec] = None,
                   in_shapes: Optional[Sequence] = None,
                   in_dtypes: Any = None) -> str:
    """Register a jittable callable as a named model for ``model=name``."""
    if in_spec is None and in_shapes is not None:
        in_spec = TensorsSpec.from_shapes(
            in_shapes, in_dtypes if in_dtypes is not None else np.float32)
    with _models_lock:
        _models[name] = ModelDef(fn, params, in_spec, name)
    return name


def unregister_model(name: str) -> None:
    with _models_lock:
        _models.pop(name, None)


def get_model(name: str) -> Optional[ModelDef]:
    with _models_lock:
        return _models.get(name)


# -- the sub-plugin ----------------------------------------------------------


class _Compiled:
    """One compiled schema-specialized executable + its I/O specs.
    ``with_pre`` records whether a fused transform prologue was baked
    in, so negotiation can detect a stale executable after the fusion
    pass re-derives (e.g. the element was re-used unfused).
    ``in_shardings`` (mesh path only) holds the per-input NamedSharding
    the executable was specialized to, so ``invoke`` can place incoming
    host/foreign arrays without a resharding surprise.  ``with_post``
    mirrors ``with_pre`` for a fused downstream epilogue (decoder
    overlay fusion)."""

    __slots__ = ("jitted", "in_spec", "out_spec", "with_pre", "with_post",
                 "in_shardings")

    def __init__(self, jitted, in_spec: TensorsSpec, out_spec: TensorsSpec,
                 with_pre: bool = False, with_post: bool = False,
                 in_shardings=None):
        self.jitted = jitted
        self.in_spec = in_spec
        self.out_spec = out_spec
        self.with_pre = with_pre
        self.with_post = with_post
        self.in_shardings = in_shardings


@register_filter
class JaxXlaFilter(FilterSubplugin):
    NAME = "jax-xla"
    ACCELERATORS = ("tpu", "cpu")
    ALLOCATE_IN_INVOKE = True
    #: micro-batching capability: TensorFilter batch>1 routes coalesced
    #: windows through invoke_batched (one dispatch per micro-batch)
    SUPPORTS_BATCH = True

    #: shared-instance table backing ``open_shared``/``close_shared``
    #: (the serving pool's framework-level dedup): key -> [instance,
    #: refcount].  One entry means ONE params copy in HBM and ONE
    #: per-bucket executable cache no matter how many filters share it.
    _shared_lock = threading.Lock()
    _shared_instances: Dict[Tuple, list] = {}

    def __init__(self):
        super().__init__()
        self._model: Optional[ModelDef] = None
        self._compiled: Optional[_Compiled] = None
        self._swap_lock = threading.Lock()
        self._shared_refs = 0  # >0 when this instance came from open_shared
        # micro-batch executables, keyed by (in_spec, bucket): the set of
        # compiled shapes is bounded by the bucket list, not by how many
        # distinct window sizes the traffic produces
        self._batch_exec: Dict[Tuple[TensorsSpec, int], Any] = {}
        self._batch_lock = threading.Lock()
        self.batch_cache_hits = 0
        self.batch_cache_misses = 0
        # per-bucket split of the hit/miss counters, for the registry's
        # nns_executable_cache_{hits,misses}_total{...,bucket} export
        # (guarded by _batch_lock like the aggregates)
        self._cache_by_bucket: Dict[int, List[int]] = {}  # b -> [hit, miss]
        self._device = None
        self._dev_kind: Optional[str] = None
        self._donate = False
        self._pre_chains: list = []  # fused transform op chains, in order
        self._post_fns: list = []    # fused downstream epilogue (≤1)
        # the ONE placement object (parallel/placement.py): resolved
        # from mesh=/sharding=/devices= at configure; _mesh/_rules/
        # _data_axis below are views of it kept for introspection
        # (element props, tests) — every compile/dispatch seam reads
        # self._placement
        self._placement = None       # parallel.ResolvedPlacement
        self._mesh = None            # jax.sharding.Mesh (mesh= property)
        self._rules = None           # param-layout rules (sharding= property)
        self._data_axis: Optional[str] = None
        # compile-stats attribution override: a swap SHADOW's configure
        # compile is a "reload", not a "cold" start (set by
        # prepare_swap before configure)
        self._compile_kind: Optional[str] = None

    def set_fused_pre(self, chains: list) -> None:
        """Install upstream transform op chains (runtime/fusion.py) to be
        compiled into this filter's program.  They apply at the NEXT
        (re)compile — the fusion pass runs before negotiation, and
        negotiation always recompiles via set_input_info when chains are
        present.  The list is kept BY REFERENCE: a transform that unfuses
        during negotiation (flexible stream) removes its chain in place
        and the change must be visible here."""
        self._pre_chains = chains

    def set_fused_post(self, posts: list) -> None:
        """Install a downstream epilogue (runtime/fusion.py decoder
        fusion): a jit-inlinable fn mapping the model's output tuple to
        the fused output tuple (e.g. the bounding-box device overlay —
        one dispatch for transform+model+NMS+overlay).  Same by-
        reference contract as :meth:`set_fused_pre`."""
        self._post_fns = posts

    # -- lifecycle -----------------------------------------------------------

    def configure(self, props: FilterProps) -> None:
        super().configure(props)
        self._parse_accelerator(props.accelerator)
        self._donate = "donate" in (props.custom or "")
        if getattr(props, "sharding", "") and not getattr(props, "mesh", ""):
            raise FilterError(
                f"jax-xla: sharding={props.sharding!r} requires mesh=")
        if getattr(props, "devices", "") and not getattr(props, "mesh", ""):
            raise FilterError(
                f"jax-xla: devices={props.devices!r} requires mesh=")
        if getattr(props, "mesh", ""):
            self._build_mesh(props)
        shared = None
        # the table key carries the CANONICAL placement key: instances
        # that share a model name but differ in placement must not
        # collide, while equivalent spellings (data:-1 vs data:8 on an
        # 8-device host) must — parallel/placement.py is the one
        # definition of "same placement".  Reuse the key of the
        # placement just resolved instead of resolving again.
        table_key = f"jax-xla:{props.shared_key}:" \
            f"{self._placement.key if self._placement is not None else self._placement_key(props)}"
        if props.shared_key:
            shared = SHARED_MODELS.get(table_key)
        if shared is not None:
            self._model, self._compiled = shared
            return
        self._model = self._resolve_model(props.model)
        in_spec = props.input_spec or self._model.in_spec
        if in_spec is None:
            raise FilterError(
                f"jax-xla: model {self._model.name} has no input spec; pass "
                "input_spec or register with in_shapes")
        self._compiled = self._compile(self._model, in_spec)
        if props.shared_key:
            self._model, self._compiled = SHARED_MODELS.insert(
                table_key, (self._model, self._compiled))

    def close(self) -> None:
        self._compiled = None
        self._model = None
        with self._batch_lock:
            self._batch_exec.clear()

    def cache_snapshot(self) -> dict:
        """One consistent read of the per-bucket executable-cache
        hit/miss counters — the pull API the metrics registry scrapes
        (``nns_executable_cache_{hits,misses}_total``)."""
        with self._batch_lock:
            return {
                "hits": self.batch_cache_hits,
                "misses": self.batch_cache_misses,
                "by_bucket": {str(b): {"hits": hm[0], "misses": hm[1]}
                              for b, hm in
                              sorted(self._cache_by_bucket.items())},
            }

    def model_name(self) -> str:
        """Name of the model this instance serves ("" before
        configure) — the join key the obs layer maps dispatch sources
        (element names, pool labels) to executable cost rows with."""
        return self._model.name if self._model is not None else ""

    def _placement_label(self) -> str:
        """Where this instance's executables run: ``mesh(<axes>)`` on a
        mesh, else the selected device platform — the ``placement``
        label on the ``nns_executable_*`` gauges."""
        if self._placement is not None:
            return self._placement.describe()
        return self._dev_kind or (self._device.platform
                                  if self._device is not None else "")

    def _platform(self) -> str:
        if self._placement is not None:
            return self._placement.platform
        return self._device.platform if self._device is not None else ""

    def weight_bytes(self) -> Optional[dict]:
        """Weight-footprint pull API for the metrics registry
        (``nns_model_weight_bytes{pool,placement}``): total param bytes
        and where they currently live — ``host`` before placement,
        ``device`` once committed via device_put, ``mesh`` when laid
        out over a mesh.  None for param-less models."""
        model = self._model
        if model is None or model.params is None:
            return None
        placement = "mesh" if model._mesh_params else (
            "device" if model._dev_params else "host")
        return {"bytes": _xfer.params_nbytes(model.params),
                "placement": placement}

    # -- shared instances (ModelPool / open_shared) --------------------------

    @staticmethod
    def _placement_key(props: FilterProps) -> Tuple:
        """Canonical placement key of a props set — the one identity
        ``parallel.Placement`` resolves every equivalent spelling to."""
        from ..parallel import Placement

        return Placement.from_props(props).key()

    @classmethod
    def _share_key(cls, props: FilterProps) -> Tuple:
        model = props.model
        mkey = model if isinstance(model, str) else f"obj:{id(model)}"
        return (mkey, cls._placement_key(props),
                str(props.custom or ""),
                str(props.input_spec or ""), str(props.output_spec or ""),
                str(props.shared_key or ""))

    @classmethod
    def open_shared(cls, props: FilterProps) -> "JaxXlaFilter":
        """Ref-counted shared open: ONE instance per (model, placement,
        custom, forced-spec) config — N sharers see one params copy and
        one lock-protected executable cache.  Pair every call with
        :meth:`close_shared`."""
        key = cls._share_key(props)
        with cls._shared_lock:
            ent = cls._shared_instances.get(key)
            if ent is None:
                sp = cls()
                sp.configure(props)
                ent = cls._shared_instances[key] = [sp, 0]
            ent[1] += 1
            ent[0]._shared_refs = ent[1]
            return ent[0]

    @classmethod
    def close_shared(cls, sp: "JaxXlaFilter") -> None:
        """Drop one reference; the instance closes only when the last
        sharer releases it.  An instance not found in the table (a plain
        ``configure`` open handed in by mistake) closes immediately."""
        last = False
        with cls._shared_lock:
            for key, ent in list(cls._shared_instances.items()):
                if ent[0] is sp:
                    ent[1] -= 1
                    sp._shared_refs = max(ent[1], 0)
                    if ent[1] <= 0:
                        del cls._shared_instances[key]
                        last = True
                    break
            else:
                last = True
        if last:
            sp.close()

    def _parse_accelerator(self, accl: str) -> None:
        """Parity: parse_accl_hw_fill (tensor_filter_common.c). Grammar:
        "true:tpu", "tpu", "cpu", "" (auto = first platform device).
        The kind parse is the SHARED one (parallel.parse_accel_kind)
        so the canonical placement key and the device selection can
        never disagree."""
        from ..parallel import parse_accel_kind

        jax = _jax()
        kind = parse_accel_kind(accl)
        try:
            devs = jax.devices(kind) if kind else jax.devices()
        except RuntimeError as e:
            raise FilterError(f"jax-xla: no {kind} devices: {e}") from None
        self._dev_kind = kind
        self._device = devs[0]

    def _build_mesh(self, props: FilterProps) -> None:
        """Resolve the ``mesh=`` / ``sharding=`` / ``devices=`` properties
        through the ONE placement layer (parallel/placement.py) into a
        device mesh + param-layout rules.  The mesh is laid over the
        devices the ``accelerator=`` property selected (so tests run the
        same code path on the 8-virtual-CPU mesh that production runs over
        a TPU slice); ``devices=`` restricts it to an index subset, the
        SUBMESH placement that lets two pipeline stages occupy disjoint
        chips with device-to-device handoff between their invokes; and
        ``dcn.``-prefixed axes span the processes of a jax.distributed
        group (the multi-host placement — one logical model served by a
        fleet of processes).  SURVEY.md §7.6: this is the pjit redesign
        of the reference's remote tensor_filter
        (tensor_query_client.c:673-741) — the "query servers" are chips
        on the mesh and the transport is ICI/DCN."""
        from ..parallel import Placement

        try:
            self._placement = Placement.from_props(props).resolve(
                self._dev_kind)
        except (ValueError, TypeError) as e:
            raise FilterError(f"jax-xla: mesh {props.mesh!r}: {e}") from e
        rp = self._placement
        self._mesh = rp.mesh
        self._rules = rp.rules
        self._data_axis = rp.data_axis

    def _resolve_model(self, model) -> ModelDef:
        if isinstance(model, ModelDef):
            return model
        if callable(model):
            return ModelDef(model)
        if isinstance(model, str):
            m = get_model(model)
            if m is not None:
                return m
            if os.path.isfile(model):
                return self._load_file(model)
            raise FilterError(
                f"jax-xla: model {model!r} is neither a registered name nor "
                "a file")
        raise FilterError(f"jax-xla: unsupported model object {type(model)}")

    def _load_file(self, path: str) -> ModelDef:
        ext = os.path.splitext(path)[1].lower()
        if ext in (".npz", ".safetensors"):
            return self._load_weights_file(path, ext)
        if ext in (".jaxexp", ".stablehlo", ".mlir"):
            jax = _jax()
            with open(path, "rb") as f:
                exported = jax.export.deserialize(bytearray(f.read()))
            in_spec = TensorsSpec.from_shapes(
                [a.shape for a in exported.in_avals],
                [np.dtype(a.dtype) for a in exported.in_avals])
            return ModelDef(exported.call, None, in_spec, name=path)
        if ext in (".pkl", ".msgpack"):
            return self._load_pickled(path, ext)
        raise FilterError(f"jax-xla: unsupported model file type {ext!r}")

    def _load_weights_file(self, path: str, ext: str) -> ModelDef:
        """Checkpoint-interop model files (models/params_io.py): a
        weight pytree plus an ``apply`` "module:callable" import path in
        the metadata — so npz/safetensors checkpoints are directly
        loadable via ``model=weights.safetensors`` (parity: the
        reference's framework-native checkpoint loading,
        tensor_filter_tensorflow_lite.cc:242-280)."""
        import json
        import struct as _struct

        from ..models.params_io import load_npz, load_safetensors

        try:
            params, meta = (load_npz(path) if ext == ".npz"
                            else load_safetensors(path))
            in_shapes = meta.get("in_shapes")
            if isinstance(in_shapes, str):
                in_shapes = json.loads(in_shapes)
        except (ValueError, KeyError, OSError, _struct.error,
                json.JSONDecodeError) as e:
            raise FilterError(f"jax-xla: {path}: {e}") from e
        apply = meta.get("apply")
        if not apply:
            raise FilterError(
                f"jax-xla: {path} carries no 'apply' metadata (write it "
                "with models.params_io.save_npz/save_safetensors)")
        fn = self._resolve_apply(apply, path)
        in_spec = None
        if in_shapes:
            in_spec = TensorsSpec.from_shapes(
                in_shapes, np.dtype(meta.get("in_dtypes") or "float32"))
        return ModelDef(fn, params, in_spec, name=path)

    def _resolve_apply(self, target, path: str) -> Callable:
        import importlib

        if callable(target):
            return target
        if isinstance(target, str):
            mod, _, attr = target.partition(":")
            try:
                return getattr(importlib.import_module(mod), attr)
            except (ImportError, AttributeError) as e:
                raise FilterError(
                    f"jax-xla: cannot resolve apply {target!r} "
                    f"({path}): {e}") from e
        raise FilterError(f"jax-xla: bad apply entry {type(target)}")

    def _load_pickled(self, path: str, ext: str) -> ModelDef:
        """Params-file format: a dict with ``apply`` = "module:callable"
        import path, ``params`` = weight pytree, optional ``in_shapes`` /
        ``in_dtypes`` — the framework's analog of a checkpoint file consumed
        by a named architecture (cf. caffe2's two-file init/predict model,
        tensor_filter_caffe2.cc)."""
        if ext == ".pkl":
            import pickle

            with open(path, "rb") as f:
                blob = pickle.load(f)
        else:
            try:
                from flax import serialization
            except ImportError as e:
                raise FilterError(
                    f"jax-xla: .msgpack needs flax: {e}") from None
            with open(path, "rb") as f:
                blob = serialization.msgpack_restore(f.read())
        if not isinstance(blob, dict) or "apply" not in blob:
            raise FilterError(
                f"jax-xla: {path} must hold a dict with an 'apply' "
                "\"module:callable\" entry")
        fn = self._resolve_apply(blob["apply"], path)
        in_spec = None
        if blob.get("in_shapes") is not None:
            in_spec = TensorsSpec.from_shapes(
                blob["in_shapes"], blob.get("in_dtypes", np.float32))
        return ModelDef(fn, blob.get("params"), in_spec, name=path)

    # -- compile -------------------------------------------------------------

    def _chain_digest(self) -> Optional[str]:
        """Ordered identity of every fused stage baked into this
        instance's executables (transform prologues + decoder
        epilogue), or None when ANY fused stage is un-digestable —
        the caller must then keep the program out of the persistent
        cache, because a wrong hit is the one failure mode a compile
        cache must never have.  Empty string: nothing is fused."""
        parts: List[str] = []
        for c in self._pre_chains:
            if not hasattr(c, "digest"):
                return None
            parts.append("pre:" + c.digest())
        for p in self._post_fns:
            dig = getattr(p, "chain_digest", None)
            if dig is None:
                return None
            parts.append("post:" + dig)
        return ";".join(parts)

    def _persist_key(self, model: ModelDef, in_spec: Any,
                     bucket: int) -> Optional[str]:
        """Persistent-cache key for one executable of this instance
        (``runtime/compilecache.py``), or None when the cache is
        disarmed — or when a fused stage carries no digest.  Fused
        whole-graph programs key on the model digest PLUS the ordered
        chain digest (transform op chains, decoder epilogue config), so
        they get warm-process cold starts like plain models do while a
        changed stage config misses instead of wrongly hitting."""
        from ..runtime import compilecache as _pcache

        if not _pcache.enabled():
            return None
        chain = self._chain_digest()
        if chain is None:
            return None
        model_dig = _pcache.model_digest(model)
        if chain:
            model_dig = f"{model_dig}+chain:{_chain_sha(chain)}"
        placement = self._placement.key if self._placement is not None \
            else ("dev", self._dev_kind or "")
        return _pcache.make_key(model_dig, in_spec,
                                bucket, placement,
                                donate=self._donate)

    def _normalized_fn(self, model: ModelDef, in_spec: TensorsSpec):
        """The per-frame computation as one traceable callable: fused
        transform prologue + model + fused decoder epilogue, outputs
        normalized to a tuple.  Shared by the single-frame compile and
        the per-bucket micro-batch compiles (which vmap it)."""
        fn = model.mesh_fn(self._mesh, self._rules) \
            if self._mesh is not None else model.flat_fn(self._device)
        pre = self._pre_fns(in_spec) if self._pre_chains else None
        post = self._post_fns[0] if self._post_fns else None

        def normalized(*inputs):
            if pre is not None:
                inputs = [g(x) for g, x in zip(pre, inputs)]
            out = fn(*inputs)
            out = tuple(out) if isinstance(out, (list, tuple)) else (out,)
            if post is not None:
                # fused downstream epilogue (decoder device overlay):
                # still ONE XLA program, one dispatch
                out = tuple(post(*out))
            return out

        return normalized, pre is not None, post is not None

    def _compile(self, model: ModelDef, in_spec: TensorsSpec,
                 kind: str = "cold") -> _Compiled:
        jax = _jax()
        if self._compile_kind is not None:
            kind = self._compile_kind
        mesh = self._mesh
        t_compile0 = time.perf_counter()
        normalized, with_pre, with_post = self._normalized_fn(model, in_spec)
        kw = {}
        if self._donate:
            kw["donate_argnums"] = tuple(range(in_spec.num_tensors))
        in_shardings = None
        if mesh is not None:
            in_shardings = tuple(
                self._input_sharding(t) for t in in_spec.tensors)
            kw["in_shardings"] = in_shardings
        jitted = jax.jit(normalized, **kw)
        # Infer output schema without running the device: the jit
        # LOWERING yields the out avals AND the executable's static
        # cost (HLO cost analysis — no XLA build, measured ~1 ms) in
        # one trace; eval_shape stays as the fallback for backends
        # whose lowering stage lacks out_info/cost_analysis.
        avals = [jax.ShapeDtypeStruct(t.shape, t.dtype.np_dtype)
                 for t in in_spec.tensors]
        lowered = None
        try:
            try:
                lowered = jitted.lower(*avals)
                out_avals = jax.tree_util.tree_leaves(lowered.out_info)
            except (AttributeError, TypeError):
                lowered = None
                out_avals = jax.tree_util.tree_leaves(
                    jax.eval_shape(normalized, *avals))
        except Exception as e:
            raise FilterError(
                f"jax-xla: model {model.name} rejects input {in_spec}: {e}"
            ) from e
        # compile telemetry: one count per _compile call (`kind` names
        # the path — cold/reshape/reload), seconds = trace+abstract-eval
        # here plus the executable's first invocation (the lazy XLA
        # compile) attributed via the wrapper
        skey = COMPILE_STATS.record(
            kind, time.perf_counter() - t_compile0)
        out_spec = TensorsSpec.from_shapes(
            [o.shape for o in out_avals],
            [np.dtype(o.dtype) for o in out_avals])
        fn = jitted
        if lowered is not None:
            # executable cost capture (obs/xlacost.py): bucket 0 is the
            # single-frame executable; a reshape/reload overwrites the
            # row so the gauges describe what currently serves
            _xlacost.capture(
                model.name, lowered, bucket=0,
                placement=self._placement_label(),
                platform=self._platform(),
                in_bytes=_avals_nbytes(avals),
                out_bytes=_avals_nbytes(out_avals))
            pkey = self._persist_key(model, in_spec, 0)
            if pkey is not None:
                # persistent cache armed: serve the single-frame path
                # AOT off this same lowering too, so a warm-cache
                # process skips the XLA build here exactly like on the
                # bucket path (jit fallback on signature rejection)
                fn = _aot_call(lowered, jitted, pkey=pkey, bucket=0)
        return _Compiled(_timed_first_call(fn, skey), in_spec, out_spec,
                         with_pre=with_pre,
                         with_post=with_post,
                         in_shardings=in_shardings)

    def _input_sharding(self, tspec: TensorSpec):
        """Batch-shard an input over the placement's data axes when its
        leading dim divides the data parallelism; replicate otherwise
        (small/odd inputs — e.g. a batch=1 frame on an 8-chip mesh —
        must still run)."""
        return self._placement.input_sharding(tspec.shape)

    def _pre_fns(self, in_spec: TensorsSpec):
        """Per-input composition of the fused transform chains: traces
        each chain's op fn for the schema flowing into it, so the whole
        prologue + model compiles as one XLA program."""
        specs = list(in_spec.tensors)
        stages = []  # list of per-tensor fn lists, chain-major
        for chain in self._pre_chains:
            stages.append([chain.fn_for(sp) for sp in specs])
            specs = [chain.out_spec_of(sp) for sp in specs]

        def compose(i):
            fns = [st[i] for st in stages]

            def g(x):
                for f in fns:
                    x = f(x)
                return x

            return g

        return [compose(i) for i in range(len(in_spec.tensors))]

    # -- model info ----------------------------------------------------------

    def get_model_info(self) -> Tuple[TensorsSpec, TensorsSpec]:
        c = self._compiled
        if c is None:
            raise FilterError("jax-xla: not configured")
        return c.in_spec, c.out_spec

    def set_input_info(self, in_spec: TensorsSpec
                       ) -> Tuple[TensorsSpec, TensorsSpec]:
        """Reshape by recompiling for the new schema (XLA retraces; static
        shapes per schema — SURVEY.md §7 'Dynamic shapes vs XLA').

        Shared instances (``open_shared``): re-negotiating a schema the
        executable already serves is idempotent (every sharer negotiates
        the same caps — only the first pays the compile), while an
        actual reshape is rejected when other sharers still depend on
        the current schema (one pipeline must not recompile the model
        under another's feet)."""
        if self._shared_refs > 0:
            with self._swap_lock:
                c = self._compiled
            if c is not None and not self._pre_chains and not self._post_fns \
                    and in_spec.is_compatible(c.in_spec):
                return c.in_spec, c.out_spec
            if self._shared_refs > 1:
                raise FilterError(
                    f"jax-xla: model {self._model.name if self._model else '?'} "
                    f"is shared by {self._shared_refs} filters; a sharer "
                    f"cannot reshape it to {in_spec} — sharers must "
                    f"negotiate identical input schemas")
        c = self._compile(self._model, in_spec, kind="reshape")
        with self._swap_lock:
            self._compiled = c
        with self._batch_lock:
            # bucket executables are keyed by in_spec, so entries for the
            # old schema are dead weight; drop them all
            self._batch_exec.clear()
        return c.in_spec, c.out_spec

    # -- hot path ------------------------------------------------------------

    def invoke(self, inputs: Sequence[Any]) -> List[Any]:
        c = self._compiled
        if c is None:
            raise FilterError("jax-xla: not configured")
        if c.in_shardings is not None:
            # Mesh path: place each frame per the executable's sharding
            # (scatter over the data axis rides ICI; already-matching
            # device arrays pass through untouched).
            jax = _jax()
            inputs = [
                x if hasattr(x, "sharding")
                and s.is_equivalent_to(x.sharding, getattr(x, "ndim", 0))
                else self._put_input(jax, x, s)
                for x, s in zip(inputs, c.in_shardings)]
        else:
            dev = self._device
            if dev is not None:
                # Honor accelerator=: route inputs to the selected device
                # unless already resident there (committed params also pin
                # the compute, but fn-only models have no params to pin).
                inputs = [
                    x if hasattr(x, "devices") and dev in x.devices()
                    else self._put_input(_jax(), x, dev)
                    for x in inputs]
        out = c.jitted(*inputs)
        DISPATCH_STATS.count("filter")
        if self._placement is not None:
            # per-shard attribution (obs/meshstat.py): the leading dim
            # batch-shards over the data axes when divisible, else the
            # input was replicated onto every chip
            b = 1
            if c.in_spec.tensors and c.in_spec.tensors[0].shape:
                b = int(c.in_spec.tensors[0].shape[0] or 1)
            self._record_mesh(
                slots=b, frames=b,
                sharded=b % self._placement.data_axis_size == 0)
        return list(out)

    @staticmethod
    def _put_input(jax, x, where):
        """``device_put`` one input to a device/sharding, counting the
        host→device crossing into the transfer ledger (byte-exact; a
        device→device reshard counts too — it crosses the boundary the
        roundtrip floor is made of)."""
        if not _xfer.ACTIVE:
            return jax.device_put(x, where)
        t0 = time.perf_counter()
        y = jax.device_put(x, where)
        _xfer.record("h2d", "input", int(getattr(x, "nbytes", 0)),
                     time.perf_counter() - t0)
        return y

    def _record_mesh(self, slots: int, frames: int,
                     sharded: bool, local: bool = False) -> None:
        """Feed one mesh dispatch into the per-shard attribution store
        (keyed by model name, like the executable cost rows).  The
        placement's full data-axes tuple goes along, so a multi-tier
        window (``dcn.data`` x ``data``) attributes over every shard
        it actually spread across.  ``local=True`` (the stacked-window
        path) restricts a MULTI-PROCESS placement to its local (ICI)
        data axes: this process only sees its own ``slots``/``frames``
        slice of the global window, so splitting them over the global
        shard product would zero every count — multi-process mesh
        attribution is per-process-local by design
        (Documentation/serving.md)."""
        rp = self._placement
        axes = rp.data_axes if rp is not None else self._data_axis
        if local and rp is not None and rp.num_processes > 1:
            from ..parallel.placement import DCN_PREFIX

            axes = tuple(a for a in rp.data_axes
                         if not a.startswith(DCN_PREFIX)) or axes
        _meshstat.record_dispatch(
            self._model.name if self._model is not None else "?",
            self._mesh, axes, slots, frames, sharded)

    # -- micro-batched hot path ----------------------------------------------

    def _compile_batched(self, model: ModelDef, in_spec: TensorsSpec,
                         bucket: int):
        """One executable per (in_spec, bucket): takes ``bucket`` frames'
        tensors as flat args (frame-major), stacks each input along a new
        leading micro-batch axis INSIDE the program, vmaps the per-frame
        computation over it, and returns per-frame output tensors — so a
        whole window is exactly one XLA dispatch, stack/unstack included.

        Multi-chip: the micro-batch axis is sharded over the mesh's data
        axis (the same ``_data_axis`` the single-frame path batch-shards
        over) via a sharding constraint on the stacked arrays, so a
        ``mesh="data:-1"`` filter spreads the window across chips instead
        of padding one frame onto all of them."""
        jax = _jax()
        import jax.numpy as jnp

        t_compile0 = time.perf_counter()
        normalized, _, _ = self._normalized_fn(model, in_spec)
        nt = in_spec.num_tensors
        constraint = None
        if self._placement is not None:
            # the placement layer owns the divisibility rule: shard the
            # stacked micro-batch axis over the data axes when the
            # window splits evenly, else leave it replicated
            constraint = self._placement.window_sharding(bucket)

        def batched(*flat):
            stacked = [jnp.stack([flat[i * nt + j] for i in range(bucket)])
                       for j in range(nt)]
            if constraint is not None:
                stacked = [jax.lax.with_sharding_constraint(s, constraint)
                           for s in stacked]
            outs = jax.vmap(normalized)(*stacked)
            per_frame = []
            for i in range(bucket):
                per_frame.extend(o[i] for o in outs)
            return tuple(per_frame)

        kw = {}
        if self._donate:
            kw["donate_argnums"] = tuple(range(bucket * nt))
        jitted = jax.jit(batched, **kw)
        # executable cost capture for this bucket's window program: ONE
        # trace — the capture's Lowered is also what serves dispatches
        # (AOT-compiled on the first call, so the XLA build stays lazy
        # and first-call-attributed exactly as before; jit's own call
        # path would re-trace since lower() doesn't seed its cache)
        lowered = None
        try:
            avals = [jax.ShapeDtypeStruct(t.shape, t.dtype.np_dtype)
                     for _ in range(bucket) for t in in_spec.tensors]
            lowered = jitted.lower(*avals)
            _xlacost.capture(
                model.name, lowered, bucket=bucket,
                placement=self._placement_label(),
                platform=self._platform(),
                in_bytes=_avals_nbytes(avals),
                out_bytes=_avals_nbytes(
                    jax.tree_util.tree_leaves(lowered.out_info)))
        except Exception:  # noqa: BLE001 - capture must not break compile
            lowered = None
        skey = COMPILE_STATS.record(
            "bucket", time.perf_counter() - t_compile0, bucket=bucket)
        fn = _aot_call(lowered, jitted,
                       pkey=self._persist_key(model, in_spec, bucket),
                       bucket=bucket) if lowered is not None else jitted
        return _timed_first_call(fn, skey)

    def _compile_batched_stacked(self, model: ModelDef,
                                 in_spec: TensorsSpec, bucket: int):
        """The mesh-placement window executable: takes ONE
        ``(global_bucket, ...)`` stacked array per input tensor with
        the micro-batch axis sharded over the placement's data axes
        via ``in_shardings`` — each shard's bytes travel straight to
        its own device instead of landing replicated and resharding
        inside the program — vmaps the per-frame computation, and
        returns the stacked outputs under the same batch sharding (the
        caller demuxes per-frame results).  On a multi-process
        placement ``global_bucket = num_processes * bucket``: every
        process stacks its OWN window and the dispatch spans the fleet
        (per-process window formation, globally sharded dispatch)."""
        jax = _jax()
        rp = self._placement
        t_compile0 = time.perf_counter()
        normalized, _, _ = self._normalized_fn(model, in_spec)
        nt = in_spec.num_tensors
        gbucket = bucket * rp.num_processes
        sharding = rp.batch_sharding()

        def batched(*stacked):
            outs = jax.vmap(normalized)(*stacked)
            return tuple(outs)

        # out_shardings pinned to the batch sharding: the demux relies
        # on each process's rows being addressable locally
        kw = {"in_shardings": (sharding,) * nt,
              "out_shardings": sharding}
        if self._donate:
            kw["donate_argnums"] = tuple(range(nt))
        jitted = jax.jit(batched, **kw)
        lowered = None
        try:
            avals = [jax.ShapeDtypeStruct((gbucket,) + tuple(t.shape),
                                          t.dtype.np_dtype)
                     for t in in_spec.tensors]
            lowered = jitted.lower(*avals)
            _xlacost.capture(
                model.name, lowered, bucket=gbucket,
                placement=self._placement_label(),
                platform=self._platform(),
                in_bytes=_avals_nbytes(avals),
                out_bytes=_avals_nbytes(
                    jax.tree_util.tree_leaves(lowered.out_info)))
        except Exception:  # noqa: BLE001 - capture must not break compile
            lowered = None
        skey = COMPILE_STATS.record(
            "bucket", time.perf_counter() - t_compile0, bucket=gbucket)
        # the stacked window program takes ONE (gbucket, ...) array per
        # tensor where the flat program takes bucket*nt flat args — the
        # "stacked" tag keys them apart in the persistent cache
        fn = _aot_call(lowered, jitted,
                       pkey=self._persist_key(
                           model, ("stacked", in_spec), gbucket),
                       bucket=gbucket) if lowered is not None else jitted
        return _timed_first_call(fn, skey)

    def _invoke_batched_stacked(self, frames: Sequence[Sequence[Any]],
                                bucket: int, c: _Compiled,
                                model: ModelDef) -> List[List[Any]]:
        """Mesh-placement window dispatch: stack the window ONCE on the
        host (pad slots replay the last frame; ``np.stack`` copies, so
        donation can never consume a caller's buffer twice), place each
        stacked tensor with the batch axis sharded over the data axes,
        and run one XLA dispatch.  Replaces the flat per-frame feed —
        which landed every frame replicated on the mesh and resharded
        inside the program — with bytes that go straight to their own
        shard's device."""
        rp = self._placement
        n = len(frames)
        key = (c.in_spec, bucket, "stacked")
        with self._batch_lock:
            jitted = self._batch_exec.get(key)
            if jitted is not None:
                self.batch_cache_hits += 1
                self._cache_by_bucket.setdefault(bucket, [0, 0])[0] += 1
        if jitted is None:
            jitted = self._compile_batched_stacked(model, c.in_spec,
                                                   bucket)
            with self._batch_lock:
                self.batch_cache_misses += 1
                self._cache_by_bucket.setdefault(bucket, [0, 0])[1] += 1
                if self._compiled is c:
                    self._batch_exec[key] = jitted
        pad_rows = bucket - n
        stacked: List[np.ndarray] = []
        for j in range(c.in_spec.num_tensors):
            rows = [np.asarray(f[j]) for f in frames]
            if pad_rows:
                # pad slots replay the last frame (discarded on demux);
                # they still burn device time — counted below and by
                # the mesh attribution store
                rows.extend(rows[-1:] * pad_rows)
            stacked.append(np.stack(rows))
        if _xfer.ACTIVE:
            per_frame = sum(int(a.nbytes) // bucket for a in stacked)
            t0 = time.perf_counter()
            arrs = rp.feed_window(stacked)
            _xfer.record("h2d", "input", per_frame * n,
                         time.perf_counter() - t0)
            if pad_rows:
                _xfer.record("h2d", "pad", per_frame * pad_rows)
        else:
            arrs = rp.feed_window(stacked)
        out = jitted(*arrs)
        DISPATCH_STATS.count("filter")
        self._record_mesh(slots=bucket, frames=n, sharded=True,
                          local=True)
        if rp.num_processes > 1:
            # globally sharded output: this process demuxes only ITS
            # rows (the window it formed), via the addressable shards
            out = [rp.local_rows(o) for o in out]
        return [[o[i] for o in out] for i in range(n)]

    def invoke_batched(self, frames: Sequence[Sequence[Any]],
                       bucket: int) -> List[List[Any]]:
        """Run ``frames`` (n per-frame input lists, n <= bucket) as ONE
        XLA dispatch padded up to ``bucket``; returns n per-frame output
        lists.  Pad slots replay the last frame (copies when donation is
        on — a buffer must not be donated twice) and their outputs are
        discarded."""
        with self._swap_lock:
            # consistent (model, compiled) snapshot: a concurrent reload
            # swaps both together under this lock
            c = self._compiled
            model = self._model
        if c is None:
            raise FilterError("jax-xla: not configured")
        n = len(frames)
        if n == 0:
            return []
        if n > bucket:
            raise FilterError(
                f"jax-xla: {n} frames exceed bucket {bucket}")
        rp = self._placement
        if rp is not None and rp.window_sharding(bucket) is not None \
                and (rp.num_processes > 1
                     or all(isinstance(x, np.ndarray)
                            for f in frames for x in f)):
            # mesh placement + host frames (or a multi-process
            # placement, where the global dispatch REQUIRES explicit
            # global-array formation): the stack-once sharded window.
            # Device-resident single-process frames keep the flat path
            # below — stacking them on the host would force a d2h
            # round-trip the program-side stack avoids.
            return self._invoke_batched_stacked(frames, bucket, c, model)
        key = (c.in_spec, bucket)
        with self._batch_lock:
            jitted = self._batch_exec.get(key)
            if jitted is not None:
                self.batch_cache_hits += 1
                self._cache_by_bucket.setdefault(bucket, [0, 0])[0] += 1
        if jitted is None:
            jitted = self._compile_batched(model, c.in_spec, bucket)
            with self._batch_lock:
                self.batch_cache_misses += 1
                self._cache_by_bucket.setdefault(bucket, [0, 0])[1] += 1
                if self._compiled is c:
                    self._batch_exec[key] = jitted
                # else: a reload/reshape swapped the model mid-compile
                # and cleared the cache — this window still runs the
                # executable it started with, but caching it would pin
                # the OLD model for every future window of this bucket
        jax = _jax()
        # Explicit placement only when accelerator= picked a NON-default
        # device: for the default device the executable's own arg
        # handling places host arrays on a faster path than a per-frame
        # device_put, and device arrays are already where they belong.
        dev = self._device if self._mesh is None \
            and self._dev_kind is not None else None
        flat: List[Any] = []
        for f in frames:
            for x in f:
                if dev is not None and not (
                        hasattr(x, "devices") and dev in x.devices()):
                    x = self._put_input(jax, x, dev)
                elif _xfer.ACTIVE and isinstance(x, np.ndarray):
                    # batched-window feed: the executable's own arg
                    # handling transfers host arrays — counted at the
                    # feed site (byte-exact; the transfer itself is
                    # not separately timeable, hence duration 0)
                    _xfer.record("h2d", "input", int(x.nbytes))
                flat.append(x)
        if n < bucket:
            last = flat[-len(frames[-1]):]
            for _ in range(bucket - n):
                if self._donate:
                    # a buffer must not be donated twice: each pad slot
                    # gets its own copy of the replayed frame
                    import jax.numpy as jnp

                    for x in last:
                        if _xfer.ACTIVE and isinstance(x, np.ndarray):
                            # copying a HOST replay uploads it: a pad
                            # crossing (device-resident replays copy
                            # on-device and never cross)
                            t0 = time.perf_counter()
                            y = jnp.copy(x)
                            _xfer.record("h2d", "pad", int(x.nbytes),
                                         time.perf_counter() - t0)
                        else:
                            y = jnp.copy(x)
                        flat.append(y)
                else:
                    if _xfer.ACTIVE:
                        for x in last:
                            if isinstance(x, np.ndarray):
                                # host replays re-fed to the executable
                                # transfer again, once per pad slot
                                _xfer.record("h2d", "pad",
                                             int(x.nbytes))
                    flat.extend(last)
        out = jitted(*flat)
        DISPATCH_STATS.count("filter")
        if self._mesh is not None:
            # window attribution: bucket slots over the data axis (pads
            # included — they burn device time, which is the point of
            # the nns_mesh_pad_slots counter and nns-lint NNS509)
            axis = int(self._mesh.shape[self._data_axis])
            self._record_mesh(slots=bucket, frames=n,
                              sharded=bucket % axis == 0)
        nt_out = len(out) // bucket
        return [list(out[i * nt_out:(i + 1) * nt_out]) for i in range(n)]

    # -- double-buffered hot swap (runtime/lifecycle.py drives this) ---------

    def hot_buckets(self) -> Tuple[int, ...]:
        """Bucket sizes with a live window executable right now — the
        set a replacement model must have warm BEFORE the flip, so the
        first post-swap window dispatches instead of compiling."""
        with self._batch_lock:
            return tuple(sorted({int(k[1]) for k in self._batch_exec}))

    def prepare_swap(self, model: Any, buckets: Sequence[int] = (),
                     warm: bool = True) -> "JaxXlaFilter":
        """Load + compile a replacement model OFF the dispatch path:
        returns a fully-configured SHADOW instance (same placement /
        accelerator / custom / fused-chain config as this one, new
        model) whose executables are built — and, with ``warm=True``,
        have paid their lazy first-call XLA build on zero inputs — while
        this instance keeps serving untouched.  :meth:`commit_swap`
        flips the shadow's state in atomically; the lifecycle layer
        (``runtime/lifecycle.py``) also dispatches canary windows
        through the shadow directly.

        ``model`` may be anything ``model=`` accepts, or a bare params
        pytree (dict) — the weights-only swap: the architecture (this
        instance's ``fn``) is kept and only the weights change, which is
        how ``trainers/checkpoint.py`` orbax checkpoints hot-load into
        a serving pool."""
        if self.props is None:
            raise FilterError("jax-xla: not configured (nothing to swap)")
        import dataclasses as _dc

        cur = self._compiled
        if isinstance(model, dict) and "apply" not in model:
            # weights-only swap: same architecture, new params
            if self._model is None or self._model.params is None:
                raise FilterError(
                    "jax-xla: weights-only swap needs a params-carrying "
                    "model to swap into")
            model = ModelDef(self._model.fn, model,
                             self._model.in_spec,
                             name=f"{self._model.name}@weights")
        shadow = type(self)()
        # the shadow compiles the SAME program shape: fused chains ride
        # along (by reference, like set_fused_pre documents), and the
        # negotiated input schema is forced so the executables the flip
        # installs serve the caps already flowing
        shadow._pre_chains = self._pre_chains
        shadow._post_fns = self._post_fns
        shadow._compile_kind = "reload"
        props = _dc.replace(
            self.props, model=model,
            input_spec=cur.in_spec if cur is not None
            else self.props.input_spec,
            # the shadow must not collide with SHARED_MODELS: it is a
            # private staging instance until commit
            shared_key=None)
        shadow.configure(props)
        if cur is not None \
                and shadow._compiled.out_spec != cur.out_spec:
            raise FilterError(
                f"jax-xla: replacement model {shadow.model_name()!r} "
                f"changes the output schema "
                f"({cur.out_spec} -> {shadow._compiled.out_spec}) — a "
                f"hot swap must preserve negotiated caps; restart the "
                f"pipeline to change schemas")
        want = tuple(sorted(set(int(b) for b in buckets)
                            or self.hot_buckets()))
        if warm:
            self._warm_shadow(shadow, want)
        return shadow

    def _warm_shadow(self, shadow: "JaxXlaFilter",
                     buckets: Tuple[int, ...]) -> None:
        """Run the shadow's executables once on zeros: jit builds
        lazily, so without this the first post-flip dispatch would pay
        the XLA build ON the dispatch path — the exact stall
        double-buffering exists to remove.  With the persistent cache
        armed the build is usually a deserialize anyway; warming also
        covers the backends where it is not."""
        from ..runtime.serving import block_all

        c = shadow._compiled
        zeros = [np.zeros(t.shape, t.dtype.np_dtype)
                 for t in c.in_spec.tensors]
        block_all(shadow.invoke(list(zeros)))
        for b in buckets:
            frames = [list(zeros) for _ in range(int(b))]
            outs = shadow.invoke_batched(frames, int(b))
            block_all([o for out in outs for o in out])

    def commit_swap(self, shadow: "JaxXlaFilter") -> None:
        """Atomically adopt a prepared shadow's (model, executable,
        bucket cache): the double-buffer flip.  Serving threads snapshot
        (model, compiled) under ``_swap_lock``, so no dispatch ever
        sees a torn pair; the lifecycle layer additionally flips at a
        window boundary so not even a window straddles the swap."""
        with self._swap_lock:
            self._model = shadow._model
            self._compiled = shadow._compiled
        with self._batch_lock:
            self._batch_exec = dict(shadow._batch_exec)

    # -- events --------------------------------------------------------------

    def handle_event(self, event: Event) -> None:
        if event.kind != EventKind.RELOAD_MODEL:
            return
        if self.props is None or not self.props.is_updatable:
            raise FilterError("jax-xla: model is not updatable")
        # double-buffered reload: the replacement (single-frame AND the
        # currently-hot bucket/window executables — meshed filters
        # included) loads, compiles and warms OFF the dispatch path;
        # the old executables serve until the atomic flip.  The old
        # path cleared _batch_exec instead, which made the first
        # post-reload window recompile INLINE on the dispatch path —
        # on a meshed filter that stall was the whole stacked build.
        shadow = self.prepare_swap(event.data["model"])
        self.commit_swap(shadow)


def export_model(fn: Callable, example_inputs: Sequence[Any], path: str,
                 params: Any = None) -> str:
    """Serialize a jitted computation to a ``.jaxexp`` file loadable via
    ``model=path`` (the framework's on-disk model format)."""
    jax = _jax()
    if params is not None:
        inner = fn

        def fn(*xs):
            return inner(params, *xs)

    exported = jax.export.export(jax.jit(fn))(
        *[jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype)
          if not hasattr(x, "shape") else
          jax.ShapeDtypeStruct(x.shape, x.dtype) for x in example_inputs])
    data = exported.serialize()
    with open(path, "wb") as f:
        f.write(bytes(data))
    return path


def save_params_model(path: str, apply: str, params: Any,
                      in_shapes: Optional[Sequence] = None,
                      in_dtypes: Any = None) -> str:
    """Write a ``.pkl`` params-file loadable via ``model=path``:
    ``apply`` is a "module:callable" import path, params the weight pytree
    (host copies are stored)."""
    import pickle

    jax = _jax()
    host = jax.tree_util.tree_map(np.asarray, params)
    with open(path, "wb") as f:
        pickle.dump({"apply": apply, "params": host,
                     "in_shapes": in_shapes, "in_dtypes": in_dtypes}, f)
    return path

"""``tensor_transform`` — element-wise tensor stream ops, XLA-compiled.

Parity target: /root/reference/gst/nnstreamer/elements/gsttensor_transform.c
(2345 LoC) with its seven modes (gsttensor_transform.h:57-68):
``dimchg, typecast, arithmetic, transpose, stand, clamp, padding`` and the
arithmetic mini-language (``typecast:float32,add:-127.5,div:127.5``),
including multi-op chaining in one instance (gsttensor_transform.md:12-14).

TPU-native redesign: where the reference hand-vectorizes with Orc SIMD
kernels (gsttensor_transform.c:473-483, elements/nnstreamer-orc.orc), here
each negotiated schema compiles ONE jitted XLA computation for the whole op
chain — XLA fuses the elementwise chain into a single VPU kernel, and the
pipeline-level fusion pass can inline it into an adjacent filter's
computation (SURVEY.md §7 stage 4).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from ..core import Buffer, Caps, DType, Tensor, TensorSpec, TensorsSpec
from ..runtime.element import NegotiationError, Pad, TransformElement
from ..runtime.registry import register_element
from ..utils.stats import DISPATCH_STATS


def _jnp():
    import jax.numpy as jnp

    return jnp


# -- option grammar parsing --------------------------------------------------


def parse_arith_ops(option: str) -> List[Tuple[str, object]]:
    """Parse the arithmetic mini-language:
    ``typecast:float32,add:-127.5,div:127.5,per-channel-add:1;2;3``."""
    ops: List[Tuple[str, object]] = []
    for tok in option.split(","):
        tok = tok.strip()
        if not tok:
            continue
        if ":" not in tok:
            raise ValueError(f"arithmetic op missing ':': {tok!r}")
        name, _, arg = tok.partition(":")
        name = name.strip().lower()
        if name == "typecast":
            ops.append(("typecast", DType.from_string(arg)))
        elif name in ("add", "sub", "mul", "div", "pow"):
            ops.append((name, float(arg)))
        elif name.startswith("per-channel-"):
            base = name[len("per-channel-"):]
            if base not in ("add", "sub", "mul", "div"):
                raise ValueError(f"bad per-channel op {name!r}")
            vec = np.array([float(v) for v in arg.split(";")],
                           dtype=np.float64)
            ops.append((f"pc-{base}", vec))
        else:
            raise ValueError(f"unknown arithmetic op {name!r}")
    if not ops:
        raise ValueError(f"empty arithmetic option {option!r}")
    return ops


def _fold_affine(ops, in_dtype=None) -> Optional[tuple]:
    """Fold ``[typecast:float32?] add/sub/mul/div…`` into (a, b, f32)
    with chain(x) == a*x + b, or None when the chain isn't a pure affine
    map (pow, per-channel, mid-chain casts) or when the unfused chain
    would NOT produce float32 — f16/bf16/f64 inputs keep their dtype
    under jax weak-scalar promotion, so folding them to the kernel's f32
    would change the negotiated output schema."""
    a, b = 1.0, 0.0
    out_dt = np.dtype(np.float32)
    has_cast = ops and ops[0][0] == "typecast"
    if not has_cast and in_dtype is not None:
        dt = np.dtype(in_dtype)
        if dt.kind != "f" and dt.name == "bfloat16" or \
                dt.kind == "f" and dt != np.dtype(np.float32):
            return None  # chain would keep f16/bf16/f64 unfused
    for i, (name, arg) in enumerate(ops):
        if name == "typecast":
            if i != 0 or arg.np_dtype != np.dtype(np.float32):
                return None  # kernel computes in f32 only
            out_dt = np.dtype(np.float32)
        elif name == "add":
            b += arg
        elif name == "sub":
            b -= arg
        elif name == "mul":
            a *= arg
            b *= arg
        elif name == "div":
            if arg == 0:
                return None
            a /= arg
            b /= arg
        else:
            return None
    if a == 0:
        return None
    return a, b, out_dt


def _dim_axis(spec: TensorSpec, dim_index: int) -> int:
    """nnstreamer dim index (innermost-first) → numpy axis."""
    return spec.rank - 1 - dim_index


class _OpChain:
    """Compiled representation of one transform instance's op list; builds a
    jittable fn specialized to the negotiated input spec."""

    def __init__(self, mode: str, option: str, acceleration: bool = True,
                 backend: str = "xla"):
        self.mode = mode
        self.option = option
        self.acceleration = acceleration
        self.backend = backend  # "xla" (default) | "pallas" (ops/ kernel)
        # per-(op, dtype) device constants for per-channel operands:
        # the old code called jnp.asarray(arg) inside the op fn, which
        # re-staged the host vector on EVERY uncompiled evaluation (and
        # on every retrace) — one device constant per (op index, dtype)
        # is the steady state the ledger asserts (zero transform h2d)
        self._const_cache: dict = {}

    def _pc_const(self, op_index: int, arr, dtype):
        key = (op_index, np.dtype(dtype).str)
        vec = self._const_cache.get(key)
        if vec is None:
            import jax

            vec = _jnp().asarray(arr, dtype=dtype)
            if isinstance(vec, jax.core.Tracer):
                # created under an abstract trace (eval_shape during
                # negotiation): a tracer must not outlive its trace —
                # return it uncached; the first CONCRETE evaluation
                # populates the cache
                return vec
            self._const_cache[key] = vec
        return vec

    def digest(self) -> str:
        """Stable identity of this op chain for the persistent AOT
        compile-cache key (runtime/compilecache.py).  Everything that
        changes the traced program is in the constructor args — the
        per-channel constants are derived from ``option``, and the
        input schema is keyed separately by the cache."""
        return "|".join((self.mode, self.option,
                         "1" if self.acceleration else "0", self.backend))

    def out_spec_of(self, spec: TensorSpec) -> TensorSpec:
        import jax

        fn = self.fn_for(spec)
        o = jax.eval_shape(
            fn, jax.ShapeDtypeStruct(spec.shape, spec.dtype.np_dtype))
        return TensorSpec.from_shape(o.shape, np.dtype(o.dtype),
                                     name=spec.name)

    def fn_for(self, spec: TensorSpec) -> Callable:
        """Return fn(array) -> array for this op chain on this schema."""
        jnp = _jnp()
        mode, option = self.mode, self.option

        if mode == "typecast":
            dt = DType.from_string(option).np_dtype

            def fn(x):
                return x.astype(dt)

        elif mode == "arithmetic":
            ops = parse_arith_ops(option)
            # acceleration=true is the default and means the XLA-jitted
            # chain (one fused VPU kernel — measured faster than the
            # hand-written Pallas kernel for this memory-bound op, since
            # XLA also fuses neighbors).  backend="pallas" opts into the
            # ops/ kernel explicitly (the Orc-analog escape hatch).
            folded = _fold_affine(ops, spec.dtype.np_dtype) \
                if self.acceleration and self.backend == "pallas" else None
            if folded is not None:
                a, b, out_dt = folded

                def fn(x, _a=a, _b=b, _dt=out_dt):
                    from ..ops import scale_bias_cast

                    return scale_bias_cast(x, _a, _b / _a, _dt)

                return fn

            def fn(x):
                for i, (name, arg) in enumerate(ops):
                    if name == "typecast":
                        x = x.astype(arg.np_dtype)
                    elif name == "add":
                        x = x + arg
                    elif name == "sub":
                        x = x - arg
                    elif name == "mul":
                        x = x * arg
                    elif name == "div":
                        x = x / arg
                    elif name == "pow":
                        x = x ** arg
                    elif name.startswith("pc-"):
                        # per-channel: channel = innermost dim (= last
                        # axis); the operand is a cached DEVICE constant
                        # per (op, dtype) — never re-staged per frame
                        vec = self._pc_const(i, arg, x.dtype)
                        if name == "pc-add":
                            x = x + vec
                        elif name == "pc-sub":
                            x = x - vec
                        elif name == "pc-mul":
                            x = x * vec
                        else:
                            x = x / vec
                return x

        elif mode == "transpose":
            # option "1:0:2:3": new dim i comes from old dim perm[i]
            # (innermost-first) → convert to numpy axes permutation.
            perm = [int(p) for p in option.split(":") if p.strip()]
            rank = spec.rank
            if len(perm) != rank:
                # pad with identity for unspecified outer dims
                perm = perm + list(range(len(perm), rank))
            axes = [rank - 1 - perm[rank - 1 - ax] for ax in range(rank)]

            def fn(x):
                return jnp.transpose(x, axes)

        elif mode == "dimchg":
            # option "from:to" moves dim index from→to (innermost-first):
            # parity with dimchg 0:2 (gsttensor_transform.md).
            f, _, t = option.partition(":")
            f, t = int(f), int(t)
            src_ax = _dim_axis(spec, f)
            dst_ax = _dim_axis(spec, t)

            def fn(x):
                return jnp.moveaxis(x, src_ax, dst_ax)

        elif mode == "stand":
            opt = option.split(":")
            kind = opt[0].strip().lower() or "default"
            per_channel = len(opt) > 1 and opt[1].strip() == "per-channel"
            axis = None if not per_channel else tuple(range(spec.rank - 1))

            def fn(x):
                xf = x.astype(jnp.float32)
                mean = xf.mean(axis=axis, keepdims=per_channel)
                if kind == "default":
                    std = xf.std(axis=axis, keepdims=per_channel)
                    return (xf - mean) / (std + 1e-10)
                elif kind == "dc-average":
                    return xf - mean
                else:
                    raise ValueError(f"unknown stand mode {kind!r}")

        elif mode == "clamp":
            lo, _, hi = option.partition(":")
            lo, hi = float(lo), float(hi)

            def fn(x):
                return jnp.clip(x, lo, hi)

        elif mode == "padding":
            # option "d0b:d0e,d1b:d1e,...[,value:v]" innermost-first
            pads_nns = []
            value = 0.0
            for tok in option.split(","):
                tok = tok.strip()
                if tok.startswith("value:"):
                    value = float(tok[len("value:"):])
                    continue
                b, _, e = tok.partition(":")
                pads_nns.append((int(b), int(e) if e else int(b)))
            pad_width = [(0, 0)] * spec.rank
            for i, (b, e) in enumerate(pads_nns):
                pad_width[_dim_axis(spec, i)] = (b, e)

            def fn(x):
                return jnp.pad(x, pad_width, constant_values=value)

        else:
            raise ValueError(f"unknown transform mode {self.mode!r}")
        return fn


@register_element("tensor_transform")
class TensorTransform(TransformElement):
    FACTORY = "tensor_transform"

    def __init__(self, name=None, mode: str = "", option: str = "",
                 acceleration: bool = True, backend: str = "xla",
                 donate: bool = False, **props):
        self.mode = mode
        self.option = option
        self.acceleration = acceleration
        self.backend = backend  # "xla" (default) | "pallas" opt-in
        # donate=true: the standalone (unfused) chain donates its input
        # buffer to XLA — shape/dtype-preserving chains then transform
        # in place in HBM instead of allocating a second array per
        # frame.  The consumed input is marked (core/buffer.py
        # mark_donated) so a re-read fails loudly.  Fused chains inherit
        # the downstream filter's donation instead.
        self.donate = donate
        super().__init__(name, **props)
        self._chain_def: Optional[_OpChain] = None
        self._fns: List[Callable] = []
        # set by the pipeline fusion pass: this element's op chain was
        # inlined into the downstream jax-xla filter — act as passthrough
        self._fused = False
        self._fusion_filter = None  # the filter holding our op chain
        # (shape, dtype) → jitted fn; LRU-bounded so a genuinely dynamic
        # flexible stream cannot accumulate executables without limit
        self._flex_cache: "OrderedDict" = OrderedDict()

    FLEX_CACHE_MAX = 64

    def _opchain(self) -> _OpChain:
        if self._chain_def is None:
            if not self.mode:
                raise NegotiationError(f"{self.name}: mode not set")
            backend = str(self.backend).lower()
            if backend not in ("xla", "pallas"):
                raise NegotiationError(
                    f"{self.name}: unknown backend {self.backend!r} "
                    "(expected 'xla' or 'pallas')")
            self._chain_def = _OpChain(self.mode, str(self.option),
                                       self.acceleration, backend)
        return self._chain_def

    # -- negotiation ---------------------------------------------------------

    def _unfuse(self) -> None:
        """Back out of fusion: flexible streams compile per-buffer, so the
        pre-negotiation fusion decision is withdrawn and the op chain is
        returned from the downstream filter to this element."""
        self._fused = False
        flt = self._fusion_filter
        self._fusion_filter = None
        if flt is not None and self._chain_def is not None:
            try:
                flt._fused_pre.remove(self._chain_def)
            except ValueError:
                pass

    def propose_src_caps(self, pad: Pad) -> Caps:
        in_spec = self.sinkpad.spec
        if in_spec is None:
            raise NegotiationError(
                f"{self.name}: tensor_transform needs tensor input caps")
        if self._fused and not in_spec.is_static():
            self._unfuse()
        if self._fused:
            return Caps.from_spec(in_spec)  # chain runs inside the filter
        if not in_spec.is_static():
            return Caps.from_spec(in_spec)  # flexible: per-buffer transform
        oc = self._opchain()
        try:
            outs = tuple(oc.out_spec_of(t) for t in in_spec.tensors)
        except (ValueError, TypeError) as e:
            raise NegotiationError(
                f"{self.name}: mode={self.mode} option={self.option!r} "
                f"invalid for {in_spec}: {e}") from e
        return Caps.from_spec(in_spec.with_tensors(outs))

    def caps_negotiated(self, pad: Pad) -> None:
        in_spec = pad.spec
        if self._fused:
            if in_spec is None or not in_spec.is_static():
                self._unfuse()  # flexible after all: run the chain here
            else:
                self._fns = []
                return
        if in_spec is None or not in_spec.is_static():
            self._fns = []
            return
        import jax

        oc = self._opchain()
        kw = {"donate_argnums": (0,)} if self.donate else {}
        self._fns = [jax.jit(oc.fn_for(t), **kw) for t in in_spec.tensors]

    # -- hot path ------------------------------------------------------------

    def _flex_fn(self, spec: TensorSpec) -> Callable:
        """Spec-keyed compile cache for flexible streams: each distinct
        per-buffer schema compiles once, then hits the cache (mirrors the
        filter's schema-specialized executable cache)."""
        key = (spec.shape, spec.dtype)
        fn = self._flex_cache.get(key)
        if fn is None:
            import jax

            kw = {"donate_argnums": (0,)} if self.donate else {}
            fn = jax.jit(self._opchain().fn_for(spec), **kw)
            self._flex_cache[key] = fn
            while len(self._flex_cache) > self.FLEX_CACHE_MAX:
                self._flex_cache.popitem(last=False)
        else:
            self._flex_cache.move_to_end(key)
        return fn

    def transform(self, buf: Buffer) -> Buffer:
        if self._fused:
            return buf  # op chain executes inside the fused filter
        if not self._fns:  # flexible stream: per-buffer schema, cached jit
            fns = [self._flex_fn(t.spec) for t in buf.tensors]
        else:
            fns = self._fns
        out = [Tensor(fn(t.jax())) for fn, t in zip(fns, buf.tensors)]
        DISPATCH_STATS.count("transform", len(fns))
        if self.donate:
            # the dispatch above consumed device-resident inputs
            buf.mark_donated()
        return Buffer(tensors=out, pts=buf.pts, duration=buf.duration,
                      offset=buf.offset, format=buf.format,
                      meta=dict(buf.meta))

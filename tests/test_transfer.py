"""Data-movement observability tests — ISSUE-8 surface.

Transfer-ledger byte-exactness (h2d and d2h, element and pool paths),
weight-placement accounting, pad-slot crossings, residency tagging and
the tracer's crossings-per-frame figure, Chrome-trace xfer sub-spans,
device-memory accounting (CPU-backend graceful fallback included),
flight-recorder trigger paths (element error, breaker open, admission
hard-shed, /dump endpoint), the snapshot-v6 shape, nns-top XFER/DEVICE
rendering, and the nns-bench-diff ``--against`` record-vs-record mode.
"""

import json
import urllib.request

import numpy as np
import pytest

from nnstreamer_tpu.core import Buffer, Tensor, TensorsSpec
from nnstreamer_tpu.elements.basic import AppSink, AppSrc, Queue
from nnstreamer_tpu.elements.filter import TensorFilter
from nnstreamer_tpu.filters.jax_xla import register_model, unregister_model
from nnstreamer_tpu.obs import REGISTRY, LatencyTracer, hooks
from nnstreamer_tpu.obs import transfer as xfer
from nnstreamer_tpu.obs.devicemem import (
    device_memory_summary,
    device_memory_table,
)
from nnstreamer_tpu.obs.flightrec import FLIGHT, FlightRecorder
from nnstreamer_tpu.runtime import Pipeline

SHAPE = (4,)
FRAME_BYTES = 16  # 4 x float32


@pytest.fixture(scope="module", autouse=True)
def _model():
    register_model("_t_xfer", lambda x: x * 2.0 + 1.0,
                   in_shapes=[SHAPE], in_dtypes=np.float32)
    yield
    unregister_model("_t_xfer")


@pytest.fixture(autouse=True)
def _fresh_obs():
    xfer.set_enabled(True)
    xfer.LEDGER.clear()
    FLIGHT.clear()
    yield
    hooks.detach()
    xfer.set_enabled(True)
    FLIGHT.disarm()
    FLIGHT.min_dump_interval_s = 5.0


def _pipeline(name, batch=1, n=32, model="_t_xfer", buckets=""):
    spec = TensorsSpec.from_shapes([SHAPE], np.float32)
    p = Pipeline(name=name)
    src = AppSrc(name="src", spec=spec, max_buffers=n + 4)
    q = Queue(name="q", max_size_buffers=n + 4)
    flt = TensorFilter(name="net", framework="jax-xla", model=model,
                       batch=batch, batch_timeout_ms=5.0,
                       batch_buckets=buckets)
    sink = AppSink(name="out", max_buffers=n + 4)
    p.add(src, q, flt, sink).link(src, q, flt, sink)
    return p, src, flt, sink


def _run(p, src, sink, n=16, drain=True):
    outs = []
    for i in range(n):
        src.push_buffer(Buffer.of(
            np.full(SHAPE, float(i), np.float32), pts=i))
    for _ in range(n):
        b = sink.pull(timeout=10)
        assert b is not None, f"stalled after {len(outs)}"
        if drain:
            for t in b.tensors:
                t.np()
        outs.append(b)
    src.end_of_stream()
    assert p.wait_eos(timeout=10)
    return outs


# -- ledger byte-exactness ----------------------------------------------------


def test_ledger_byte_exact_h2d_and_d2h():
    """Seed single-filter pipeline: h2d input bytes == N x frame
    nbytes (upload at the filter), d2h drain bytes == N x output
    nbytes — exact, warmup-free, and the registry export agrees."""
    n = 16
    p, src, flt, sink = _pipeline("xt_exact", n=n)
    p.start()
    try:
        _run(p, src, sink, n=n)
    finally:
        p.stop()
    h2d_count, h2d_bytes = xfer.LEDGER.totals(
        pipeline="xt_exact", direction="h2d", reason="input")
    assert (h2d_count, h2d_bytes) == (n, n * FRAME_BYTES)
    d2h_count, d2h_bytes = xfer.LEDGER.totals(
        direction="d2h", reason="drain")
    assert (d2h_count, d2h_bytes) == (n, n * FRAME_BYTES)
    # label context: the upload happened while the FILTER owned the buf
    rows = {(r["pipeline"], r["source"]): r
            for r in xfer.LEDGER.snapshot()
            if r["direction"] == "h2d" and r["reason"] == "input"}
    assert ("xt_exact", "net") in rows
    # exported flat counters derive from the same table
    snap = REGISTRY.snapshot()
    fam = snap["metrics"]["nns_transfer_bytes_total"]
    exported = sum(s["value"] for s in fam["samples"]
                   if s["labels"]["pipeline"] == "xt_exact"
                   and s["labels"]["direction"] == "h2d")
    assert exported == n * FRAME_BYTES
    assert "nns_transfer_seconds" in snap["metrics"]
    expo = REGISTRY.exposition()
    assert 'nns_transfer_bytes_total{direction="h2d"' in expo


def test_ledger_batched_feed_and_pad():
    """Micro-batched path: host frames fed to the batched executable
    count as h2d input; a partial window's pad-slot replays count
    under reason=pad."""
    n = 6  # batch=4, pinned bucket → one full window + one padded
    p, src, flt, sink = _pipeline("xt_batch", batch=4, n=n,
                                  buckets="4")
    p.start()
    try:
        _run(p, src, sink, n=n, drain=False)
    finally:
        p.stop()
    c_in, b_in = xfer.LEDGER.totals(
        pipeline="xt_batch", direction="h2d", reason="input")
    assert (c_in, b_in) == (n, n * FRAME_BYTES)
    c_pad, b_pad = xfer.LEDGER.totals(
        pipeline="xt_batch", direction="h2d", reason="pad")
    assert c_pad >= 1 and b_pad == c_pad * FRAME_BYTES


def test_ledger_weights_recorded():
    """Param placement (ModelDef device_put) records reason=weights
    with the exact pytree payload size."""
    w = np.ones((8,), np.float32)
    register_model("_t_xfer_w", lambda p, x: x * p["w"][0],
                   params={"w": w}, in_shapes=[SHAPE],
                   in_dtypes=np.float32)
    try:
        p, src, flt, sink = _pipeline("xt_w", model="_t_xfer_w", n=4)
        p.start()
        try:
            _run(p, src, sink, n=4, drain=False)
        finally:
            p.stop()
        c, b = xfer.LEDGER.totals(direction="h2d", reason="weights")
        assert c == 1 and b == w.nbytes
        assert flt.subplugin is None or True  # stopped; checked via pool
    finally:
        unregister_model("_t_xfer_w")


def test_ledger_disabled_records_nothing():
    xfer.set_enabled(False)
    t = Tensor(np.ones(SHAPE, np.float32))
    t.jax()
    assert xfer.LEDGER.snapshot() == []


# -- residency + tracer crossings --------------------------------------------


def test_buffer_residency_tagging():
    host = Buffer.of(np.ones(SHAPE, np.float32))
    assert host.residency == "host"
    t = Tensor(np.ones(SHAPE, np.float32))
    dev = Buffer(tensors=[Tensor(t.jax())])
    assert dev.residency == "device"
    mixed = Buffer(tensors=[Tensor(np.ones(SHAPE, np.float32)),
                            Tensor(t.jax())])
    assert mixed.residency == "mixed"


def test_tracer_crossings_per_frame_and_xfer_spans():
    """Host source → device filter output: exactly one residency flip
    per frame at the sink boundary, and the sampled frames carry
    ledger xfer sub-spans into the Chrome trace."""
    n = 8
    p, src, flt, sink = _pipeline("xt_trace", n=n)
    with LatencyTracer(sample_every=1) as tr:
        p.start()
        try:
            _run(p, src, sink, n=n, drain=False)
        finally:
            p.stop()
    s = tr.summary()
    assert s["count"] == n
    assert s["crossings_per_frame"] == pytest.approx(1.0)
    recs = tr.records()
    assert all(r["crossings"] == 1 for r in recs)
    assert any(r["xfers"] for r in recs)
    doc = tr.chrome_trace()
    cats = {e["cat"] for e in doc["traceEvents"]}
    assert "xfer" in cats
    names = {e["name"] for e in doc["traceEvents"]
             if e["cat"] == "xfer"}
    assert any(nm.startswith("net:h2d:input") for nm in names)
    assert any("residency host->device" in nm for nm in names)


# -- device memory ------------------------------------------------------------


class _FakeDev:
    def __init__(self, stats):
        self._stats = stats

    def __str__(self):
        return "FakeTPU:0"

    def memory_stats(self):
        if isinstance(self._stats, BaseException):
            raise self._stats
        return self._stats


def test_device_memory_table_fake_device():
    rows = device_memory_table(devices=[_FakeDev(
        {"bytes_in_use": 100, "peak_bytes_in_use": 200,
         "bytes_limit": 400})])
    assert rows == [{"device": "FakeTPU:0", "in_use": 100,
                     "peak": 200, "limit": 400}]
    summary = device_memory_summary(devices=[_FakeDev(
        {"bytes_in_use": 7})])
    assert summary == [{"device": "FakeTPU:0", "in_use": 7}]


def test_device_memory_cpu_backend_graceful():
    """The CPU backend reports None / raises — the table must degrade
    to empty, never error (and the real backend here IS cpu)."""
    assert device_memory_table(devices=[_FakeDev(None)]) == []
    assert device_memory_table(
        devices=[_FakeDev(NotImplementedError())]) == []
    import jax

    assert device_memory_table(devices=jax.devices()) in ([], [
        r for r in device_memory_table(devices=jax.devices())])
    # the registry snapshot carries the table either way
    assert isinstance(REGISTRY.snapshot()["device_memory"], list)


def test_pool_weight_bytes_exported():
    """share-model pool entries export their weight footprint."""
    w = np.ones((16,), np.float32)
    register_model("_t_xfer_pool", lambda p, x: x + p["w"][0],
                   params={"w": w}, in_shapes=[SHAPE],
                   in_dtypes=np.float32)
    try:
        spec = TensorsSpec.from_shapes([SHAPE], np.float32)
        p = Pipeline(name="xt_pool")
        src = AppSrc(name="src", spec=spec, max_buffers=8)
        flt = TensorFilter(name="net", framework="jax-xla",
                           model="_t_xfer_pool", share_model=True)
        sink = AppSink(name="out", max_buffers=8)
        p.add(src, flt, sink).link(src, flt, sink)
        p.start()
        try:
            snap = REGISTRY.snapshot()
            pool = [r for r in snap["pools"]
                    if "_t_xfer_pool" in r["pool"]][0]
            assert pool["weights"]["bytes"] == w.nbytes
            assert pool["weights"]["placement"] in (
                "host", "device", "mesh")
            fam = snap["metrics"]["nns_model_weight_bytes"]
            assert any(s["value"] == w.nbytes for s in fam["samples"])
        finally:
            p.stop()
    finally:
        unregister_model("_t_xfer_pool")


# -- flight recorder ----------------------------------------------------------


def _wait_dumps(n=1, deadline_s=10.0):
    """Dump writes are offloaded off the triggering thread
    (trigger_async) — poll for the files."""
    import time as _time

    t0 = _time.monotonic()
    while len(FLIGHT.dumps) < n and _time.monotonic() - t0 < deadline_s:
        _time.sleep(0.01)
    return FLIGHT.dumps


def _valid_dump(trace_path, snap_path):
    with open(trace_path) as f:
        trace = json.load(f)
    assert isinstance(trace["traceEvents"], list)
    with open(snap_path) as f:
        snap = json.load(f)
    assert snap["snapshot"]["version"] == 10
    return trace, snap


def test_flightrec_element_error_trigger(tmp_path):
    """An uncaught chain error reaching the bus dumps the black box."""
    from nnstreamer_tpu.runtime.element import TransformElement

    FLIGHT.arm(str(tmp_path))
    FLIGHT.min_dump_interval_s = 0.0

    class Boom(TransformElement):
        FACTORY = "t_boom"

        def transform(self, buf):
            raise RuntimeError("injected chain failure")

    spec = TensorsSpec.from_shapes([SHAPE], np.float32)
    p = Pipeline(name="xt_err")
    src = AppSrc(name="src", spec=spec, max_buffers=8)
    boom = Boom(name="boom")
    sink = AppSink(name="out", max_buffers=8)
    p.add(src, boom, sink).link(src, boom, sink)
    p.start()
    try:
        src.push_buffer(Buffer.of(np.ones(SHAPE, np.float32), pts=0))
        deadline = 10.0
        import time as _time

        t0 = _time.monotonic()
        # the error dump is offloaded off the streaming thread — poll
        # for the written files, not just the trigger count
        while not FLIGHT.dumps \
                and _time.monotonic() - t0 < deadline:
            _time.sleep(0.01)
    finally:
        p.stop()
    assert FLIGHT.triggers.get("element-error", 0) >= 1
    assert FLIGHT.dumps, "armed trigger must write a dump"
    _valid_dump(*FLIGHT.dumps[0])
    kinds = {e["kind"] for e in FLIGHT.events()}
    assert "error" in kinds and "trigger" in kinds


def test_flightrec_breaker_open_trigger(tmp_path):
    from nnstreamer_tpu.chaos.retrypolicy import RetryPolicy

    FLIGHT.arm(str(tmp_path))
    FLIGHT.min_dump_interval_s = 0.0
    pol = RetryPolicy(name="t-link", fail_threshold=2, seed=1)
    pol.failure(RuntimeError("x"), what="dial")
    assert FLIGHT.triggers.get("breaker-open", 0) == 0
    pol.failure(RuntimeError("x"), what="dial")
    assert FLIGHT.triggers.get("breaker-open", 0) == 1
    assert _wait_dumps(), "armed trigger must write a dump"
    _valid_dump(*FLIGHT.dumps[-1])


def test_flightrec_hard_shed_trigger(tmp_path):
    """The shed feeder triggers a dump exactly when the ramp is at
    1.0 (hard shed)."""
    FLIGHT.arm(str(tmp_path))
    FLIGHT.min_dump_interval_s = 0.0
    FLIGHT.shed("jax-xla:m", "low", "slo", total_shed=3, hard=False)
    assert FLIGHT.triggers.get("admission-hard-shed", 0) == 0
    FLIGHT.shed("jax-xla:m", "low", "slo", total_shed=9, hard=True)
    assert FLIGHT.triggers.get("admission-hard-shed", 0) == 1
    assert _wait_dumps(), "armed trigger must write a dump"
    trace, snap = _valid_dump(*FLIGHT.dumps[-1])
    shed_marks = [e for e in trace["traceEvents"]
                  if e["name"].startswith("shed")]
    assert shed_marks and shed_marks[-1]["args"]["total_shed"] == 9


def test_flightrec_warn_shed_wiring(tmp_path):
    """serving._warn_shed feeds the recorder (hard=ramp saturated)."""
    from nnstreamer_tpu.runtime.admission import (
        AdmissionController,
        StreamPolicy,
    )
    from nnstreamer_tpu.runtime.serving import ModelPool, PoolEntry

    FLIGHT.arm(str(tmp_path))
    FLIGHT.min_dump_interval_s = 0.0

    class Owner:
        name = "own"

        def post_message(self, msg):
            self.last = msg

    entry = PoolEntry(ModelPool(), ("jax-xla", "m", ""), object(),
                      lambda sp: None)
    adm = AdmissionController(slo_s=0.001)
    for _ in range(64):
        adm.observe(1.0)  # p99 far past the SLO → ramp saturates
    assert adm.shed_probability >= 1.0
    entry.admission = adm
    owner = Owner()
    entry._warn_shed(owner, StreamPolicy(priority=2), adm,
                     reason="slo")
    assert FLIGHT.triggers.get("admission-hard-shed", 0) >= 1


def test_flightrec_dump_endpoint():
    from nnstreamer_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    srv = reg.serve(port=0)
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/dump", timeout=5) as r:
            doc = json.loads(r.read().decode())
        assert isinstance(doc["trace"]["traceEvents"], list)
        assert doc["snapshot"]["version"] == 10
        assert FLIGHT.triggers.get("endpoint", 0) >= 1
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz", timeout=5) as r:
            hz = json.loads(r.read().decode())
        assert "device_memory" in hz
    finally:
        srv.close()


def test_flightrec_rate_limit_and_horizon():
    rec = FlightRecorder(max_events=4, horizon_s=0.0,
                         min_dump_interval_s=3600.0)
    for i in range(8):
        rec.note("k", f"e{i}")
    assert len(rec._events) == 4  # bounded ring
    assert rec.events() == []     # horizon 0: nothing recent enough
    assert rec.trigger("x") is None  # unarmed: no files
    assert rec.triggers["x"] == 1


# -- snapshot v6 + nns-top ----------------------------------------------------


def test_snapshot_v8_shape_golden():
    """The exact top-level snapshot shape: adding a table is a
    deliberate version bump, not a silent append (ISSUE-8 satellite;
    v5 added ``executables`` + ``mesh``, ISSUE-9; v6 added the
    ``control`` table, ISSUE-11; v7 added the ``models`` table —
    the lifecycle version registry, ISSUE-14; v8 adds the ``stages``
    table — pipeline-split handoff/offload rows, ISSUE-18; v9 adds
    ``tenants`` — per-tenant device-second/cost attribution — and
    ``forecasts`` — trend-forecast rule rows + capacity headroom,
    ISSUE-19; v10 adds ``profile`` — the host-execution profiler's
    per-element CPU/run/wait accounts + top stacks, ISSUE-20)."""
    snap = REGISTRY.snapshot()
    assert snap["version"] == 10
    assert sorted(snap.keys()) == [
        "compiles", "control", "device_memory", "executables",
        "forecasts", "host", "links", "mesh", "metrics", "models",
        "pipelines", "pools", "profile", "stages", "tenants", "time",
        "transfers", "version"]
    assert sorted(snap["profile"].keys()) == [
        "elements", "gil_waiters", "profiler", "stacks"]
    assert sorted(snap["control"].keys()) == [
        "actions_total", "audit", "controllers", "last_action",
        "playbooks"]
    for row in snap["transfers"]:
        assert sorted(row.keys()) == [
            "buckets", "bytes", "count", "direction", "pipeline",
            "reason", "seconds", "source"]


def test_nns_top_renders_xfer_and_devicemem():
    from nnstreamer_tpu.obs.top import render

    base = {"time": 100.0, "pipelines": [{
        "pipeline": "p", "playing": True, "elements": [{
            "element": "net", "factory": "tensor_filter",
            "stats": {"buffers_in": 10, "buffers_out": 10}}]}],
        "pools": [], "links": [], "compiles": [],
        "transfers": [{"pipeline": "p", "source": "net",
                       "direction": "h2d", "reason": "input",
                       "count": 10, "bytes": 640, "seconds": 0.0,
                       "buckets": []}],
        "device_memory": [{"device": "TPU:0", "in_use": 2_000_000,
                           "peak": 3_000_000, "limit": 8_000_000}]}
    cur = json.loads(json.dumps(base))
    cur["time"] = 101.0
    cur["pipelines"][0]["elements"][0]["stats"] = {
        "buffers_in": 20, "buffers_out": 20}
    cur["transfers"][0].update(count=20, bytes=1280)
    out = render(cur, base)
    assert "XFER B/s" in out and "X/FRAME" in out
    assert "DEVICE" in out and "TPU:0" in out
    row = [ln for ln in out.splitlines() if "net" in ln][0]
    # 640 B over 1 s, 10 crossings over 10 frames
    assert "640" in row and "1.00" in row


# -- nns-bench-diff --against -------------------------------------------------


def test_bench_diff_against_record(tmp_path, capsys):
    from nnstreamer_tpu.obs.benchgate import main as diff_main

    hist = tmp_path / "h.jsonl"
    recs = [
        {"scenario": "s", "git_sha": "aaa111", "time": 1,
         "scalars": {"value": 10.0, "fps": 100.0}},
        {"scenario": "s", "git_sha": "bbb222", "time": 2,
         "scalars": {"value": 9.95, "fps": 99.0}},
        {"scenario": "other", "git_sha": "ccc333", "time": 3,
         "scalars": {"value": 1.0}},
    ]
    with open(hist, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    # latest (bbb222) vs first (index 0): within default tolerance
    rc = diff_main(["--history", str(hist), "--scenario", "s",
                    "--against", "0"])
    assert rc == 0
    # sha-prefix selector + explicit --record, tight tolerance → fail
    rc = diff_main(["--history", str(hist), "--scenario", "s",
                    "--against", "aaa", "--record", "-1",
                    "--tolerance", "0.001"])
    assert rc == 1
    # selector that matches nothing → missing baseline (exit 2)
    rc = diff_main(["--history", str(hist), "--scenario", "s",
                    "--against", "deadbeef"])
    assert rc == 2
    # --baseline and --against are mutually exclusive
    with pytest.raises(SystemExit):
        diff_main(["--history", str(hist), "--scenario", "s",
                   "--against", "0", "--baseline", "x.json"])
    capsys.readouterr()


def test_bench_diff_exact_direction():
    """direction=exact regresses on a move EITHER way — the
    crossings-per-frame gate (an analytically-known figure, so an
    increase is as much a regression as a drop)."""
    from nnstreamer_tpu.obs.benchgate import diff

    base = {"metrics": {"value": {"baseline": 1.0, "tolerance": 0.0,
                                  "direction": "exact"}}}

    def verdict(v):
        return diff({"scenario": "s", "scalars": {"value": v}},
                    base)["verdict"]

    assert verdict(1.0) == "pass"
    assert verdict(2.0) == "regression"   # extra crossing slipped in
    assert verdict(0.0) == "regression"   # crossings no longer counted
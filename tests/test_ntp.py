"""SNTP client against a mock UDP server (the reference mocks its NTP
util the same way, tests/gstreamer_mqtt/unittest_ntp_util_mock.cc)."""

import socket
import struct
import threading
import time

import pytest

from nnstreamer_tpu.edge.ntputil import (
    NTP_UNIX_DELTA,
    PeerClock,
    get_epoch,
    ntp_epoch_fn,
    offset_and_delay,
    query_server,
    query_server_sample,
)


class MockNtpServer:
    """Answers one SNTP request with a fixed transmit timestamp."""

    def __init__(self, epoch_s: float):
        self.epoch_s = epoch_s
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind(("127.0.0.1", 0))
        self.port = self._sock.getsockname()[1]
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self):
        self._sock.settimeout(5.0)
        try:
            while True:
                data, addr = self._sock.recvfrom(512)
                resp = bytearray(48)
                resp[0] = (0 << 6) | (4 << 3) | 4  # mode=4 (server)
                ntp_sec = int(self.epoch_s) + NTP_UNIX_DELTA
                frac = int((self.epoch_s % 1) * (1 << 32))
                resp[40:48] = struct.pack(">II", ntp_sec, frac)
                self._sock.sendto(bytes(resp), addr)
        except (socket.timeout, OSError):
            pass

    def stop(self):
        self._sock.close()


def test_query_mock_server():
    t = 1_700_000_000.5
    srv = MockNtpServer(t)
    try:
        us = query_server("127.0.0.1", srv.port)
        assert abs(us - t * 1e6) < 1e3  # sub-ms of the mock's clock
    finally:
        srv.stop()


def test_get_epoch_walks_server_list_and_falls_back():
    # first server dead (no listener), second answers
    t = 1_600_000_000.0
    srv = MockNtpServer(t)
    try:
        us = get_epoch([("127.0.0.1", 1), ("127.0.0.1", srv.port)],
                       timeout=0.3)
        assert abs(us - t * 1e6) < 1e3
    finally:
        srv.stop()
    # all dead: local clock fallback
    us = get_epoch([("127.0.0.1", 1)], timeout=0.2)
    assert abs(us - time.time() * 1e6) < 5e6


def test_epoch_fn_caches_and_advances():
    t = 1_500_000_000.0
    srv = MockNtpServer(t)
    try:
        fn = ntp_epoch_fn([("127.0.0.1", srv.port)], refresh_s=60)
        a = fn()
        time.sleep(0.05)
        b = fn()  # cached base + monotonic delta, no second query
        assert b > a
        assert abs((b - a) - 50_000) < 40_000  # ~50ms advance
    finally:
        srv.stop()


def test_offset_and_delay_known_exchange():
    """Remote clock 10 ahead, 1s each way, 0.5s server processing."""
    t1 = 100.0
    t2 = 100.0 + 1.0 + 10.0      # arrives after 1s, remote reads +10
    t3 = t2 + 0.5
    t4 = 100.0 + 1.0 + 0.5 + 1.0
    offset, delay = offset_and_delay(t1, t2, t3, t4)
    assert offset == pytest.approx(10.0)
    assert delay == pytest.approx(2.0)


def test_offset_containment_property():
    """The documented guarantee behind merged traces: remote events
    mapped with the per-exchange offset always land inside the local
    [t1, t4] window, whatever the true (asymmetric) path was."""
    for skew in (-50.0, 0.0, 1e6):
        for up, down in ((0.001, 0.2), (0.2, 0.001), (0.05, 0.05)):
            t1 = 7.0
            t2 = t1 + up + skew
            t3 = t2 + 0.01
            t4 = t1 + up + 0.01 + down
            offset, delay = offset_and_delay(t1, t2, t3, t4)
            assert t1 <= t2 - offset <= t4
            assert t1 <= t3 - offset <= t4
            assert (t3 - offset) - (t2 - offset) == pytest.approx(0.01)
            assert delay == pytest.approx(up + down)


def test_peer_clock_min_delay_filter():
    pc = PeerClock(window=8)
    assert pc.offset == 0.0 and pc.delay is None and len(pc) == 0
    pc.add(offset=5.0, delay=0.10)   # slow sample, skewed offset
    pc.add(offset=4.2, delay=0.01)   # fast sample: wins
    pc.add(offset=6.0, delay=0.50)
    assert pc.offset == 4.2
    assert pc.delay == pytest.approx(0.01)
    assert pc.to_local(10.0) == pytest.approx(5.8)
    # the window ages out the fast sample after 8 more
    for _ in range(8):
        pc.add(offset=1.0, delay=0.2)
    assert pc.offset == 1.0
    o, d = pc.add_exchange(0.0, 2.0, 2.0, 1.0)
    assert (o, d) == (pytest.approx(1.5), pytest.approx(1.0))


def test_query_server_sample_full_exchange():
    t = 1_650_000_000.0
    srv = MockNtpServer(t)
    try:
        s = query_server_sample("127.0.0.1", srv.port)
        assert set(s) == {"epoch_us", "offset_us", "delay_us"}
        assert abs(s["epoch_us"] - t * 1e6) < 1e3
        # offset ≈ mock epoch − real clock (huge, negative): sanity only
        assert abs(s["offset_us"] - (t * 1e6 - time.time() * 1e6)) < 5e6
        assert s["delay_us"] >= 0
    finally:
        srv.stop()


def test_mqtt_sink_accepts_ntp_clock():
    from nnstreamer_tpu.runtime.registry import make

    t = 1_400_000_000.0
    srv = MockNtpServer(t)
    try:
        fn = ntp_epoch_fn([("127.0.0.1", srv.port)])
        snk = make("mqttsink", el_name="mk", epoch_fn=fn)
        assert abs(snk._epoch_us() - t * 1e6) < 1e6
    finally:
        srv.stop()

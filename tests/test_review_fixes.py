"""Regression tests for review findings (converter batching, source-thread
error surfacing, auto-detection, .pkl model files, accelerator routing)."""

import numpy as np
import pytest
from fractions import Fraction

from nnstreamer_tpu.core import Buffer, Caps, CapsStruct, TensorsSpec
from nnstreamer_tpu.elements.basic import AppSink, AppSrc
from nnstreamer_tpu.elements.converter import TensorConverter
from nnstreamer_tpu.runtime import Pipeline
from nnstreamer_tpu.runtime.events import MessageKind


def audio_caps(rate=16000, channels=2):
    return Caps.new(CapsStruct.make(
        "audio/x-raw", format="S16LE", rate=rate, channels=channels,
        framerate=Fraction(0)))


class TestConverterBatching:
    def _run(self, n, frames):
        p = Pipeline()
        src = AppSrc(name="src", caps=audio_caps())
        conv = TensorConverter(name="conv", frames_per_tensor=n,
                               input_dim="2:1600", input_type="int16")
        sink = AppSink(name="out")
        p.add(src, conv, sink).link(src, conv, sink)
        with p:
            for f in frames:
                src.push_buffer(Buffer.of(f))
            src.end_of_stream()
            assert p.wait_eos(timeout=10)
            out = []
            while True:
                b = sink.pull(timeout=0.2)
                if b is None:
                    break
                out.append(b)
        return out, conv

    def test_rank2_frames_batch_without_squaring(self):
        frames = [np.full((1600, 2), i, np.int16) for i in range(4)]
        out, conv = self._run(2, frames)
        # out spec must be 2:1600:2 (not 2:1600:4), two buffers of 2 frames
        assert conv._out_spec.tensors[0].dims == (2, 1600, 2)
        assert len(out) == 2
        assert out[0].tensors[0].shape == (2, 1600, 2)
        np.testing.assert_array_equal(
            out[1].tensors[0].np()[1], np.full((1600, 2), 3, np.int16))

    def test_partial_batch_dropped_at_eos(self):
        frames = [np.zeros((1600, 2), np.int16)] * 3
        out, _ = self._run(2, frames)
        assert len(out) == 1  # one full batch; the odd tail is dropped


class TestErrorSurfacing:
    def test_filter_error_posts_bus_error_not_thread_death(self):
        from nnstreamer_tpu.elements.filter import TensorFilter
        from nnstreamer_tpu.filters.jax_xla import register_model

        def broken(x):
            raise RuntimeError("boom at invoke")

        register_model("broken_model", broken, in_shapes=[(2, 2)])
        p = Pipeline()
        src = AppSrc(name="src",
                     spec=TensorsSpec.from_shapes([(2, 2)], np.float32))
        flt = TensorFilter(name="f", framework="jax-xla",
                           model="broken_model")
        sink = AppSink(name="out")
        p.add(src, flt, sink).link(src, flt, sink)
        errors = []
        p.bus.add_watch(lambda m: errors.append(m)
                        if m.kind == MessageKind.ERROR else None)
        # negotiation fails at eval_shape time -> start() raises, or the
        # error reaches the bus on first buffer; either way it must surface.
        try:
            with p:
                src.push_buffer(
                    Buffer.of(np.zeros((2, 2), np.float32)))
                src.end_of_stream()
                p.wait_eos(timeout=5)
        except Exception:
            return  # surfaced at negotiation: acceptable
        assert errors, "invoke failure must post an ERROR message"


class TestAutoDetect:
    def test_registered_jax_model_name_autodetects(self):
        from nnstreamer_tpu.filters.jax_xla import register_model
        from nnstreamer_tpu.filters.registry import detect_framework

        register_model("autodetect_me", lambda x: x, in_shapes=[(1,)])
        assert detect_framework("autodetect_me") == "jax-xla"

    def test_pkl_roundtrip(self, tmp_path):
        from nnstreamer_tpu.elements.filter import FilterSingle
        from nnstreamer_tpu.filters.jax_xla import save_params_model

        path = str(tmp_path / "tiny.pkl")
        save_params_model(
            path, "tests.test_review_fixes:pkl_apply",
            {"w": np.full((3,), 2.0, np.float32)}, in_shapes=[(3,)])
        with FilterSingle(framework="auto", model=path) as f:
            out = f.invoke([np.ones((3,), np.float32)])
            np.testing.assert_allclose(np.asarray(out[0]), [2.0] * 3)


def pkl_apply(params, x):
    return x * params["w"]


class TestAcceleratorRouting:
    def test_accelerator_cpu_runs_on_cpu(self):
        import jax

        from nnstreamer_tpu.elements.filter import FilterSingle
        from nnstreamer_tpu.filters.jax_xla import register_model

        register_model("accel_test", lambda p, x: x + p["b"],
                       params={"b": np.float32(1)}, in_shapes=[(4,)])
        with FilterSingle(framework="jax-xla", model="accel_test",
                          accelerator="cpu") as f:
            out = f.invoke([np.zeros((4,), np.float32)])[0]
            assert list(out.devices())[0].platform == "cpu"

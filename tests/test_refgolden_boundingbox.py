"""Decode/NMS verified against GENUINELY TRAINED detector outputs
(round-4 verdict #4): the reference records real-model tensors and
golden overlay renders in tests/nnstreamer_decoder_boundingbox/; here
the same tensors run through our reference-compat decode and the
rendered border geometry must match the reference's golden frames
BIT-FOR-BIT outside the label-glyph blocks (which use a font table we
deliberately do not copy — refcompat module doc).

Parity: runTest.sh cases 6 (yolov5), 8 (yolov8);
box_properties/yolo.cc, tensordec-boundingbox.cc draw()/nms().
"""

import os

import numpy as np
import pytest

from nnstreamer_tpu.decoders.refcompat import (
    PIXEL_VALUE,
    draw_reference,
    label_mask,
    ref_iou,
    ref_nms,
    RefDetection,
    yolo_decode,
)

REF = "/root/reference/tests/nnstreamer_decoder_boundingbox"

needs_ref = pytest.mark.skipif(
    not os.path.isdir(REF), reason="reference decoder assets absent")


def _labels(name):
    with open(os.path.join(REF, name), encoding="utf-8") as f:
        return [ln.strip() for ln in f if ln.strip()]


def _golden_vs_render(dets, golden_name, labels, size=320):
    golden = np.fromfile(os.path.join(REF, golden_name),
                         dtype="<u4").reshape(size, size)
    ours = draw_reference(dets, size, size, size, size)
    glyphs = label_mask(dets, labels, size, size, size, size)
    cmp = ~glyphs
    mismatches = int(np.count_nonzero(golden[cmp] != ours[cmp]))
    assert mismatches == 0, (
        f"{mismatches} non-glyph pixels differ from {golden_name} "
        f"({len(dets)} detections)")
    # the comparison must not be vacuous: boxes were actually drawn
    # and the golden actually carries them
    assert np.count_nonzero(ours) > 100
    assert np.count_nonzero(golden[cmp] == PIXEL_VALUE) > 100


class TestYoloGolden:
    @needs_ref
    def test_yolov5_real_model_golden(self):
        arr = np.fromfile(os.path.join(REF, "yolov5_decoder_input.raw"),
                          np.float32).reshape(6300, 85)
        dets = yolo_decode(arr, v8=False, conf_threshold=0.25,
                           iou_threshold=0.45, in_w=320, in_h=320,
                           scaled_output=False)
        assert dets, "real yolov5 output decoded to zero detections"
        _golden_vs_render(dets, "yolov5_result_golden.raw",
                          _labels("coco-80.txt"))

    @needs_ref
    def test_yolov8_real_model_golden(self):
        # dim "84:2100" = 84 contiguous values per box (boxinput[b*84+c])
        arr = np.fromfile(os.path.join(REF, "yolov8_decoder_input.raw"),
                          np.float32).reshape(2100, 84)
        dets = yolo_decode(arr, v8=True, conf_threshold=0.25,
                           iou_threshold=0.45, in_w=320, in_h=320,
                           scaled_output=False)
        assert dets, "real yolov8 output decoded to zero detections"
        _golden_vs_render(dets, "yolov8_result_golden.raw",
                          _labels("coco-80.txt"))

    @needs_ref
    def test_yolov5_track_mode_golden(self):
        arr = np.fromfile(os.path.join(REF, "yolov5_decoder_input.raw"),
                          np.float32).reshape(6300, 85)
        dets = yolo_decode(arr, v8=False, conf_threshold=0.25,
                           iou_threshold=0.45, in_w=320, in_h=320,
                           scaled_output=False)
        for i, d in enumerate(dets):
            d.tracking_id = i + 1  # reference assigns 1-based ids in order
        golden = np.fromfile(
            os.path.join(REF, "yolov5_track_result_golden.raw"),
            dtype="<u4").reshape(320, 320)
        ours = draw_reference(dets, 320, 320, 320, 320)
        glyphs = label_mask(dets, _labels("coco-80.txt"), 320, 320,
                            320, 320, track=True)
        cmp = ~glyphs
        assert int(np.count_nonzero(golden[cmp] != ours[cmp])) == 0


class TestMobilenetSsdGolden:
    """Raw-anchor mobilenet-ssd decode (box_priors.txt) against the
    reference's recorded real-model tensors and goldens — note the
    golden frames are BGRx (videoconvert in the reference pipeline), so
    red is the word 0xFFFF0000 there; ours renders RGBA words."""

    BGRX_RED = np.uint32(0xFFFF0000)

    @needs_ref
    @pytest.mark.parametrize("case", [0, 1])
    def test_real_model_golden(self, case):
        from nnstreamer_tpu.decoders.refcompat import (
            load_box_priors,
            mobilenet_ssd_decode,
        )

        priors = load_box_priors(os.path.join(REF, "box_priors.txt"))
        loc = np.fromfile(
            os.path.join(REF, f"mobilenetssd_tensors.0.{case}"),
            np.float32).reshape(1917, 4)
        sc = np.fromfile(
            os.path.join(REF, f"mobilenetssd_tensors.1.{case}"),
            np.float32).reshape(1917, 91)
        dets = mobilenet_ssd_decode(loc, sc, priors, 0.5, 0.5, 300, 300)
        assert dets, "real ssd output decoded to zero detections"
        golden = np.fromfile(
            os.path.join(REF, f"mobilenetssd_golden.{case}"),
            dtype="<u4").reshape(120, 160)
        ours = draw_reference(dets, 160, 120, 300, 300)
        expected = np.where(ours != 0, self.BGRX_RED, np.uint32(0))
        glyphs = label_mask(dets, _labels("coco_labels_list.txt"),
                            160, 120, 300, 300)
        cmp = ~glyphs
        mm = int(np.count_nonzero(golden[cmp] != expected[cmp]))
        assert mm == 0, f"{mm} non-glyph pixels differ ({len(dets)} dets)"
        assert np.count_nonzero(ours) > 50


class TestPalmGolden:
    """mp-palm-detection against the reference's recorded palm-model
    tensors (RGBA goldens; no labels in the reference pipeline, so the
    comparison is over EVERY pixel)."""

    @needs_ref
    @pytest.mark.parametrize("case", [0, 1])
    def test_real_model_golden(self, case):
        from nnstreamer_tpu.decoders.refcompat import (
            palm_anchors,
            palm_decode,
        )

        anch = palm_anchors(1.0, 1.0, 0.5, 0.5, (8, 16, 16, 16))
        assert anch.shape == (2016, 4)
        boxes = np.fromfile(
            os.path.join(REF, f"palm_detection_input_0.{case}"),
            np.float32).reshape(2016, 18)
        scores = np.fromfile(
            os.path.join(REF, f"palm_detection_input_1.{case}"),
            np.float32)
        dets = palm_decode(boxes, scores, anch, 0.5, 300, 300)
        assert dets, "real palm output decoded to zero detections"
        golden = np.fromfile(
            os.path.join(REF, f"palm_detection_result_golden.{case}"),
            dtype="<u4").reshape(120, 160)
        ours = draw_reference(dets, 160, 120, 300, 300)
        assert int(np.count_nonzero(golden != ours)) == 0
        assert np.count_nonzero(ours) > 50


class TestSsdPostprocessGolden:
    """mobilenet-ssd-postprocess against the reference's recorded
    4-tensor real-model outputs (BGRx goldens, 640x480 input space)."""

    @needs_ref
    @pytest.mark.parametrize("case", [0, 1])
    def test_real_model_golden(self, case):
        from nnstreamer_tpu.decoders.refcompat import ssd_pp_decode

        def t(i):
            return np.fromfile(os.path.join(
                REF, f"mobilenetssd_postprocess_tensors.{i}.{case}"),
                np.float32)

        num, classes, scores = t(0)[0], t(1), t(2)
        boxes = t(3).reshape(100, 4)
        dets = ssd_pp_decode(boxes, classes, scores, int(num), 640, 480)
        assert len(dets) == int(num)
        golden = np.fromfile(
            os.path.join(REF, f"mobilenetssd_postprocess_golden.{case}"),
            dtype="<u4").reshape(120, 160)
        ours = draw_reference(dets, 160, 120, 640, 480)
        expected = np.where(ours != 0, np.uint32(0xFFFF0000),
                            np.uint32(0))
        glyphs = label_mask(dets, _labels("coco_labels_list.txt"),
                            160, 120, 640, 480)
        cmp = ~glyphs
        assert int(np.count_nonzero(golden[cmp] != expected[cmp])) == 0


class TestRefNmsSemantics:
    def test_global_not_class_aware(self):
        # two same-position boxes with different classes: the
        # reference's nms is class-AGNOSTIC, the weaker one dies
        a = RefDetection(10, 10, 50, 50, class_id=1, prob=0.9)
        b = RefDetection(12, 12, 50, 50, class_id=2, prob=0.8)
        kept = ref_nms([a, b], 0.45)
        assert kept == [a]

    def test_strict_threshold(self):
        a = RefDetection(0, 0, 10, 10, class_id=0, prob=0.9)
        b = RefDetection(0, 5, 10, 10, class_id=0, prob=0.8)
        i = ref_iou(a, b)
        # suppression only when iou STRICTLY exceeds the threshold
        assert ref_nms([a, b], i) == [a, b]
        assert ref_nms([a, b], i - 1e-4) == [a]

    def test_iou_plus_one_inclusive(self):
        # identical 1x1 boxes: inclusive intersection (w+1)*(h+1)=4,
        # union 2*1-4 < 0 => the reference clamps negatives to 0
        a = RefDetection(0, 0, 1, 1, class_id=0, prob=0.9)
        assert ref_iou(a, a) == 0.0
        # adjacent boxes sharing only a corner still intersect by 1
        b = RefDetection(1, 1, 1, 1, class_id=0, prob=0.8)
        assert ref_iou(a, b) > 0

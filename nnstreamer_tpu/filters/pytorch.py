"""``pytorch`` filter framework: TorchScript models in the pipeline.

Parity target: the reference's pytorch sub-plugin
(/root/reference/ext/nnstreamer/tensor_filter/tensor_filter_pytorch.cc
— loads a TorchScript file and invokes it through libtorch).  Unlike
the importer backends (tflite/tensorflow → XLA), TorchScript's op
surface is too large to re-import, so this adapter runs the model
through torch itself on the HOST CPU — the same execution model as the
reference's CPU path — and the pipeline moves tensors host↔device at
the filter boundary.  Use it for interop/migration; the XLA-compiled
frameworks are the TPU performance path.
"""

from __future__ import annotations

import os
import threading
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from ..core import TensorsSpec
from .api import FilterError, FilterProps, FilterSubplugin
from .registry import register_filter


def _torch():
    try:
        import torch

        return torch
    except ImportError as e:  # pragma: no cover - torch is baked in
        raise FilterError(f"pytorch: torch unavailable: {e}") from e


@register_filter
class PyTorchFilter(FilterSubplugin):
    NAME = "pytorch"
    ACCELERATORS = ("cpu",)
    ALLOCATE_IN_INVOKE = True

    def __init__(self):
        super().__init__()
        self._model = None
        self._in_spec: Optional[TensorsSpec] = None
        self._out_spec: Optional[TensorsSpec] = None
        # TorchScript modules are not guaranteed thread-safe for
        # concurrent forward calls on one instance
        self._lock = threading.Lock()

    def configure(self, props: FilterProps) -> None:
        super().configure(props)
        torch = _torch()
        model = props.model
        if isinstance(model, str):
            if not os.path.isfile(model):
                raise FilterError(f"pytorch: no such model file {model!r}")
            try:
                self._model = torch.jit.load(model, map_location="cpu")
            except (RuntimeError, ValueError) as e:
                raise FilterError(
                    f"pytorch: cannot load {model!r}: {e}") from e
        elif hasattr(model, "forward"):
            self._model = model  # in-process nn.Module / ScriptModule
        else:
            raise FilterError(
                f"pytorch: unsupported model object {type(model)}")
        self._model.eval()
        if props.input_spec is None:
            raise FilterError(
                "pytorch: input spec required (TorchScript carries no "
                "tensor schema — pass input=/inputtype= or input_spec)")
        self._in_spec = props.input_spec
        self._out_spec = props.output_spec or \
            self._infer_out_spec(self._in_spec)

    def _infer_out_spec(self, in_spec: TensorsSpec) -> TensorsSpec:
        torch = _torch()
        # numpy bridge derives the exact torch dtype — no lookup table
        dummies = [torch.from_numpy(
            np.zeros(tuple(t.shape), t.dtype.np_dtype))
            for t in in_spec.tensors]
        try:
            # forward calls are serialized: TorchScript modules are not
            # thread-safe, and negotiation can race a streaming invoke
            with self._lock, torch.no_grad():
                out = self._model(*dummies)
        except (RuntimeError, TypeError, ValueError) as e:
            raise FilterError(
                f"pytorch: model rejects input {in_spec}: {e}") from e
        outs = self._out_tensors(out)
        try:
            dtypes = [np.dtype(str(o.dtype).replace("torch.", ""))
                      for o in outs]
        except TypeError as e:
            raise FilterError(
                f"pytorch: model output dtype unsupported by the tensor "
                f"core: {e}") from e
        return TensorsSpec.from_shapes(
            [tuple(o.shape) for o in outs], dtypes)

    @staticmethod
    def _out_tensors(out) -> tuple:
        torch = _torch()
        outs = out if isinstance(out, (list, tuple)) else (out,)
        if not all(isinstance(o, torch.Tensor) for o in outs):
            raise FilterError(
                "pytorch: model output must be a Tensor or a flat "
                f"list/tuple of Tensors, got {type(out).__name__}")
        return tuple(outs)

    def close(self) -> None:
        self._model = None

    def get_model_info(self) -> Tuple[TensorsSpec, TensorsSpec]:
        if self._model is None:
            raise FilterError("pytorch: not configured")
        return self._in_spec, self._out_spec

    def set_input_info(self, in_spec: TensorsSpec
                       ) -> Tuple[TensorsSpec, TensorsSpec]:
        # infer FIRST: a rejected reshape must not leave _in_spec and
        # _out_spec describing different schemas
        out_spec = self._infer_out_spec(in_spec)
        self._in_spec, self._out_spec = in_spec, out_spec
        return self._in_spec, self._out_spec

    def invoke(self, inputs: Sequence[Any]) -> List[Any]:
        if self._model is None:
            raise FilterError("pytorch: not configured")
        torch = _torch()
        tins = [torch.from_numpy(np.ascontiguousarray(np.asarray(x)))
                for x in inputs]
        with self._lock, torch.no_grad():
            out = self._model(*tins)
        return [o.numpy() for o in self._out_tensors(out)]

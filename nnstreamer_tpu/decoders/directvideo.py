"""``direct_video`` decoder: tensor → video/x-raw.

Parity target: /root/reference/ext/nnstreamer/tensor_decoder/
tensordec-directvideo.c (:381 register; 410 LoC): uint8 tensors of 1/3/4
channels become GRAY8/RGB/RGBx video (option1 may force BGR ordering).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..core import Buffer, Caps, CapsStruct, DType, Tensor, TensorsSpec
from . import Decoder, register_decoder


_CH_TO_FMT = {1: "GRAY8", 3: "RGB", 4: "RGBx"}


@register_decoder
class DirectVideo(Decoder):
    MODE = "direct_video"

    def _fmt(self, channels: int) -> str:
        if channels not in _CH_TO_FMT:
            raise ValueError(
                f"direct_video: {channels} channels unsupported (1/3/4)")
        fmt = _CH_TO_FMT[channels]
        if self.options[0].upper() == "BGR" and channels == 3:
            fmt = "BGR"
        return fmt

    def out_caps(self, in_spec: TensorsSpec) -> Caps:
        t = in_spec.tensors[0]
        if t.dtype != DType.UINT8:
            raise ValueError("direct_video: input must be uint8")
        ch, w, h = t.dims[0], t.dims[1], t.dims[2] if t.rank > 2 else 1
        return Caps.new(CapsStruct.make(
            "video/x-raw", format=self._fmt(ch), width=w, height=h,
            framerate=in_spec.rate))

    def decode(self, buf: Buffer, in_spec: Optional[TensorsSpec]) -> Buffer:
        t = buf.tensors[0]
        arr = t.np().reshape(t.spec.shape[-3:])  # (H, W, C)
        return Buffer(tensors=[Tensor(np.ascontiguousarray(arr))],
                      pts=buf.pts, duration=buf.duration,
                      meta=dict(buf.meta))

"""Checkpoint backends for in-pipeline training.

Parity target: ``model-save-path`` / ``model-load-path`` on the
reference trainer (gsttensor_trainer.c:96-98).  Two formats:

- file paths (``.pkl``/``.msgpack``) save the jax-xla filter's loadable
  model format (``filters/jax_xla.save_params_model``) — inference
  pipelines hot-load the trained model directly;
- directory paths save **orbax** checkpoints — the TPU-idiomatic
  format: async-safe, multi-host aware (each host writes its shard),
  and restorable onto a different mesh.

Step layout: a checkpoint ROOT directory holds numbered step
subdirectories (``root/100/``, ``root/200/``, …) — the orbax
convention for a continuously-retrained model.  The step helpers below
resolve ``root@123`` / ``root@latest`` references
(``filters/modeluri.py``) to a concrete step directory + version tag,
which is how a serving pool's hot-swap path
(``runtime/lifecycle.py``) loads "the newest trained weights" with an
auditable provenance tag.
"""

from __future__ import annotations

import os
from typing import Any, List, Optional, Tuple


def is_orbax_path(path: str) -> bool:
    """Directory-shaped paths (trailing separator or an extension-less
    basename) use orbax; ANY file extension means a single-file format
    (`.pkl`/`.msgpack` loadable models; unknown extensions still go to
    the file path so `model.ckpt` is never silently turned into an
    orbax directory)."""
    if path.endswith(os.sep) or path.endswith("/"):
        return True
    return os.path.splitext(os.path.basename(path))[1] == ""


def list_steps(root: str) -> List[int]:
    """Numeric step subdirectories of a checkpoint root, ascending."""
    try:
        names = os.listdir(root)
    except OSError:
        return []
    return sorted(int(n) for n in names
                  if n.isdigit() and os.path.isdir(os.path.join(root, n)))


def latest_step(root: str) -> Optional[int]:
    steps = list_steps(root)
    return steps[-1] if steps else None


def step_dir(root: str, step: int) -> str:
    return os.path.join(root, str(int(step)))


def resolve_step_dir(root: str, tag: str) -> Tuple[str, str]:
    """``(step directory, concrete tag)`` for a ``root@tag`` reference:
    ``latest`` picks the highest numbered step; a numeric tag must name
    an existing step.  Raises ``ValueError`` with the available steps —
    the caller (``filters/modeluri.py``) wraps it with the full URI."""
    tag = str(tag).strip()
    if tag.lower() in ("latest", "newest", "last"):
        step = latest_step(root)
        if step is None:
            raise ValueError(
                f"no numeric step directories under {root!r}")
        return step_dir(root, step), str(step)
    if not tag.isdigit():
        raise ValueError(
            f"step tag {tag!r} is neither numeric nor 'latest'")
    path = step_dir(root, int(tag))
    if not os.path.isdir(path):
        avail = list_steps(root)
        raise ValueError(
            f"step {tag} not found (available: "
            f"{avail if avail else 'none'})")
    return path, str(int(tag))


def save_orbax_step(root: str, step: int, pytree: Any) -> str:
    """Save one training step under the step layout (``root/<step>/``)
    and return its directory — the producer side of the
    ``root@latest`` hot-swap reference."""
    path = step_dir(root, step)
    save_orbax(path, pytree)
    return path


def save_orbax(path: str, pytree: Any) -> None:
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, pytree, force=True)
    ckptr.wait_until_finished()


def load_orbax(path: str, template: Optional[Any] = None) -> Any:
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    if template is not None:
        import jax

        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)
            if hasattr(x, "shape") else x, template)
        return ckptr.restore(path, abstract)
    return ckptr.restore(path)

"""SNTP client: NTP-disciplined epoch for cross-host timestamp sync.

Parity target: /root/reference/gst/mqtt/ntputil.c (245 LoC,
``ntputil_get_epoch``): query a list of (host, port) NTP servers in
order, return the first answer as unix epoch microseconds, falling back
to the local clock — the clock source behind ``mqtt-ntp-sync`` so
publisher ``sent_time`` stamps are comparable across hosts
(Documentation/synchronization-in-mqtt-elements.md).

Wire format: 48-byte SNTPv4 packet; the server's transmit timestamp
(seconds since 1900 + 32-bit fraction) converts to the unix epoch.
``MqttSink(epoch_fn=ntp_epoch_fn([...]))`` plugs it into the MQTT
header stamps.
"""

from __future__ import annotations

import socket
import struct
import time
from typing import Callable, List, Optional, Sequence, Tuple

NTP_PORT = 123
#: seconds between the NTP era (1900) and the unix epoch (1970)
NTP_UNIX_DELTA = 2_208_988_800


def _parse_transmit_ts(packet: bytes) -> int:
    """Server transmit timestamp (bytes 40..47) → unix epoch µs."""
    if len(packet) < 48:
        raise ValueError(f"ntp: short packet ({len(packet)}B)")
    sec, frac = struct.unpack(">II", packet[40:48])
    if sec == 0:
        raise ValueError("ntp: empty transmit timestamp")
    usec = (sec - NTP_UNIX_DELTA) * 1_000_000 + (frac * 1_000_000 >> 32)
    return usec


def query_server(host: str, port: int = NTP_PORT,
                 timeout: float = 2.0) -> int:
    """One SNTP round-trip → unix epoch µs from the server clock."""
    req = bytearray(48)
    req[0] = (0 << 6) | (4 << 3) | 3  # LI=0, VN=4, mode=3 (client)
    with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
        s.settimeout(timeout)
        s.sendto(bytes(req), (host, int(port)))
        data, _ = s.recvfrom(512)
    return _parse_transmit_ts(data)


def get_epoch(servers: Optional[Sequence[Tuple[str, int]]] = None,
              timeout: float = 2.0) -> int:
    """Epoch µs from the first answering server; local clock fallback
    (parity: ntputil_get_epoch's host-list walk + default server)."""
    for host, port in servers or ():
        try:
            return query_server(host, port, timeout)
        except (OSError, ValueError):
            continue
    return int(time.time() * 1e6)


def ntp_epoch_fn(servers: Sequence[Tuple[str, int]],
                 refresh_s: float = 60.0) -> Callable[[], int]:
    """Clock callable for ``MqttSink(epoch_fn=...)``: queries NTP at
    most every ``refresh_s`` and advances with the local monotonic
    clock in between (the reference's cacheing TODO, done)."""
    state = {"base_us": None, "base_mono": 0.0}

    def epoch() -> int:
        now = time.monotonic()
        if state["base_us"] is None or \
                now - state["base_mono"] >= refresh_s:
            state["base_us"] = get_epoch(servers)
            state["base_mono"] = now
            return state["base_us"]
        return state["base_us"] + int((now - state["base_mono"]) * 1e6)

    return epoch

"""Mesh-sharded ``tensor_filter`` — multi-chip inference from the element
graph.

The reference scales inference out by offloading a tensor_filter to remote
query-server processes over TCP (/root/reference/gst/nnstreamer/
tensor_query/tensor_query_client.c:673-741).  The TPU-native form is the
``mesh=`` / ``sharding=`` filter properties: ONE pjit-compiled invoke spans
a `jax.sharding.Mesh` and XLA inserts the ICI collectives (SURVEY.md §7.6).
These tests run that exact code path over the 8-virtual-CPU mesh.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nnstreamer_tpu.core import Buffer, TensorsSpec
from nnstreamer_tpu.elements.basic import AppSink, AppSrc
from nnstreamer_tpu.elements.filter import FilterSingle, TensorFilter
from nnstreamer_tpu.elements.transform import TensorTransform
from nnstreamer_tpu.filters import register_model, unregister_model
from nnstreamer_tpu.filters.api import FilterError
from nnstreamer_tpu.runtime import Pipeline, parse_launch

CPUS = jax.devices("cpu")
pytestmark = pytest.mark.skipif(
    len(CPUS) < 8, reason="needs 8 virtual CPU devices")

RNG = np.random.default_rng(7)
W = RNG.standard_normal((16, 8)).astype(np.float32)
B = RNG.standard_normal((8,)).astype(np.float32)


@pytest.fixture(autouse=True)
def _models():
    register_model("sh_mlp", lambda p, x: jnp.dot(x, p["w"]) + p["b"],
                   params={"w": jnp.asarray(W), "b": jnp.asarray(B)},
                   in_shapes=[(8, 16)])
    register_model("sh_add1", lambda x: x + 1.0, in_shapes=[(8, 16)])
    yield
    unregister_model("sh_mlp")
    unregister_model("sh_add1")


def _expected(x):
    return x.astype(np.float32) @ W + B


class TestDeviceIndexGrammar:
    def test_forms(self):
        from nnstreamer_tpu.parallel import parse_device_indices

        assert parse_device_indices("0-3", 8) == (0, 1, 2, 3)
        assert parse_device_indices("4,5,6,7", 8) == (4, 5, 6, 7)
        assert parse_device_indices("0-1,6", 8) == (0, 1, 6)
        assert parse_device_indices("3", 8) == (3,)
        assert parse_device_indices("1, 1, 2", 8) == (1, 2)  # dedup

    def test_errors(self):
        from nnstreamer_tpu.parallel import parse_device_indices

        with pytest.raises(ValueError):
            parse_device_indices("8", 8)
        with pytest.raises(ValueError):
            parse_device_indices("3-1", 8)
        with pytest.raises(ValueError):
            parse_device_indices("", 8)
        with pytest.raises(ValueError):
            parse_device_indices("x", 8)


class TestFilterSingleMesh:
    def test_data_parallel_invoke(self):
        fs = FilterSingle(framework="jax-xla", model="sh_mlp",
                          accelerator="cpu", mesh="data:-1")
        sp = fs.subplugin
        assert sp._mesh is not None
        assert sp._mesh.devices.size == 8
        x = RNG.standard_normal((8, 16)).astype(np.float32)
        out = fs.invoke([x])
        np.testing.assert_allclose(np.asarray(out[0]), _expected(x),
                                   rtol=1e-4, atol=1e-4)
        # output lives on the whole mesh, not one chip
        assert len(out[0].sharding.device_set) == 8

    def test_tensor_parallel_rules(self):
        fs = FilterSingle(framework="jax-xla", model="sh_mlp",
                          accelerator="cpu", mesh="data:4,model:2",
                          sharding="tp")
        sp = fs.subplugin
        # the dense 'w' (16,8) shards its output dim over model:2
        w = sp._model._mesh_params[(sp._mesh, sp._rules)]["w"]
        spec = w.sharding.spec
        assert tuple(spec) == (None, "model")
        x = RNG.standard_normal((8, 16)).astype(np.float32)
        out = fs.invoke([x])
        np.testing.assert_allclose(np.asarray(out[0]), _expected(x),
                                   rtol=1e-4, atol=1e-4)

    def test_batch1_falls_back_to_replicated_input(self):
        fs = FilterSingle(framework="jax-xla", model="sh_mlp",
                          accelerator="cpu", mesh="data:-1",
                          input_spec=TensorsSpec.parse("16:1", "float32"))
        x = RNG.standard_normal((1, 16)).astype(np.float32)
        out = fs.invoke([x])
        np.testing.assert_allclose(np.asarray(out[0]), _expected(x),
                                   rtol=1e-4, atol=1e-4)

    def test_fixed_axes_use_subset_of_devices(self):
        fs = FilterSingle(framework="jax-xla", model="sh_add1",
                          accelerator="cpu", mesh="data:4")
        assert fs.subplugin._mesh.devices.size == 4
        out = fs.invoke([np.zeros((8, 16), np.float32)])
        np.testing.assert_allclose(np.asarray(out[0]), 1.0)

    def test_bad_mesh_raises(self):
        with pytest.raises(FilterError):
            FilterSingle(framework="jax-xla", model="sh_add1",
                         accelerator="cpu", mesh="data:3,model:5")
        with pytest.raises(FilterError):
            FilterSingle(framework="jax-xla", model="sh_add1",
                         accelerator="cpu", mesh="data:-1",
                         sharding="no-such-rules")

    def test_sharding_without_mesh_rejected(self):
        with pytest.raises(FilterError):
            FilterSingle(framework="jax-xla", model="sh_add1",
                         accelerator="cpu", sharding="tp")

    def test_shared_key_does_not_collide_across_mesh_configs(self):
        plain = FilterSingle(framework="jax-xla", model="sh_add1",
                             accelerator="cpu", shared_key="shk")
        meshed = FilterSingle(framework="jax-xla", model="sh_add1",
                              accelerator="cpu", shared_key="shk",
                              mesh="data:-1")
        assert plain.subplugin._compiled.in_shardings is None
        assert meshed.subplugin._compiled.in_shardings is not None

    def test_set_input_info_keeps_mesh(self):
        fs = FilterSingle(framework="jax-xla", model="sh_add1",
                          accelerator="cpu", mesh="data:-1")
        fs.set_input_info(TensorsSpec.parse("4:16", "float32"))
        out = fs.invoke([np.zeros((16, 4), np.float32)])
        assert np.asarray(out[0]).shape == (16, 4)
        assert fs.subplugin._compiled.in_shardings is not None


class TestPipelineMesh:
    def test_parse_launch_mesh_property(self):
        p = parse_launch(
            "appsrc name=src ! tensor_filter framework=jax-xla "
            "model=sh_mlp mesh=data:-1 accelerator=cpu name=f ! "
            "appsink name=out")
        src, f, sink = (p.elements[n] for n in ("src", "f", "out"))
        src.spec = TensorsSpec.parse("16:8", "float32", rate=0)
        x = RNG.standard_normal((8, 16)).astype(np.float32)
        with p:
            src.push_buffer(Buffer.of(x, pts=3))
            src.end_of_stream()
            assert p.wait_eos(timeout=60)
            out = sink.pull(timeout=1)
            assert f.subplugin._mesh is not None
            assert f.subplugin._mesh.devices.size == 8
        np.testing.assert_allclose(out[0].np(), _expected(x),
                                   rtol=1e-4, atol=1e-4)
        assert out.pts == 3

    def test_fused_prologue_compiles_onto_mesh(self):
        # transform chain fuses into the sharded executable: the whole
        # prologue+model is ONE SPMD program (runtime/fusion.py + mesh=)
        p = Pipeline()
        src = AppSrc(name="src",
                     spec=TensorsSpec.parse("16:8", "uint8", rate=0))
        t = TensorTransform(name="t", mode="arithmetic",
                            option="typecast:float32,add:-127.5,div:127.5")
        f = TensorFilter(name="f", framework="jax-xla", model="sh_mlp",
                         accelerator="cpu", mesh="data:-1")
        sink = AppSink(name="out")
        p.add(src, t, f, sink).link(src, t, f, sink)
        x = RNG.integers(0, 255, (8, 16), dtype=np.uint8)
        with p:
            src.push_buffer(Buffer.of(x))
            src.end_of_stream()
            assert p.wait_eos(timeout=60)
            out = sink.pull(timeout=1)
            c = f.subplugin._compiled
            assert c.with_pre and c.in_shardings is not None
        exp = _expected((x.astype(np.float32) - 127.5) / 127.5)
        np.testing.assert_allclose(out[0].np(), exp, rtol=1e-4, atol=1e-4)

    def test_two_stage_pipeline_on_disjoint_submeshes(self):
        # SURVEY §7.6 endgame: stage A occupies chips 0-3, stage B chips
        # 4-7, and the buffer hands off device-to-device between the two
        # NamedShardings (ICI on real hardware) — the TPU-native form of
        # the reference's client/server offload
        # (tensor_query_client.c:673-741).
        p = parse_launch(
            "appsrc name=src ! "
            "tensor_filter framework=jax-xla model=sh_mlp "
            "mesh=data:4 devices=0-3 accelerator=cpu name=a ! "
            "tensor_filter framework=jax-xla model=sh_head "
            "mesh=data:4 devices=4-7 accelerator=cpu name=b ! "
            "appsink name=out")
        register_model("sh_head", lambda x: x * 2.0, in_shapes=[(8, 8)])
        try:
            src, a, b, sink = (p.elements[n]
                               for n in ("src", "a", "b", "out"))
            src.spec = TensorsSpec.parse("16:8", "float32", rate=0)
            x = RNG.standard_normal((8, 16)).astype(np.float32)
            with p:
                src.push_buffer(Buffer.of(x))
                src.end_of_stream()
                assert p.wait_eos(timeout=60)
                out = sink.pull(timeout=1)
                set_a = set(a.subplugin._mesh.devices.flat)
                set_b = set(b.subplugin._mesh.devices.flat)
                assert set_a == set(CPUS[:4])
                assert set_b == set(CPUS[4:8])
                assert not (set_a & set_b)
                # the handoff actually moved the stream: the final output
                # lives on stage B's submesh
                assert out[0].jax().sharding.device_set == set_b
            np.testing.assert_allclose(out[0].np(), _expected(x) * 2.0,
                                       rtol=1e-4, atol=1e-4)
        finally:
            unregister_model("sh_head")

    def test_devices_subset_single_stage(self):
        fs = FilterSingle(framework="jax-xla", model="sh_add1",
                          accelerator="cpu", mesh="data:-1", devices="2,5")
        mesh = fs.subplugin._mesh
        assert set(mesh.devices.flat) == {CPUS[2], CPUS[5]}
        out = fs.invoke([np.zeros((8, 16), np.float32)])
        np.testing.assert_allclose(np.asarray(out[0]), 1.0)

    def test_devices_without_mesh_rejected(self):
        with pytest.raises(FilterError):
            FilterSingle(framework="jax-xla", model="sh_add1",
                         accelerator="cpu", devices="0-3")

    def test_devices_out_of_range_rejected(self):
        with pytest.raises(FilterError):
            FilterSingle(framework="jax-xla", model="sh_add1",
                         accelerator="cpu", mesh="data:-1",
                         devices="0-99")

    def test_shared_key_does_not_collide_across_device_subsets(self):
        lo = FilterSingle(framework="jax-xla", model="sh_add1",
                          accelerator="cpu", shared_key="shk2",
                          mesh="data:4", devices="0-3")
        hi = FilterSingle(framework="jax-xla", model="sh_add1",
                          accelerator="cpu", shared_key="shk2",
                          mesh="data:4", devices="4-7")
        assert set(lo.subplugin._mesh.devices.flat).isdisjoint(
            hi.subplugin._mesh.devices.flat)

    def test_ici_query_offload_onto_submesh(self):
        # The ICI-native offload mode for query semantics: the client
        # pipeline offloads a stage with tensor_query_client, the server
        # stage runs on its OWN submesh (devices=4-7), and because the
        # inproc transport passes Buffers by reference, the only data
        # movement is the device-to-device reshard inside the server
        # filter's invoke — no serialization, no sockets.  Reference
        # analog: tensor_query_client.c:673-741 offloading over TCP.
        from nnstreamer_tpu.core import Caps
        from nnstreamer_tpu.runtime.registry import make

        register_model("sh_ici", lambda p, x: jnp.dot(x, p["w"]) + p["b"],
                       params={"w": jnp.asarray(W), "b": jnp.asarray(B)},
                       in_shapes=[(8, 16)])
        spec = TensorsSpec.parse("16:8", "float32", rate=0)
        try:
            sp = Pipeline(name="ici-server")
            qsrc = make("tensor_query_serversrc", el_name="qsrc",
                        host="inproc-ici", port=7050,
                        connect_type="inproc", id=50,
                        caps=Caps.from_spec(spec))
            flt = make("tensor_filter", el_name="f", framework="jax-xla",
                       model="sh_ici", accelerator="cpu",
                       mesh="data:4", devices="4-7")
            qsink = make("tensor_query_serversink", el_name="qsink", id=50)
            sp.add(qsrc, flt, qsink).link(qsrc, flt, qsink)
            with sp:
                cp = Pipeline(name="ici-client")
                src = AppSrc(name="src", spec=spec)
                cli = make("tensor_query_client", el_name="cli",
                           host="inproc-ici", port=7050,
                           connect_type="inproc", timeout=30000)
                snk = AppSink(name="out")
                cp.add(src, cli, snk).link(src, cli, snk)
                x = RNG.standard_normal((8, 16)).astype(np.float32)
                with cp:
                    src.push_buffer(Buffer.of(x))
                    src.end_of_stream()
                    assert cp.wait_eos(timeout=60)
                    out = snk.pull(timeout=1)
                    # server stage computed on its submesh; the inproc
                    # reply carries the device-resident result by
                    # reference (never serialized)
                    assert out[0].jax().sharding.device_set == \
                        set(CPUS[4:8])
            np.testing.assert_allclose(out[0].np(), _expected(x),
                                       rtol=1e-4, atol=1e-4)
        finally:
            unregister_model("sh_ici")

    def test_mesh_matches_single_device_result(self):
        x = RNG.standard_normal((8, 16)).astype(np.float32)

        def run(**fkw):
            p = Pipeline()
            src = AppSrc(name="src",
                         spec=TensorsSpec.parse("16:8", "float32", rate=0))
            f = TensorFilter(name="f", framework="jax-xla", model="sh_mlp",
                             accelerator="cpu", **fkw)
            sink = AppSink(name="out")
            p.add(src, f, sink).link(src, f, sink)
            with p:
                src.push_buffer(Buffer.of(x))
                src.end_of_stream()
                assert p.wait_eos(timeout=60)
                return sink.pull(timeout=1)[0].np()

        np.testing.assert_allclose(
            run(mesh="data:2,model:4", sharding="mobilenet"), run(),
            rtol=1e-4, atol=1e-4)

"""Model zoo + parallel layer tests.

Modeled on the reference's use of tiny deterministic models as fixtures
(/root/reference/tests/test_models/, SURVEY.md §4): small widths/sizes keep
compiles fast while exercising the real code paths.  Sharding tests run on
the 8 virtual CPU devices (conftest); a mini-convnet stands in for the full
backbone where only the sharding mechanics are under test.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nnstreamer_tpu.models import mobilenet, ssd
from nnstreamer_tpu.parallel import (
    MeshSpec,
    ShardedModel,
    make_mesh,
    shard_params,
    train_step,
)
from nnstreamer_tpu.parallel import collectives


def cpu_devices(n=8):
    devs = jax.devices("cpu")
    if len(devs) < n:
        pytest.skip(f"need {n} cpu devices, have {len(devs)}")
    return devs[:n]


def mini_convnet_init(seed=0, ch=8, classes=8):
    """2-conv + dense stand-in with the same param naming convention as the
    zoo models, so mobilenet_param_rules applies."""
    rng = np.random.default_rng(seed)
    return {
        "stem": mobilenet._conv_init(rng, 3, 3, 3, ch),
        "pw": mobilenet._conv_init(rng, 1, 1, ch, ch * 2),
        "head": mobilenet._dense_init(rng, ch * 2, classes),
    }


def mini_convnet_apply(p, x, train=False):
    x = x.astype(jnp.bfloat16)
    x = mobilenet._conv_bn(p["stem"], x, stride=2, train=train)
    x = mobilenet._conv_bn(p["pw"], x, stride=1, train=train)
    x = jnp.mean(x, axis=(1, 2))
    return mobilenet._dense(p["head"], x).astype(jnp.float32)


class TestMobileNet:
    def test_v1_forward_shape_and_determinism(self):
        p = mobilenet.mobilenet_v1_init(7, 10, width=0.25)
        p2 = mobilenet.mobilenet_v1_init(7, 10, width=0.25)
        np.testing.assert_array_equal(p["stem"]["w"], p2["stem"]["w"])
        x = jnp.ones((2, 32, 32, 3), jnp.float32)
        out = jax.jit(lambda x: mobilenet.mobilenet_v1_apply(p, x))(x)
        assert out.shape == (2, 10) and out.dtype == jnp.float32
        assert np.isfinite(np.asarray(out)).all()

    def test_v2_forward_and_train_mode(self):
        p = mobilenet.mobilenet_v2_init(0, 10, width=0.25)
        x = np.random.default_rng(1).standard_normal(
            (2, 32, 32, 3)).astype(np.float32)
        out = jax.jit(lambda x: mobilenet.mobilenet_v2_apply(p, x))(x)
        out_t = jax.jit(
            lambda x: mobilenet.mobilenet_v2_apply(p, x, train=True))(x)
        assert out.shape == out_t.shape == (2, 10)
        assert np.isfinite(np.asarray(out)).all()

    def test_register_with_filter(self):
        from nnstreamer_tpu.elements.filter import FilterSingle

        mobilenet.register_mobilenet("m_test_v1", width=0.25, num_classes=10,
                                     batch=1, size=32)
        with FilterSingle(framework="jax-xla", model="m_test_v1") as f:
            assert f.out_spec.tensors[0].shape == (1, 10)
            out = f.invoke([np.zeros((1, 32, 32, 3), np.float32)])
            assert np.asarray(out[0]).shape == (1, 10)


class TestSSD:
    def test_heads_match_anchor_count(self):
        p = ssd.ssd_mobilenet_v2_init(0, num_classes=5)
        x = jnp.ones((1, 128, 128, 3), jnp.float32)
        loc, cls = jax.jit(
            lambda x: ssd.ssd_mobilenet_v2_apply(p, x))(x)
        fs = tuple(int(np.ceil(128 / s)) for s in (16, 32, 64, 128, 256, 512))
        anchors = ssd.ssd_anchors(128, fs)
        assert loc.shape[1] == anchors.shape[0]
        assert cls.shape == (1, anchors.shape[0], 5)

    def test_decode_identity_at_zero_regression(self):
        anchors = ssd.ssd_anchors(128, (2, 1, 1, 1, 1, 1))
        loc = jnp.zeros((anchors.shape[0], 4))
        boxes = np.asarray(ssd.decode_boxes(loc, anchors))
        # zero regression must reproduce the anchor itself (corner form)
        np.testing.assert_allclose(
            boxes[:, 2] - boxes[:, 0], anchors[:, 2], rtol=1e-5)
        np.testing.assert_allclose(
            (boxes[:, 1] + boxes[:, 3]) / 2, anchors[:, 1], rtol=1e-4,
            atol=1e-5)

    def test_nms_suppresses_overlap(self):
        boxes = jnp.array([[0, 0, 1, 1], [0, 0, 0.98, 0.98], [2, 2, 3, 3]],
                          jnp.float32)
        scores = jnp.array([0.9, 0.8, 0.7], jnp.float32)
        ob, os_ = ssd.nms_single(boxes, scores, max_out=3, iou_thresh=0.5,
                                 score_thresh=0.1)
        kept = np.asarray(os_) > 0
        assert kept.sum() == 2  # overlapping second box suppressed
        np.testing.assert_allclose(np.asarray(os_)[0], 0.9, rtol=1e-6)

    def test_matrix_nms_suppresses_and_keeps_classes(self):
        # two overlapping boxes, distinct classes: per-class fast NMS
        # must keep each class's best and suppress the duplicate
        boxes = jnp.array([[0, 0, 1, 1], [0, 0, 0.98, 0.98], [2, 2, 3, 3]],
                          jnp.float32)
        scores = jnp.array([  # columns: background, classA, classB
            [0.0, 0.9, 0.1], [0.0, 0.8, 0.1], [0.0, 0.1, 0.7]], jnp.float32)
        b, s, c = ssd.batched_nms(boxes, scores, max_out=4,
                                  score_thresh=0.2)
        kept = np.asarray(s) > 0
        assert kept.sum() == 2
        assert set(np.asarray(c)[kept]) == {1, 2}

    def test_matrix_nms_small_input_smaller_than_max_out(self):
        """Regression: min(pre_topk, A) * (C-1) < max_out must pad, not
        crash top_k (2-class model, few anchors, default max_out)."""
        boxes = jnp.array([[0, 0, 1, 1], [2, 2, 3, 3]], jnp.float32)
        scores = jnp.array([[0.1, 0.9], [0.2, 0.8]], jnp.float32)
        b, s, c = ssd.batched_nms(boxes, scores, max_out=100)
        assert b.shape == (100, 4) and s.shape == (100,) and c.shape == (100,)
        assert (np.asarray(s) > 0).sum() == 2

    def test_end_to_end_detector_fixed_output(self):
        p = ssd.ssd_mobilenet_v2_init(0, num_classes=4)
        fs = tuple(int(np.ceil(64 / s)) for s in (16, 32, 64, 128, 256, 512))
        fn = ssd.ssd_detect_fn(p, ssd.ssd_anchors(64, fs), max_out=7)
        b, s, c = jax.jit(fn)(jnp.zeros((1, 64, 64, 3)))
        assert b.shape == (1, 7, 4) and s.shape == (1, 7) and c.shape == (1, 7)
        assert c.dtype == jnp.int32


class TestMesh:
    def test_mesh_spec_parse_resolve(self):
        spec = MeshSpec.parse("data:-1,model:2")
        assert spec.resolve(8) == (("data", 4), ("model", 2))
        with pytest.raises(ValueError):
            spec.resolve(7)
        with pytest.raises(ValueError):
            MeshSpec.parse("a:-1,b:-1").resolve(8)

    def test_make_mesh(self):
        mesh = make_mesh("data:2,model:4", devices=cpu_devices(8))
        assert mesh.axis_names == ("data", "model")
        assert mesh.devices.shape == (2, 4)


class TestSharded:
    def test_sharded_invoke_matches_single_device(self):
        devs = cpu_devices(8)
        mesh = make_mesh("data:4,model:2", devices=devs)
        p = mini_convnet_init()
        x = np.random.default_rng(0).standard_normal(
            (8, 16, 16, 3)).astype(np.float32)
        ref = np.asarray(mini_convnet_apply(
            jax.device_put(p, devs[0]), jnp.asarray(x)))
        sharded = ShardedModel(mesh, mini_convnet_apply, p)
        out = np.asarray(sharded(jnp.asarray(x)))
        np.testing.assert_allclose(out, ref, rtol=2e-2, atol=2e-2)

    def test_shard_params_places_head_on_model_axis(self):
        mesh = make_mesh("data:4,model:2", devices=cpu_devices(8))
        sp = shard_params(mesh, mini_convnet_init())
        assert tuple(sp["head"]["w"].sharding.spec) == (None, "model")
        assert tuple(sp["pw"]["w"].sharding.spec) == \
            (None, None, None, "model")
        # depthwise-shaped / non-divisible leaves stay replicated
        assert tuple(sp["stem"]["bias"].sharding.spec) == ()

    def test_train_step_runs_and_reduces_loss(self):
        mesh = make_mesh("data:-1,model:2", devices=cpu_devices(8))
        step, p, opt = train_step(mesh, mini_convnet_apply,
                                  mini_convnet_init(classes=4))
        rng = np.random.default_rng(0)
        shard = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec("data"))
        x = jax.device_put(
            rng.standard_normal((8, 16, 16, 3)).astype(np.float32), shard)
        y = jax.device_put(np.arange(8, dtype=np.int32) % 4, shard)
        losses = []
        for _ in range(5):
            p, opt, loss = step(p, opt, x, y)
            losses.append(float(loss))
        assert losses[-1] < losses[0]  # optimizing the same batch must descend


class TestCollectives:
    def test_all_gather_merge(self):
        mesh = make_mesh("data:8", devices=cpu_devices(8))
        x = jax.device_put(
            np.arange(16, dtype=np.float32).reshape(16, 1),
            jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("data")))
        out = collectives.all_gather_merge(mesh, "data", 0)(x)
        np.testing.assert_array_equal(
            np.asarray(out).ravel(), np.arange(16, dtype=np.float32))

    def test_psum_reduce(self):
        mesh = make_mesh("data:8", devices=cpu_devices(8))
        x = jax.device_put(
            np.ones((8, 3), np.float32),
            jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("data")))
        out = np.asarray(collectives.psum_reduce(mesh, "data")(x))
        np.testing.assert_array_equal(out, np.full((1, 3), 8.0))

    def test_ring_shift(self):
        mesh = make_mesh("data:8", devices=cpu_devices(8))
        x = jax.device_put(
            np.arange(8, dtype=np.float32).reshape(8, 1),
            jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("data")))
        out = np.asarray(collectives.ring_shift(mesh, "data", 1)(x)).ravel()
        np.testing.assert_array_equal(out, np.roll(np.arange(8.0), 1))

    def test_ring_attention_matches_reference_softmax(self):
        mesh = make_mesh("data:4", devices=cpu_devices(4))
        rng = np.random.default_rng(0)
        B, S, H = 2, 16, 8
        q, k, v = (rng.standard_normal((B, S, H)).astype(np.float32)
                   for _ in range(3))
        sh = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(None, "data"))
        out = np.asarray(collectives.ring_attention(mesh, "data")(
            jax.device_put(q, sh), jax.device_put(k, sh),
            jax.device_put(v, sh)))
        # reference: plain softmax attention over the full sequence
        s = (q @ k.transpose(0, 2, 1)) / np.sqrt(H)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        np.testing.assert_allclose(out, p @ v, rtol=1e-4, atol=1e-4)

"""Multi-host helpers: hybrid ICI/DCN mesh construction and sharded
compute over it (single-process: DCN axes of size 1, 8 virtual CPU
devices from the conftest XLA flags)."""

import numpy as np
import pytest

from nnstreamer_tpu.parallel.multihost import hybrid_mesh, process_info


def cpu_devices(n):
    import jax

    devs = jax.devices("cpu")
    if len(devs) < n:
        pytest.skip(f"need {n} cpu devices, have {len(devs)}")
    return devs


class TestHybridMesh:
    def test_single_slice_mesh_keeps_axis_names(self):
        devs = cpu_devices(4)
        m = hybrid_mesh([("model", 2), ("data", 2)], devices=devs[:4])
        assert m.axis_names == ("replica", "model", "data")
        assert m.shape == {"replica": 1, "model": 2, "data": 2}

    def test_sharded_compute_over_mesh(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        devs = cpu_devices(8)
        m = hybrid_mesh([("model", 2), ("data", 4)], devices=devs[:8])
        x = np.arange(64, dtype=np.float32).reshape(8, 8)
        s = NamedSharding(m, P("data", "model"))
        xd = jax.device_put(x, s)
        y = jax.jit(lambda a: a * 2 + 1, out_shardings=s)(xd)
        np.testing.assert_array_equal(np.asarray(y), x * 2 + 1)

    def test_insufficient_devices_raises(self):
        devs = cpu_devices(1)
        with pytest.raises(ValueError):
            hybrid_mesh([("model", 64)], devices=devs)

    def test_process_info_single_host(self):
        idx, count = process_info()
        assert idx == 0 and count >= 1

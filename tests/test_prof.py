"""Host-execution profiler (``obs/prof.py``, ISSUE 20).

Covers the three pieces and their surfaces: the deterministic
thread-name registry (coverage on a RUNNING composite pipeline), the
sampling profiler (bounded table + eviction, registry attribution,
collapsed/Perfetto goldens via ``_record`` injection), the exact
per-element run/wait/CPU accounting (crafted slow-chain element;
cpu-sum vs ``time.process_time()``), alert-triggered deep profiles
(once per episode, rate-limited, disabled-inert), and the export
surfaces (snapshot-v10 ``profile`` table, flat families, ``/prof``
endpoint, flight-recorder ``host_stacks`` embed, nns-top PROF section,
the ``nns-prof`` CLI).
"""

import io
import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

from nnstreamer_tpu.core import Buffer, TensorsSpec
from nnstreamer_tpu.elements.basic import AppSink, AppSrc, Queue
from nnstreamer_tpu.obs import REGISTRY
from nnstreamer_tpu.obs import prof
from nnstreamer_tpu.runtime import Pipeline

SHAPE = (4,)


@pytest.fixture(autouse=True)
def _clean_profiler_state():
    yield
    prof.PROFILER.stop()
    prof.PROFILER.clear()
    prof.PROFILER.configure(0.0)
    prof.DEEP.disarm()
    prof.DEEP.clear()
    prof._reset_accounts()


def _spec():
    return TensorsSpec.from_shapes([SHAPE], np.float32)


class SlowSink(AppSink):
    """Crafted run-side load: the chain spins ~spin_s of real CPU in
    the UPSTREAM element's loop thread before queueing the buffer."""

    spin_s = 0.01

    def chain(self, pad, buf):
        t0 = time.monotonic()
        while time.monotonic() - t0 < self.spin_s:
            pass
        return super().chain(pad, buf)


def _slow_pipeline(name):
    p = Pipeline(name=name)
    src = AppSrc(name="src", spec=_spec(), max_buffers=64)
    q = Queue(name="q", max_size_buffers=64)
    sink = SlowSink(name="out", max_buffers=64)
    p.add(src, q, sink).link(src, q, sink)
    return p, src, sink


# -- thread names + registry --------------------------------------------------


def test_thread_name_scheme():
    assert prof.thread_name("watch", "sampler") == "nns:watch:sampler"
    assert prof.thread_name("prof") == "nns:prof"
    assert prof.thread_name("src", "s", pipeline="p", element="e") \
        == "nns:p:e"


def test_named_thread_registers_and_unregisters():
    seen = {}
    release = threading.Event()

    def work():
        seen["info"] = prof.THREADS.lookup(threading.get_ident())
        seen["name"] = threading.current_thread().name
        release.wait(timeout=5)

    t = prof.named_thread("watch", "sampler", work)
    t.start()
    deadline = time.monotonic() + 5
    while "info" not in seen and time.monotonic() < deadline:
        time.sleep(0.01)
    assert seen["name"] == "nns:watch:sampler"
    assert seen["info"]["role"] == "watch"
    assert seen["info"]["owner"] == "sampler"
    assert seen["info"]["name"] == "nns:watch:sampler"
    ident = t.ident
    release.set()
    t.join(timeout=5)
    assert prof.THREADS.lookup(ident) is None  # gone with the thread


def test_registry_coverage_on_running_composite_pipeline():
    """Every runtime thread of a RUNNING composite pipeline carries
    the deterministic ``nns:`` name AND a registry entry — the join
    the profiler, lockdep labels and py-spy output all rely on."""
    p = Pipeline(name="profcov")
    src = AppSrc(name="src", spec=_spec(), max_buffers=32)
    q1 = Queue(name="q1", max_size_buffers=32)
    q2 = Queue(name="q2", max_size_buffers=32)
    sink = AppSink(name="out", max_buffers=32)
    p.add(src, q1, q2, sink).link(src, q1, q2, sink)
    p.start()
    try:
        live = {t.ident: t.name for t in threading.enumerate()
                if t.name.startswith("nns:")}
        assert {"nns:profcov:src", "nns:profcov:q1",
                "nns:profcov:q2"} <= set(live.values())
        for ident, name in live.items():
            info = prof.THREADS.lookup(ident)
            assert info is not None, f"unregistered nns thread {name}"
            assert info["name"] == name
        # element loops carry the (pipeline, element) join key
        by_name = {v["name"]: v for v in prof.THREADS.snapshot()}
        assert by_name["nns:profcov:q1"]["pipeline"] == "profcov"
        assert by_name["nns:profcov:q1"]["element"] == "q1"
    finally:
        src.end_of_stream()
        p.wait_eos(timeout=10)
        p.stop()


def test_registry_inert_when_disabled(monkeypatch):
    monkeypatch.setattr(prof._hooks, "DISABLED", True)
    prof.THREADS.register("x", "y")
    assert prof.THREADS.lookup(threading.get_ident()) is None
    assert prof.element_account("p", "e") is None


# -- sampling profiler --------------------------------------------------------


def test_bounded_table_lowest_count_eviction():
    sp = prof.SamplingProfiler(max_stacks=3)
    for _ in range(5):
        sp._record("a", "f.py:hot")
    for _ in range(3):
        sp._record("b", "f.py:warm")
    sp._record("c", "f.py:cold")
    assert sp.evicted_total == 0
    sp._record("d", "f.py:new")  # 4th stack: the cold one is evicted
    assert sp.evicted_total == 1
    labels = {label for label, _ in sp._table}
    assert labels == {"a", "b", "d"}
    assert sp.samples_total == 10


def test_tick_attributes_samples_through_registry():
    sp = prof.SamplingProfiler()
    release = threading.Event()

    def element_loop_body():
        release.wait(timeout=10)

    t = prof.named_thread("queue", "q0", element_loop_body,
                          pipeline="pipeA", element="q0")
    t.start()
    try:
        deadline = time.monotonic() + 5
        while prof.THREADS.lookup(t.ident) is None \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        sampled = sp.tick()
        assert sampled >= 1 and sp.ticks_total == 1
        assert sp.element_samples().get(("pipeA", "q0"), 0) >= 1
        labels = {label for label, _ in sp._table}
        assert "pipeA:q0" in labels  # pipeline:element, not tid-...
        stack = next(s for (lb, s) in sp._table if lb == "pipeA:q0")
        assert "element_loop_body" in stack  # root-first frames
    finally:
        release.set()
        t.join(timeout=5)


def test_gil_proxy_counts_runnable_threads():
    sp = prof.SamplingProfiler()
    stop = [False]  # plain flag: the spin leaf frame stays `spin`

    def spin():
        n = 0
        while not stop[0]:
            n += 1

    threads = [threading.Thread(target=spin, daemon=True)
               for _ in range(2)]
    for t in threads:
        t.start()
    try:
        time.sleep(0.05)
        sp.tick()
        assert sp.runnable_last >= 2
        assert sp.gil_waiters >= 1  # at most one of them holds the GIL
    finally:
        stop[0] = True
        for t in threads:
            t.join(timeout=5)


def test_start_refuses_unconfigured_and_disabled(monkeypatch):
    sp = prof.SamplingProfiler()
    assert sp.start() is False  # hz 0: unconfigured
    monkeypatch.setenv("NNS_TPU_OBS_DISABLE", "1")
    assert sp.configure(50).start() is False  # kill switch: inert
    assert sp._thread is None and not sp.running
    monkeypatch.delenv("NNS_TPU_OBS_DISABLE")
    assert sp.start() is True
    try:
        assert threading.current_thread().name != sp._thread.name
        assert sp._thread.name == "nns:prof:sampler"
        assert sp.start() is False  # already running
    finally:
        sp.stop()
    assert sp.ticks_total > 0


def test_collapsed_and_ring_goldens():
    sp = prof.SamplingProfiler()
    sp._record("p:q", "a.py:main;a.py:loop", ts=10.0)
    sp._record("p:q", "a.py:main;a.py:loop", ts=11.0)
    sp._record("watch:sampler", "w.py:run", ts=12.0)
    assert sp.collapsed() == (
        "p:q;a.py:main;a.py:loop 2\n"
        "watch:sampler;w.py:run 1")
    # the ring honors its cutoff: only samples newer than now - last_s
    assert sp.ring_collapsed(last_s=1.5, now=12.0) == (
        "p:q;a.py:main;a.py:loop 1\n"
        "watch:sampler;w.py:run 1")
    assert sp.ring_collapsed(last_s=0.5, now=20.0) == ""


def test_chrome_trace_golden_merges_consecutive_samples():
    sp = prof.SamplingProfiler(hz=10.0)
    sp._record("p:q", "a.py:main;a.py:work", ts=1.0)
    sp._record("p:q", "a.py:main;a.py:work", ts=1.1)
    sp._record("p:q", "a.py:main;a.py:idle", ts=1.2)
    doc = sp.chrome_trace()
    assert doc["displayTimeUnit"] == "ms"
    meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert len(meta) == 1 and meta[0]["args"]["name"] == "p:q"
    slices = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert [s["name"] for s in slices] == ["a.py:work", "a.py:idle"]
    assert slices[0]["args"]["samples"] == 2
    assert slices[0]["dur"] == 200000.0  # 2 samples at 10 Hz, in us
    assert slices[0]["ts"] == 1000000.0
    assert slices[1]["args"]["stack"] == "a.py:main;a.py:idle"


def test_top_stacks_and_summary():
    sp = prof.SamplingProfiler(hz=10.0)
    for _ in range(3):
        sp._record("a", "f.py:hot")
    sp._record("b", "f.py:cold")
    top = sp.top_stacks(1)
    assert top == [{"label": "a", "stack": "f.py:hot", "count": 3}]
    s = sp.summary()
    assert s["samples"] == 4 and s["stacks"] == 2
    assert s["running"] is False and s["hz"] == 10.0


# -- exact run/wait/CPU accounting --------------------------------------------


def test_run_wait_split_on_crafted_element():
    """Gapped arrivals + a spinning downstream chain: the queue loop's
    wait side sees the arrival gaps, its run side sees the spin (the
    whole downstream chain runs in the queue's thread), and the CPU
    side stays near the spin (the waits are blocking, not burning)."""
    p, src, sink = _slow_pipeline("profsplit")
    p.start()
    try:
        n, gap = 8, 0.03
        for i in range(n):
            src.push_buffer(Buffer.of(
                np.zeros(SHAPE, np.float32), pts=i))
            time.sleep(gap)
        for _ in range(n):
            assert sink.pull(timeout=10) is not None
        rows = {(r["pipeline"], r["element"]): r
                for r in prof.account_rows()}
        q = rows[("profsplit", "q")]
        assert q["iters"] >= n
        # run >= the spins the chain burned; wait >= the gaps minus
        # scheduling slack; the split must not blur the two
        assert q["run_s"] >= n * SlowSink.spin_s * 0.8, q
        assert q["wait_s"] >= (n - 1) * gap * 0.5, q
        assert q["wait_s"] > q["run_s"], q
        # the source thread waited for pushes and ran ~nothing
        s = rows[("profsplit", "src")]
        assert s["wait_s"] > s["run_s"], s
    finally:
        src.end_of_stream()
        p.wait_eos(timeout=10)
        p.stop()


def test_cpu_sum_stays_within_process_time():
    """The attribution-exactness invariant the --hostprof bench gates:
    summed per-element thread CPU can never exceed the process-wide
    ``time.process_time()`` delta over the same window."""
    before = {(r["pipeline"], r["element"]): r["cpu_s"]
              for r in prof.account_rows()}
    cpu0 = time.process_time()
    p, src, sink = _slow_pipeline("profexact")
    p.start()
    try:
        for i in range(16):
            src.push_buffer(Buffer.of(
                np.zeros(SHAPE, np.float32), pts=i))
        for _ in range(16):
            assert sink.pull(timeout=10) is not None
    finally:
        src.end_of_stream()
        p.wait_eos(timeout=10)
        p.stop()
    process_delta = time.process_time() - cpu0
    acct = sum(r["cpu_s"] - before.get(
        (r["pipeline"], r["element"]), 0.0)
        for r in prof.account_rows())
    assert acct > 0  # the spins are real CPU, and they were accounted
    assert acct <= process_delta * 1.02 + 0.005, \
        (acct, process_delta)


def test_element_account_single_writer_math():
    a = prof.ElementAccount("p", "e")
    a.add(0.5, 0.25, 0.1)
    a.add(-0.1, 0.0, -0.2)  # clock hiccups never go negative
    assert a.wait_s == 0.5 and a.run_s == 0.25 and a.cpu_s == 0.1
    assert a.iters == 2


# -- deep profiles ------------------------------------------------------------


def _wait_captures(deep, n, timeout=10.0):
    deadline = time.monotonic() + timeout
    while len(deep.captures) < n and time.monotonic() < deadline:
        time.sleep(0.02)
    return list(deep.captures)


def test_deep_profile_once_per_episode_and_rate_limited(tmp_path):
    d = prof.DeepProfiler()
    d.arm(str(tmp_path), seconds=0.2, hz=100.0, min_interval_s=60.0)
    assert d.trigger("qfull") is True
    # the SAME episode cannot double-capture: rate-limited out
    assert d.trigger("qfull") is False
    assert d.episodes == 1 and d.skipped == 1
    caps = _wait_captures(d, 1)
    assert len(caps) == 1
    text = open(caps[0]).read()
    first = text.splitlines()[0]
    assert first.startswith("# nns-prof deep capture: reason=qfull")
    assert "seconds=0.2" in first and "hz=100" in first
    # dense host sampling really ran: collapsed lines follow the header
    assert len(text.splitlines()) > 1
    assert os.path.basename(caps[0]) == "deepprof-001-qfull.txt"


def test_deep_profile_interval_elapses_then_fires_again(tmp_path):
    d = prof.DeepProfiler()
    d.arm(str(tmp_path), seconds=0.05, hz=50.0, min_interval_s=0.1)
    assert d.trigger("a") is True
    _wait_captures(d, 1)
    time.sleep(0.15)  # past min_interval: the next episode may fire
    assert d.trigger("b") is True
    caps = _wait_captures(d, 2)
    assert [os.path.basename(c) for c in caps] == [
        "deepprof-001-a.txt", "deepprof-002-b.txt"]


def test_deep_profile_unarmed_and_disabled_inert(tmp_path, monkeypatch):
    d = prof.DeepProfiler()
    assert d.trigger("x") is False  # unarmed: strict no-op
    d.arm(str(tmp_path), seconds=0.05)
    monkeypatch.setenv("NNS_TPU_OBS_DISABLE", "1")
    assert d.trigger("x") is False  # kill switch: inert even armed
    assert d.episodes == 0 and d.captures == []


def test_deep_capture_runs_off_the_calling_thread(tmp_path):
    d = prof.DeepProfiler()
    d.arm(str(tmp_path), seconds=0.3, hz=50.0)
    t0 = time.monotonic()
    assert d.trigger("slow") is True
    # trigger returns immediately; the 0.3 s capture is elsewhere
    assert time.monotonic() - t0 < 0.2
    assert _wait_captures(d, 1)


# -- env activation -----------------------------------------------------------


def test_maybe_start_from_env(tmp_path, monkeypatch):
    monkeypatch.setattr(prof, "_env_checked", False)
    monkeypatch.setenv("NNS_TPU_PROF", "50")
    monkeypatch.setenv("NNS_TPU_PROF_DEEP_DIR", str(tmp_path / "deep"))
    monkeypatch.setenv("NNS_TPU_PROF_DEEP_SECONDS", "0.5")
    monkeypatch.setenv("NNS_TPU_PROF_DEEP_HZ", "75")
    prof.maybe_start_from_env()
    try:
        assert prof.PROFILER.running and prof.PROFILER.hz == 50.0
        assert prof.DEEP.armed and prof.DEEP.seconds == 0.5
        assert prof.DEEP.hz == 75.0
        assert os.path.isdir(tmp_path / "deep")
        # second call is a no-op (one-shot hook, like the watchdog's)
        prof.maybe_start_from_env()
    finally:
        prof.PROFILER.stop()


def test_env_hook_inert_under_obs_disable(tmp_path, monkeypatch):
    monkeypatch.setattr(prof, "_env_checked", False)
    monkeypatch.setenv("NNS_TPU_PROF", "50")
    monkeypatch.setenv("NNS_TPU_PROF_DEEP_DIR", str(tmp_path / "d2"))
    monkeypatch.setenv("NNS_TPU_OBS_DISABLE", "1")
    prof.maybe_start_from_env()
    assert not prof.PROFILER.running
    assert not prof.DEEP.armed
    assert not os.path.exists(tmp_path / "d2")  # no dir, no thread


def test_env_hook_bad_rate_does_not_start(monkeypatch):
    monkeypatch.setattr(prof, "_env_checked", False)
    monkeypatch.setenv("NNS_TPU_PROF", "not-a-rate")
    prof.maybe_start_from_env()
    assert not prof.PROFILER.running


# -- export surfaces ----------------------------------------------------------


def test_snapshot_profile_table_and_flat_families():
    from nnstreamer_tpu.obs.metrics import SNAPSHOT_VERSION

    assert SNAPSHOT_VERSION == 10
    p, src, sink = _slow_pipeline("profsnap")
    p.start()
    try:
        for i in range(4):
            src.push_buffer(Buffer.of(
                np.zeros(SHAPE, np.float32), pts=i))
        for _ in range(4):
            assert sink.pull(timeout=10) is not None
        snap = REGISTRY.snapshot()
        assert snap["version"] == 10
        table = snap["profile"]
        assert sorted(table.keys()) == [
            "elements", "gil_waiters", "profiler", "stacks"]
        rows = {(r["pipeline"], r["element"]): r
                for r in table["elements"]}
        q = rows[("profsnap", "q")]
        assert q["iters"] >= 4 and 0.0 <= q["wait_share"] <= 1.0
        assert {"cpu_s", "run_s", "wait_s", "samples",
                "sample_share"} <= set(q)
        # flat families ride the single collection walk
        fams = {s["name"]: s
                for s in snap["metrics"]["families"]} \
            if isinstance(snap["metrics"], dict) \
            and "families" in snap["metrics"] else None
        text_names = [f for f in (
            "nns_element_cpu_seconds_total",
            "nns_element_run_seconds_total",
            "nns_element_wait_seconds_total")]
        if fams is not None:
            assert all(n in fams for n in text_names)
    finally:
        src.end_of_stream()
        p.wait_eos(timeout=10)
        p.stop()


def test_prof_endpoint_and_healthz_and_families():
    from nnstreamer_tpu.obs.metrics import serve_metrics

    p, src, sink = _slow_pipeline("profhttp")
    p.start()
    srv = serve_metrics(port=0)
    try:
        for i in range(4):
            src.push_buffer(Buffer.of(
                np.zeros(SHAPE, np.float32), pts=i))
        for _ in range(4):
            assert sink.pull(timeout=10) is not None
        prof.PROFILER.clear()
        prof.PROFILER._record(
            "profhttp:q", "x.py:main;x.py:loop",
            ts=time.monotonic())
        base = f"http://127.0.0.1:{srv.port}"
        text = urllib.request.urlopen(f"{base}/prof").read().decode()
        assert "profhttp:q;x.py:main;x.py:loop 1" in text
        doc = json.loads(urllib.request.urlopen(
            f"{base}/prof?format=trace").read().decode())
        assert any(e.get("args", {}).get("name") == "profhttp:q"
                   for e in doc["traceEvents"])
        ring = urllib.request.urlopen(
            f"{base}/prof?last=60").read().decode()
        assert "profhttp:q" in ring
        metrics = urllib.request.urlopen(
            f"{base}/metrics").read().decode()
        assert "nns_element_cpu_seconds_total" in metrics
        assert 'pipeline="profhttp"' in metrics
        health = json.loads(urllib.request.urlopen(
            f"{base}/healthz").read().decode())
        assert "prof" in health
        assert {"running", "deep_armed",
                "deep_episodes"} <= set(health["prof"])
    finally:
        srv.close()
        src.end_of_stream()
        p.wait_eos(timeout=10)
        p.stop()


def test_flightrec_dump_embeds_profiler_ring(tmp_path):
    from nnstreamer_tpu.obs.flightrec import FlightRecorder

    rec = FlightRecorder()
    rec.arm(str(tmp_path))
    prof.PROFILER.clear()
    prof.PROFILER.configure(50.0)
    assert prof.PROFILER.start()
    try:
        time.sleep(0.1)  # a few real ticks into the ring
        doc = rec.dump_json("test")
        assert "host_stacks" in doc
        assert doc["host_stacks"].count("\n") >= 0
        assert doc["host_stacks"]  # the ring had samples
    finally:
        prof.PROFILER.stop()
    # not running: no embed key at all (absent, not empty)
    doc = rec.dump_json("test2")
    assert "host_stacks" not in doc


def test_nns_top_renders_prof_section():
    from nnstreamer_tpu.obs.top import render

    def snap(t, cpu, run, wait):
        return {
            "time": t, "pipelines": [], "pools": [], "links": [],
            "compiles": [],
            "profile": {
                "elements": [{
                    "pipeline": "p", "element": "q", "cpu_s": cpu,
                    "run_s": run, "wait_s": wait, "iters": 100,
                    "samples": 40, "sample_share": 0.5,
                    "wait_share": 0.8}],
                "stacks": [{"label": "p:q",
                            "stack": "a.py:main;a.py:loop",
                            "count": 40}],
                "gil_waiters": 2,
                "profiler": {"running": True, "hz": 47.0,
                             "ticks": 80, "samples": 160,
                             "stacks": 12, "evicted": 0, "errors": 0,
                             "gil_waiters": 2, "runnable": 3,
                             "self_cpu_s": 0.01}}}

    prev = snap(100.0, 1.0, 2.0, 8.0)
    cur = snap(101.0, 1.1, 2.2, 8.8)
    out = render(cur, prev)
    assert "PROF ELEMENT" in out and "WAIT%" in out
    row = [ln for ln in out.splitlines()
           if ln.startswith("q") and "p" in ln][0]
    # 0.1 s CPU over the 1 s window -> 10.0%; wait 0.8 s -> 80.0%
    assert "10.0" in row and "80.0" in row
    assert "top stack: p:q a.py:loop x40" in out
    assert "profiler: 47 Hz" in out and "gil_waiters 2" in out


def test_nns_prof_cli_in_process_and_file_out(tmp_path, monkeypatch):
    monkeypatch.delenv("NNS_TPU_METRICS_PORT", raising=False)
    prof.PROFILER.clear()
    prof.PROFILER._record("p:e", "m.py:main;m.py:step",
                          ts=time.monotonic())
    buf = io.StringIO()
    assert prof.main([], out=buf) == 0
    assert "p:e;m.py:main;m.py:step 1" in buf.getvalue()
    buf = io.StringIO()
    assert prof.main(["--format", "trace"], out=buf) == 0
    doc = json.loads(buf.getvalue())
    assert doc["traceEvents"]
    out_file = tmp_path / "stacks.txt"
    assert prof.main(["--out", str(out_file)]) == 0
    assert "p:e;m.py:main;m.py:step 1" in out_file.read_text()
    # a dead endpoint is a clean failure, not a traceback
    assert prof.main(["--connect", "127.0.0.1:1"],
                     out=io.StringIO()) == 1

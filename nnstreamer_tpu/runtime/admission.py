"""SLO-aware admission control for the shared serving path.

Open-loop traffic (millions of independent users) does not slow down
when the server does — arrivals keep coming, queues grow without
bound, and EVERY request's latency blows through the SLO.  The only
defenses are the classic overload-control trio this module provides
for the :class:`~nnstreamer_tpu.runtime.serving.SharedBatcher`:

- **priority classes** — each sharing stream (``tensor_filter
  priority=high|normal|low``) names how much it matters;
- **bounded per-stream queues with backpressure** — a stream may park
  at most ``queue-limit`` frames in the cross-stream window; past
  that its producer thread BLOCKS (which is exactly the backpressure
  that fills the upstream ``queue`` and, closed-loop, slows the
  source) instead of growing the window unboundedly;
- **load shedding under SLO risk** — the controller watches the pool's
  recent serve latencies; when the p99 estimate crosses the pool's
  ``slo-ms`` it starts shedding sub-high-priority frames at admission
  (cheapest possible point: before any queueing or dispatch work).
  Every shed bumps ``nns_admission_shed_total`` and posts a
  (rate-limited) bus WARNING — never a silent drop.

Batch formation turns earliest-deadline-first while admission is
armed: the window dispatches the frames whose deadlines expire
soonest, so a latency-critical stream is not stuck behind a bulk
stream's backlog.  Per-stream FIFO order is preserved — deadlines are
monotonic within one stream, and the EDF sort is stable.

Shedding is graded, not on/off: the shed probability ramps linearly
from 0 at ``RAMP_START``×SLO (0.7) to 1 at the SLO, so the system
settles at an equilibrium p99 just under the SLO instead of
duty-cycling (a hard threshold alternates flood and famine, and the
flood spikes hit the protected class too).  ``at_risk`` reports
"shedding possible" — i.e. the p99 has entered the ramp.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, Dict, Optional

#: stream priority classes, best first (the exported label keeps the
#: name, comparisons use the rank)
PRIORITY_CLASSES = {"high": 0, "normal": 1, "low": 2}

#: Buffer.meta key carrying the pipeline-ingress timestamp.  Stamped by
#: SourceElement._loop ONLY while at least one admission controller is
#: armed in the process (the ACTIVE flag below) — a full window
#: dispatches inline on the producer thread, so overload backlog lives
#: in the UPSTREAM queue elements; anchoring deadlines and the latency
#: signal at ingress is the only way the controller can see it.
INGRESS_TS_META = "_nns_ingress_ts"

#: fast-path flag the sources read (one attribute load per frame, same
#: cost class as the tracer hook); maintained by the counter below
ACTIVE = False

_active_lock = threading.Lock()
_active_count = 0


def _controller_armed() -> None:
    global ACTIVE, _active_count
    with _active_lock:
        _active_count += 1
        ACTIVE = True


def _controller_disarmed() -> None:
    global ACTIVE, _active_count
    with _active_lock:
        _active_count = max(_active_count - 1, 0)
        ACTIVE = _active_count > 0

_PRIORITY_NAMES = {v: k for k, v in PRIORITY_CLASSES.items()}


def parse_priority(value) -> int:
    """``high``/``normal``/``low`` (or a 0-2 rank) → rank."""
    if isinstance(value, int) and value in _PRIORITY_NAMES:
        return value
    name = str(value or "normal").strip().lower()
    if name not in PRIORITY_CLASSES:
        raise ValueError(
            f"unknown priority {value!r}; one of "
            f"{list(PRIORITY_CLASSES)} (or 0-2)")
    return PRIORITY_CLASSES[name]


def priority_name(rank: int) -> str:
    return _PRIORITY_NAMES.get(int(rank), str(rank))


class StreamPolicy:
    """One stream's admission settings (derived from tensor_filter
    props at pool attach)."""

    __slots__ = ("priority", "deadline_s", "queue_limit", "tenant")

    def __init__(self, priority: int = 1, deadline_s: float = 0.0,
                 queue_limit: int = 0, tenant: str = "default"):
        self.priority = int(priority)
        self.deadline_s = float(deadline_s)
        self.queue_limit = int(queue_limit)
        # who this stream's frames are billed to: the tenant= filter
        # prop, attributed per dispatch by obs/tenantstat.py
        self.tenant = str(tenant) or "default"


class AdmissionController:
    """Per-pool overload controller: latency window → p99 estimate →
    at-risk flag → shed verdicts, plus the per-priority accounting the
    metrics registry exports."""

    #: recompute the p99 estimate every N observations (a sort of the
    #: whole window per frame would throttle the hot path)
    RECOMPUTE_EVERY = 16
    #: how many per-recompute histogram deltas the rolling distribution
    #: sums over — 32 × RECOMPUTE_EVERY ≈ the same 512-observation
    #: window the private deque keeps
    HIST_WINDOW_DELTAS = 32
    #: the shed-probability ramp: 0 below RAMP_START×SLO, 1 at the SLO.
    #: A hard on/off threshold duty-cycles — every "off" half-period
    #: floods the window with the backlog parked upstream and the spike
    #: hits the protected class too; the graded ramp (RED/CoDel-style)
    #: settles the system at an equilibrium p99 just under the SLO with
    #: the protected class continuously clean.
    RAMP_START = 0.7

    def __init__(self, slo_s: float, window: int = 512, hist=None):
        import random

        if slo_s <= 0:
            raise ValueError(f"slo_s must be > 0, got {slo_s}")
        self.slo_s = float(slo_s)
        # instance copy of the ramp start so an external controller can
        # retune the shed aggressiveness at runtime (the "ramp-start"
        # actuator, runtime/actuators.py) without reclassing; the class
        # constant stays the documented default
        self.ramp_start = float(self.RAMP_START)
        self._lat: Deque[float] = deque(maxlen=int(window))
        self._lock = threading.Lock()
        self._rng = random.Random(0)
        self._since_recompute = 0
        self._p99 = 0.0
        # the registry's exported serve-latency histogram (a metrics
        # _Child with hist_state(); runtime/serving.py wires the
        # per-pool nns_admission_latency_seconds child in).  When
        # attached, every observation feeds it and the p99 the shed
        # decision acts on is DERIVED from its buckets — an external
        # controller scraping the registry reads the very signal the
        # in-process shedder uses.  The private deque stays as the
        # fallback for a detached registry (hist=None) and for
        # latencies past the last finite bucket, where bucket
        # interpolation has no upper bound to interpolate toward.
        self._hist = hist
        self._hist_prev = None  # cumulative buckets at last recompute
        self._hist_deltas: Deque[list] = deque(
            maxlen=self.HIST_WINDOW_DELTAS)
        self.at_risk = False
        self.risk_episodes = 0  # times the at-risk flag armed
        # pre-seeded per-priority counters: the hot path only ever
        # does `d[k] += 1` under the lock (ranks are validated by
        # parse_priority before they reach here)
        zero = {p: 0 for p in PRIORITY_CLASSES.values()}
        self.submitted: Dict[int, int] = dict(zero)
        self.shed: Dict[int, int] = dict(zero)
        self.shed_queue_full: Dict[int, int] = dict(zero)

    # -- the latency signal ---------------------------------------------------

    def observe(self, lat_s: float) -> None:
        """Feed one serve latency (window park → results demuxed).
        Sampled dispatches include blocked device execution; unsampled
        ones time queueing + dispatch issue — under overload the
        queueing term is what explodes, which is the signal admission
        control needs."""
        hist = self._hist
        if hist is not None:
            # the exported histogram is the primary signal store; its
            # own (family) lock serializes this, so it stays OUTSIDE
            # the controller lock
            hist.observe(float(lat_s))
        with self._lock:
            self._lat.append(float(lat_s))
            self._since_recompute += 1
            if self._since_recompute >= self.RECOMPUTE_EVERY:
                self._recompute_locked()

    def _recompute_locked(self) -> None:
        self._since_recompute = 0
        if not self._lat:
            return
        p99 = self._hist_p99_locked() if self._hist is not None else None
        if p99 is None:
            # registry detached (or the tail ran past the last finite
            # bucket): the private window is the fallback signal
            s = sorted(self._lat)
            p99 = s[min(int(0.99 * len(s)), len(s) - 1)]
        self._p99 = p99
        was = self.at_risk
        self.at_risk = self._shed_probability_locked() > 0.0
        if self.at_risk and not was:
            self.risk_episodes += 1

    def _hist_p99_locked(self) -> Optional[float]:
        """p99 estimate from the exported histogram: diff the
        cumulative bucket counts since the last recompute, sum the
        recent deltas into a rolling-window distribution, and
        interpolate via the shared
        :func:`~nnstreamer_tpu.obs.metrics.bucket_quantile` (ONE
        histogram→quantile definition, also used by ``obs/watch.py`` —
        a watchdog or external controller deriving the p99 from a
        scrape computes exactly this number).  None when the histogram
        has no recent data or the p99 falls in the +Inf bucket (no
        upper bound to interpolate toward — the caller falls back to
        the private window)."""
        from ..obs.metrics import bucket_quantile

        buckets, _sum, _count = self._hist.hist_state()
        prev = self._hist_prev
        self._hist_prev = buckets
        if prev is None or len(prev) != len(buckets):
            return None
        delta = [c - p for c, p in zip(buckets, prev)]
        if any(d < 0 for d in delta):  # histogram child was reset
            return None
        self._hist_deltas.append(delta)
        dist = [sum(col) for col in zip(*self._hist_deltas)]
        return bucket_quantile(self._hist.bucket_bounds, dist, 0.99)

    def _shed_probability_locked(self) -> float:
        """0 while the p99 sits safely under the SLO, ramping linearly
        to 1 as it reaches it."""
        start = self.ramp_start * self.slo_s
        if self._p99 <= start:
            return 0.0
        return min((self._p99 - start) / (self.slo_s - start), 1.0)

    def set_ramp_start(self, frac: float) -> None:
        """Retune the shed ramp (the external controller's knob): the
        shed probability stays 0 until the p99 crosses ``frac``×SLO and
        reaches 1 at the SLO.  Lower = shed earlier/harder.  The
        at-risk flag re-derives immediately so a retune takes effect on
        this window, not RECOMPUTE_EVERY observations later."""
        frac = float(frac)
        if not 0.0 < frac < 1.0:
            raise ValueError(f"ramp_start must be in (0, 1), got {frac}")
        with self._lock:
            self.ramp_start = frac
            was = self.at_risk
            self.at_risk = self._shed_probability_locked() > 0.0
            if self.at_risk and not was:
                self.risk_episodes += 1

    def reset_signal(self) -> None:
        """Drop the accumulated latency signal (bench/test warmup: a
        fresh pool pays XLA compile on its first windows, and those
        latencies must not arm the controller before real traffic).
        The exported histogram keeps its cumulative counts — resetting
        a Prometheus counter would break scrapers — but the rolling
        delta window restarts from its current state, so pre-reset
        observations stop influencing the p99."""
        hist_state = self._hist.hist_state() if self._hist is not None \
            else None
        with self._lock:
            self._lat.clear()
            self._p99 = 0.0
            self.at_risk = False
            self._since_recompute = 0
            self._hist_deltas.clear()
            if hist_state is not None:
                self._hist_prev = hist_state[0]

    @property
    def shed_probability(self) -> float:
        with self._lock:
            return self._shed_probability_locked()

    @property
    def p99_s(self) -> float:
        with self._lock:
            return self._p99

    # -- verdicts -------------------------------------------------------------

    def admit(self, priority: int) -> bool:
        """Whether a frame of ``priority`` may enter the window now.
        False = shed (already counted).  The high class is never shed
        here (it is protected by backpressure + everyone else's
        sheds); lower classes shed with the ramp probability."""
        with self._lock:
            self.submitted[priority] += 1
            if priority <= PRIORITY_CLASSES["high"]:
                return True
            p = self._shed_probability_locked()
            if p > 0.0 and (p >= 1.0 or self._rng.random() < p):
                self.shed[priority] += 1
                return False
            return True

    def count_queue_full(self, priority: int) -> None:
        """A frame dropped because its stream's bounded queue never
        drained within the backpressure window (wedged device)."""
        with self._lock:
            self.shed_queue_full[priority] += 1

    # -- pull side ------------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "slo_ms": self.slo_s * 1e3,
                "p99_ms": self._p99 * 1e3,
                "ramp_start": self.ramp_start,
                "at_risk": self.at_risk,
                "shed_probability": round(
                    self._shed_probability_locked(), 4),
                "risk_episodes": self.risk_episodes,
                "submitted": {priority_name(k): v
                              for k, v in sorted(self.submitted.items())},
                "shed": {priority_name(k): v
                         for k, v in sorted(self.shed.items())},
                "shed_queue_full": {
                    priority_name(k): v
                    for k, v in sorted(self.shed_queue_full.items())},
            }

    @property
    def total_shed(self) -> int:
        with self._lock:
            return sum(self.shed.values()) \
                + sum(self.shed_queue_full.values())

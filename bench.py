#!/usr/bin/env python
"""Benchmark: end-to-end streaming-inference pipeline throughput on one chip.

Pipeline (the framework's flagship slice, BASELINE.md composite config):

    device_src(uint8 NHWC frames staged in HBM)
        ! tensor_transform(typecast+normalize)
        ! tensor_filter framework=jax-xla model=mobilenet_v1+argmax
        ! appsink

The classification argmax ("image_labeling") is fused into the same XLA
computation as the backbone, so only (batch,) int32 labels cross back to
host — the TPU-native form of the reference's CPU decoder stage.  Frames are
staged device-resident by device_src (the TPU equivalent of the reference
converter's zero-copy ingestion; host→HBM staging happens once, off the
timed path — on real v5e hosts the DMA ingest rate far exceeds this
pipeline's frame rate, but through a remote-tunnel device it would dominate
and measure the tunnel, not the framework).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline: BASELINE.md target 10,000 fps on v5e-8 => 1,250 fps/chip.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402

BATCH = int(os.environ.get("BENCH_BATCH", "512"))
BUFFERS = int(os.environ.get("BENCH_BUFFERS", "30"))
WARMUP = int(os.environ.get("BENCH_WARMUP", "3"))
SIZE = 224
BASELINE_FPS_PER_CHIP = 10_000 / 8.0


def build_pipeline():
    import jax

    from nnstreamer_tpu.core import TensorsSpec
    from nnstreamer_tpu.elements.basic import AppSink
    from nnstreamer_tpu.elements.devicesrc import DeviceSrc
    from nnstreamer_tpu.elements.filter import TensorFilter
    from nnstreamer_tpu.elements.transform import TensorTransform
    from nnstreamer_tpu.filters.jax_xla import register_model
    from nnstreamer_tpu.models.mobilenet import (
        mobilenet_v1_apply,
        mobilenet_v1_init,
    )
    from nnstreamer_tpu.runtime import Pipeline

    params = mobilenet_v1_init(jax.random.PRNGKey(0), num_classes=1001)

    def classify(params, x):
        logits = mobilenet_v1_apply(params, x)
        return jax.numpy.argmax(logits, axis=-1).astype(jax.numpy.int32)

    register_model("bench_mobilenet_v1", classify, params=params,
                   in_shapes=[(BATCH, SIZE, SIZE, 3)])

    spec = TensorsSpec.from_shapes([(BATCH, SIZE, SIZE, 3)], np.uint8)
    p = Pipeline()
    src = DeviceSrc(name="src", spec=spec, pattern="noise", pool_size=4,
                    num_buffers=WARMUP + BUFFERS)
    tf = TensorTransform(name="norm", mode="arithmetic",
                         option="typecast:float32,add:-127.5,div:127.5")
    flt = TensorFilter(name="net", framework="jax-xla",
                       model="bench_mobilenet_v1")
    sink = AppSink(name="out", max_buffers=BUFFERS + WARMUP + 4)
    p.add(src, tf, flt, sink).link(src, tf, flt, sink)
    return p, sink


def main():
    p, sink = build_pipeline()
    with p:
        # warmup: compile + steady state; block on the last warmup buffer
        for _ in range(WARMUP):
            b = sink.pull(timeout=600)
        b.tensors[0].np()

        t0 = time.perf_counter()
        last = None
        for _ in range(BUFFERS):
            nb = sink.pull(timeout=600)
            if nb is not None:
                last = nb
        last.tensors[0].np()  # block on the final device computation
        elapsed = time.perf_counter() - t0

    fps = BATCH * BUFFERS / elapsed
    print(json.dumps({
        "metric": "e2e pipeline throughput, MobileNetV1 classify "
                  f"(batch={BATCH}, device-staged uint8, fused "
                  "normalize+argmax)",
        "value": round(fps, 1),
        "unit": "frames/sec/chip",
        "vs_baseline": round(fps / BASELINE_FPS_PER_CHIP, 3),
        "batch_latency_ms": round(elapsed / BUFFERS * 1e3, 2),
    }))


if __name__ == "__main__":
    main()

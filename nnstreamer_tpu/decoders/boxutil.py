"""Shared box post-processing + drawing utilities for decoders.

Parity target: the IoU/NMS helpers and label handling shared by the
reference's bounding-box decoder strategies
(/root/reference/ext/nnstreamer/tensor_decoder/tensordec-boundingbox.cc and
box_properties/*; label/util code in tensordecutil.c).

These are the *host-side compatibility* implementations used by the
decoder elements on small per-frame outputs; the performance path runs
decode+NMS on-device inside the model (models/ssd.py ssd_detect_fn).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import numpy as np


@dataclasses.dataclass
class Detection:
    """One detected object in normalized [0,1] image coordinates."""

    x: float  # left
    y: float  # top
    w: float
    h: float
    class_id: int
    score: float
    label: str = ""


def load_labels(path: str) -> List[str]:
    with open(path, "r", encoding="utf-8") as f:
        return [ln.strip() for ln in f if ln.strip()]


def iou_xywh(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """IoU between one box (4,) and many boxes (N,4), xywh layout."""
    ax2, ay2 = a[0] + a[2], a[1] + a[3]
    bx2, by2 = b[:, 0] + b[:, 2], b[:, 1] + b[:, 3]
    ix = np.maximum(
        0, np.minimum(ax2, bx2) - np.maximum(a[0], b[:, 0]))
    iy = np.maximum(
        0, np.minimum(ay2, by2) - np.maximum(a[1], b[:, 1]))
    inter = ix * iy
    union = a[2] * a[3] + b[:, 2] * b[:, 3] - inter
    return inter / np.maximum(union, 1e-9)


def nms(dets: List[Detection], iou_thresh: float = 0.5,
        max_out: Optional[int] = None) -> List[Detection]:
    """Greedy class-aware NMS (parity: nms() in tensordec-boundingbox.cc)."""
    out: List[Detection] = []
    by_class: dict = {}
    for d in dets:
        by_class.setdefault(d.class_id, []).append(d)
    for cid, cds in by_class.items():
        cds.sort(key=lambda d: -d.score)
        boxes = np.array([[d.x, d.y, d.w, d.h] for d in cds], np.float32)
        alive = np.ones(len(cds), bool)
        for i, d in enumerate(cds):
            if not alive[i]:
                continue
            out.append(d)
            if i + 1 < len(cds):
                sup = iou_xywh(boxes[i], boxes[i + 1:]) > iou_thresh
                alive[i + 1:] &= ~sup
    out.sort(key=lambda d: -d.score)
    return out[:max_out] if max_out else out


def sigmoid(x: np.ndarray) -> np.ndarray:
    return 1.0 / (1.0 + np.exp(-x))


def softmax(x: np.ndarray, axis: int = -1) -> np.ndarray:
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


# -- drawing (parity: draw() in tensordec-boundingbox.cc; labels are
# stamped with the bitmap-font overlay, tensordec-font.c analog) ------------


def draw_boxes(dets: Sequence[Detection], width: int, height: int,
               thickness: int = 2, labels: bool = False,
               out: Optional[np.ndarray] = None) -> np.ndarray:
    """Render detections into an RGBA overlay frame (H, W, 4) uint8.

    With ``labels=True``, each detection carrying a ``label`` gets its
    text stamped above the box (parity: draw_label users,
    tensordec-boundingbox.cc / tensordec-font.c).  ``out`` draws into an
    existing zeroed frame (batched decode preallocates one (B,H,W,4)
    block instead of stacking per-frame copies).
    """
    img = np.zeros((height, width, 4), np.uint8) if out is None else out
    palette = np.array([
        [255, 0, 0, 255], [0, 255, 0, 255], [0, 0, 255, 255],
        [255, 255, 0, 255], [255, 0, 255, 255], [0, 255, 255, 255]],
        np.uint8)
    for d in dets:
        color = palette[d.class_id % len(palette)]
        # pure-python clipping: np.clip on scalars costs ~10µs per call,
        # which dominates batched overlay drawing (4 clips × every box)
        x0 = min(max(int(d.x * width), 0), width - 1)
        y0 = min(max(int(d.y * height), 0), height - 1)
        x1 = min(max(int((d.x + d.w) * width), 0), width - 1)
        y1 = min(max(int((d.y + d.h) * height), 0), height - 1)
        t = thickness
        img[y0:y0 + t, x0:x1 + 1] = color
        img[max(y1 - t + 1, 0):y1 + 1, x0:x1 + 1] = color
        img[y0:y1 + 1, x0:x0 + t] = color
        img[y0:y1 + 1, max(x1 - t + 1, 0):x1 + 1] = color
        if labels and d.label:
            from .font import draw_text, label_anchor

            lx, ly = label_anchor(x0, y0)
            draw_text(img, lx, ly, d.label, color)
    return img

"""nnstreamer_tpu — a TPU-native streaming-inference pipeline framework.

A from-scratch rebuild of the capabilities of nnstreamer
(github.com/nnstreamer/nnstreamer) designed for JAX/XLA/Pallas/pjit:
typed tensor streams (static/flexible/sparse), a dataflow pipeline runtime
with caps negotiation / QoS / timestamp sync, a sub-plugin model whose
flagship ``jax-xla`` filter dispatches zero-copy into XLA computations
resident in TPU HBM, a converter/transform/decoder library, data-dependent
flow control, and distributed pipelines sharded over a TPU mesh (ICI/DCN).
"""

__version__ = "0.1.0"

import os as _os

if _os.environ.get("NNS_TPU_LOCKDEP"):
    # arm the runtime lock-order witness BEFORE any package module
    # constructs a lock (Documentation/robustness.md, "Concurrency
    # analysis & lockdep"); a plain env check keeps the common path
    # import-free
    from .utils import lockdep as _lockdep

    _lockdep.maybe_enable_from_env()

from .core import (  # noqa: F401
    Buffer,
    Caps,
    CapsStruct,
    DType,
    MediaType,
    MetaInfo,
    Tensor,
    TensorFormat,
    TensorSpec,
    TensorsSpec,
)

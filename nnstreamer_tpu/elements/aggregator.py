"""``tensor_aggregator`` — temporal batching / windowing.

Parity target: /root/reference/gst/nnstreamer/elements/gsttensor_aggregator.c
(props ``frames-in``, ``frames-out``, ``frames-flush``, ``frames-dim``,
``concat`` — :64-70): the element reinterprets the stream's outermost frame
axis, e.g. 30fps of d=300:300 → 15fps of d=300:300:2, with a sliding-window
overlap when ``frames_flush < frames_out``.

TPU note: this element is the stream's *micro-batcher* — it is how a
single-frame stream becomes an MXU-sized batch before tensor_filter
(SURVEY.md §7 "aggregator as micro-batcher").  Concatenation happens on
device when inputs are device-resident.
"""

from __future__ import annotations

from fractions import Fraction
from typing import List, Optional

import numpy as np

from ..core import Buffer, Caps, Tensor, TensorSpec, TensorsSpec
from ..runtime.element import NegotiationError, Pad, TransformElement
from ..runtime.registry import register_element


@register_element("tensor_aggregator")
class TensorAggregator(TransformElement):
    FACTORY = "tensor_aggregator"

    def __init__(self, name=None, frames_in: int = 1, frames_out: int = 1,
                 frames_flush: int = 0, frames_dim: Optional[int] = None,
                 concat: bool = True, **props):
        self.frames_in = frames_in
        self.frames_out = frames_out
        self.frames_flush = frames_flush
        self.frames_dim = frames_dim
        self.concat = concat
        super().__init__(name, **props)
        self._window: List[np.ndarray] = []  # frame-granular ring
        self._pts0: Optional[int] = None

    # -- negotiation ---------------------------------------------------------

    def _dim_axis(self, spec: TensorSpec) -> int:
        d = self.frames_dim if self.frames_dim is not None \
            else len(spec.dims) - 1
        return len(spec.dims) - 1 - int(d)  # innermost-first → numpy axis

    def _is_passthrough(self) -> bool:
        fin, fout = int(self.frames_in), int(self.frames_out)
        flush = int(self.frames_flush) or fout
        return bool(self.concat) and fin == fout and flush == fout

    def _per_frame_dims(self, t: TensorSpec):
        d = self.frames_dim if self.frames_dim is not None \
            else len(t.dims) - 1
        dims = list(t.dims)
        dims[int(d)] = dims[int(d)] // max(int(self.frames_in), 1)
        return int(d), dims

    def propose_src_caps(self, pad: Pad) -> Caps:
        in_spec = self.sinkpad.spec
        if in_spec is None:
            raise NegotiationError(f"{self.name}: no input caps")
        t = in_spec.tensors[0]
        fin, fout = int(self.frames_in), int(self.frames_out)
        flush = int(self.frames_flush) or fout
        rate = in_spec.rate
        if self._is_passthrough():
            return Caps.from_spec(TensorsSpec.of(t, rate=rate))
        d, per_frame = self._per_frame_dims(t)
        # window emission rate: fin frames arrive per input buffer; one
        # window leaves per `flush` frames consumed
        out_rate = rate * Fraction(fin, flush) if rate else rate
        if self.concat:
            dims = list(per_frame)
            dims[d] = dims[d] * fout
            return Caps.from_spec(TensorsSpec.of(
                t.with_dims(dims), rate=out_rate))
        # concat=False: the window leaves as fout separate per-frame tensors
        return Caps.from_spec(TensorsSpec(
            tensors=tuple(t.with_dims(per_frame) for _ in range(fout)),
            rate=out_rate))

    # -- hot path -------------------------------------------------------------

    def transform(self, buf: Buffer) -> Optional[Buffer]:
        t = buf.tensors[0]
        fin, fout = int(self.frames_in), int(self.frames_out)
        flush = int(self.frames_flush) or fout
        if self._is_passthrough():
            return buf
        ax = self._dim_axis(t.spec)
        arr = t.jax() if t.is_device else t.np()
        # split incoming buffer into its fin frames along ax
        n_per = arr.shape[ax] // max(fin, 1)
        frames = [
            arr[tuple(slice(i * n_per, (i + 1) * n_per) if a == ax
                      else slice(None) for a in range(arr.ndim))]
            for i in range(fin)]
        if self._pts0 is None:
            self._pts0 = buf.pts
        self._window.extend(frames)
        # Per-frame duration (ns) so follow-on windows completed by this
        # same input buffer carry synthesized timestamps instead of None
        # (which would break downstream time-based elements, e.g.
        # tensor_rate).
        rate = self.sinkpad.spec.rate if self.sinkpad.spec else None
        if rate:
            frame_ns = 1e9 / (float(rate) * max(fin, 1))
        elif buf.duration is not None:
            frame_ns = buf.duration / max(fin, 1)
        else:
            frame_ns = None
        base, emitted = self._pts0, 0
        # emit every complete window (fin > flush can complete several)
        while len(self._window) >= fout:
            out_frames = self._window[:fout]
            self._window = self._window[flush:]
            if not emitted:
                pts = base
            elif base is not None and frame_ns is not None:
                pts = base + int(emitted * flush * frame_ns)
            else:
                pts = None  # clockless stream: keep pts-less passthrough
            emitted += 1
            if self.concat:
                if all(hasattr(f, "devices") for f in out_frames):
                    import jax.numpy as jnp

                    merged = jnp.concatenate(out_frames, axis=ax)
                else:
                    merged = np.concatenate(
                        [np.asarray(f) for f in out_frames], axis=ax)
                self.push(Buffer(tensors=[Tensor(merged)], pts=pts,
                                 meta=dict(buf.meta)))
            else:
                self.push(Buffer(
                    tensors=[Tensor(np.asarray(f)
                                    if not hasattr(f, "devices") else f)
                             for f in out_frames],
                    pts=pts, meta=dict(buf.meta)))
        if emitted:
            # Leftover frames (fin not divisible by flush) started at
            # base + emitted*flush*frame_ns — carry that forward so the
            # next window is stamped with ITS first frame's time, not the
            # next input buffer's pts.
            if self._window and base is not None and frame_ns is not None:
                self._pts0 = base + int(emitted * flush * frame_ns)
            else:
                self._pts0 = None
        return None

    def on_eos(self) -> None:
        self._window = []
        self._pts0 = None

"""``tensor_trainer`` — in-pipeline training node.

Parity target: /root/reference/gst/nnstreamer/elements/gsttensor_trainer.c
(props ``framework``, ``model-config``, ``model-save-path``,
``model-load-path``, ``num-inputs``, ``num-labels``,
``num-training-samples``, ``num-validation-samples``, ``epochs`` —
:94-104): each incoming buffer is ONE sample whose first ``num-inputs``
tensors are model inputs and next ``num-labels`` tensors are labels; the
trainer sub-plugin trains asynchronously and signals
EPOCH/TRAINING_COMPLETION through its notifier; the element pushes a
per-sample status tensor downstream ([epoch, training_loss,
training_accuracy, validation_loss, validation_accuracy], float64) and
holds EOS until training completes (gsttensor_trainer.c:889).

TPU note: the heavy lifting is the sub-plugin's mesh-sharded jitted
step — this element is thin control flow, so sample ingest stays on the
streaming thread and never blocks on the device except for epoch-boundary
backpressure (parity: wait_for_epoch_completion,
gsttensor_trainer.c:561-593).
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from ..core import Buffer, Caps, Tensor, TensorsSpec
from ..runtime.element import Element, NegotiationError, Pad, StreamError
from ..runtime.events import Event, EventKind, Message, MessageKind
from ..runtime.registry import register_element
from ..trainers import (
    EVENT_EPOCH_COMPLETION,
    EVENT_TRAINING_COMPLETION,
    TrainerError,
    TrainerProps,
    find_trainer,
)

STATUS_FIELDS = ("epoch", "training_loss", "training_accuracy",
                 "validation_loss", "validation_accuracy")


@register_element("tensor_trainer")
class TensorTrainer(Element):
    FACTORY = "tensor_trainer"

    def __init__(self, name=None, framework: str = "jax-optax",
                 model_config=None, model_save_path: str = "",
                 model_load_path: str = "", num_inputs: int = 1,
                 num_labels: int = 1, num_training_samples: int = 0,
                 num_validation_samples: int = 0, epochs: int = 1,
                 completion_timeout: float = 300.0, **props):
        self.framework = framework
        self.model_config = model_config
        self.model_save_path = model_save_path
        self.model_load_path = model_load_path
        self.num_inputs = num_inputs
        self.num_labels = num_labels
        self.num_training_samples = num_training_samples
        self.num_validation_samples = num_validation_samples
        self.epochs = epochs
        self.completion_timeout = completion_timeout
        super().__init__(name, **props)
        self.add_sink_pad()
        self.add_src_pad()
        self.subplugin = None
        self._pushed = 0
        self._epoch_evt = threading.Event()
        self._done_evt = threading.Event()

    # -- open -----------------------------------------------------------------

    def _open(self) -> None:
        if self.subplugin is not None:
            return
        cls = find_trainer(self.framework)
        sp = cls()
        sp.configure(TrainerProps(
            framework=self.framework, model_config=self.model_config,
            model_save_path=self.model_save_path,
            model_load_path=self.model_load_path,
            num_inputs=int(self.num_inputs),
            num_labels=int(self.num_labels),
            num_training_samples=int(self.num_training_samples),
            num_validation_samples=int(self.num_validation_samples),
            num_epochs=int(self.epochs)), self._notify)
        self.subplugin = sp

    def _notify(self, event: str, data: dict) -> None:
        """Sub-plugin notifier → bus messages + downstream events
        (parity: TRAINER_EVENT_* through GstTensorTrainerEventNotifier)."""
        self.post_message(Message(MessageKind.ELEMENT, self.name,
                                  data={"event": event, **data}))
        if event == EVENT_EPOCH_COMPLETION:
            self._epoch_evt.set()
            self.forward_event(Event(EventKind.EPOCH_COMPLETE, dict(data)))
        elif event == EVENT_TRAINING_COMPLETION:
            self._done_evt.set()
            self.forward_event(
                Event(EventKind.TRAINING_COMPLETE, dict(data)))

    # -- negotiation ----------------------------------------------------------

    def pad_template_caps(self, pad: Pad) -> Caps:
        return Caps.any_tensors()

    def caps_negotiated(self, pad: Pad) -> None:
        spec = pad.spec
        need = int(self.num_inputs) + int(self.num_labels)
        if spec is not None and spec.is_static() and \
                spec.num_tensors < need:
            raise NegotiationError(
                f"{self.name}: stream has {spec.num_tensors} tensors but "
                f"num-inputs+num-labels = {need}")
        self._open()

    def propose_src_caps(self, pad: Pad) -> Caps:
        rate = self.sinkpad.spec.rate if self.sinkpad.spec else None
        spec = TensorsSpec.parse("5:1", "float64")
        if rate:
            spec = spec.with_rate(rate)
        return Caps.from_spec(spec)

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        self._open()
        self.subplugin.start()

    def stop(self) -> None:
        if self.subplugin is not None:
            self.subplugin.stop()
            self.subplugin = None

    # -- hot path -------------------------------------------------------------

    def chain(self, pad: Pad, buf: Buffer) -> None:
        sp = self.subplugin
        if sp is None:
            raise StreamError(f"{self.name}: trainer not opened")
        ni, nl = int(self.num_inputs), int(self.num_labels)
        if buf.num_tensors < ni + nl:
            raise StreamError(
                f"{self.name}: sample has {buf.num_tensors} tensors, "
                f"need {ni + nl}")
        inputs = [buf.tensors[i].np() for i in range(ni)]
        labels = [buf.tensors[ni + i].np() for i in range(nl)]
        try:
            sp.push_data(inputs, labels)
        except TrainerError as e:
            raise StreamError(str(e)) from e
        self._pushed += 1
        per_epoch = int(self.num_training_samples) + \
            int(self.num_validation_samples)
        if per_epoch and self._pushed % per_epoch == 0:
            # epoch boundary: wait for the sub-plugin to finish the epoch
            # before feeding the next one (parity:
            # gst_tensor_trainer_wait_for_epoch_completion); wake early
            # if the trainer died so the error surfaces instead of a hang
            import time as _time

            deadline = _time.monotonic() + float(self.completion_timeout)
            while not self._epoch_evt.wait(timeout=0.2):
                err = sp.error
                if err is not None:
                    raise StreamError(
                        f"{self.name}: training failed: {err}")
                if self._done_evt.is_set():
                    break
                if _time.monotonic() > deadline:
                    raise StreamError(
                        f"{self.name}: epoch did not complete within "
                        f"{self.completion_timeout}s")
            self._epoch_evt.clear()
        if self.srcpad.peer is not None:
            st = sp.get_status()
            arr = np.array([[st.get(k, 0.0) for k in STATUS_FIELDS]],
                           np.float64).reshape(1, 5)
            self.push(Buffer(tensors=[Tensor(arr)], pts=buf.pts,
                             meta=dict(buf.meta)))

    # -- EOS gating -----------------------------------------------------------

    def handle_event(self, pad: Pad, event: Event) -> None:
        if event.kind == EventKind.EOS:
            # hold EOS until training completes (parity:
            # gsttensor_trainer.c:889 "got EOS but training is not
            # completed")
            done = self.subplugin.finished if self.subplugin else None
            if done is not None and not done.wait(
                    timeout=self.completion_timeout):
                self.post_error(StreamError(
                    f"{self.name}: EOS but training did not complete "
                    f"within {self.completion_timeout}s"))
        super().handle_event(pad, event)

"""Pipeline runtime tests: assembly, negotiation, scheduling, events, parser.

Modeled on the reference's programmatic-pipeline gtests
(/root/reference/tests/nnstreamer_plugins/, unittest_sink.cc): build
pipelines with appsrc/appsink, push frames, assert arrival/ordering/EOS.
"""

import threading
import time

import numpy as np
import pytest
from fractions import Fraction

from nnstreamer_tpu.core import Buffer, Caps, TensorsSpec
from nnstreamer_tpu.runtime import (
    NegotiationError,
    Pipeline,
    make,
    parse_launch,
)
from nnstreamer_tpu.elements.basic import AppSink, AppSrc, Queue, TensorSink


SPEC = TensorsSpec.parse("4:3", "float32", rate=Fraction(0))


def frame(v, pts=None):
    return Buffer.of(np.full((3, 4), v, dtype=np.float32), pts=pts)


def build_simple(*mid_names):
    """appsrc ! [mids] ! appsink pipeline."""
    p = Pipeline()
    src = AppSrc(name="src", spec=SPEC)
    sink = AppSink(name="out")
    mids = [make(m) for m in mid_names]
    p.add(src, sink, *mids)
    p.link(src, *mids, sink)
    return p, src, sink


class TestFlow:
    def test_push_through_identity(self):
        p, src, sink = build_simple("identity")
        with p:
            for i in range(5):
                src.push_buffer(frame(i, pts=i * 1000))
            src.end_of_stream()
            assert p.wait_eos(timeout=5)
            got = [sink.pull(timeout=1) for _ in range(5)]
        assert [int(g[0].np()[0, 0]) for g in got] == list(range(5))
        assert got[0].pts == 0 and got[4].pts == 4000

    def test_queue_thread_boundary_preserves_order(self):
        p, src, sink = build_simple("queue")
        with p:
            for i in range(50):
                src.push_buffer(frame(i))
            src.end_of_stream()
            assert p.wait_eos(timeout=5)
            vals = []
            while True:
                b = sink.pull(timeout=0.2)
                if b is None:
                    break
                vals.append(int(b[0].np()[0, 0]))
        assert vals == list(range(50))

    def test_queue_leaky_downstream_drops_old(self):
        q = Queue(name="q", max_size_buffers=4, leaky="downstream")
        p = Pipeline()
        src = AppSrc(name="src", spec=SPEC)
        sink = AppSink(name="out", max_buffers=128)
        p.add(src, sink, q).link(src, q, sink)
        # fill queue before starting its consumer: only last 4 remain
        for i in range(10):
            q.chain(q.sinkpad, frame(i))
        assert q.current_level_buffers == 4

    def test_tee_fanout(self):
        p = Pipeline()
        src = AppSrc(name="src", spec=SPEC)
        t = make("tee", el_name="t")
        s1, s2 = AppSink(name="s1"), AppSink(name="s2")
        p.add(src, t, s1, s2)
        p.link(src, t)
        p.link_pads(t, "src_%u", s1, "sink")
        p.link_pads(t, "src_%u", s2, "sink")
        with p:
            src.push_buffer(frame(7))
            src.end_of_stream()
            assert p.wait_eos(timeout=5)
            b1, b2 = s1.pull(timeout=1), s2.pull(timeout=1)
        assert b1 is not None and b2 is not None
        assert int(b1[0].np()[0, 0]) == 7 == int(b2[0].np()[0, 0])

    def test_tensor_sink_callback(self):
        seen = []
        p = Pipeline()
        src = AppSrc(name="src", spec=SPEC)
        sink = TensorSink(name="ts", callback=lambda b: seen.append(b))
        p.add(src, sink).link(src, sink)
        with p:
            src.push_buffer(frame(1))
            src.push_buffer(frame(2))
            src.end_of_stream()
            assert p.wait_eos(timeout=5)
        assert len(seen) == 2
        assert sink.buffers_rendered == 2


class TestNegotiation:
    def test_caps_propagate_to_all_pads(self):
        p, src, sink = build_simple("identity", "queue")
        p.start()
        try:
            for e in p.elements.values():
                for pad in e.sinkpads + e.srcpads:
                    if pad.peer:
                        assert pad.caps is not None and pad.caps.is_fixed()
            assert sink.sinkpad.spec.is_compatible(SPEC)
        finally:
            p.stop()

    def test_capsfilter_mismatch_fails(self):
        p = Pipeline()
        src = AppSrc(name="src", spec=SPEC)
        sink = AppSink(name="out")
        cf = make("capsfilter",
                  caps="other/tensors,format=static,dimensions=5:5,"
                       "types=float32,num_tensors=1")
        p.add(src, sink, cf).link(src, cf, sink)
        with pytest.raises(NegotiationError):
            p.start()
        p.stop()

    def test_unlinked_sink_pad_fails(self):
        p = Pipeline()
        src = AppSrc(name="src", spec=SPEC)
        sink = AppSink(name="out")
        p.add(src, sink)  # not linked
        with pytest.raises(NegotiationError):
            p.start()
        p.stop()

    def test_no_source_fails(self):
        p = Pipeline()
        p.add(AppSink(name="out"))
        with pytest.raises(NegotiationError):
            p.start()


class TestErrors:
    def test_element_error_reaches_bus(self):
        class Boom(TensorSink):
            FACTORY = "boom"

            def render(self, buf):
                raise ValueError("boom")

        p = Pipeline()
        src = AppSrc(name="src", spec=SPEC)
        sink = Boom(name="b")
        p.add(src, sink).link(src, sink)
        with p:
            src.push_buffer(frame(0))
            with pytest.raises(RuntimeError, match="boom"):
                src.end_of_stream()
                p.wait_eos(timeout=5)


class TestParser:
    def test_parse_linear(self):
        p = parse_launch("appsrc name=src ! identity ! queue "
                         "max-size-buffers=8 ! appsink name=out")
        assert set(p.elements) >= {"src", "out"}
        src, out = p["src"], p["out"]
        assert isinstance(src, AppSrc) and isinstance(out, AppSink)
        q = [e for e in p.elements.values() if isinstance(e, Queue)][0]
        assert q.max_size_buffers == 8
        src.spec = SPEC
        with p:
            src.push_buffer(frame(3))
            src.end_of_stream()
            assert p.wait_eos(timeout=5)
            assert int(out.pull(timeout=1)[0].np()[0, 0]) == 3

    def test_parse_branches_by_reference(self):
        p = parse_launch(
            "appsrc name=src ! tee name=t "
            "t. ! queue ! appsink name=a "
            "t. ! queue ! appsink name=b")
        p["src"].spec = SPEC
        with p:
            p["src"].push_buffer(frame(9))
            p["src"].end_of_stream()
            assert p.wait_eos(timeout=5)
            assert int(p["a"].pull(timeout=1)[0].np()[0, 0]) == 9
            assert int(p["b"].pull(timeout=1)[0].np()[0, 0]) == 9

    def test_parse_caps_string_segment(self):
        p = parse_launch(
            "appsrc name=src ! other/tensors,format=static,"
            "num_tensors=1,dimensions=4:3,types=float32 ! appsink name=out")
        p["src"].spec = SPEC
        with p:
            p["src"].push_buffer(frame(1))
            p["src"].end_of_stream()
            assert p.wait_eos(timeout=5)

    def test_parse_unknown_element(self):
        from nnstreamer_tpu.runtime.parser import ParseError

        with pytest.raises(ParseError) as ei:
            parse_launch("appsrc ! nosuchelement ! appsink")
        # error points at the offending token
        assert ei.value.pos == len("appsrc ! ")

    def test_parse_fraction_property(self):
        from nnstreamer_tpu.runtime.parser import _parse_value

        assert _parse_value("30/1") == Fraction(30, 1)
        assert _parse_value("640") == 640
        assert _parse_value("RGB") == "RGB"
        # booleans and floats from pipeline strings (gst-launch grammar)
        assert _parse_value("false") is False
        assert _parse_value("TRUE") is True
        assert _parse_value("0.5") == 0.5
        assert _parse_value("300:300") == "300:300"
        assert _parse_value("/path/to.pkl") == "/path/to.pkl"

    def test_parse_bool_property_reaches_element(self):
        p = parse_launch(
            "appsrc name=src ! tensor_transform mode=arithmetic "
            "option=mul:2.0 acceleration=false ! appsink name=out")
        t = next(e for e in p.elements.values()
                 if e.FACTORY == "tensor_transform")
        assert t.acceleration is False


class TestConfigFile:
    """Per-element config files (parity: config-file prop,
    gst_tensor_parse_config_file)."""

    def test_properties_from_file(self, tmp_path):
        cfg = tmp_path / "t.conf"
        cfg.write_text("# transform settings\n"
                       "mode=arithmetic\n"
                       "option=mul:2.0\n"
                       "acceleration=false\n")
        from nnstreamer_tpu.elements.transform import TensorTransform

        t = TensorTransform(name="t", config_file=str(cfg))
        assert t.mode == "arithmetic"
        assert t.option == "mul:2.0"
        assert t.acceleration is False

    def test_file_overrides_ctor_and_set_property_overrides_file(
            self, tmp_path):
        cfg = tmp_path / "t.conf"
        cfg.write_text("mode=typecast\noption=float32\n")
        from nnstreamer_tpu.elements.transform import TensorTransform

        # documented precedence: file > constructor values
        t = TensorTransform(name="t", config_file=str(cfg),
                            option="float64")
        assert t.mode == "typecast"
        assert t.option == "float32"
        # ... and later set_property > file
        t.set_property("option", "float64")
        assert t.option == "float64"

    def test_unknown_key_and_bad_line_raise(self, tmp_path):
        from nnstreamer_tpu.elements.transform import TensorTransform

        bad = tmp_path / "bad.conf"
        bad.write_text("nosuchprop=1\n")
        with pytest.raises(ValueError):
            TensorTransform(name="t", config_file=str(bad))
        mal = tmp_path / "mal.conf"
        mal.write_text("just-a-token\n")
        with pytest.raises(ValueError):
            TensorTransform(name="t", config_file=str(mal))

    def test_config_file_via_parse_launch(self, tmp_path):
        cfg = tmp_path / "t.conf"
        cfg.write_text("mode=arithmetic\noption=add:1.0\n")
        p = parse_launch(f"appsrc name=src ! tensor_transform "
                         f"config-file={cfg} ! appsink name=out")
        p["src"].spec = SPEC
        with p:
            p["src"].push_buffer(frame(1))
            p["src"].end_of_stream()
            assert p.wait_eos(timeout=60)
            out = p["out"].pull(timeout=1)
        np.testing.assert_allclose(out.tensors[0].np()[0, 0], 2.0)

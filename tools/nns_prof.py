#!/usr/bin/env python
"""In-tree shim: implementation lives in nnstreamer_tpu.obs.prof."""
import os
import sys

try:
    import nnstreamer_tpu  # noqa: F401
except ImportError:
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

from nnstreamer_tpu.obs.prof import main

if __name__ == "__main__":
    sys.exit(main() or 0)

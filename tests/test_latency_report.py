"""Latency-reporting regression tests (round-4 verdict #6).

Pins the SEMANTICS of the latency/throughput numbers, not just their
signs: the ``latency_us``/``throughput`` element props (parity:
/root/reference/tests/nnstreamer_latency/unittest_latency.cc and the
property contract in tensor_filter_common.c:982-996) and the bench's
probe-bracketing derivation (bench.derive_latency_stats) that turns
raw e2e timings + transport-probe floors into the published
p50/p99/floor report.
"""

import numpy as np
import pytest

from nnstreamer_tpu.bench import derive_latency_stats
from nnstreamer_tpu.utils.stats import InvokeStats

# -- InvokeStats props ---------------------------------------------------------


class TestInvokeStatsProps:
    def test_latency_unset_is_minus_one(self):
        assert InvokeStats().latency_us == -1

    def test_latency_is_mean_of_recent_window_us(self):
        st = InvokeStats(window=4)
        for s in (0.001, 0.002, 0.003):
            st.record(s)
        assert st.latency_us == pytest.approx(2000, abs=2)

    def test_latency_window_rolls(self):
        st = InvokeStats(window=2)
        for s in (0.010, 0.001, 0.003):
            st.record(s)
        # only the last two samples (1 ms, 3 ms) remain
        assert st.latency_us == pytest.approx(2000, abs=2)

    def test_counted_invokes_do_not_pollute_latency(self):
        st = InvokeStats()
        st.record(0.002)
        st.count()  # async dispatch: throughput-only
        assert st.latency_us == pytest.approx(2000, abs=2)
        assert st.total_invoke_num == 2

    def test_throughput_needs_two_invokes(self):
        st = InvokeStats()
        assert st.throughput_milli_fps == -1
        st.record(0.001)
        assert st.throughput_milli_fps == -1

    def test_throughput_is_interval_based_milli_fps(self, monkeypatch):
        import nnstreamer_tpu.utils.stats as stats_mod

        ts = iter([10.0, 10.5, 11.0])  # 2 intervals over 1 s
        monkeypatch.setattr(stats_mod.time, "monotonic", lambda: next(ts))
        st = InvokeStats()
        for _ in range(3):
            st.count()
        # (n-1)/(last-first) = 2 fps → 2000 milli-fps
        assert st.throughput_milli_fps == 2000

    def test_latency_report_threshold(self):
        st = InvokeStats()
        st.record(0.001)
        first = st.latency_to_report()
        assert first is not None and first > 0
        # unchanged latency: below threshold, no re-report
        assert st.latency_to_report() is None


# -- bench derivation ----------------------------------------------------------


class TestDeriveLatencyStats:
    def test_pure_device_no_link(self):
        # zero-floor probes: device excess IS the latency
        lats = [2.0, 2.2, 1.8, 2.0, 2.1, 1.9, 2.0, 2.0]
        r = derive_latency_stats(lats, [0.0] * len(lats))
        assert r["p99_frame_latency_note"] == "device-dominated"
        assert r["tail_excluded_frames"] == 0
        assert r["p50_device_ms"] == pytest.approx(2.0, abs=0.01)
        assert r["p50_frame_latency_ms"] == pytest.approx(2.0, abs=0.01)
        assert r["latency_probe_floor_ms"] == 0.0

    def test_link_dominated_annotation(self):
        # 90 ms of link under every frame, ~2 ms device time: the floor
        # exceeds device p50 → link-dominated, and device percentiles
        # recover the ~2 ms
        floors = [90.0] * 10
        lats = [92.0, 92.1, 91.9, 92.0, 92.2, 91.8, 92.0, 92.1, 91.9,
                92.0]
        r = derive_latency_stats(lats, floors)
        assert r["p99_frame_latency_note"] == "link-dominated"
        assert r["p50_device_ms"] == pytest.approx(2.0, abs=0.1)
        assert r["latency_probe_floor_ms"] == pytest.approx(90.0)
        # raw percentiles keep the transport (honest reporting)
        assert r["p50_frame_latency_ms"] == pytest.approx(92.0, abs=0.1)

    def test_burst_frames_excluded_from_device_tail(self):
        # one frame hit by a 500 ms burst that neither probe saw:
        # excluded from device percentiles, counted
        floors = [10.0] * 10
        lats = [12.0] * 9 + [510.0]
        r = derive_latency_stats(lats, floors)
        assert r["tail_excluded_frames"] == 1
        assert r["p99_device_ms"] == pytest.approx(2.0, abs=0.1)
        # raw p99 still shows the burst (nothing hidden)
        assert r["p99_frame_latency_ms"] > 400.0

    def test_exclusion_threshold_is_3x_median_plus_1ms(self):
        floors = [0.0] * 9
        # median excess = 2.0 → threshold 7.0: 6.9 kept, 7.1 dropped
        lats = [2.0] * 7 + [6.9, 7.1]
        r = derive_latency_stats(lats, floors)
        assert r["tail_excluded_frames"] == 1

    def test_negative_excess_clamped(self):
        # probe slower than the frame (jitter): excess clamps at 0
        r = derive_latency_stats([5.0, 5.0, 5.0, 5.0],
                                 [6.0, 6.0, 6.0, 6.0])
        assert r["p50_device_ms"] == 0.0
        assert r["tail_excluded_frames"] == 0

    def test_floor_is_median_of_probes(self):
        lats = [10.0] * 5
        floors = [1.0, 2.0, 3.0, 4.0, 100.0]
        r = derive_latency_stats(lats, floors)
        assert r["latency_probe_floor_ms"] == pytest.approx(3.0)

"""Decoder sub-plugin tests: bounding_boxes (ssd/yolo), image_segment,
pose, tensor_region (+crop cascade), octet_stream, flexbuf.

Modeled on the reference's decoder test dirs
(/root/reference/tests/nnstreamer_decoder_boundingbox, ..._pose, etc.):
synthetic model outputs with known geometry → golden assertions.
"""

import numpy as np
import pytest

from nnstreamer_tpu.core import Buffer, TensorsSpec
from nnstreamer_tpu.decoders import find_decoder, list_decoders
from nnstreamer_tpu.decoders.boxutil import Detection, iou_xywh, nms


class TestBoxUtil:
    def test_iou(self):
        a = np.array([0, 0, 2, 2], np.float32)
        b = np.array([[1, 1, 2, 2], [4, 4, 1, 1]], np.float32)
        got = iou_xywh(a, b)
        np.testing.assert_allclose(got, [1 / 7, 0.0], rtol=1e-6)

    def test_nms_keeps_best_per_overlap(self):
        dets = [
            Detection(0, 0, 1, 1, class_id=1, score=0.9),
            Detection(0.05, 0.05, 1, 1, class_id=1, score=0.8),
            Detection(0.5, 0.5, 1, 1, class_id=2, score=0.7),
        ]
        kept = nms(dets, iou_thresh=0.5)
        assert len(kept) == 2
        assert kept[0].score == 0.9 and kept[1].class_id == 2


class TestBoundingBoxes:
    def test_ssd_postprocess_layout(self):
        dec = find_decoder("bounding_boxes")()
        dec.set_option(0, "mobilenet-ssd-postprocess")
        dec.set_option(3, "100:100")
        boxes = np.array([[0.1, 0.2, 0.5, 0.6]], np.float32)  # ymin..xmax
        buf = Buffer.of(boxes, np.array([3.0], np.float32),
                        np.array([0.9], np.float32),
                        np.array([1.0], np.float32))
        out = dec.decode(buf, None)
        dets = out.meta["detections"]
        assert len(dets) == 1
        d = dets[0]
        assert (round(d.x, 3), round(d.y, 3)) == (0.2, 0.1)
        assert d.class_id == 3 and d.score > 0.85
        frame = out.tensors[0].np()
        assert frame.shape == (100, 100, 4)
        assert frame[10, 30, 3] == 255  # top edge drawn (alpha set)

    def test_device_render_matches_host(self):
        # option7=device rasterizes on the accelerator; pixels must match
        # the host draw_boxes path exactly (same rounding/clip/order)
        rng = np.random.default_rng(3)
        b, n = 3, 5
        raw = rng.uniform(0.05, 0.95, (b, n, 4)).astype(np.float32)
        boxes = np.stack([np.minimum(raw[..., 0], raw[..., 2]),
                          np.minimum(raw[..., 1], raw[..., 3]),
                          np.maximum(raw[..., 0], raw[..., 2]),
                          np.maximum(raw[..., 1], raw[..., 3])], -1)
        # sliver boxes thinner/shorter than the 2px stroke: the host
        # slices overdraw past the far edge and the device must match
        boxes[0, 0] = [0.3, 0.3, 0.3, 0.6]    # zero-height
        boxes[0, 1] = [0.5, 0.7, 0.52, 0.705]  # ~1px wide
        classes = rng.integers(0, 10, (b, n)).astype(np.float32)
        scores = rng.uniform(0.3, 1.0, (b, n)).astype(np.float32)
        scores[1, 2] = 0.1  # below conf threshold → not drawn
        num = np.array([5, 3, 0], np.float32)  # frame 2 draws nothing

        def run(backend):
            dec = find_decoder("bounding_boxes")()
            dec.set_option(0, "mobilenet-ssd-postprocess")
            dec.set_option(3, "120:80")
            if backend:
                dec.set_option(6, backend)
            buf = Buffer.of(boxes, classes, scores, num)
            return dec.decode(buf, None)

        host = run(None).tensors[0].np()
        out = run("device")
        dev = out.tensors[0].np()
        assert dev.shape == host.shape == (3, 80, 120, 4)
        np.testing.assert_array_equal(dev, host)
        assert (dev[2] == 0).all()  # num=0 frame stays blank
        dd = out.meta["detections_device"]
        assert np.asarray(dd["boxes"]).shape == (b, n, 4)

    def test_device_render_single_frame_rank_matches_host(self):
        # (1,N,4) canonical single-frame layout: both backends emit an
        # UNbatched (H,W,4) frame per the negotiated caps
        boxes = np.array([[[0.1, 0.2, 0.5, 0.6]]], np.float32)
        args = (np.array([[3.0]], np.float32),
                np.array([[0.9]], np.float32),
                np.array([[1.0]], np.float32))

        def run(backend):
            dec = find_decoder("bounding_boxes")()
            dec.set_option(0, "mobilenet-ssd-postprocess")
            dec.set_option(3, "100:100")
            if backend:
                dec.set_option(6, backend)
            return dec.decode(Buffer.of(boxes, *args), None)

        host = run(None).tensors[0].np()
        dev = run("device").tensors[0].np()
        assert host.shape == dev.shape == (100, 100, 4)
        np.testing.assert_array_equal(dev, host)

    def test_device_backend_opts_out_of_host_prefetch(self):
        """tensor_decoder must not issue device→host copies for a
        decoder that renders on-device (review finding, round 3)."""
        dec = find_decoder("bounding_boxes")()
        assert dec.wants_host_input()          # host path reads on host
        dec.set_option(0, "mobilenet-ssd-postprocess")
        dec.set_option(6, "device")
        assert not dec.wants_host_input()      # device path stays in HBM
        dec.set_option(0, "yolov5")            # no device renderer → host
        assert dec.wants_host_input()

    def test_yolov5_layout(self):
        dec = find_decoder("bounding_boxes")()
        dec.set_option(0, "yolov5")
        dec.set_option(2, "0.4:0.5")
        dec.set_option(4, "640:640")
        # one anchor above threshold: centered box, class 2
        arr = np.zeros((1, 3, 8), np.float32)  # (1, A, 5+3)
        arr[0, 1] = [320, 320, 64, 64, 3.0, -5, -5, 3.0]  # logits→sigmoid? no: raw
        # yolov5 exports post-sigmoid values; emulate directly:
        arr[0, 1, 4] = 0.9
        arr[0, 1, 5:] = [0.1, 0.2, 0.95]
        out = dec.decode(Buffer.of(arr), None)
        dets = out.meta["detections"]
        assert len(dets) == 1
        d = dets[0]
        assert d.class_id == 2
        assert abs(d.x - (320 - 32) / 640) < 1e-5
        assert abs(d.w - 0.1) < 1e-5

    def test_yolov8_layout(self):
        dec = find_decoder("bounding_boxes")()
        dec.set_option(0, "yolov8")
        dec.set_option(2, "0.5:0.5")
        dec.set_option(4, "640:640")
        arr = np.zeros((1, 7, 4), np.float32)  # (1, 4+C, A), C=3
        arr[0, :4, 2] = [160, 160, 32, 32]
        arr[0, 4 + 1, 2] = 0.8  # class 1
        out = dec.decode(Buffer.of(arr), None)
        dets = out.meta["detections"]
        assert len(dets) == 1 and dets[0].class_id == 1


    def test_ov_person_detection_layout(self):
        """(7,200) descriptor rows terminated by image_id<0 (parity:
        box_properties/ovdetection.cc)."""
        dec = find_decoder("bounding_boxes")()
        dec.set_option(0, "ov-person-detection")
        dec.set_option(4, "100:100")
        arr = np.zeros((200, 7), np.float32)
        arr[0] = [0, 1, 0.95, 0.1, 0.2, 0.5, 0.6]   # kept
        arr[1] = [0, 1, 0.30, 0.2, 0.2, 0.4, 0.4]   # below 0.8
        arr[2] = [-1, 0, 0, 0, 0, 0, 0]             # terminator
        arr[3] = [0, 1, 0.99, 0.0, 0.0, 1.0, 1.0]   # after terminator
        out = dec.decode(Buffer.of(arr), None)
        dets = out.meta["detections"]
        assert len(dets) == 1
        d = dets[0]
        assert abs(d.x - 0.1) < 1e-6 and abs(d.w - 0.4) < 1e-6
        assert abs(d.y - 0.2) < 1e-6 and abs(d.h - 0.4) < 1e-6

    def test_mp_palm_detection_layout(self):
        """MediaPipe palm: 2016 anchors (192-input, strides 8/16/16/16,
        two unit anchors per layer-run member), clamped-sigmoid scores
        (parity: box_properties/mppalmdetection.cc)."""
        dec = find_decoder("bounding_boxes")()
        dec.set_option(0, "mp-palm-detection")
        dec.set_option(4, "192:192")
        anchors = dec._palm_anchors()
        assert anchors.shape == (2016, 4)  # 24²·2 + 12²·6
        boxes = np.zeros((2016, 18), np.float32)
        scores = np.full((2016,), -10.0, np.float32)  # sigmoid ≈ 0
        # a central anchor (cell 12,12): zero offsets → box centered on
        # the anchor itself, away from the border clamp
        idx = 2 * (12 * 24 + 12)
        scores[idx] = 5.0                             # sigmoid ≈ 0.993
        boxes[idx, :4] = [0.0, 0.0, 96.0, 96.0]       # h=w=96px → 0.5
        out = dec.decode(Buffer.of(boxes, scores), None)
        dets = out.meta["detections"]
        assert len(dets) == 1
        d = dets[0]
        ay, ax = anchors[idx, 0], anchors[idx, 1]
        assert abs(d.w - 0.5) < 1e-5 and abs(d.h - 0.5) < 1e-5
        assert abs((d.x + d.w / 2) - ax) < 1e-5
        assert abs((d.y + d.h / 2) - ay) < 1e-5
        assert d.score > 0.99

    def test_mp_palm_threshold_option(self):
        dec = find_decoder("bounding_boxes")()
        dec.set_option(0, "mp-palm-detection")
        dec.set_option(2, "0.9")
        boxes = np.zeros((2016, 18), np.float32)
        scores = np.full((2016,), 1.0, np.float32)   # sigmoid ≈ 0.731
        out = dec.decode(Buffer.of(boxes, scores), None)
        assert len(out.meta["detections"]) == 0      # 0.731 < 0.9


class TestImageSegment:
    def test_deeplab_argmax_colors(self):
        dec = find_decoder("image_segment")()
        scores = np.zeros((4, 4, 3), np.float32)
        scores[:2, :, 1] = 5.0  # top half class 1
        scores[2:, :, 2] = 5.0  # bottom half class 2
        out = dec.decode(Buffer.of(scores), None)
        seg = out.meta["segment_map"]
        assert seg.shape == (4, 4)
        assert (seg[:2] == 1).all() and (seg[2:] == 2).all()
        frame = out.tensors[0].np()
        assert frame.shape == (4, 4, 4)
        assert (frame[0, 0] != frame[3, 0]).any()  # distinct colors


class TestPose:
    def test_heatmap_argmax_keypoints(self):
        dec = find_decoder("pose_estimation")()
        dec.set_option(0, "64:64")
        hm = np.full((8, 8, 2), -10.0, np.float32)
        hm[2, 6, 0] = 9.0   # kp0 at x=6/7, y=2/7
        hm[5, 1, 1] = 9.0   # kp1 at x=1/7, y=5/7
        out = dec.decode(Buffer.of(hm), None)
        kps = out.meta["keypoints"]
        assert len(kps) == 2
        assert abs(kps[0]["x"] - 6 / 7) < 1e-6
        assert abs(kps[1]["y"] - 5 / 7) < 1e-6
        assert kps[0]["score"] > 0.99
        assert out.tensors[0].np().shape == (64, 64, 4)


class TestRegionCropCascade:
    def test_region_feeds_crop(self):
        """Detection → tensor_region → tensor_crop cascade (parity:
        tests/nnstreamer_decoder_tensorRegion)."""
        from nnstreamer_tpu.elements.basic import AppSink, AppSrc
        from nnstreamer_tpu.runtime import Pipeline, make

        dec = find_decoder("tensor_region")()
        dec.set_option(0, "1")
        dec.set_option(2, "8:8")
        boxes = np.array([[0.25, 0.25, 0.75, 0.75]], np.float32)
        buf = Buffer.of(boxes, np.array([1.0], np.float32),
                        np.array([0.9], np.float32),
                        np.array([1.0], np.float32))
        region_buf = dec.decode(buf, None)
        regions = region_buf.tensors[0].np()
        np.testing.assert_array_equal(regions[0], [2, 2, 4, 4])

        p = Pipeline()
        raw = AppSrc(name="raw", spec=TensorsSpec.parse("3:8:8", "uint8"))
        info = AppSrc(name="info", spec=TensorsSpec.parse("4:1", "uint32"))
        crop = make("tensor_crop", el_name="c")
        sink = AppSink(name="out")
        p.add(raw, info, crop, sink)
        p.link_pads(raw, "src", crop, "sink_raw")
        p.link_pads(info, "src", crop, "sink_info")
        p.link(crop, sink)
        img = np.arange(8 * 8 * 3, dtype=np.uint8).reshape(8, 8, 3)
        with p:
            raw.push_buffer(Buffer.of(img))
            info.push_buffer(region_buf)
            raw.end_of_stream()
            info.end_of_stream()
            assert p.wait_eos(timeout=5)
            out = sink.pull(timeout=1)
        np.testing.assert_array_equal(
            out.tensors[0].np(), img[2:6, 2:6, :])


class TestWireDecoders:
    def test_octet_stream_concat(self):
        dec = find_decoder("octet_stream")()
        buf = Buffer.of(np.array([1, 2], np.uint8),
                        np.array([3.5], np.float32))
        out = dec.decode(buf, None)
        raw = out.tensors[0].np().tobytes()
        assert raw[:2] == b"\x01\x02"
        assert np.frombuffer(raw[2:], np.float32)[0] == 3.5

    def test_flexbuf_roundtrip(self):
        # flexbuf now emits the real FlexBuffers wire (other/flexbuf),
        # decoded by the flexbuf converter codec
        from nnstreamer_tpu.converters.codecs import flexbuf_decode

        dec = find_decoder("flexbuf")()
        x = np.arange(6, dtype=np.int32).reshape(2, 3)
        out = dec.decode(Buffer.of(x), None)
        restored, _spec = flexbuf_decode(out.tensors[0].tobytes())
        np.testing.assert_array_equal(restored.tensors[0].np(), x)

    def test_all_reference_decoder_modes_present(self):
        """SURVEY.md §2.4 decoder inventory coverage check."""
        modes = set(list_decoders())
        for required in ("direct_video", "image_labeling", "bounding_boxes",
                         "image_segment", "pose_estimation", "tensor_region",
                         "octet_stream", "flexbuf"):
            assert required in modes, f"missing decoder {required}"

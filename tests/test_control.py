"""`runtime/actuators.py` + `obs/control.py` — the actuation plane
(ISSUE-11 surface).

Actuator guards (min/max clamping reported, cooldown rejection,
reversibility restoring the EXACT prior config incl. per-stream queue
limits), the concurrent-actuation-vs-`Pipeline.stop()` race (mirror of
the PR-10 scrape-vs-stop race), the batcher pause/resume seam,
breaker forced transitions (+ the kicked sleep), playbook grammar
(TOML/JSON, malformed files, duplicate names), the controller loop
(alert → playbook → actuation, alert-label target narrowing, cooldown
and guard outcomes, on_resolve revert), the decision audit ring vs the
exported `nns_control_actions_total` (counts equal), the snapshot-v6
`control` table + shape golden companion, `/healthz` control summary,
the nns-top CONTROL section, the strict kill-switch no-op, and the
`nns-ctl` CLI."""

import io
import json
import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.chaos.retrypolicy import (CLOSED, HALF_OPEN, OPEN,
                                              RetryPolicy)
from nnstreamer_tpu.core import Buffer, TensorsSpec
from nnstreamer_tpu.elements.basic import AppSink, AppSrc, Queue
from nnstreamer_tpu.elements.filter import TensorFilter
from nnstreamer_tpu.filters.jax_xla import register_model, unregister_model
from nnstreamer_tpu.obs import control as control_mod
from nnstreamer_tpu.obs import hooks as obs_hooks
from nnstreamer_tpu.obs.control import (Controller, Playbook,
                                        PlaybookError, control_health,
                                        control_table,
                                        default_playbooks,
                                        lint_playbook, load_playbooks,
                                        parse_playbooks)
from nnstreamer_tpu.obs.metrics import REGISTRY
from nnstreamer_tpu.obs.watch import AlertRule, Watch
from nnstreamer_tpu.runtime import Pipeline
from nnstreamer_tpu.runtime.actuators import (ActuationError, Actuator,
                                              CooldownActive,
                                              find_actuators,
                                              list_actuators)

SHAPE = (4,)


@pytest.fixture(scope="module", autouse=True)
def _model():
    register_model("_t_ctl", lambda x: x + 1.0, in_shapes=[SHAPE],
                   in_dtypes=np.float32)
    yield
    unregister_model("_t_ctl")


def _pool_pipe(name, slo_ms=0.0, priority="normal", batch=4):
    spec = TensorsSpec.from_shapes([SHAPE], np.float32)
    p = Pipeline(name=name)
    src = AppSrc(name="src", spec=spec, max_buffers=64)
    q = Queue(name="q", max_size_buffers=64)
    flt = TensorFilter(name="net", framework="jax-xla", model="_t_ctl",
                       batch=batch, batch_timeout_ms=2.0,
                       batch_buckets=str(batch), share_model=True,
                       slo_ms=slo_ms, priority=priority)
    sink = AppSink(name="sink", max_buffers=64)
    p.add(src, q, flt, sink).link(src, q, flt, sink)
    return p, {"src": src, "q": q, "flt": flt, "sink": sink}


# -- actuator guards (satellite: edge cases) ----------------------------------


def test_actuator_clamps_and_reports():
    v = {"x": 5.0}
    act = Actuator("knob", "pool", "t", get_fn=lambda: v["x"],
                   set_fn=lambda n: v.update(x=n), lo=1.0, hi=10.0,
                   cooldown_s=0.0)
    res = act.actuate(25.0)
    assert res["applied"] == 10.0 and res["clamped"] is True
    assert res["requested"] == 25.0 and v["x"] == 10.0
    res = act.actuate(-3.0)
    assert res["applied"] == 1.0 and res["clamped"] is True
    res = act.actuate(7.0)
    assert res["applied"] == 7.0 and res["clamped"] is False


def test_actuator_cooldown_rejects_then_admits():
    v = {"x": 0.0}
    act = Actuator("knob", "pool", "t", get_fn=lambda: v["x"],
                   set_fn=lambda n: v.update(x=n), cooldown_s=0.2)
    act.actuate(1.0)
    with pytest.raises(CooldownActive):
        act.actuate(2.0)
    assert v["x"] == 1.0  # the rejected write never landed
    time.sleep(0.25)
    assert act.actuate(2.0)["applied"] == 2.0


def test_actuator_revert_restores_exact_prior():
    """Two forward actuations then revert: the knob returns to the
    value BEFORE the first steer, not the intermediate one; revert
    bypasses the cooldown (backing out is always allowed) and a second
    revert is a no-op."""
    v = {"x": 3.0}
    act = Actuator("knob", "pool", "t", get_fn=lambda: v["x"],
                   set_fn=lambda n: v.update(x=n), cooldown_s=0.0)
    act.actuate(5.0)
    act.actuate(9.0)
    act.cooldown_s = 60.0  # revert must not care
    res = act.revert()
    assert res["applied"] == 3.0 and res["prior"] == 9.0
    assert v["x"] == 3.0
    assert act.revert() is None


def test_pool_actuators_bounds_and_revert():
    """The real PoolEntry knobs: window-ms/max-batch clamp to their
    guards, queue-limit restores PER STREAM on revert (the exact-prior
    contract on a non-scalar config)."""
    pa, ea = _pool_pipe("act-a", slo_ms=50.0)
    pb, eb = _pool_pipe("act-b", slo_ms=50.0)
    pa.start()
    pb.start()
    try:
        entry = ea["flt"].pool
        acts = entry.actuators()
        for act in acts.values():
            act.cooldown_s = 0.0
        # max-batch: hi is the largest compiled bucket
        res = acts["max-batch"].actuate(99.0)
        assert res["applied"] == 4.0 and res["clamped"]
        res = acts["max-batch"].actuate(1.0)
        assert entry.batcher.max_batch == 1
        acts["max-batch"].revert()
        assert entry.batcher.max_batch == 4
        # window-ms: floor guard
        res = acts["window-ms"].actuate(0.0)
        assert res["applied"] == 0.1 and res["clamped"]
        acts["window-ms"].revert()
        assert entry.batcher.timeout_s == pytest.approx(0.002)
        # queue-limit: distinct per-stream priors restore exactly
        with entry._lock:
            pols = list(entry._policies.values())
            pols[0].queue_limit = 7
            pols[1].queue_limit = 13
        acts["queue-limit"].actuate(2.0)
        assert {p.queue_limit for p in pols} == {2}
        acts["queue-limit"].revert()
        assert sorted(p.queue_limit for p in pols) == [7, 13]
        # ramp-start clamps into (0.3, 0.99)
        res = acts["ramp-start"].actuate(0.01)
        assert res["applied"] == 0.3 and res["clamped"]
        assert entry.admission.ramp_start == 0.3
        acts["ramp-start"].revert()
        assert entry.admission.ramp_start == pytest.approx(0.7)
    finally:
        pa.stop()
        pb.stop()


def test_window_ms_revert_restores_settle_too():
    """_set_window_ms shrinks the adaptive settle alongside the
    deadline (settle <= timeout invariant); revert must restore BOTH
    — a scalar prior would leave settle collapsed forever while the
    knob reports clean (review finding)."""
    p, e = _pool_pipe("settle")
    p.start()
    try:
        entry = e["flt"].pool
        b = entry.batcher
        act = entry.actuators()["window-ms"]
        act.cooldown_s = 0.0
        settle0, timeout0 = b.settle_s, b.timeout_s
        act.actuate(0.2)  # 0.2 ms deadline collapses settle under it
        assert b.settle_s <= 0.0002
        act.revert()
        assert b.timeout_s == pytest.approx(timeout0)
        assert b.settle_s == pytest.approx(settle0)
    finally:
        p.stop()


def test_actuation_races_pipeline_stop():
    """Actuators hammered from threads while pipelines start, stream
    and stop must never crash: a torn-down window fails the actuation
    with a clean ActuationError (counted, not raised through) — the
    mirror of the PR-10 scrape-vs-stop race."""
    spec = TensorsSpec.from_shapes([SHAPE], np.float32)
    errors = []
    stop_evt = threading.Event()
    outcomes = {"ok": 0, "gone": 0}

    def actuator_thread():
        while not stop_evt.is_set():
            try:
                for act in list_actuators("pool"):
                    try:
                        act.cooldown_s = 0.0
                        act.actuate(5.0 if act.name == "window-ms"
                                    else 2.0)
                        act.revert()
                        outcomes["ok"] += 1
                    except ActuationError:
                        outcomes["gone"] += 1  # stop() won the race
            except Exception as e:  # noqa: BLE001 - the assertion
                errors.append(e)
                return

    threads = [threading.Thread(target=actuator_thread)
               for _ in range(3)]
    for t in threads:
        t.start()
    try:
        for round_i in range(6):
            p, e = _pool_pipe(f"actrace-{round_i}")
            p.start()
            for n in range(4):
                e["src"].push_buffer(Buffer.of(
                    np.zeros(SHAPE, np.float32), pts=n))
            e["src"].end_of_stream()
            p.wait_eos(timeout=10, raise_on_error=False)
            p.stop()
    finally:
        stop_evt.set()
        for t in threads:
            t.join(timeout=10)
    assert not errors, errors
    assert outcomes["ok"] > 0


# -- batcher pause / breaker transitions --------------------------------------


def test_pause_parks_resume_drains_eos_ignores_pause():
    p, e = _pool_pipe("pause-a")
    p.start()
    try:
        entry = e["flt"].pool
        act = entry.actuators()["coalescing"]
        act.cooldown_s = 0.0
        act.actuate(0.0)
        for n in range(6):
            e["src"].push_buffer(Buffer.of(
                np.zeros(SHAPE, np.float32), pts=n))
        deadline = time.monotonic() + 5
        while entry.batcher.pending < 6 and \
                time.monotonic() < deadline:
            time.sleep(0.01)
        assert entry.batcher.pending == 6  # full window did NOT flush
        assert e["sink"].pull(timeout=0.1) is None
        act.actuate(1.0)
        got = 0
        deadline = time.monotonic() + 10
        while got < 6 and time.monotonic() < deadline:
            if e["sink"].pull(timeout=0.2) is not None:
                got += 1
        assert got == 6  # full windows + the timer'd remainder
        # EOS through a paused window: frames still drain (never lost)
        act.actuate(0.0)
        e["src"].push_buffer(Buffer.of(np.zeros(SHAPE, np.float32),
                                       pts=7))
        e["src"].end_of_stream()
        assert p.wait_eos(timeout=10)
        assert e["sink"].pull(timeout=1.0) is not None
    finally:
        p.stop()


def test_breaker_forced_transitions_and_kicked_wait():
    pol = RetryPolicy(name="lnk", fail_threshold=2, open_s=30.0)
    pol.failure(RuntimeError("x"))
    pol.failure(RuntimeError("x"))
    assert pol.state == OPEN
    # a loop sleeping out the 30s open window wakes on the forced probe
    woke = []

    def sleeper():
        t0 = time.monotonic()
        pol.wait(max_s=10.0)
        woke.append(time.monotonic() - t0)

    t = threading.Thread(target=sleeper)
    t.start()
    time.sleep(0.1)
    pol.force_half_open()
    t.join(timeout=5)
    assert woke and woke[0] < 5.0  # not the full max_s
    assert pol.state == HALF_OPEN
    # a force landing BEFORE the wait is not lost either: the delay is
    # computed AFTER the kick clears, so it reflects the forced state
    # (review finding: clear-after-delay erased such a kick and slept
    # the stale open window out)
    pol.failure(RuntimeError("x"))  # half-open probe fails: re-OPEN
    assert pol.state == OPEN
    pol.force_half_open()
    t0 = time.monotonic()
    assert pol.wait(max_s=10.0) is True
    assert time.monotonic() - t0 < 2.0  # backoff, not the open window
    pol.reset()
    assert pol.state == CLOSED and pol.consecutive_failures == 0
    pol.force_open()
    assert pol.state == OPEN
    # the breaker actuator maps values onto the forced transitions
    act = pol.actuators()["breaker"]
    act.cooldown_s = 0.0
    assert act.actuate(1.0)["applied"] == 1.0
    assert pol.state == HALF_OPEN
    assert act.actuate(0.0)["applied"] == 0.0
    assert pol.state == CLOSED
    assert find_actuators("link", "lnk", "breaker")


# -- playbook grammar ---------------------------------------------------------


def test_playbook_parse_and_errors(tmp_path):
    pbs = parse_playbooks({"playbook": [
        {"name": "a", "rule": "slo-burn", "kind": "pool",
         "actuator": "ramp-start", "action": "set", "value": 0.5,
         "cooldown": "2s", "on_resolve": "revert"}]})
    assert pbs[0].cooldown_s == 2.0 and pbs[0].on_resolve == "revert"
    with pytest.raises(PlaybookError, match="unknown key"):
        parse_playbooks([{"name": "a", "rule": "r", "kind": "pool",
                          "actuator": "x", "frobnicate": 1}])
    with pytest.raises(PlaybookError, match="unknown target kind"):
        parse_playbooks([{"name": "a", "rule": "r", "kind": "zray",
                          "actuator": "x", "value": 1}])
    with pytest.raises(PlaybookError, match="unknown action"):
        parse_playbooks([{"name": "a", "rule": "r", "kind": "pool",
                          "actuator": "x", "action": "yeet",
                          "value": 1}])
    # a set/step playbook without an explicit value would silently
    # actuate 0.0 (for coalescing: PAUSE the window it meant to fix)
    with pytest.raises(PlaybookError, match="explicit 'value'"):
        parse_playbooks([{"name": "a", "rule": "r", "kind": "pool",
                          "actuator": "coalescing"}])
    with pytest.raises(PlaybookError, match="duplicate"):
        parse_playbooks([
            {"name": "a", "rule": "r", "kind": "pool",
             "actuator": "x", "value": 1},
            {"name": "a", "rule": "r", "kind": "pool",
             "actuator": "x", "value": 1}])
    with pytest.raises(PlaybookError, match="never moves"):
        parse_playbooks([{"name": "a", "rule": "r", "kind": "pool",
                          "actuator": "x", "action": "step",
                          "value": 0}])
    bad = tmp_path / "bad.json"
    bad.write_text("{nope")
    with pytest.raises(PlaybookError, match="invalid JSON"):
        load_playbooks(str(bad))
    # TOML round-trip (tomllib is 3.11+; JSON is the portable form)
    toml = tmp_path / "pb.toml"
    toml.write_text('[[playbook]]\nname = "t"\nrule = "slo-burn"\n'
                    'kind = "pool"\nactuator = "ramp-start"\n'
                    'value = 0.4\ncooldown = "1s"\n')
    try:
        import tomllib  # noqa: F401
    except ImportError:
        pass
    else:
        assert load_playbooks(str(toml))[0].value == 0.4


def test_lint_playbook_and_default_pack_clean():
    ok = Playbook(name="p", rule="slo-burn", kind="pool",
                  actuator="ramp-start")
    assert lint_playbook(ok, ["slo-burn"]) == []
    bad = Playbook(name="p", rule="slo-burn", kind="pool",
                   actuator="warp-drive")
    assert any("does not exist" in s
               for s in lint_playbook(bad, ["slo-burn"]))
    assert any("never trigger" in s
               for s in lint_playbook(ok, ["other-rule"]))
    from nnstreamer_tpu.obs.watch import default_rules

    names = [r.name for r in default_rules()]
    for pb in default_playbooks():
        assert lint_playbook(pb, names) == [], pb.name


# -- the controller loop ------------------------------------------------------


def _ctl_rig(slo_ms=0.0, rules=None, playbooks=None):
    p, e = _pool_pipe("ctl-rig", slo_ms=slo_ms)
    p.start()
    w = Watch(rules=rules or [], interval_s=0.02)
    ctl = Controller(playbooks=playbooks or [], watch=w,
                     interval_s=0.02)
    return p, e, w, ctl


def test_controller_closes_the_loop_and_reverts_on_resolve():
    """pool-stall fires → playbook resumes coalescing; when the rule
    resolves, a second on_resolve=revert playbook restores the knob it
    steered — all of it visible in the audit ring and the exported
    counter with EQUAL counts."""
    rules = [AlertRule(name="pool-stall", kind="threshold",
                       metric="nns_pool_pending", op=">=", value=6.0)]
    playbooks = [
        Playbook(name="resume", rule="pool-stall", kind="pool",
                 actuator="coalescing", action="set", value=1.0,
                 cooldown_s=0.1),
        Playbook(name="narrow", rule="pool-stall", kind="pool",
                 actuator="window-ms", action="set", value=1.0,
                 cooldown_s=0.1, on_resolve="revert"),
    ]
    before = _counter_total()
    p, e, w, ctl = _ctl_rig(rules=rules, playbooks=playbooks)
    try:
        entry = e["flt"].pool
        pause = entry.actuators()["coalescing"]
        pause.cooldown_s = 0.0
        entry.actuators()["window-ms"].cooldown_s = 0.0
        pause.actuate(0.0)
        for n in range(8):
            e["src"].push_buffer(Buffer.of(
                np.zeros(SHAPE, np.float32), pts=n))
        deadline = time.monotonic() + 5
        while entry.batcher.pending < 8 and \
                time.monotonic() < deadline:
            time.sleep(0.01)
        w.sample_once()  # gauge levels bind on the first tick
        w.sample_once()
        assert any(a["rule"] == "pool-stall" and a["firing"]
                   for a in w.alerts())
        decisions = ctl.tick()
        outcomes = {(d["playbook"], d["outcome"]) for d in decisions}
        assert ("resume", "applied") in outcomes
        assert ("narrow", "applied") in outcomes
        assert not entry.batcher.paused
        assert entry.batcher.timeout_s == pytest.approx(0.001)
        # drain → rule resolves → the narrow playbook reverts its knob
        deadline = time.monotonic() + 10
        while entry.batcher.pending > 0 and \
                time.monotonic() < deadline:
            while e["sink"].pull(timeout=0.05) is not None:
                pass
            time.sleep(0.01)
        w.sample_once()
        w.sample_once()
        decisions = ctl.tick()
        assert ("narrow", "reverted") in {
            (d["playbook"], d["outcome"]) for d in decisions}
        assert entry.batcher.timeout_s == pytest.approx(0.002)
        # audit == exported counter, every outcome included
        assert ctl.actions_total == len(ctl.audit)
        assert _counter_total() - before == ctl.actions_total
        # only the revert-on-resolve playbook retained its actuator;
        # a fire-and-forget playbook holding one would pin the pool
        # for the controller's lifetime (review finding)
        assert ctl._states["resume"].applied == {}
        assert ctl._states["narrow"].applied == {}  # drained by revert
        # the alert's own pool label narrowed the target
        assert all(d["target"] == entry.label() for d in ctl.audit)
    finally:
        ctl.stop()
        w.stop()
        p.stop()


def _counter_total():
    fam = REGISTRY.collect().get("nns_control_actions_total", {})
    return sum(s["value"] for s in fam.get("samples", []))


def test_controller_cooldown_no_target_and_guard_outcomes():
    rules = [AlertRule(name="pool-stall", kind="threshold",
                       metric="nns_pool_pending", op=">=", value=0.0)]

    def firing_watch():
        w = Watch(rules=rules, interval_s=0.02, source=lambda: [
            {"endpoint": "local", "error": None, "snap": {
                "pools": [],
                "metrics": {"nns_pool_pending": {
                    "name": "nns_pool_pending", "kind": "gauge",
                    "help": "", "samples": [
                        {"labels": {"pool": "nowhere:pool"},
                         "value": 9.0}]}}}}])
        w.sample_once()
        w.sample_once()
        return w

    w = firing_watch()
    # no-target: the alert names a pool this process doesn't own
    ctl = Controller(playbooks=[Playbook(
        name="p", rule="pool-stall", kind="pool",
        actuator="coalescing", action="set", value=1.0,
        cooldown_s=10.0)], watch=w, interval_s=0.02)
    d = ctl.tick()
    assert [x["outcome"] for x in d] == ["no-target"]
    # playbook cooldown: the SAME firing episode is not even re-decided
    assert ctl.tick() == []
    w.stop()
    # guard-hold: mfu at the ceiling blocks a widen playbook
    w2 = Watch(rules=rules, interval_s=0.02, source=lambda: [
        {"endpoint": "local", "error": None, "snap": {
            "pools": [],
            "metrics": {
                "nns_pool_pending": {
                    "name": "nns_pool_pending", "kind": "gauge",
                    "help": "", "samples": [{"labels": {},
                                             "value": 9.0}]},
                "nns_mfu": {
                    "name": "nns_mfu", "kind": "gauge", "help": "",
                    "samples": [{"labels": {"source": "m"},
                                 "value": 0.95}]}}}}])
    w2.sample_once()
    w2.sample_once()
    ctl2 = Controller(playbooks=[Playbook(
        name="widen", rule="pool-stall", kind="pool",
        actuator="max-batch", action="step", value=4.0,
        guard="mfu-headroom", cooldown_s=10.0)], watch=w2,
        interval_s=0.02)
    d = ctl2.tick()
    assert [x["outcome"] for x in d] == ["guard-hold"]
    w2.stop()


def test_controller_strictly_inert_when_disabled(monkeypatch):
    p, e = _pool_pipe("inert")
    p.start()
    try:
        before = control_table()["controllers"]
        monkeypatch.setattr(obs_hooks, "DISABLED", True)
        ctl = Controller()
        assert ctl.enabled is False
        assert ctl.start() is False
        assert ctl.tick() == []
        assert ctl.apply("pool", "*", "window-ms", value=5.0) == []
        assert ctl.actions_total == 0 and len(ctl.audit) == 0
        monkeypatch.setattr(obs_hooks, "DISABLED", False)
        assert control_table()["controllers"] == before
    finally:
        p.stop()


# -- export surfaces: snapshot v6, /healthz, nns-top --------------------------


def test_snapshot_control_table_and_health():
    p, e = _pool_pipe("snap6")
    p.start()
    ctl = Controller(playbooks=default_playbooks(), watch=None)
    try:
        entry = e["flt"].pool
        entry.actuators()["window-ms"].cooldown_s = 0.0
        ctl.apply("pool", "*", "window-ms", value=5.0)
        snap = REGISTRY.snapshot()
        assert snap["version"] == 10
        c = snap["control"]
        assert c["controllers"] >= 1
        assert c["actions_total"] >= 1
        assert c["last_action"]["actuator"] == "window-ms"
        assert c["last_action"]["outcome"] == "applied"
        assert any(d["playbook"] == "manual" for d in c["audit"])
        h = control_health()
        assert h["actions_total"] >= 1
        assert h["last_action"]["actuator"] == "window-ms"
        # counter total equals audit total across live controllers
        from nnstreamer_tpu.obs.top import render

        txt = render(snap)
        assert "CONTROL" in txt and "window-ms" in txt \
            and "manual" in txt
    finally:
        ctl.stop()
        p.stop()


def test_healthz_carries_control_summary():
    import urllib.request

    from nnstreamer_tpu.obs.metrics import MetricsServer

    p, e = _pool_pipe("hz6")
    p.start()
    srv = MetricsServer(REGISTRY, port=0)
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz",
                timeout=5) as resp:
            doc = json.loads(resp.read().decode())
        assert "control" in doc
        assert {"controllers", "playbooks", "actions_total",
                "last_action"} <= set(doc["control"])
    finally:
        srv.close()
        p.stop()


# -- the nns-ctl CLI ----------------------------------------------------------


def test_nns_ctl_cli_list_apply_revert():
    from nnstreamer_tpu.obs.control import main as ctl_main

    p, e = _pool_pipe("cli")
    p.start()
    try:
        entry = e["flt"].pool
        for a in entry.actuators().values():
            a.cooldown_s = 0.0
        label = entry.label()
        buf = io.StringIO()
        assert ctl_main(["--list"], out=buf) == 0
        out = buf.getvalue()
        assert "window-ms" in out and label in out
        buf = io.StringIO()
        rc = ctl_main(["--apply", f"pool:{label}:window-ms=5",
                       "--json"], out=buf)
        assert rc == 0
        decisions = json.loads(buf.getvalue())
        assert decisions[0]["outcome"] == "applied"
        assert decisions[0]["applied"] == 5.0
        assert entry.batcher.timeout_s == pytest.approx(0.005)
        buf = io.StringIO()
        rc = ctl_main(["--revert", f"pool:{label}:window-ms",
                       "--json"], out=buf)
        assert rc == 0
        assert entry.batcher.timeout_s == pytest.approx(0.002)
        # an out-of-catalog actuation spec errors cleanly
        assert ctl_main(["--apply", "nonsense"],
                        out=io.StringIO()) == 2
        # audit mode aggregates LIVE controllers (the CLI's one-shot
        # controllers die with their invocation): hold one open
        ctl = Controller(playbooks=[], watch=None)
        ctl.apply("pool", label, "window-ms", value=3.0)
        buf = io.StringIO()
        assert ctl_main(["--audit"], out=buf) == 0
        assert "manual" in buf.getvalue()
        ctl.apply("pool", label, "window-ms", revert=True)
    finally:
        p.stop()


def test_nns_ctl_cli_rejects_bad_playbooks(tmp_path):
    from nnstreamer_tpu.obs.control import main as ctl_main

    bad = tmp_path / "bad.json"
    bad.write_text("{nope")
    assert ctl_main(["--run", "--once", "1",
                     "--playbooks", str(bad)],
                    out=io.StringIO()) == 2

"""Profiling hooks (jax.profiler traces, per-element annotation) and the
hardware capability probe."""

import glob
import os

import numpy as np

from nnstreamer_tpu.core import Buffer, TensorsSpec
from nnstreamer_tpu.elements.basic import AppSink, AppSrc
from nnstreamer_tpu.runtime import Pipeline
from nnstreamer_tpu.runtime.registry import make
from nnstreamer_tpu.utils import hw
from nnstreamer_tpu.utils.profile import (
    annotate,
    pipeline_trace,
    trace_active,
)


class TestProfile:
    def test_annotate_noop_without_trace(self):
        assert not trace_active()
        with annotate("x"):  # must not touch jax at all
            pass

    def test_pipeline_trace_captures(self, tmp_path):
        log_dir = str(tmp_path / "trace")
        p = Pipeline()
        src = AppSrc(name="src", spec=TensorsSpec.parse("4", "float32"))
        t = make("tensor_transform", el_name="t", mode="arithmetic",
                 option="mul:2.0")
        sink = AppSink(name="out")
        p.add(src, t, sink).link(src, t, sink)
        with pipeline_trace(log_dir):
            assert trace_active()
            with p:
                src.push_buffer(Buffer.of(np.ones(4, np.float32)))
                src.end_of_stream()
                assert p.wait_eos(timeout=60)
        assert not trace_active()
        # a trace directory with at least one event artifact exists
        found = glob.glob(os.path.join(log_dir, "**", "*"), recursive=True)
        assert any(os.path.isfile(f) for f in found)


class TestHwProbe:
    def test_probe_reports_devices(self):
        caps = hw.probe()
        assert caps, "no platforms visible"
        for platform, devs in caps.items():
            assert devs and all("kind" in d for d in devs)

    def test_accelerator_available(self):
        # at least one of cpu/tpu must resolve in any environment
        assert hw.accelerator_available("cpu") or \
            hw.accelerator_available("tpu")

"""The placement layer (`parallel/placement.py`) and mesh-native shared
serving (ISSUE-12).

Covers the Placement grammar (incl. ``dcn.``-prefixed multi-host axes),
the canonical resolved key (equivalent spellings — ``data:-1`` vs
``data:8``, rule aliases, accelerator spellings — map to ONE key), the
satellite ModelPool bugfix (both spellings join one pool), the
PoolConflictError on genuinely different placements, the stacked
sharded window dispatch (values exact vs the per-frame computation,
pads discarded), the ``mesh=data:1`` frame-for-frame equivalence with
an unsharded pool (the acceptance criterion), and the pool ↔ meshstat
obs join (snapshot pool row shard fields + nns-top POOL columns).
"""

import json

import numpy as np
import pytest

from nnstreamer_tpu.core import Buffer, TensorsSpec
from nnstreamer_tpu.elements.basic import AppSink, AppSrc, Queue
from nnstreamer_tpu.elements.filter import TensorFilter
from nnstreamer_tpu.filters.api import FilterProps
from nnstreamer_tpu.filters.jax_xla import (
    JaxXlaFilter,
    register_model,
    unregister_model,
)
from nnstreamer_tpu.parallel import Placement
from nnstreamer_tpu.runtime import MODEL_POOL, Pipeline
from nnstreamer_tpu.runtime.serving import PoolConflictError, pool_key

SHAPE = (4,)
SPEC = TensorsSpec.from_shapes([SHAPE], np.float32)
W = np.asarray(np.random.RandomState(7).randn(4, 4), np.float32)


@pytest.fixture(scope="module", autouse=True)
def _models():
    register_model("_t_place", lambda x: x @ W + 1.0,
                   in_shapes=[SHAPE], in_dtypes=np.float32)
    yield
    unregister_model("_t_place")


@pytest.fixture(autouse=True)
def _pool_clean():
    yield
    MODEL_POOL.clear()
    with JaxXlaFilter._shared_lock:
        JaxXlaFilter._shared_instances.clear()


# -- Placement: grammar + canonical key ---------------------------------------


class TestPlacementKey:
    def test_equivalent_spellings_one_key(self):
        import jax

        n = len(jax.devices("cpu"))
        assert Placement(mesh="data:-1", accelerator="cpu").key() == \
            Placement(mesh=f"data:{n}", accelerator="true:cpu").key()

    def test_rule_aliases_one_key(self):
        assert Placement(mesh="data:2,model:2", sharding="tp",
                         accelerator="cpu").key() == \
            Placement(mesh="data:2,model:2", sharding="mobilenet",
                      accelerator="cpu").key()

    def test_device_subset_spellings_one_key(self):
        assert Placement(mesh="data:4", devices="0-3",
                         accelerator="cpu").key() == \
            Placement(mesh="data:-1", devices="0,1,2,3",
                      accelerator="cpu").key()

    def test_different_placements_different_keys(self):
        a = Placement(mesh="data:4", accelerator="cpu").key()
        b = Placement(mesh="data:2", accelerator="cpu").key()
        c = Placement(mesh="data:2,model:2", accelerator="cpu").key()
        assert len({a, b, c}) == 3

    def test_null_placement_keys_by_kind(self):
        assert Placement(accelerator="true:cpu").key() == \
            Placement(accelerator="cpu").key()
        assert Placement().key()[0] == "device"

    def test_unresolvable_spec_falls_back_to_raw(self):
        k = Placement(mesh="data:5,model:7", accelerator="cpu").key()
        assert k[0] == "raw"

    def test_dcn_axes_must_lead(self):
        with pytest.raises(ValueError):
            Placement(mesh="data:4,dcn.data:2",
                      accelerator="cpu").resolve()
        with pytest.raises(ValueError):
            Placement(mesh="dcn.data:2", accelerator="cpu").resolve()

    def test_dcn_single_process_resolves(self):
        rp = Placement(mesh="dcn.data:1,data:4",
                       accelerator="cpu").resolve()
        assert rp.data_axes == ("dcn.data", "data")
        assert rp.data_axis == "data"
        assert rp.data_axis_size == 4
        assert rp.num_processes == 1
        assert rp.window_sharding(8) is not None
        assert rp.window_sharding(3) is None
        assert rp.describe() == "mesh(dcn.data:1,data:4)"

    def test_devices_subset_rejected_on_dcn_mesh(self):
        with pytest.raises(ValueError):
            Placement(mesh="dcn.data:1,data:4", devices="0-3",
                      accelerator="cpu").resolve()


# -- satellite bugfix: both spellings join ONE pool ---------------------------


def _shared_pipe(name, mesh, model="_t_place", batch=4, **kw):
    p = Pipeline(name=name)
    src = AppSrc(name="src", spec=SPEC, max_buffers=batch + 4)
    q = Queue(name="q", max_size_buffers=batch + 4)
    flt = TensorFilter(name="net", framework="jax-xla", model=model,
                       share_model=True, batch=batch,
                       batch_timeout_ms=5.0, batch_buckets=str(batch),
                       mesh=mesh, accelerator="cpu", **kw)
    sink = AppSink(name="out", max_buffers=64)
    p.add(src, q, flt, sink).link(src, q, flt, sink)
    return p, src, flt, sink


class TestPoolCanonicalKey:
    def test_pool_key_canonicalizes_mesh_spelling(self):
        import jax

        n = len(jax.devices("cpu"))
        a = pool_key("jax-xla", FilterProps(
            framework="jax-xla", model="_t_place", mesh="data:-1",
            accelerator="cpu"))
        b = pool_key("jax-xla", FilterProps(
            framework="jax-xla", model="_t_place", mesh=f"data:{n}",
            accelerator="true:cpu"))
        assert a == b

    def test_both_spellings_join_one_pool(self):
        """ISSUE-12 satellite: mesh=data:-1 and mesh=data:8 on an
        8-device host used to open TWO pools (raw-string keys) and
        silently defeat sharing."""
        import jax

        n = len(jax.devices("cpu"))
        p1, s1, f1, k1 = _shared_pipe("pk_a", "data:-1")
        p2, s2, f2, k2 = _shared_pipe("pk_b", f"data:{n}")
        p1.start()
        p2.start()
        try:
            assert len(MODEL_POOL) == 1
            assert f1.pool is f2.pool
            assert f1.pool.refcount == 2
            # and the shared window really coalesces both streams
            x1 = np.ones(SHAPE, np.float32)
            x2 = np.full(SHAPE, 2.0, np.float32)
            for i in range(2):
                s1.push_buffer(Buffer.of(x1 * (i + 1), pts=i))
                s2.push_buffer(Buffer.of(x2 * (i + 1), pts=i))
            for i in range(2):
                a = k1.pull(timeout=20)
                b = k2.pull(timeout=20)
                np.testing.assert_allclose(
                    a.tensors[0].np(), x1 * (i + 1) @ W + 1.0,
                    rtol=1e-5)
                np.testing.assert_allclose(
                    b.tensors[0].np(), x2 * (i + 1) @ W + 1.0,
                    rtol=1e-5)
        finally:
            s1.end_of_stream()
            s2.end_of_stream()
            p1.wait_eos(timeout=20)
            p2.wait_eos(timeout=20)
            p1.stop()
            p2.stop()

    def test_conflicting_placements_raise_pool_conflict(self):
        p1, s1, f1, k1 = _shared_pipe("pc_a", "data:4")
        p2, s2, f2, k2 = _shared_pipe("pc_b", "data:2")
        p1.start()
        try:
            with pytest.raises(Exception) as ei:
                p2.start()
            msg = str(ei.value)
            assert "placement" in msg
            # the runtime error class is PoolConflictError (it may
            # surface wrapped in the negotiation error)
            assert isinstance(ei.value, PoolConflictError) \
                or "disagree on placement" in msg
        finally:
            p1.stop()


# -- the stacked sharded window ----------------------------------------------


class TestStackedWindow:
    def test_values_and_pads_via_invoke_batched(self):
        sp = JaxXlaFilter()
        sp.configure(FilterProps(framework="jax-xla", model="_t_place",
                                 mesh="data:2", accelerator="cpu"))
        frames = [[np.full(SHAPE, float(i), np.float32)]
                  for i in range(3)]
        outs = sp.invoke_batched(frames, 4)  # 3 frames pad to 4
        assert len(outs) == 3
        for i, out in enumerate(outs):
            np.testing.assert_allclose(
                np.asarray(out[0]),
                np.full(SHAPE, float(i), np.float32) @ W + 1.0,
                rtol=1e-5)
        # the stacked executable is cached per (in_spec, bucket)
        assert sp.batch_cache_misses == 1
        sp.invoke_batched(frames, 4)
        assert sp.batch_cache_hits == 1
        sp.close()

    def test_stacked_window_outputs_are_sharded(self):
        sp = JaxXlaFilter()
        sp.configure(FilterProps(framework="jax-xla", model="_t_place",
                                 mesh="data:2", accelerator="cpu"))
        frames = [[np.zeros(SHAPE, np.float32)] for _ in range(4)]
        outs = sp.invoke_batched(frames, 4)
        # per-frame outputs are slices of ONE batch-sharded global
        # array: the dispatch spread over both devices
        devs = {d for o in outs for d in o[0].sharding.device_set}
        assert len(devs) >= 1  # slices commit to their shard's device
        sp.close()

    def test_multiprocess_attribution_restricts_to_local_axes(self):
        """A multi-process stacked window records its LOCAL slice over
        the local (ICI) data axes only — splitting this process's
        frames over the global shard product would zero every count
        (review fix)."""
        from nnstreamer_tpu.obs.meshstat import MESH_STATS

        sp = JaxXlaFilter()
        sp.configure(FilterProps(framework="jax-xla", model="_t_place",
                                 mesh="dcn.data:1,data:2",
                                 accelerator="cpu"))
        sp._placement.num_processes = 2  # simulate a 2-process group
        sp._record_mesh(slots=4, frames=3, sharded=True, local=True)
        row = MESH_STATS.get("_t_place")
        assert row["data_axis"] == "data"  # dcn tier stripped
        assert row["shards"] == 2          # local axes only
        assert row["shard_frames"] == [2, 1]
        sp._placement.num_processes = 1
        sp.close()
        MESH_STATS.reset()

    def test_dcn_single_process_window_dispatch(self):
        sp = JaxXlaFilter()
        sp.configure(FilterProps(framework="jax-xla", model="_t_place",
                                 mesh="dcn.data:1,data:2",
                                 accelerator="cpu"))
        frames = [[np.full(SHAPE, float(i), np.float32)]
                  for i in range(4)]
        outs = sp.invoke_batched(frames, 4)
        for i, out in enumerate(outs):
            np.testing.assert_allclose(
                np.asarray(out[0]),
                np.full(SHAPE, float(i), np.float32) @ W + 1.0,
                rtol=1e-5)
        sp.close()


# -- acceptance: mesh=data:1 == unsharded, frame for frame --------------------


def _run_pool_once(mesh):
    n = 8
    p, src, flt, sink = _shared_pipe(f"eq_{mesh or 'none'}", mesh,
                                     batch=4)
    outs = []
    with p:
        for i in range(n):
            src.push_buffer(Buffer.of(
                np.full(SHAPE, float(i + 1), np.float32), pts=i))
        for _ in range(n):
            b = sink.pull(timeout=20)
            assert b is not None
            outs.append((b.pts, np.asarray(b.tensors[0].np()).copy()))
        src.end_of_stream()
        assert p.wait_eos(timeout=20)
    MODEL_POOL.clear()
    with JaxXlaFilter._shared_lock:
        JaxXlaFilter._shared_instances.clear()
    return outs


def test_mesh_data1_matches_unsharded_frame_for_frame():
    """ISSUE-12 acceptance: a sharded pool with ``mesh=data:1`` yields
    the SAME pts, order, and values as the unsharded pool."""
    plain = _run_pool_once("")
    meshed = _run_pool_once("data:1")
    assert [p for p, _ in plain] == [p for p, _ in meshed]
    for (_, a), (_, b) in zip(plain, meshed):
        np.testing.assert_array_equal(a, b)


# -- pool <-> meshstat obs join ----------------------------------------------


def test_pool_snapshot_and_top_render_shard_fields():
    from nnstreamer_tpu.obs.metrics import REGISTRY
    from nnstreamer_tpu.obs.top import render

    p, src, flt, sink = _shared_pipe("obsj", "data:2", batch=4)
    with p:
        for i in range(4):
            src.push_buffer(Buffer.of(
                np.full(SHAPE, float(i), np.float32), pts=i))
        for _ in range(4):
            assert sink.pull(timeout=20) is not None
        snap = REGISTRY.snapshot()
        src.end_of_stream()
        assert p.wait_eos(timeout=20)
    row = [r for r in snap["pools"] if "_t_place" in r["pool"]][0]
    assert row["placement"] == "mesh(data:2)"
    m = row["mesh"]
    assert sorted(m.keys()) == [
        "imbalance", "max_shard_share", "pad_frac", "processes",
        "replicated_dispatches", "shards"]
    assert m["shards"] == 2
    assert m["imbalance"] == 0.0  # 4 frames over 2 shards, even
    assert m["pad_frac"] == 0.0
    assert m["max_shard_share"] == pytest.approx(0.5)
    # flat samples join
    fam = snap["metrics"]["nns_pool_shard_imbalance"]["samples"]
    assert any(s["value"] == 0.0 for s in fam)
    # nns-top POOL columns render the join
    cur = json.loads(json.dumps(snap, default=str))
    out = render(cur, None)
    assert "SHARE%" in out and "IMBAL" in out and "PAD%" in out


def test_placement_property_on_pool_entry():
    p, src, flt, sink = _shared_pipe("pp", "data:2", batch=4)
    p.start()
    try:
        rp = flt.pool.placement
        assert rp is not None
        assert rp.data_axis_size == 2
        assert flt.data_shards == 2
    finally:
        p.stop()
